"""Device-resident windowed statistics engine (the stream_calc_stats rebuild).

The reference buckets elapsed times into 10 s intervals per (server, service)
dict key and, on each new interval, walks every key computing TPM / average /
p75 / p95 over a sliding window (stream_calc_stats.js:157-203). Here the same
computation is one batched XLA program over dense tensors:

- state: ``counts [S, NB]``, ``sums [S, NB]``, ``samples [S, NB, CAP]``,
  ``nsamples [S, NB]`` — a bucket ring keyed ``slot = label % NB`` with
  ``NB = windowSize + bufferSize + 1`` slots, exactly the label range the
  reference retains after ``removeOldBuckets`` (stream_calc_stats.js:103-113).
- :func:`ingest`: scatter-add a micro-batch of (row, bucket-label, elapsed)
  triples, including within-batch duplicate-key sample placement.
- :func:`tick`: on a new latest label, compute per-row window stats for ALL
  rows at once. Window = labels ``[latest-keep, latest-buffer]`` inclusive — 31
  labels for the stock config, reproducing the reference's inclusive range
  (stream_calc_stats.js:172).

Exactness notes (SURVEY.md §7.3):
- Percentiles are exact order statistics over the stored window samples,
  using the reference's index formula (util_methods.js:112-142) evaluated in
  *integer* arithmetic — provably equal to the reference's float64 index math
  for p in {75, 95} and realistic n.
- Each (row, bucket) stores at most CAP samples. Below CAP the stored set is
  every sample and percentiles are exact. Beyond CAP, reservoir sampling
  (Algorithm R) keeps a uniform random CAP-subset of that bucket's arrivals,
  and the window percentile (default "sort" impl) pools the buckets with
  each sample weighted by its bucket's count/stored — the importance weight
  that keeps a bursty bucket's arrival mass intact (an unweighted pool
  would flatten every bucket to <=CAP samples and bias toward quiet
  buckets). Per-bucket sampling error is O(1/sqrt(CAP)) in rank; first-CAP
  truncation, by contrast, is arbitrarily biased toward early arrivals.
  The reservoir's randomness is a deterministic hash of (row, bucket label,
  arrival index), so replay and resume reproduce the same reservoir
  bit-for-bit. ``overflowed`` in the tick output reports rows whose window
  percentile used a reservoir (counts/averages stay exact regardless).
- ``average`` is sum/count like the reference; NaN where the window is empty
  (the reference's ``undefined``).
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np


class StatsConfig(NamedTuple):
    capacity: int  # S rows
    window_sz: int = 30  # windowSizeInIntervals
    buffer_sz: int = 6  # bufferSizeInIntervals
    interval_len_s: int = 10  # intervalLengthInSeconds
    samples_per_bucket: int = 128  # CAP
    dtype: jnp.dtype = jnp.float32
    # percentile implementation — all exact below samplesPerBucket:
    #   "auto"   -> adaptive: top_k while no bucket overflows, weighted sort
    #               the moment one does (lax.cond on the overflow flag)
    #   "sort"   -> argsort + count-weighted reference index math (the only
    #               impl that keeps burst arrival mass intact in overflow)
    #   "topk"   -> jax.lax.top_k over the top quarter of each row
    #   "pallas" -> bit-binary-search selection kernel (opt-in until proven
    #               on real TPU hardware; interpret-mode off-TPU)
    percentile_impl: str = "auto"

    @property
    def num_keep(self) -> int:
        # NUM_KEEP_INTERVALS = window + buffer (stream_calc_stats.js:233)
        return self.window_sz + self.buffer_sz

    @property
    def num_buckets(self) -> int:
        # ring must hold labels latest-num_keep .. latest
        return self.num_keep + 1

    @property
    def window_label_count(self) -> int:
        # inclusive [latest-keep, latest-buffer]
        return self.num_keep - self.buffer_sz + 1


class StatsState(NamedTuple):
    latest_bucket: jnp.ndarray  # int32 scalar
    counts: jnp.ndarray  # [S, NB] int32
    sums: jnp.ndarray  # [S, NB] float
    samples: jnp.ndarray  # [S, NB, CAP] float (NaN = empty)
    nsamples: jnp.ndarray  # [S, NB] int32 (clamped at CAP)


def init_state(cfg: StatsConfig) -> StatsState:
    S, NB, CAP = cfg.capacity, cfg.num_buckets, cfg.samples_per_bucket
    return StatsState(
        latest_bucket=jnp.zeros((), jnp.int32),
        counts=jnp.zeros((S, NB), jnp.int32),
        sums=jnp.zeros((S, NB), cfg.dtype),
        samples=jnp.full((S, NB, CAP), jnp.nan, cfg.dtype),
        nsamples=jnp.zeros((S, NB), jnp.int32),
    )


def bucket_label(end_ts_ms) -> np.ndarray:
    """ms timestamp -> 10 s bucket label: the reference truncates the last 4

    digits of the decimal string (stream_calc_stats.js:89-96) == floor/10^4.
    Host-side (numpy): ms timestamps need 64-bit; the device only ever sees
    the int32 labels."""
    return (np.asarray(end_ts_ms, np.int64) // 10000).astype(np.int32)


def ts_from_bucket_label(label) -> int:
    return int(label) * 10000  # stream_calc_stats.js:98-101


def _batch_cumcount(keys: jnp.ndarray, valid: jnp.ndarray) -> jnp.ndarray:
    """Per-entry occurrence index among equal keys, in arrival order.

    Used to place duplicate (row, bucket) samples at consecutive slots within
    one scatter. Invalid entries get arbitrary values (masked by caller).
    """
    B = keys.shape[0]
    big = jnp.where(valid, keys, jnp.iinfo(jnp.int32).max)
    perm = jnp.argsort(big, stable=True)
    sorted_keys = big[perm]
    seg_start = jnp.concatenate(
        [jnp.ones((1,), bool), sorted_keys[1:] != sorted_keys[:-1]]
    )
    idx = jnp.arange(B, dtype=jnp.int32)
    run_start = jax.lax.cummax(jnp.where(seg_start, idx, 0))
    idx_in_run = idx - run_start
    out = jnp.zeros((B,), jnp.int32).at[perm].set(idx_in_run)
    return out


def _mix32(x: jnp.ndarray) -> jnp.ndarray:
    """murmur3 finalizer over uint32: the deterministic per-arrival hash
    driving reservoir replacement (full avalanche, wraps mod 2^32)."""
    x = x.astype(jnp.uint32)
    x = (x ^ (x >> 16)) * jnp.uint32(0x85EBCA6B)
    x = (x ^ (x >> 13)) * jnp.uint32(0xC2B2AE35)
    return x ^ (x >> 16)


def _keep_last(keys: jnp.ndarray, valid: jnp.ndarray) -> jnp.ndarray:
    """True for the last (in arrival order) valid occurrence of each key.

    XLA scatter leaves duplicate-index write order undefined; masking all but
    the final writer per target keeps ingest deterministic (replay parity).
    """
    B = keys.shape[0]
    big = jnp.where(valid, keys, jnp.iinfo(jnp.int32).max)
    perm = jnp.argsort(big, stable=True)
    sk = big[perm]
    is_last = jnp.concatenate([sk[:-1] != sk[1:], jnp.ones((1,), bool)])
    return jnp.zeros((B,), bool).at[perm].set(is_last) & valid


def ingest(state: StatsState, cfg: StatsConfig, rows, labels, elapsed, valid) -> StatsState:
    """Scatter a micro-batch into the bucket ring.

    rows [B] int32, labels [B] int32, elapsed [B] float, valid [B] bool.
    Entries whose label is stale (<= latest - NB) or beyond latest are dropped:
    the driver must tick() to advance latest BEFORE ingesting newer labels
    (mirroring consumeMsg's stats-before-addData order,
    stream_calc_stats.js:348-370).
    """
    NB, CAP = cfg.num_buckets, cfg.samples_per_bucket
    # the reservoir dedupe key below composes (row, slot, pos) in int32; this
    # is a static shape property, so enforce it at trace time rather than
    # letting a grown fleet silently wrap the key space
    if cfg.capacity * NB * (CAP + 1) > np.iinfo(np.int32).max:
        raise ValueError(
            f"capacity {cfg.capacity} x num_buckets {NB} x (samples_per_bucket+1) "
            f"{CAP + 1} exceeds the int32 dedupe-key space (~450k rows at stock "
            f"window sizes); shard the fleet across devices instead"
        )
    rows = jnp.asarray(rows, jnp.int32)
    labels = jnp.asarray(labels, jnp.int32)
    elapsed = jnp.asarray(elapsed, cfg.dtype)

    in_range = (labels > state.latest_bucket - NB) & (labels <= state.latest_bucket)
    valid = jnp.asarray(valid, bool) & in_range
    slots = jnp.where(valid, labels % NB, 0)
    srows = jnp.where(valid, rows, 0)

    one = jnp.where(valid, 1, 0).astype(jnp.int32)
    counts = state.counts.at[srows, slots].add(one, mode="drop")
    sums = state.sums.at[srows, slots].add(jnp.where(valid, elapsed, 0), mode="drop")

    key = srows * NB + slots
    cum = _batch_cumcount(key, valid)
    # arrival index among ALL arrivals ever seen by this (row, bucket) —
    # state.counts counts every valid arrival, including reservoir-dropped ones
    t = state.counts[srows, slots] + cum
    # Reservoir sampling (Algorithm R): arrivals 0..CAP-1 fill slots in order;
    # arrival t >= CAP replaces slot j = hash(row, label, t) % (t+1) iff
    # j < CAP (probability CAP/(t+1)), keeping the stored set a uniform sample
    # of all t+1 arrivals. The hash is deterministic in (row, label, t) so
    # replay/resume reproduce the reservoir bit-for-bit.
    h = _mix32(
        srows.astype(jnp.uint32) * jnp.uint32(0x9E3779B9)
        ^ labels.astype(jnp.uint32) * jnp.uint32(0x85EBCA6B)
        ^ t.astype(jnp.uint32)
    )
    j = (h % (t.astype(jnp.uint32) + 1)).astype(jnp.int32)
    pos = jnp.where(t < CAP, t, jnp.where(j < CAP, j, CAP))
    ok = valid & (pos < CAP)
    pos = jnp.where(ok, pos, CAP)  # CAP is out of bounds -> dropped
    # dedupe within-batch writes to the same (row, slot, pos): keep the latest
    # arrival. wkey staying inside int32 is enforced by the trace-time check
    # at the top of this function.
    wkey = key * (CAP + 1) + pos
    ok = ok & _keep_last(wkey, ok)
    pos = jnp.where(ok, pos, CAP)
    samples = state.samples.at[srows, slots, pos].set(
        jnp.where(ok, elapsed, jnp.nan), mode="drop"
    )
    nsamples = jnp.minimum(state.nsamples.at[srows, slots].add(one, mode="drop"), CAP)

    return state._replace(counts=counts, sums=sums, samples=samples, nsamples=nsamples)


def _advance(state: StatsState, cfg: StatsConfig, new_label: jnp.ndarray) -> StatsState:
    """Zero ring slots claimed by labels (old_latest, new_label] and bump
    latest. Single-program form: the samples clear is a whole-buffer select
    (handles any label jump in one shot, but costs a full [S, NB, CAP]
    rewrite — XLA:CPU also copies it under donation). Latency-critical hosts
    dispatch :func:`advance_one` per new label instead (make_engine_step)."""
    NB = cfg.num_buckets
    old = state.latest_bucket
    k = jnp.minimum(new_label - old, NB)
    offsets = jnp.arange(1, NB + 1, dtype=jnp.int32)
    slot_ids = (old + offsets) % NB
    clear = jnp.zeros((NB,), bool).at[slot_ids].max(offsets <= k)
    counts = jnp.where(clear[None, :], 0, state.counts)
    sums = jnp.where(clear[None, :], 0, state.sums)
    nsamples = jnp.where(clear[None, :], 0, state.nsamples)
    samples = jnp.where(clear[None, :, None], jnp.nan, state.samples)
    return StatsState(new_label.astype(jnp.int32), counts, sums, samples, nsamples)


def _clear_slot(state: StatsState, slot: jnp.ndarray) -> StatsState:
    """Zero ONE ring slot via contiguous dynamic_update_slices — the
    in-place-aliasing op shape shared by :func:`advance_one` and
    :func:`advance_span` (latest_bucket is left for the caller)."""
    CAP = state.samples.shape[-1]
    z = jnp.zeros((), jnp.int32)  # same index dtype as slot (x64-safe)
    S = state.counts.shape[0]
    hole = jnp.zeros((S, 1), state.counts.dtype)
    counts = jax.lax.dynamic_update_slice(state.counts, hole, (z, slot))
    sums = jax.lax.dynamic_update_slice(state.sums, hole.astype(state.sums.dtype), (z, slot))
    nsamples = jax.lax.dynamic_update_slice(state.nsamples, hole, (z, slot))
    nan_slab = jnp.full((S, 1, CAP), jnp.nan, state.samples.dtype)
    samples = jax.lax.dynamic_update_slice(state.samples, nan_slab, (z, slot, z))
    return state._replace(counts=counts, sums=sums, samples=samples, nsamples=nsamples)


def advance_one(state: StatsState, cfg: StatsConfig, next_label) -> StatsState:
    """Advance the ring by EXACTLY ONE label: clear the slot ``next_label``
    claims and bump latest. The samples clear is one contiguous
    dynamic_update_slice — the in-place-aliasing op — so a donated dispatch
    never rewrites (or copies) the [S, NB, CAP] reservoir the way the
    whole-buffer select in :func:`_advance` does. The host loop calls this
    once per new label (bounded by NB calls on a label jump; the ring only
    holds NB labels), exactly like the z-score ring_write staging."""
    NB = cfg.num_buckets
    next_label = jnp.asarray(next_label, jnp.int32)
    return _clear_slot(state, next_label % NB)._replace(latest_bucket=next_label)


def advance_span(state: StatsState, cfg: StatsConfig, new_label) -> StatsState:
    """Advance the ring to a TRACED ``new_label`` entirely in-program: clear
    the slots claimed by labels (latest, new_label] — at most NB, since the
    ring only holds NB labels — and bump latest. Each clear is the same
    contiguous DUS as :func:`advance_one`, issued from a bounded fori_loop
    whose off iterations pass the state through untouched (lax.cond), so a
    donated dispatch keeps the [S, NB, CAP] reservoir in place for any jump
    size. This is what lets the fused single-dispatch executor take the new
    label as a device scalar — no host mirror of latest_bucket, no
    device->host sync per tick. Stale labels (<= latest) clamp to a no-op
    clear, exactly like :func:`tick`'s guard."""
    NB = cfg.num_buckets
    nl = jnp.maximum(jnp.asarray(new_label, jnp.int32), state.latest_bucket)
    k = jnp.minimum(nl - state.latest_bucket, NB)

    def body(j, st):
        # newest-first: label nl - j claims slot (nl - j) % NB; order is
        # irrelevant (pure clears of distinct slots)
        return _clear_slot(st, (nl - j) % NB)

    # dynamic trip count (lowers to a while_loop): the common +1 tick runs
    # exactly one clear instead of NB masked iterations
    return jax.lax.fori_loop(0, k, body, state)._replace(latest_bucket=nl)


def percentile_rank(n: jnp.ndarray, p: int):
    """The reference's percentile index math (util_methods.js:112-142) as
    (1-indexed rank, take_pair): value = take_pair ? mean(v[rank], v[rank+1])
    : v[rank]. Integer-exact; the single source shared by the sort path below
    and the Pallas selection kernel (ops/pallas_kernels.py)."""
    pn = p * n  # int32
    is_int = (pn % 100) == 0
    idx_exact = pn // 100 - 1
    idx_ceil = (pn - 1) // 100  # ceil(pn/100 - 1) for non-integral pn/100
    last = n - 1
    idx1 = jnp.where(is_int | (n == 1), jnp.maximum(idx_exact, 0), idx_ceil)
    take_pair = (~is_int) & (n > 1) & (idx_ceil != last)
    return (idx1 + 1).astype(jnp.int32), take_pair


def topk_percentiles(window: jnp.ndarray, n: jnp.ndarray, ps, n_max: int = None) -> tuple:
    """Exact reference percentiles via ``jax.lax.top_k`` instead of a full sort.

    For p >= 75 both the rank element and its interpolation neighbor always
    sit within the top ``0.25n + 1`` values of the row: the r-th smallest of
    n (1-indexed, a[r-1] ascending) is d[n-r] in descending order, and
    r >= ceil(p*n/100) - 1 >= 0.75n - 1 bounds n-r. top_k is O(N log k) and
    maps far better onto the TPU than the O(N log^2 N) bitonic sort of the
    whole window; the result is the exact order statistic, not an
    approximation (property-tested against the sort path). NaN = empty
    slots (sorted past +inf by the sort path) become -inf here so they fall
    OUT of the top-k window instead. ``n_max`` tightens k when the array is
    wider than the possible valid count (the masked full-ring read passes
    W*CAP while the array spans NB*CAP).
    """
    if min(ps) < 75:  # the k bound above assumes p >= 75
        raise ValueError(f"topk percentile path requires p >= 75, got {ps}")
    N = window.shape[-1]
    bound = N if n_max is None else min(n_max, N)
    k = min(N, bound // 4 + 2)
    neg = jnp.where(jnp.isnan(window), -jnp.inf, window)
    top = jax.lax.top_k(neg, k)[0]  # [..., k] descending
    outs = []
    for p in ps:
        rank, take_pair = percentile_rank(n, p)
        idx1 = jnp.clip(n - rank, 0, k - 1)  # d[n-r] == a[r-1]
        idx2 = jnp.clip(jnp.where(take_pair, n - rank - 1, idx1), 0, k - 1)
        v1 = jnp.take_along_axis(top, idx1[..., None], axis=-1)[..., 0]
        v2 = jnp.take_along_axis(top, idx2[..., None], axis=-1)[..., 0]
        out = jnp.where(take_pair, (v1 + v2) / 2.0, v1)
        outs.append(jnp.where(n > 0, out, jnp.nan))
    return tuple(outs)


def reference_percentile_sorted(sorted_vals: jnp.ndarray, n: jnp.ndarray, p: int) -> jnp.ndarray:
    """Vectorized util_methods.js:112-142 over ``[..., K]`` ascending-sorted
    arrays (NaN tail) with ``n`` valid entries per row: value at the
    :func:`percentile_rank` rank, averaged with its successor on take_pair."""
    rank, take_pair = percentile_rank(n, p)
    idx1 = jnp.clip(rank - 1, 0, sorted_vals.shape[-1] - 1)
    idx2 = jnp.clip(jnp.where(take_pair, idx1 + 1, idx1), 0, sorted_vals.shape[-1] - 1)
    v1 = jnp.take_along_axis(sorted_vals, idx1[..., None], axis=-1)[..., 0]
    v2 = jnp.take_along_axis(sorted_vals, idx2[..., None], axis=-1)[..., 0]
    out = jnp.where(take_pair, (v1 + v2) / 2.0, v1)
    return jnp.where(n > 0, out, jnp.nan)


# percentile_rank computes p*n in int32; clamp n so it cannot overflow
# (22M arrivals per window row = far beyond any real per-service rate; at
# that scale a +-1 rank shift is far below the estimator's own error)
_MAX_RANK_N = (2**31 - 1) // 100


def weighted_reference_percentiles(
    window: jnp.ndarray,  # [S, K] samples (NaN = empty slot)
    weights: jnp.ndarray,  # [S, K] arrivals each sample represents (0 = empty)
    n_arrivals: jnp.ndarray,  # [S] int32 TOTAL window arrival count
    ps,
) -> tuple:
    """Reference percentiles over the weighted empirical distribution.

    Each stored sample in bucket b stands for ``count_b / stored_b`` arrivals
    (its reservoir's sampling weight), so the pooled window estimate weights
    bursty buckets by their true arrival mass instead of flattening every
    bucket to CAP samples. The rank is the reference's index math in ARRIVAL
    space (util_methods.js:112-142 over all n arrivals); the value at rank r
    is the first sorted sample whose cumulative weight reaches r, averaged
    with the sample at rank r+1 on take_pair. With no overflow every weight
    is exactly 1, cumulative weight of the i-th sample is i, and this reduces
    bit-for-bit to :func:`reference_percentile_sorted`.
    """
    order = jnp.argsort(window, axis=-1)  # NaN sorts to the end
    sv = jnp.take_along_axis(window, order, axis=-1)
    sw = jnp.take_along_axis(weights, order, axis=-1)
    cum = jnp.cumsum(sw, axis=-1)  # [S, K]
    n_r = jnp.minimum(n_arrivals, _MAX_RANK_N)
    K = window.shape[-1]
    outs = []
    for p in ps:
        rank, take_pair = percentile_rank(n_r, p)
        # first index with cum >= rank; the 0.5 tolerance absorbs float
        # cumsum drift (exact-integer cums are never within 0.5 of a
        # boundary, and fractional-weight drift is ~ulps)
        idx1 = jnp.sum(cum < rank[..., None].astype(cum.dtype) - 0.5, axis=-1)
        idx2 = jnp.sum(cum < (rank + 1)[..., None].astype(cum.dtype) - 0.5, axis=-1)
        idx1 = jnp.clip(idx1, 0, K - 1)
        idx2 = jnp.clip(jnp.where(take_pair, idx2, idx1), 0, K - 1)
        v1 = jnp.take_along_axis(sv, idx1[..., None], axis=-1)[..., 0]
        v2 = jnp.take_along_axis(sv, idx2[..., None], axis=-1)[..., 0]
        out = jnp.where(take_pair, (v1 + v2) / 2.0, v1)
        outs.append(jnp.where(n_arrivals > 0, out, jnp.nan))
    return tuple(outs)


def edge_ts_ms(new_label: int, cfg: StatsConfig) -> int:
    """Host-side: the timestamp all stats emitted by tick(new_label) carry —

    the end of the last window bucket, (latest - buffer - 1) * 1e4
    (stream_calc_stats.js:356). Host int to avoid int64-on-device issues."""
    return (int(new_label) - cfg.buffer_sz - 1) * 10000


class TickResult(NamedTuple):
    tpm: jnp.ndarray  # [S]
    average: jnp.ndarray  # [S] (NaN = undefined)
    per75: jnp.ndarray  # [S]
    per95: jnp.ndarray  # [S]
    count: jnp.ndarray  # [S] int32 window tx count
    overflowed: jnp.ndarray  # [S] bool: percentile computed on truncated samples


def _window_panels(state: StatsState, cfg: StatsConfig):
    """(in_window [NB], cnt, total, stored — each [S]) from the SMALL bucket
    panels only; the shared front half of window_pre and window_stats."""
    NB = cfg.num_buckets
    latest = state.latest_bucket
    # window labels: latest-keep .. latest-buffer (31 for stock config)
    offsets = jnp.arange(cfg.buffer_sz, cfg.num_keep + 1, dtype=jnp.int32)
    slots_w = (latest - offsets) % NB  # [W]
    in_window = jnp.zeros((NB,), bool).at[slots_w].set(True)  # [NB]
    cnt = jnp.sum(jnp.where(in_window[None, :], state.counts, 0), axis=1)  # [S]
    total = jnp.sum(jnp.where(in_window[None, :], state.sums, 0), axis=1)  # [S]
    stored = jnp.sum(jnp.where(in_window[None, :], state.nsamples, 0), axis=1)  # [S]
    return in_window, cnt, total, stored


def window_pre(state: StatsState, cfg: StatsConfig) -> TickResult:
    """Window statistics WITHOUT percentiles (per75/per95 = NaN): the
    tiny program the native-percentile staging dispatches first — it reads
    only the [S, NB] bucket panels, never the sample reservoir. The host
    then fills the percentiles (native selection kernel, or the weighted
    jitted fallback on overflow) and hands the completed TickResult to the
    core program."""
    in_window, cnt, total, stored = _window_panels(state, cfg)
    average = jnp.where(cnt > 0, total / cnt, jnp.nan)
    overflowed = stored < cnt
    tpm = cnt / (cfg.window_sz * cfg.interval_len_s / 60.0)
    nanv = jnp.full(cnt.shape, jnp.nan, cfg.dtype)
    return TickResult(tpm, average.astype(cfg.dtype), nanv, nanv, cnt, overflowed)


def window_stats(state: StatsState, cfg: StatsConfig) -> TickResult:
    """Window statistics at the CURRENT latest label — strictly read-only
    (the staged executor runs it in a program that never writes the big
    buffers, so XLA keeps them in place; :func:`tick` composes it with the
    advance for single-program use).

    The window's buckets are selected by an in-register [NB] slot mask
    instead of a gathered [S, W, CAP] copy: excluded slots read as NaN
    (weight 0 / -inf under top_k), which XLA fuses into the percentile
    pass — one streaming read of the reservoir, no materialized permutation.
    """
    NB, CAP = cfg.num_buckets, cfg.samples_per_bucket
    in_window, cnt, total, stored = _window_panels(state, cfg)
    average = jnp.where(cnt > 0, total / cnt, jnp.nan)
    overflowed = stored < cnt

    S_rows = state.samples.shape[0]
    window_samples = jnp.where(
        in_window[None, :, None], state.samples, jnp.nan
    ).reshape(S_rows, NB * CAP)
    impl = cfg.percentile_impl
    if impl == "native":
        # the native nth_element kernel lives on the HOST side of the staged
        # executor (pipeline.make_engine_step); inside a single program the
        # adaptive jitted path is its exact equivalent
        impl = "auto"

    def _weighted():
        # count-weighted percentiles: each bucket's reservoir samples carry
        # weight count/stored (== 1 with no overflow, where this is bit-exact
        # reference math over every sample). The only impl whose pooled
        # estimate keeps a bursty bucket's arrival mass intact under
        # cross-bucket skew. Excluded slots carry weight 0 and value NaN, so
        # they sort to the tail and never touch a rank.
        w_bucket = jnp.where(
            in_window[None, :],
            state.counts.astype(cfg.dtype)
            / jnp.maximum(state.nsamples, 1).astype(cfg.dtype),
            0,
        )  # [S, NB]
        w_flat = jnp.broadcast_to(
            w_bucket[:, :, None], (S_rows, NB, CAP)
        ).reshape(S_rows, NB * CAP)
        weights = jnp.where(jnp.isnan(window_samples), 0, w_flat)
        return weighted_reference_percentiles(window_samples, weights, cnt, (75, 95))

    if impl == "auto":
        # adaptive: with no overflow anywhere, top_k is exact and touches
        # only the top quarter of each row; the moment any bucket overflows,
        # the weighted sort takes over so burst mass is not flattened.
        # (pallas stays opt-in until its hardware proof,
        # benchmarks/bench_pallas.py.)
        per75, per95 = jax.lax.cond(
            jnp.any(overflowed),
            _weighted,
            lambda: topk_percentiles(
                window_samples, stored, (75, 95),
                n_max=cfg.window_label_count * CAP,
            ),
        )
    elif impl == "topk":
        per75, per95 = topk_percentiles(
            window_samples, stored, (75, 95), n_max=cfg.window_label_count * CAP
        )
    elif impl == "pallas":
        if cfg.dtype == jnp.float64:
            # the kernel is f32-only; a silent downcast would break the f64
            # reference-parity mode (auto never picks pallas for f64)
            raise ValueError("percentile_impl='pallas' requires float32 (got float64)")
        from .pallas_kernels import window_percentiles

        per75, per95 = window_percentiles(
            window_samples, stored, (75, 95),
            interpret=jax.default_backend() != "tpu",
        )
    else:
        per75, per95 = _weighted()

    tpm = cnt / (cfg.window_sz * cfg.interval_len_s / 60.0)  # stream_calc_stats.js:186

    return TickResult(tpm, average.astype(cfg.dtype), per75, per95, cnt, overflowed)


def tick(state: StatsState, cfg: StatsConfig, new_label) -> Tuple[TickResult, StatsState]:
    """New-interval step: advance, then compute window stats for all rows.

    Mirrors the consumeMsg new-bucket branch (stream_calc_stats.js:348-366):
    latestBucket = new_label; removeOldBuckets; stats over
    [latest-keep, latest-buffer] stamped edgeTs = (latest - buffer - 1) * 1e4.
    """
    # Guard against non-increasing labels (the reference only advances on
    # strictly greater, stream_calc_stats.js:348): clamping makes a stale tick
    # a harmless re-emission for the current window instead of state corruption.
    new_label = jnp.maximum(jnp.asarray(new_label, jnp.int32), state.latest_bucket)
    state = _advance(state, cfg, new_label)
    return window_stats(state, cfg), state


def quantize_half_up(x: jnp.ndarray, digits: int) -> jnp.ndarray:
    """Round to ``digits`` decimals, ties toward +inf — the wire rounding the

    reference applies via toFixed/parseFloat between pipeline stages
    (entries.js:72,117). NaN passes through."""
    scale = 10.0**digits
    return jnp.floor(x * scale + 0.5) / scale


def grow_state(state: StatsState, cfg: StatsConfig, new_capacity: int) -> Tuple[StatsState, StatsConfig]:
    """Re-allocate state for a larger row capacity (growth-by-recompile)."""
    S_old = state.counts.shape[0]
    if new_capacity < S_old:
        raise ValueError("cannot shrink")
    if new_capacity * cfg.num_buckets * (cfg.samples_per_bucket + 1) > np.iinfo(np.int32).max:
        raise ValueError(
            f"growing to {new_capacity} rows would overflow the int32 reservoir "
            f"dedupe-key space (~450k rows at stock window sizes); shard the "
            f"fleet across devices instead"
        )
    pad = new_capacity - S_old
    new_cfg = cfg._replace(capacity=new_capacity)
    return StatsState(
        latest_bucket=state.latest_bucket,
        counts=jnp.pad(state.counts, ((0, pad), (0, 0))),
        sums=jnp.pad(state.sums, ((0, pad), (0, 0))),
        samples=jnp.pad(state.samples, ((0, pad), (0, 0), (0, 0)), constant_values=jnp.nan),
        nsamples=jnp.pad(state.nsamples, ((0, pad), (0, 0))),
    ), new_cfg
