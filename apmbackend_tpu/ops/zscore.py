"""Device-resident smoothed z-score anomaly baselining (stream_calc_z_score rebuild).

The reference keeps, per (server, service, lag), three rolling JS arrays
(avg/p75/p95 histories) and on every StatEntry recomputes mean + population
std over the whole window, derives bounds avg ± threshold*std, emits a signal
in {-1, 0, +1}, and appends an influence-damped value
(stream_calc_z_score.js:66-104, 195-311). Here the state is a dense ring
``values [S, 3, L]`` and the whole key space steps in one fused XLA program.

Quirk parity (tested against the float64 host oracle in tests/):
- Warm-up gating is on *raw pushed length* (including NaN entries):
  ``prevValuesList.length >= lag`` (stream_calc_z_score.js:75) — modeled by a
  per-row ``fill`` counter; all 3 metric lists always share one length.
- mean/std skip NaN entries (util_methods.js:10-50); all-NaN -> undefined.
- zero variance -> std undefined -> no bounds, no signal
  (util_methods.js:44-48).
- signal iff |new - avg| > threshold*std strictly; NaN new value -> 0.
- influence damping applies only when a signal fired AND the most recently
  pushed value is non-NaN (stream_calc_z_score.js:96-97); the *damped* value
  is what enters the ring.
- stats are computed over the window BEFORE the shift+push.

The per-step cost depends on the variance mode:
- two-pass / one-pass: a masked reduction over the whole [S, 3, L] ring —
  bandwidth-bound; the exactness baseline.
- sliding (``ZScoreConfig.sliding``, the production default): O(S*3) per
  step. Per-row running aggregates (valid count, raw sum, anchored sum of
  squares) are maintained incrementally — the evicted value is read from
  the single ring slot being overwritten, the pushed value is added — so
  the step never reads the ring beyond two one-element-per-row gathers.
  The ring becomes write-mostly cold storage whose only remaining jobs are
  exact periodic rebuilds (every ``rebuild_every`` ticks, one fused pass,
  cancelling float drift) and snapshot/restore (the aggregate is DERIVED
  state: ``build_agg`` reconstructs it from the ring, so resume files keep
  their schema). The zero-variance quirk stays EXACT via a run-length
  counter: the window's valid entries are precisely the last ``cnt`` valid
  pushes, so "all window values equal" ⟺ "the maximal equal suffix of
  valid pushes covers them" (``run_len >= cnt``) — no min/max scan needed.
  f64 parity mode and robust (median/MAD) lags never take this branch.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

N_METRICS = 3  # average, per75, per95 (in that order on axis 1)


class ZScoreConfig(NamedTuple):
    capacity: int  # S
    lag: int  # L (window length in intervals)
    dtype: jnp.dtype = jnp.float32
    # robust mode (no reference equivalent): baseline = window median, spread
    # = 1.4826 * MAD instead of mean/std. The classic z-score's weakness is
    # self-contamination — past outliers inflate the window std and mask
    # later anomalies until they age out of the lag window; median/MAD has a
    # 50% breakdown point, so bounds stay tight through outlier bursts. Costs
    # two sorts over [S, 3, L] per step instead of one reduction.
    robust: bool = False
    # STORAGE dtype of the values ring; None = same as ``dtype``. The ring is
    # the engine's dominant HBM buffer ([S, 3, L]: ~850 MB/tick of read
    # traffic at 8192 rows x lag 8640 in f32), and the step is bandwidth-
    # bound — storing it bfloat16 halves that traffic while every statistic
    # still accumulates in ``dtype`` (values upcast in-register on load, the
    # standard TPU mixed-precision pattern). Costs ~0.4% relative rounding
    # on stored values; gating semantics (warm-up, NaN, zero-variance,
    # all-equal) are dtype-exact either way.
    ring_dtype: jnp.dtype = None
    # Variance in ONE ring pass instead of two: sum of (x - K)^2 rides the
    # same variadic reduce as count/sum/min/max with the per-row anchor K =
    # last pushed value, then var = E[(x-K)^2] - (mean-K)^2. The anchor sits
    # inside the window's range, so the shifted squares are small and the
    # cancellation benign (measured <= ~1e-5 relative var error in f32;
    # 1.36x on the CPU reduce, ~2x of HBM read traffic saved on TPU). The
    # degenerate all-equal guard stays EXACT (min == max), so the
    # zero-variance quirk cannot flip. Two-pass remains the exactness
    # baseline; f64 parity mode must keep it.
    onepass_var: bool = False
    # O(1)-per-step incremental window aggregates (module docstring). Takes
    # precedence over onepass_var; silently inert in f64 parity mode and for
    # robust lags (both need/keep the full-window computation).
    sliding: bool = False
    # exact full-ring rebuild cadence for the sliding aggregates (ticks);
    # bounds float drift AND the post-restore blind spot of the run-length
    # all-equal guard. Amortized cost = 1/rebuild_every of one ring pass.
    rebuild_every: int = 64

    @property
    def storage_dtype(self):
        return self.ring_dtype if self.ring_dtype is not None else self.dtype

    @property
    def sliding_active(self) -> bool:
        return bool(self.sliding) and self.dtype != jnp.float64 and not self.robust


class SlidingAgg(NamedTuple):
    """Incremental window aggregates for ``ZScoreConfig.sliding`` mode.

    Everything here is derived from the values ring (``build_agg``), so it is
    never serialized; restore rebuilds it. Invariants between rebuilds:
    ``cnt`` is the count of valid (non-NaN) window entries; ``vsum``/
    ``vsumsq`` are the sums of (x - anchor) and (x - anchor)^2 over them,
    with the per-row ``anchor`` frozen since the last rebuild (or the row's
    first value). ANCHORED moments keep every accumulated quantity at
    data-spread scale — mean = anchor + vsum/cnt, var = vsumsq/cnt -
    (vsum/cnt)^2 — so neither the raw-sum ulp loss (magnitude ~1e6 windows)
    nor the E[x^2] - mean^2 cancellation can poison f32 variance.
    ``run_len`` is a lower bound on the equal-suffix length of valid pushes
    that is tight whenever it matters (run_len >= cnt ⟺ window all-equal);
    ``last_valid`` is the most recent non-NaN pushed value (storage-rounded).
    """

    cnt: jnp.ndarray  # [S, 3] int32
    vsum: jnp.ndarray  # [S, 3] dtype
    vsumsq: jnp.ndarray  # [S, 3] dtype
    anchor: jnp.ndarray  # [S, 3] dtype
    run_len: jnp.ndarray  # [S, 3] int32
    last_valid: jnp.ndarray  # [S, 3] dtype (NaN = no valid push yet)
    # mirror of ring slot g-1 per row: the most recent push INCLUDING NaN
    # pushes (storage-rounded, so it equals the ring bits exactly). Lets the
    # core step obtain the damping reference without touching the ring — on
    # XLA:CPU any read of a donated buffer in the same program as its
    # in-place update forces a whole-buffer copy (measured 736 ms vs 0.6 ms
    # at [8192, 3, 8640]), so the staged path keeps the ring write in a
    # read-free program (ring_write) and everything else ring-free.
    # (The rebuild cadence is counted on the HOST — PipelineDriver/bench —
    # so no device-side clock leaf rides the donated step.)
    last_push: jnp.ndarray  # [S, 3] dtype (NaN = never pushed / NaN push)


class ZScoreState(NamedTuple):
    values: jnp.ndarray  # [S, 3, L] ring (NaN where never written)
    fill: jnp.ndarray  # [S] int32: list length (0..L)
    # GLOBAL write cursor (scalar): next slot every row writes. Per-row
    # cursors are unnecessary — active rows push every tick and activation
    # is permanent, so rows share one rotation; a scalar cursor turns the
    # ring write into a contiguous (in-place-aliasing) dynamic_update_slice
    pos: jnp.ndarray  # [] int32
    agg: Optional[SlidingAgg] = None  # present iff cfg.sliding_active


def _zero_agg(cfg: ZScoreConfig) -> SlidingAgg:
    S = cfg.capacity
    dt = cfg.dtype
    # distinct arrays per leaf: the engine tick donates its state, and three
    # leaves aliasing one zeros buffer is a double-donation runtime error
    return SlidingAgg(
        cnt=jnp.zeros((S, N_METRICS), jnp.int32),
        vsum=jnp.zeros((S, N_METRICS), dt),
        vsumsq=jnp.zeros((S, N_METRICS), dt),
        anchor=jnp.zeros((S, N_METRICS), dt),
        run_len=jnp.zeros((S, N_METRICS), jnp.int32),
        last_valid=jnp.full((S, N_METRICS), jnp.nan, dt),
        last_push=jnp.full((S, N_METRICS), jnp.nan, dt),
    )


def init_state(cfg: ZScoreConfig) -> ZScoreState:
    S, L = cfg.capacity, cfg.lag
    return ZScoreState(
        values=jnp.full((S, N_METRICS, L), jnp.nan, cfg.storage_dtype),
        fill=jnp.zeros((S,), jnp.int32),
        pos=jnp.zeros((), jnp.int32),
        agg=_zero_agg(cfg) if cfg.sliding_active else None,
    )


def build_agg(values: jnp.ndarray, cfg: ZScoreConfig, pos=None, anchor=None) -> SlidingAgg:
    """Exact SlidingAgg from a values ring (restore path / periodic rebuild).

    Without ``anchor``: two fused passes — the first finds the window mean
    to anchor around, the second takes the anchored sums (the restore path,
    which has no prior estimate). With ``anchor`` (a [S, 3] estimate, e.g.
    the incremental mean at rebuild time): ONE pass — any anchor inside the
    window's value range keeps the moment cancellation benign, so an
    estimate is as good as the exact mean and the rebuild halves its ring
    traffic. ``pos`` (the global cursor; 0 when omitted) locates slot g-1
    for the ``last_push`` mirror. ``run_len``/``last_valid`` are only
    recoverable for all-equal windows (min == max); other rows restart at 0,
    which is conservative — the guard can only under-detect until the row's
    pushes re-establish the run or the window truly becomes all-equal
    through >= cnt equal pushes (both exact going forward; module
    docstring)."""
    L = values.shape[-1]
    vals = values.astype(cfg.dtype) if values.dtype != cfg.dtype else values
    valid = ~jnp.isnan(vals)
    if anchor is None:
        cnt0, total0, _, _ = fused_window_partials(vals, valid)
        anchor = jnp.where(cnt0 > 0, total0 / jnp.maximum(cnt0, 1), 0)
    anchor = anchor.astype(cfg.dtype)
    cnt, total, sumsq, vmin, vmax = fused_window_partials_sq(vals, valid, anchor[..., None])
    all_eq = (cnt > 0) & (vmin == vmax)
    g = jnp.zeros((), jnp.int32) if pos is None else jnp.asarray(pos, jnp.int32)
    last_push = jax.lax.dynamic_slice_in_dim(vals, (g - 1) % L, 1, axis=2)[..., 0]
    return SlidingAgg(
        cnt=cnt.astype(jnp.int32),
        vsum=total.astype(cfg.dtype),
        vsumsq=sumsq.astype(cfg.dtype),
        anchor=anchor,
        run_len=jnp.where(all_eq, cnt, 0).astype(jnp.int32),
        last_valid=jnp.where(all_eq, vmax, jnp.nan).astype(cfg.dtype),
        last_push=last_push.astype(cfg.dtype),
    )


def normalize_legacy_ring(values_np, fill_np, pos_np, L: int):
    """Host-side migration of a PRE-global-cursor snapshot (per-row cursors,
    pos shape [S]): rotate each row so its next-write slot lands on the
    shared cursor 0. Window content and eviction order are rotation-
    invariant, so the migrated engine is bit-equivalent to the legacy
    layout. Returns the rotated [S, 3, L] numpy array; the caller sets the
    scalar cursor to 0. Shared by the npz load_resume and the orbax
    checkpoint restore so the migration math cannot drift."""
    import numpy as np

    w = np.where(
        fill_np >= L,
        pos_np.astype(np.int64),
        np.minimum(fill_np, L - 1).astype(np.int64),
    )
    j = (np.arange(L)[None, :] + w[:, None]) % L  # [S, L]
    return np.take_along_axis(values_np, j[:, None, :], axis=2)


def rebuild_agg_state(state: ZScoreState, cfg: ZScoreConfig) -> ZScoreState:
    """Amortized exact rebuild of the sliding aggregates — called from the
    HOST loop every ``cfg.rebuild_every`` ticks (pipeline.engine_rebuild_aggs;
    it cannot ride inside the jitted step, whose contract is to never touch
    the whole ring). Cancels float drift in the running sums, refreshes the
    variance anchor to the current mean, and repairs the run-length all-equal
    guard for rows whose constancy predates the aggregates (post-restore
    blind spot, module docstring). No-op for non-sliding configs."""
    if not cfg.sliding_active or state.agg is None:
        return state
    old = state.agg
    # the incremental mean is a perfectly good anchor (it only needs to sit
    # inside the window's value range) — passing it makes the rebuild ONE
    # ring pass instead of two
    anchor_est = jnp.where(
        old.cnt > 0, old.anchor + old.vsum / jnp.maximum(old.cnt, 1), old.anchor
    )
    fresh = build_agg(state.values, cfg, state.pos, anchor_est)
    # rows build_agg proves all-equal (min==max) take the repaired run;
    # everything else keeps the incrementally-exact counters
    proved = fresh.run_len > 0
    agg = fresh._replace(
        run_len=jnp.where(proved, fresh.run_len, old.run_len),
        last_valid=jnp.where(proved, fresh.last_valid, old.last_valid),
    )
    return state._replace(agg=agg)


def rebuild_chunk_rows(capacity: int, rebuild_every: int) -> int:
    """Row-chunk size of the STAGGERED rebuild schedule: the whole ring is
    re-aggregated once per ``rebuild_every`` ticks, one contiguous row chunk
    per tick, so the worst tick pays ~1/rebuild_every of the full pass
    instead of one tick absorbing it all (the monolithic rebuild_agg_state
    stalled a tick for seconds at pod shapes). Every row's rebuild interval
    stays <= rebuild_every ticks — the drift/blind-spot bound is unchanged."""
    return max(1, -(-capacity // max(rebuild_every, 1)))


def build_agg_slice_partials(state: ZScoreState, cfg: ZScoreConfig, row_start, chunk: int):
    """Fresh anchored window moments for ring rows [row_start, row_start+chunk)
    — the in-program (XLA) partial producer of the staggered rebuild. Returns
    ``(cnt, vsum, vsumsq, anchor, vmin, vmax, last_push)``, each [chunk, 3].
    Per-row math is identical to build_agg's single-anchor pass (rows are
    independent under the last-axis reduce), so applying every chunk of a
    cycle reproduces rebuild_agg_state BITWISE. ``chunk`` is static;
    ``row_start`` is traced (one compiled program serves the whole rotation).
    """
    old = state.agg
    vals = jax.lax.dynamic_slice_in_dim(state.values, row_start, chunk, axis=0)
    if vals.dtype != cfg.dtype:
        vals = vals.astype(cfg.dtype)
    sl = lambda a: jax.lax.dynamic_slice_in_dim(a, row_start, chunk, axis=0)
    # the incremental mean is a valid variance anchor (rebuild_agg_state)
    cnt_o, vsum_o, anchor_o = sl(old.cnt), sl(old.vsum), sl(old.anchor)
    anchor = jnp.where(
        cnt_o > 0, anchor_o + vsum_o / jnp.maximum(cnt_o, 1), anchor_o
    ).astype(cfg.dtype)
    valid = ~jnp.isnan(vals)
    cnt, vsum, vsumsq, vmin, vmax = fused_window_partials_sq(vals, valid, anchor[..., None])
    L = state.values.shape[-1]
    g = jnp.asarray(state.pos, jnp.int32)
    last_push = jax.lax.dynamic_slice_in_dim(vals, (g - 1) % L, 1, axis=2)[..., 0]
    return (cnt.astype(jnp.int32), vsum.astype(cfg.dtype), vsumsq.astype(cfg.dtype),
            anchor, vmin, vmax, last_push.astype(cfg.dtype))


def merge_agg_slice(
    agg: SlidingAgg, cfg: ZScoreConfig, row_start,
    cnt, vsum, vsumsq, anchor, vmin, vmax, last_push,
) -> SlidingAgg:
    """Fold freshly-rebuilt chunk partials (either producer: the XLA slice
    pass above or the native streaming kernel) back into the full [S, 3]
    aggregates. ONE merge implementation so the two producers cannot drift:
    the all-equal proof (min == max) repairs run_len/last_valid exactly as
    rebuild_agg_state does; unproved rows keep their incrementally-exact
    counters. All leaves are [S, 3] — the DUS writes are noise next to the
    ring pass they retire."""
    dt = cfg.dtype
    all_eq = (cnt > 0) & (vmin == vmax)
    sl = lambda a: jax.lax.dynamic_slice_in_dim(a, row_start, cnt.shape[0], axis=0)
    run_len = jnp.where(all_eq, cnt, sl(agg.run_len)).astype(jnp.int32)
    last_valid = jnp.where(all_eq, vmax, sl(agg.last_valid)).astype(dt)
    up = lambda full, part: jax.lax.dynamic_update_slice_in_dim(
        full, part.astype(full.dtype), row_start, axis=0
    )
    return SlidingAgg(
        cnt=up(agg.cnt, cnt),
        vsum=up(agg.vsum, vsum),
        vsumsq=up(agg.vsumsq, vsumsq),
        anchor=up(agg.anchor, anchor),
        run_len=up(agg.run_len, run_len),
        last_valid=up(agg.last_valid, last_valid),
        last_push=up(agg.last_push, last_push),
    )


def rebuild_agg_slice(state: ZScoreState, cfg: ZScoreConfig, row_start, chunk: int) -> ZScoreState:
    """One staggered-rebuild step: exact re-aggregation of ring rows
    [row_start, row_start+chunk) only (rebuild_chunk_rows sizes the chunk so
    a full rotation spans cfg.rebuild_every ticks). The host loop clamps
    row_start to capacity-chunk, so when chunk does not divide capacity the
    tail chunk overlaps a few already-rebuilt rows — exact but not bitwise
    for those rows (their second rebuild derives its anchor from the
    just-refreshed aggregates). When chunk divides capacity, applying all
    chunks back-to-back is BITWISE rebuild_agg_state; ragged capacities are
    value-exact (both tested). No-op for non-sliding configs."""
    if not cfg.sliding_active or state.agg is None:
        return state
    parts = build_agg_slice_partials(state, cfg, row_start, chunk)
    return state._replace(agg=merge_agg_slice(state.agg, cfg, row_start, *parts))


def _fused_reduce(vals: jnp.ndarray, valid: jnp.ndarray, anchor=None):
    """ONE variadic lax.reduce over the last axis. Without ``anchor``:
    (count, raw sum, min, max). With ``anchor``: (count, shifted sum,
    shifted sumsq, min, max) — BOTH moments are taken around the per-row
    anchor, so every accumulated quantity lives at data-SPREAD scale: a raw
    f32 sum of lag-8640 windows at magnitude ~1e6 carries ~0.5 of ulp error,
    which poisons mean (and then variance) exactly where variance is small;
    the shifted sum is ~0 +- spread and stays exact. The single builder
    serves the two-pass, one-pass and sliding paths so their masking/init
    semantics cannot drift."""
    dt = vals.dtype
    if anchor is None:
        operands = [valid.astype(jnp.int32), jnp.where(valid, vals, 0)]
        inits = [jnp.int32(0), jnp.array(0, dt)]
    else:
        sh = jnp.where(valid, vals - anchor, 0)
        operands = [valid.astype(jnp.int32), sh, sh * sh]
        inits = [jnp.int32(0), jnp.array(0, dt), jnp.array(0, dt)]
    operands += [jnp.where(valid, vals, jnp.inf), jnp.where(valid, vals, -jnp.inf)]
    inits += [jnp.array(jnp.inf, dt), jnp.array(-jnp.inf, dt)]
    n_sum = len(inits) - 2

    def combine(a, b):
        out = tuple(a[i] + b[i] for i in range(n_sum))
        return out + (jnp.minimum(a[n_sum], b[n_sum]), jnp.maximum(a[n_sum + 1], b[n_sum + 1]))

    return jax.lax.reduce(tuple(operands), tuple(inits), combine, [vals.ndim - 1])


def fused_window_partials(vals: jnp.ndarray, valid: jnp.ndarray):
    """(count, sum, min, max) in one pass (3.2x measured vs four passes on
    the bandwidth-bound CPU path). Shared by the single-chip step and the
    window-sharded local step so the two paths cannot drift."""
    return _fused_reduce(vals, valid)


def fused_window_partials_sq(vals: jnp.ndarray, valid: jnp.ndarray, anchor: jnp.ndarray):
    """(count, shifted-sum, shifted-sumsq, min, max) in ONE pass — the
    anchored-moments variant (one-pass variance and the sliding rebuild):
    ``anchor`` is a per-row ``[..., 1]``-broadcastable constant BOTH moments
    are taken around; mean = anchor + ssum/cnt, var = ssumsq/cnt -
    (ssum/cnt)^2."""
    return _fused_reduce(vals, valid, anchor)


def _median_from_sorted(s: jnp.ndarray, cnt: jnp.ndarray) -> jnp.ndarray:
    """NaN-aware median over the last axis of an ascending-sorted array (NaN
    tail) with ``cnt`` valid entries per row; NaN where cnt == 0."""
    K = s.shape[-1]
    i1 = jnp.clip((cnt - 1) // 2, 0, K - 1)
    i2 = jnp.clip(cnt // 2, 0, K - 1)
    v1 = jnp.take_along_axis(s, i1[..., None], axis=-1)[..., 0]
    v2 = jnp.take_along_axis(s, i2[..., None], axis=-1)[..., 0]
    return jnp.where(cnt > 0, (v1 + v2) / 2, jnp.nan)


# MAD -> sigma consistency constant for normal data (1 / Phi^-1(3/4)): with
# it the robust bounds coincide with the classic ones on clean gaussian
# windows, so a per-lag THRESHOLD keeps one meaning across both modes
MAD_SIGMA = 1.4826


class ZScoreResult(NamedTuple):
    # each [S, 3] on the metric axis (average, per75, per95)
    window_avg: jnp.ndarray  # NaN = undefined
    lower_bound: jnp.ndarray
    upper_bound: jnp.ndarray
    signal: jnp.ndarray  # int32 in {-1, 0, 1}


def _emit_and_damp(
    cfg, mean, std, has_std, new_values, threshold, influence, last_val, fill
):
    """The parity-critical gating tail shared by every single-chip mode
    (sliding step_core and the full-window step): bounds, strict-exceed
    signal, and influence damping. ONE source of truth so the modes cannot
    desynchronize. Returns (ZScoreResult, pushed [S, 3] in cfg.dtype).
    (window_sharded._local_step keeps its own copy: its last-value NaNness
    arrives as a separate psum'd flag, not as NaN in last_val.)"""
    thr = threshold[:, None]
    lb = jnp.where(has_std, mean - thr * std, jnp.nan)
    ub = jnp.where(has_std, mean + thr * std, jnp.nan)
    new_ok = ~jnp.isnan(new_values)
    exceeds = has_std & new_ok & (jnp.abs(new_values - mean) > thr * std)
    signal = jnp.where(exceeds, jnp.where(new_values > mean, 1, -1), 0).astype(jnp.int32)
    # influence damping: only on signal and when the most recent push is
    # defined (NaN last_val == never pushed or NaN push)
    can_damp = exceeds & ~jnp.isnan(last_val) & (fill > 0)[:, None]
    infl = influence[:, None]
    pushed = jnp.where(can_damp, infl * new_values + (1 - infl) * last_val, new_values)
    result = ZScoreResult(
        window_avg=mean.astype(cfg.dtype),
        lower_bound=lb.astype(cfg.dtype),
        upper_bound=ub.astype(cfg.dtype),
        signal=signal,
    )
    return result, pushed


def ring_evict_read(values: jnp.ndarray, pos) -> jnp.ndarray:
    """[S, 3] content of the slot the next push will overwrite (the oldest
    entry; NaN where nothing was evicted). MUST be dispatched in a program
    that does not also write the ring (module staging contract)."""
    return jax.lax.dynamic_slice_in_dim(values, pos, 1, axis=2)[..., 0]


def ring_write(values: jnp.ndarray, pushed: jnp.ndarray, pos) -> jnp.ndarray:
    """Store this tick's [S, 3] pushes at the global cursor. The ONLY ring
    op in its program: one contiguous dynamic_update_slice with no reads, so
    a donated call updates the [S, 3, L] buffer in place (0.6 ms vs 736 ms
    with any same-program read at [8192, 3, 8640] on XLA:CPU)."""
    return jax.lax.dynamic_update_slice_in_dim(
        values, pushed[:, :, None].astype(values.dtype), pos, axis=2
    )


def step_core(
    state: ZScoreState,
    cfg: ZScoreConfig,
    new_values: jnp.ndarray,  # [S, 3]
    threshold: jnp.ndarray,  # [S]
    influence: jnp.ndarray,  # [S]
    active: jnp.ndarray,  # [S] bool
    evicted: jnp.ndarray,  # [S, 3] from ring_evict_read (storage dtype)
) -> Tuple[ZScoreResult, ZScoreState, jnp.ndarray]:
    """The ring-free sliding step: window statistics, signal, damping and
    the incremental aggregate update, all from [S, 3] state. Returns
    (result, state-with-UNTOUCHED-ring, pushed) — the caller owes a
    ring_write(state.values, pushed, old pos) to complete the tick. step()
    composes the three pieces into one program (shard_map use); staged hosts
    dispatch them separately so the ring write stays in-place (module
    docstring)."""
    assert cfg.sliding_active, "step_core is the sliding-mode path"
    S, L = cfg.capacity, cfg.lag
    agg = state.agg
    fill = state.fill
    full = fill >= L  # [S] — signal eligibility (raw length incl. NaN pushes)
    g = state.pos  # [] int32: this tick's write slot

    # O(1) window statistics straight from the running ANCHORED moments:
    # mean = anchor + E[x - K], var = E[(x-K)^2] - E[x-K]^2 — everything
    # accumulates at data-spread scale (SlidingAgg docstring)
    cnt = agg.cnt  # [S, 3]
    has_avg = (cnt > 0) & full[:, None]
    mdelta = agg.vsum / jnp.maximum(cnt, 1)
    mean_raw = agg.anchor + mdelta
    # the EXACT zero-variance guard: window all-equal ⟺ the equal suffix
    # of valid pushes covers every valid entry
    all_equal = has_avg & (agg.run_len >= cnt)
    mean = jnp.where(all_equal, agg.last_valid, jnp.where(has_avg, mean_raw, jnp.nan))
    var = agg.vsumsq / jnp.maximum(cnt, 1) - mdelta**2
    var = jnp.where(has_avg, jnp.maximum(var, 0), jnp.nan)
    has_std = has_avg & ~all_equal & (var > 0)
    std = jnp.where(has_std, jnp.sqrt(var), jnp.nan)

    # agg.last_push mirrors ring slot g-1 exactly — no ring read needed
    result, pushed_f = _emit_and_damp(
        cfg, mean, std, has_std, new_values, threshold, influence,
        agg.last_push, fill,
    )
    # inactive rows push NaN: their ring is all-NaN (activation is permanent
    # and history starts at registration), so NaN keeps the slot's content —
    # without the read-back the old scatter path needed
    pushed = jnp.where(active[:, None], pushed_f, jnp.nan)
    # what the ring will actually hold (storage-rounded, e.g. bf16): the
    # aggregates must ingest these exact bits or the periodic rebuild from
    # the ring would disagree with the incremental sums
    v_new = pushed.astype(cfg.storage_dtype).astype(cfg.dtype)
    w_old = evicted.astype(cfg.dtype) if evicted.dtype != cfg.dtype else evicted

    add = ~jnp.isnan(v_new)  # NaN == inactive or NaN push: no aggregate entry
    sub = ~jnp.isnan(w_old)
    # a row's FIRST value becomes its variance anchor: re-anchoring is only
    # legal while the window holds no valid entries (cnt == 0, the anchored
    # sums are empty — there is nothing accumulated under the old anchor to
    # go stale), and there it is exact. Every row then carries a data-scale
    # anchor even on hosts that never call rebuild_agg_state, so the
    # catastrophic E[x^2] - mean^2 cancellation (anchor 0 on large-magnitude
    # rows) cannot occur. BOTH deltas below must use the post-re-anchor
    # value: the first push contributes (v0 - v0)^2 = 0, and no eviction can
    # coincide with cnt == 0 (an all-invalid window evicts only NaN).
    # Periodic rebuilds still re-tighten the anchor to the window mean.
    anchor2 = jnp.where((cnt == 0) & add, v_new, agg.anchor)
    cnt2 = cnt + add.astype(jnp.int32) - sub.astype(jnp.int32)
    da = jnp.where(add, v_new - anchor2, 0)
    db = jnp.where(sub, w_old - anchor2, 0)
    vsum2 = agg.vsum + da - db
    vsumsq2 = agg.vsumsq + da * da - db * db
    # a drained window (cnt back to 0) zeroes its sums EXACTLY: add/sub
    # round-trips can leave ulp-scale residue that would otherwise seed the
    # next fill-up (and the cnt==0 re-anchor assumes empty sums)
    empty = cnt2 == 0
    vsum2 = jnp.where(empty, 0, vsum2)
    vsumsq2 = jnp.where(empty, 0, vsumsq2)
    run2 = jnp.where(
        add,
        jnp.where(v_new == agg.last_valid, jnp.minimum(agg.run_len + 1, L + 1), 1),
        agg.run_len,
    )
    lastv2 = jnp.where(add, v_new, agg.last_valid)
    lastp2 = jnp.where(active[:, None], v_new, agg.last_push)
    # exact periodic rebuild cadence is counted by the HOST loop
    # (rebuild_agg_state cannot ride in-program — holding the ring in an
    # unexecuted cond branch forces a whole-ring copy on CPU)
    new_agg = SlidingAgg(cnt2, vsum2, vsumsq2, anchor2, run2, lastv2, lastp2)

    new_fill = jnp.where(active, jnp.minimum(fill + 1, L), fill)
    new_state = ZScoreState(state.values, new_fill, (g + 1) % L, new_agg)
    return result, new_state, pushed


def step(
    state: ZScoreState,
    cfg: ZScoreConfig,
    new_values: jnp.ndarray,  # [S, 3]: this tick's average/per75/per95 per row
    threshold: jnp.ndarray,  # [S]
    influence: jnp.ndarray,  # [S]
    active=None,  # [S] bool: rows that exist in the registry (None = all)
) -> Tuple[ZScoreResult, ZScoreState]:
    """``active`` gates the warm-up: the reference creates a key's rolling
    lists at the key's FIRST StatEntry, so a service first seen mid-run waits
    a full lag window before signalling. Without the mask every dense row
    accrues ``fill`` from engine start and a late-registered service would
    open its warm-up gate up to ``lag`` ticks early (z-score bounds over a
    near-empty window — false alerts on fresh deploys)."""
    S, L = cfg.capacity, cfg.lag
    if active is None:
        active = jnp.ones((S,), bool)
    raw = state.values  # [S, 3, L] in storage dtype (possibly bf16)

    if cfg.sliding_active:
        # single-program composition (shard_map / tests). NOTE on XLA:CPU
        # this pays one ring copy because the program both reads (evict)
        # and writes the ring; latency-critical hosts dispatch the three
        # pieces separately instead (pipeline.make_engine_step).
        g = state.pos
        evicted = ring_evict_read(raw, g)
        result, new_state, pushed = step_core(
            state, cfg, new_values, threshold, influence, active, evicted
        )
        return result, new_state._replace(values=ring_write(raw, pushed, g))

    # ---- full-window modes (two-pass / one-pass / robust) ----------------
    # upcast on load: XLA reads the narrow ring from HBM and converts
    # in-register, so all statistics below accumulate in cfg.dtype
    vals = raw.astype(cfg.dtype) if raw.dtype != cfg.dtype else raw
    fill = state.fill  # [S]
    full = fill >= L  # [S] — signal eligibility (raw length incl. NaN pushes)

    # last pushed value: needed by influence damping, and (one-pass mode) as
    # the variance anchor. The cursor is GLOBAL (scalar): every row writes
    # the same slot each tick (active rows push, inactive rows keep NaN), so
    # "the row's newest entry" is slot g-1 for every row — a contiguous
    # dynamic_slice, not a per-row gather.
    g = state.pos  # [] int32: this tick's write slot
    last_idx = (g - 1) % L
    last_val = jax.lax.dynamic_slice_in_dim(vals, last_idx, 1, axis=2)[..., 0]  # [S, 3]

    valid = ~jnp.isnan(vals)  # [S, 3, L]
    if cfg.robust:
        # median/MAD baseline: same gating quirks as the classic mode (warm-up
        # on raw fill, zero spread -> no signal, NaN new value -> no signal)
        cnt = jnp.sum(valid.astype(jnp.int32), axis=-1)  # [S, 3]
        has_avg = (cnt > 0) & full[:, None]
        mean = jnp.where(has_avg, _median_from_sorted(jnp.sort(vals, axis=-1), cnt), jnp.nan)
        dev = jnp.where(valid, jnp.abs(vals - mean[..., None]), jnp.nan)
        mad = _median_from_sorted(jnp.sort(dev, axis=-1), cnt)
        has_std = has_avg & (mad > 0)  # MAD==0 == the zero-variance quirk
        std = jnp.where(has_std, MAD_SIGMA * mad, jnp.nan)
    elif cfg.onepass_var and cfg.dtype != jnp.float64:
        # single ring pass: shifted sumsq rides the fused reduce. The anchor
        # must sit inside the window's value range for the
        # E[(x-K)^2] - (mean-K)^2 cancellation to stay benign, INCLUDING
        # right after a data gap (a NaN push makes last_val NaN — a bare
        # 0 fallback there reintroduces the catastrophic E[x^2] - mean^2
        # cancellation for large-magnitude rows). So the anchor is the
        # nanmean of last_val plus 8 strided ring slots: a [S, 3, 8] gather,
        # negligible next to the [S, 3, L] pass it protects. All-NaN
        # candidates (=> near-empty window) fall back to 0. f64 parity mode
        # never takes this branch.
        stride_idx = jnp.arange(8, dtype=jnp.int32) * max(L // 8, 1) % L  # [8]
        samples = vals[:, :, stride_idx]  # [S, 3, 8]
        cand = jnp.concatenate([samples, last_val[..., None]], axis=-1)
        cand_ok = ~jnp.isnan(cand)
        n_cand = jnp.sum(cand_ok, axis=-1)
        anchor = jnp.where(
            n_cand > 0,
            jnp.sum(jnp.where(cand_ok, cand, 0), axis=-1) / jnp.maximum(n_cand, 1),
            0,
        )[..., None]
        cnt, ssum, sumsq, vmin, vmax = fused_window_partials_sq(vals, valid, anchor)
        has_avg = (cnt > 0) & full[:, None]
        # anchored moments throughout: mean = K + E[x-K], var = E[(x-K)^2]
        # - E[x-K]^2 — no raw 1e6-scale sum ever accumulates
        mdelta = ssum / jnp.maximum(cnt, 1)
        mean = jnp.where(has_avg, anchor[..., 0] + mdelta, jnp.nan)
        # the all-equal guard stays EXACT (min == max): the zero-variance
        # quirk cannot flip on float noise in this mode either
        all_equal = has_avg & (vmax == vmin)
        mean = jnp.where(all_equal, vmax, mean)
        var = sumsq / jnp.maximum(cnt, 1) - mdelta**2
        var = jnp.where(has_avg, jnp.maximum(var, 0), jnp.nan)
        has_std = has_avg & ~all_equal & (var > 0)
        std = jnp.where(has_std, jnp.sqrt(var), jnp.nan)
    else:
        cnt, total, vmin, vmax = fused_window_partials(vals, valid)
        has_avg = (cnt > 0) & full[:, None]
        mean = jnp.where(has_avg, total / jnp.maximum(cnt, 1), jnp.nan)

        # Degenerate (all-equal) windows are resolved EXACTLY, not by float
        # luck: whether sum(x*k)/k reproduces x depends on the value and the
        # summation order (the reference's linear JS reduce and XLA's tree
        # reduction can disagree), which would turn "zero variance -> no
        # signal" (util_methods.js:44-48, the documented intent) into a coin
        # flip with std ~ 1e-13 signalling on any deviation. max==min is
        # order-independent.
        all_equal = has_avg & (vmax == vmin)
        mean = jnp.where(all_equal, vmax, mean)

        diff = jnp.where(valid, vals - mean[..., None], 0)
        var = jnp.where(has_avg, jnp.sum(diff * diff, axis=-1) / jnp.maximum(cnt, 1), jnp.nan)
        has_std = has_avg & ~all_equal & (var > 0)  # var==0 -> std undefined (the quirk)
        std = jnp.where(has_std, jnp.sqrt(var), jnp.nan)

    result, pushed = _emit_and_damp(
        cfg, mean, std, has_std, new_values, threshold, influence, last_val, fill
    )

    # shift-at-lag semantics with a GLOBAL cursor: every row writes slot g
    # this tick — active rows push; inactive rows (not yet in the registry)
    # push NaN, which preserves their all-NaN history (it starts at
    # registration, like the reference's per-key list creation). Because
    # active rows push EVERY tick and activation is permanent, a young row's
    # entries are simply the trailing slots behind the cursor and the window
    # content is identical to a per-row-cursor layout; window statistics
    # never depended on slot order. The write is ring_write's contiguous
    # dynamic_update_slice — the aliasing-friendly op — instead of a per-row
    # scatter, which XLA:CPU turns into a full ring copy even under donation
    # (measured 599 ms vs 0.6 ms per step at [8192, 3, 8640]).
    pushed_eff = jnp.where(active[:, None], pushed, jnp.nan)
    new_vals = ring_write(raw, pushed_eff, g)
    new_fill = jnp.where(active, jnp.minimum(fill + 1, L), fill)
    new_pos = (g + 1) % L
    return result, ZScoreState(new_vals, new_fill, new_pos, None)


def grow_state(state: ZScoreState, cfg: ZScoreConfig, new_capacity: int) -> Tuple[ZScoreState, ZScoreConfig]:
    S_old = state.fill.shape[0]
    if new_capacity < S_old:
        raise ValueError("cannot shrink")
    pad = new_capacity - S_old
    new_cfg = cfg._replace(capacity=new_capacity)
    agg = state.agg
    if agg is not None:
        row_pad = ((0, pad), (0, 0))
        agg = SlidingAgg(
            cnt=jnp.pad(agg.cnt, row_pad),
            vsum=jnp.pad(agg.vsum, row_pad),
            vsumsq=jnp.pad(agg.vsumsq, row_pad),
            anchor=jnp.pad(agg.anchor, row_pad),
            run_len=jnp.pad(agg.run_len, row_pad),
            last_valid=jnp.pad(agg.last_valid, row_pad, constant_values=jnp.nan),
            last_push=jnp.pad(agg.last_push, row_pad, constant_values=jnp.nan),
        )
    return ZScoreState(
        values=jnp.pad(state.values, ((0, pad), (0, 0), (0, 0)), constant_values=jnp.nan),
        fill=jnp.pad(state.fill, (0, pad)),
        pos=state.pos,  # global cursor: new rows join the shared rotation
        agg=agg,
    ), new_cfg
