"""Device-resident smoothed z-score anomaly baselining (stream_calc_z_score rebuild).

The reference keeps, per (server, service, lag), three rolling JS arrays
(avg/p75/p95 histories) and on every StatEntry recomputes mean + population
std over the whole window, derives bounds avg ± threshold*std, emits a signal
in {-1, 0, +1}, and appends an influence-damped value
(stream_calc_z_score.js:66-104, 195-311). Here the state is a dense ring
``values [S, 3, L]`` and the whole key space steps in one fused XLA program.

Quirk parity (tested against the float64 host oracle in tests/):
- Warm-up gating is on *raw pushed length* (including NaN entries):
  ``prevValuesList.length >= lag`` (stream_calc_z_score.js:75) — modeled by a
  per-row ``fill`` counter; all 3 metric lists always share one length.
- mean/std skip NaN entries (util_methods.js:10-50); all-NaN -> undefined.
- zero variance -> std undefined -> no bounds, no signal
  (util_methods.js:44-48).
- signal iff |new - avg| > threshold*std strictly; NaN new value -> 0.
- influence damping applies only when a signal fired AND the most recently
  pushed value is non-NaN (stream_calc_z_score.js:96-97); the *damped* value
  is what enters the ring.
- stats are computed over the window BEFORE the shift+push.

The per-step cost is a masked reduction over [S, 3, L] — bandwidth-bound and
embarrassingly parallel, exactly what the VPU + HBM pipeline wants; at stock
shapes one step is far under the 10 s cadence, and throughput is benchmarked
in metrics/sec (bench.py). An O(1) incremental running-sum variant is a
planned optimization; the full reduction is the exactness baseline.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

N_METRICS = 3  # average, per75, per95 (in that order on axis 1)


class ZScoreConfig(NamedTuple):
    capacity: int  # S
    lag: int  # L (window length in intervals)
    dtype: jnp.dtype = jnp.float32
    # robust mode (no reference equivalent): baseline = window median, spread
    # = 1.4826 * MAD instead of mean/std. The classic z-score's weakness is
    # self-contamination — past outliers inflate the window std and mask
    # later anomalies until they age out of the lag window; median/MAD has a
    # 50% breakdown point, so bounds stay tight through outlier bursts. Costs
    # two sorts over [S, 3, L] per step instead of one reduction.
    robust: bool = False
    # STORAGE dtype of the values ring; None = same as ``dtype``. The ring is
    # the engine's dominant HBM buffer ([S, 3, L]: ~850 MB/tick of read
    # traffic at 8192 rows x lag 8640 in f32), and the step is bandwidth-
    # bound — storing it bfloat16 halves that traffic while every statistic
    # still accumulates in ``dtype`` (values upcast in-register on load, the
    # standard TPU mixed-precision pattern). Costs ~0.4% relative rounding
    # on stored values; gating semantics (warm-up, NaN, zero-variance,
    # all-equal) are dtype-exact either way.
    ring_dtype: jnp.dtype = None
    # Variance in ONE ring pass instead of two: sum of (x - K)^2 rides the
    # same variadic reduce as count/sum/min/max with the per-row anchor K =
    # last pushed value, then var = E[(x-K)^2] - (mean-K)^2. The anchor sits
    # inside the window's range, so the shifted squares are small and the
    # cancellation benign (measured <= ~1e-5 relative var error in f32;
    # 1.36x on the CPU reduce, ~2x of HBM read traffic saved on TPU). The
    # degenerate all-equal guard stays EXACT (min == max), so the
    # zero-variance quirk cannot flip. Two-pass remains the exactness
    # baseline; f64 parity mode must keep it.
    onepass_var: bool = False

    @property
    def storage_dtype(self):
        return self.ring_dtype if self.ring_dtype is not None else self.dtype


class ZScoreState(NamedTuple):
    values: jnp.ndarray  # [S, 3, L] ring (NaN where never written)
    fill: jnp.ndarray  # [S] int32: list length (0..L)
    pos: jnp.ndarray  # [S] int32: next write slot once full


def init_state(cfg: ZScoreConfig) -> ZScoreState:
    S, L = cfg.capacity, cfg.lag
    return ZScoreState(
        values=jnp.full((S, N_METRICS, L), jnp.nan, cfg.storage_dtype),
        fill=jnp.zeros((S,), jnp.int32),
        pos=jnp.zeros((S,), jnp.int32),
    )


def _fused_reduce(vals: jnp.ndarray, valid: jnp.ndarray, anchor=None):
    """ONE variadic lax.reduce over the last axis: (count, sum[, shifted
    sumsq], min, max). The single builder serves both the two-pass and the
    one-pass (``anchor`` given) paths so their masking/init semantics cannot
    drift."""
    dt = vals.dtype
    operands = [
        valid.astype(jnp.int32),
        jnp.where(valid, vals, 0),
    ]
    inits = [jnp.int32(0), jnp.array(0, dt)]
    if anchor is not None:
        sh = jnp.where(valid, vals - anchor, 0)
        operands.append(sh * sh)
        inits.append(jnp.array(0, dt))
    operands += [jnp.where(valid, vals, jnp.inf), jnp.where(valid, vals, -jnp.inf)]
    inits += [jnp.array(jnp.inf, dt), jnp.array(-jnp.inf, dt)]
    n_sum = len(inits) - 2

    def combine(a, b):
        out = tuple(a[i] + b[i] for i in range(n_sum))
        return out + (jnp.minimum(a[n_sum], b[n_sum]), jnp.maximum(a[n_sum + 1], b[n_sum + 1]))

    return jax.lax.reduce(tuple(operands), tuple(inits), combine, [vals.ndim - 1])


def fused_window_partials(vals: jnp.ndarray, valid: jnp.ndarray):
    """(count, sum, min, max) in one pass (3.2x measured vs four passes on
    the bandwidth-bound CPU path). Shared by the single-chip step and the
    window-sharded local step so the two paths cannot drift."""
    return _fused_reduce(vals, valid)


def fused_window_partials_sq(vals: jnp.ndarray, valid: jnp.ndarray, anchor: jnp.ndarray):
    """(count, sum, shifted-sumsq, min, max) in ONE pass — the one-pass
    variance variant (ZScoreConfig.onepass_var): ``anchor`` is a per-row
    ``[..., 1]``-broadcastable constant the squares are taken around."""
    return _fused_reduce(vals, valid, anchor)


def _median_from_sorted(s: jnp.ndarray, cnt: jnp.ndarray) -> jnp.ndarray:
    """NaN-aware median over the last axis of an ascending-sorted array (NaN
    tail) with ``cnt`` valid entries per row; NaN where cnt == 0."""
    K = s.shape[-1]
    i1 = jnp.clip((cnt - 1) // 2, 0, K - 1)
    i2 = jnp.clip(cnt // 2, 0, K - 1)
    v1 = jnp.take_along_axis(s, i1[..., None], axis=-1)[..., 0]
    v2 = jnp.take_along_axis(s, i2[..., None], axis=-1)[..., 0]
    return jnp.where(cnt > 0, (v1 + v2) / 2, jnp.nan)


# MAD -> sigma consistency constant for normal data (1 / Phi^-1(3/4)): with
# it the robust bounds coincide with the classic ones on clean gaussian
# windows, so a per-lag THRESHOLD keeps one meaning across both modes
MAD_SIGMA = 1.4826


class ZScoreResult(NamedTuple):
    # each [S, 3] on the metric axis (average, per75, per95)
    window_avg: jnp.ndarray  # NaN = undefined
    lower_bound: jnp.ndarray
    upper_bound: jnp.ndarray
    signal: jnp.ndarray  # int32 in {-1, 0, 1}


def step(
    state: ZScoreState,
    cfg: ZScoreConfig,
    new_values: jnp.ndarray,  # [S, 3]: this tick's average/per75/per95 per row
    threshold: jnp.ndarray,  # [S]
    influence: jnp.ndarray,  # [S]
    active=None,  # [S] bool: rows that exist in the registry (None = all)
) -> Tuple[ZScoreResult, ZScoreState]:
    """``active`` gates the warm-up: the reference creates a key's rolling
    lists at the key's FIRST StatEntry, so a service first seen mid-run waits
    a full lag window before signalling. Without the mask every dense row
    accrues ``fill`` from engine start and a late-registered service would
    open its warm-up gate up to ``lag`` ticks early (z-score bounds over a
    near-empty window — false alerts on fresh deploys)."""
    S, L = cfg.capacity, cfg.lag
    if active is None:
        active = jnp.ones((S,), bool)
    raw = state.values  # [S, 3, L] in storage dtype (possibly bf16)
    # upcast on load: XLA reads the narrow ring from HBM and converts
    # in-register, so all statistics below accumulate in cfg.dtype
    vals = raw.astype(cfg.dtype) if raw.dtype != cfg.dtype else raw
    fill = state.fill  # [S]
    full = fill >= L  # [S] — signal eligibility (raw length incl. NaN pushes)

    # last pushed value: needed by influence damping, and (one-pass mode) as
    # the variance anchor — gathered once, before the window reduce
    last_idx = jnp.where(full, (state.pos - 1) % L, jnp.maximum(fill - 1, 0))  # [S]
    last_val = jnp.take_along_axis(
        vals, last_idx[:, None, None].repeat(N_METRICS, 1), axis=-1
    )[..., 0]  # [S, 3]

    valid = ~jnp.isnan(vals)  # [S, 3, L]
    if cfg.robust:
        # median/MAD baseline: same gating quirks as the classic mode (warm-up
        # on raw fill, zero spread -> no signal, NaN new value -> no signal)
        cnt = jnp.sum(valid.astype(jnp.int32), axis=-1)  # [S, 3]
        has_avg = (cnt > 0) & full[:, None]
        mean = jnp.where(has_avg, _median_from_sorted(jnp.sort(vals, axis=-1), cnt), jnp.nan)
        dev = jnp.where(valid, jnp.abs(vals - mean[..., None]), jnp.nan)
        mad = _median_from_sorted(jnp.sort(dev, axis=-1), cnt)
        has_std = has_avg & (mad > 0)  # MAD==0 == the zero-variance quirk
        std = jnp.where(has_std, MAD_SIGMA * mad, jnp.nan)
    elif cfg.onepass_var and cfg.dtype != jnp.float64:
        # single ring pass: shifted sumsq rides the fused reduce. The anchor
        # must sit inside the window's value range for the
        # E[(x-K)^2] - (mean-K)^2 cancellation to stay benign, INCLUDING
        # right after a data gap (a NaN push makes last_val NaN — a bare
        # 0 fallback there reintroduces the catastrophic E[x^2] - mean^2
        # cancellation for large-magnitude rows). So the anchor is the
        # nanmean of last_val plus 8 strided ring slots: a [S, 3, 8] gather,
        # negligible next to the [S, 3, L] pass it protects. All-NaN
        # candidates (=> near-empty window) fall back to 0. f64 parity mode
        # never takes this branch.
        stride_idx = jnp.arange(8, dtype=jnp.int32) * max(L // 8, 1) % L  # [8]
        samples = vals[:, :, stride_idx]  # [S, 3, 8]
        cand = jnp.concatenate([samples, last_val[..., None]], axis=-1)
        cand_ok = ~jnp.isnan(cand)
        n_cand = jnp.sum(cand_ok, axis=-1)
        anchor = jnp.where(
            n_cand > 0,
            jnp.sum(jnp.where(cand_ok, cand, 0), axis=-1) / jnp.maximum(n_cand, 1),
            0,
        )[..., None]
        cnt, total, sumsq, vmin, vmax = fused_window_partials_sq(vals, valid, anchor)
        has_avg = (cnt > 0) & full[:, None]
        mean = jnp.where(has_avg, total / jnp.maximum(cnt, 1), jnp.nan)
        # the all-equal guard stays EXACT (min == max): the zero-variance
        # quirk cannot flip on float noise in this mode either
        all_equal = has_avg & (vmax == vmin)
        mean = jnp.where(all_equal, vmax, mean)
        var = sumsq / jnp.maximum(cnt, 1) - (mean - anchor[..., 0]) ** 2
        var = jnp.where(has_avg, jnp.maximum(var, 0), jnp.nan)
        has_std = has_avg & ~all_equal & (var > 0)
        std = jnp.where(has_std, jnp.sqrt(var), jnp.nan)
    else:
        cnt, total, vmin, vmax = fused_window_partials(vals, valid)
        has_avg = (cnt > 0) & full[:, None]
        mean = jnp.where(has_avg, total / jnp.maximum(cnt, 1), jnp.nan)

        # Degenerate (all-equal) windows are resolved EXACTLY, not by float
        # luck: whether sum(x*k)/k reproduces x depends on the value and the
        # summation order (the reference's linear JS reduce and XLA's tree
        # reduction can disagree), which would turn "zero variance -> no
        # signal" (util_methods.js:44-48, the documented intent) into a coin
        # flip with std ~ 1e-13 signalling on any deviation. max==min is
        # order-independent.
        all_equal = has_avg & (vmax == vmin)
        mean = jnp.where(all_equal, vmax, mean)

        diff = jnp.where(valid, vals - mean[..., None], 0)
        var = jnp.where(has_avg, jnp.sum(diff * diff, axis=-1) / jnp.maximum(cnt, 1), jnp.nan)
        has_std = has_avg & ~all_equal & (var > 0)  # var==0 -> std undefined (the quirk)
        std = jnp.where(has_std, jnp.sqrt(var), jnp.nan)

    thr = threshold[:, None]
    lb = jnp.where(has_std, mean - thr * std, jnp.nan)
    ub = jnp.where(has_std, mean + thr * std, jnp.nan)

    new_ok = ~jnp.isnan(new_values)
    exceeds = has_std & new_ok & (jnp.abs(new_values - mean) > thr * std)
    signal = jnp.where(
        exceeds, jnp.where(new_values > mean, 1, -1), 0
    ).astype(jnp.int32)

    # influence damping: only on signal and when the last pushed value is
    # defined (last_val gathered above, before the window reduce)
    can_damp = exceeds & ~jnp.isnan(last_val) & (fill > 0)[:, None]
    infl = influence[:, None]
    pushed = jnp.where(can_damp, infl * new_values + (1 - infl) * last_val, new_values)

    # shift-at-lag semantics: write slot = pos when full (overwriting the
    # oldest), else fill (append); fill grows to L then stays. Inactive rows
    # (not yet in the registry) do not push: their history starts at
    # registration, like the reference's per-key list creation.
    # The write stays a batched scatter (vmap dynamic-slice update): with
    # state donation it updates the [S, 3, L] ring in place. A one-hot
    # masked select measured 34x faster in isolation but 12x SLOWER inside
    # the fused donated tick (it forces rewriting the whole ring, defeating
    # the in-place aliasing) — re-evaluate on real TPU before changing.
    write_idx = jnp.where(full, state.pos, fill)  # [S]
    # the active gate rides the scatter itself: an inactive row writes its
    # slot's CURRENT value back (a no-op), via a cheap one-element-per-row
    # gather — a full-ring where(active, ...) would add a second
    # whole-buffer pass (measured 2x on the fused tick). Gather and write go
    # against the RAW ring so storage bits round-trip exactly.
    cur_at_write = jnp.take_along_axis(
        raw, write_idx[:, None, None].repeat(N_METRICS, 1), axis=-1
    )[..., 0]
    pushed_eff = jnp.where(active[:, None], pushed.astype(raw.dtype), cur_at_write)
    new_vals = jax.vmap(lambda v, i, p: v.at[:, i].set(p))(raw, write_idx, pushed_eff)
    new_fill = jnp.where(active, jnp.minimum(fill + 1, L), fill)
    new_pos = jnp.where(full & active, (state.pos + 1) % L, state.pos)

    result = ZScoreResult(
        window_avg=mean.astype(cfg.dtype),
        lower_bound=lb.astype(cfg.dtype),
        upper_bound=ub.astype(cfg.dtype),
        signal=signal,
    )
    return result, ZScoreState(new_vals, new_fill, new_pos)


def grow_state(state: ZScoreState, cfg: ZScoreConfig, new_capacity: int) -> Tuple[ZScoreState, ZScoreConfig]:
    S_old = state.fill.shape[0]
    if new_capacity < S_old:
        raise ValueError("cannot shrink")
    pad = new_capacity - S_old
    new_cfg = cfg._replace(capacity=new_capacity)
    return ZScoreState(
        values=jnp.pad(state.values, ((0, pad), (0, 0), (0, 0)), constant_values=jnp.nan),
        fill=jnp.pad(state.fill, (0, pad)),
        pos=jnp.pad(state.pos, (0, pad)),
    ), new_cfg
