"""Alert rule evaluation: vectorized device step + host-side alert manager.

Device part — the per-entry rule ladder of stream_process_alerts.js:348-471
evaluated for every (service-row, lag) at once:

- hard max: average/per75 > per-service hardMaxMsAlertThreshold (:398-408)
- upper-bound signals gated by hardMin ms and min TPM (:411-420)
- ``alertOnBothOnly``: both avg and p75 UB must fire together (:421-423)
- rolling bad-interval counter per (row, lag): one increment per entry
  regardless of cause count, capped at window size + 1; decrement on quiet
  entries; trigger only at >= required bad intervals (:366-391)
- suppression lists zero the causes (so counters decay) (:395-396)

Host part — AlertsManager: per-*service* cooldown (keyed by service name only,
like this.alerts[en.service] :449-467), alert buffering with collection-interval
doubling (:269-333), HTML table formatting, Grafana render URL, email dispatch
(gated), resume files.
"""

from __future__ import annotations

import math
import threading
import time
from typing import List, NamedTuple, Optional, Tuple

import jax.numpy as jnp

from ..entries import AlertEntry, EntryFactory, FullStatEntry
from ..utils.counters import capped_append
from ..utils.resume import load_resume_file, save_resume_file

# cause bits, in the reference's evaluation (and string join) order
CAUSE_AVG_HARD = 1 << 0  # 'average exceeded hard ms threshold'
CAUSE_P75_HARD = 1 << 1  # 'per75 exceeded hard ms threshold'
CAUSE_AVG_UB = 1 << 2  # 'average UB exceeded' (only when not alertOnBothOnly)
CAUSE_P75_UB = 1 << 3  # 'per75 UB exceeded'  (only when not alertOnBothOnly)
CAUSE_BOTH_UB = 1 << 4  # 'average and per75 UB exceeded'

_CAUSE_STRINGS = (
    (CAUSE_AVG_HARD, "average exceeded hard ms threshold"),
    (CAUSE_P75_HARD, "per75 exceeded hard ms threshold"),
    (CAUSE_AVG_UB, "average UB exceeded"),
    (CAUSE_P75_UB, "per75 UB exceeded"),
    (CAUSE_BOTH_UB, "average and per75 UB exceeded"),
)


def cause_string(bits: int) -> str:
    return ",".join(s for b, s in _CAUSE_STRINGS if bits & b)


class AlertRuleConfig(NamedTuple):
    hard_min_ms: float  # hardMinMsAlertThreshold
    hard_min_tpm: float  # hardMinTpmAlertThreshold
    alert_on_both_only: bool
    window_sz: int  # rollingAlertWindowSizeInIntervals
    required_bad: int  # requiredNumberBadIntervalsInAlertWindowToTrigger
    lag_suppressed: bool  # this lag is in suppressedLags


class AlertRuleResult(NamedTuple):
    trigger: jnp.ndarray  # [S] bool
    cause_bits: jnp.ndarray  # [S] int32
    counters: jnp.ndarray  # [S] int32 (new state)


def eval_rules(
    counters: jnp.ndarray,  # [S] int32 rolling bad-interval counts for this lag
    cfg: AlertRuleConfig,
    average: jnp.ndarray,  # [S] wire-rounded window average
    per75: jnp.ndarray,  # [S]
    tpm: jnp.ndarray,  # [S]
    avg_signal: jnp.ndarray,  # [S] int
    p75_signal: jnp.ndarray,  # [S] int
    hard_max_ms: jnp.ndarray,  # [S] per-service override vector
    suppressed: jnp.ndarray,  # [S] bool per-service suppression
) -> AlertRuleResult:
    c_avg_hard = average > hard_max_ms  # NaN compares False, like JS undefined
    c_p75_hard = per75 > hard_max_ms

    ub_avg = (avg_signal > 0) & (average > cfg.hard_min_ms) & (tpm > cfg.hard_min_tpm)
    ub_p75 = (p75_signal > 0) & (per75 > cfg.hard_min_ms) & (tpm > cfg.hard_min_tpm)

    if cfg.alert_on_both_only:
        c_avg_ub = jnp.zeros_like(ub_avg)
        c_p75_ub = jnp.zeros_like(ub_p75)
        c_both = ub_avg & ub_p75
    else:
        c_avg_ub, c_p75_ub = ub_avg, ub_p75
        c_both = jnp.zeros_like(ub_avg)

    blocked = suppressed | cfg.lag_suppressed
    c_avg_hard, c_p75_hard, c_avg_ub, c_p75_ub, c_both = (
        c & ~blocked for c in (c_avg_hard, c_p75_hard, c_avg_ub, c_p75_ub, c_both)
    )

    attempted = c_avg_hard | c_p75_hard | c_avg_ub | c_p75_ub | c_both
    # one increment per entry, only while counter <= window size (:372-377)
    counters = counters + jnp.where(attempted & (counters <= cfg.window_sz), 1, 0)

    windowed = cfg.window_sz > 1 and cfg.required_bad > 1
    passes = counters >= cfg.required_bad if windowed else jnp.ones_like(attempted)

    cause_bits = (
        jnp.where(c_avg_hard & passes, CAUSE_AVG_HARD, 0)
        | jnp.where(c_p75_hard & passes, CAUSE_P75_HARD, 0)
        | jnp.where(c_avg_ub & passes, CAUSE_AVG_UB, 0)
        | jnp.where(c_p75_ub & passes, CAUSE_P75_UB, 0)
        | jnp.where(c_both & passes, CAUSE_BOTH_UB, 0)
    ).astype(jnp.int32)
    trigger = cause_bits != 0

    # quiet entry: decay (:427-434)
    counters = jnp.where(~attempted & (counters > 0), counters - 1, counters)
    counters = jnp.maximum(counters, 0)

    return AlertRuleResult(trigger, cause_bits, counters)


class AlertsManager:
    """Host-side: per-service cooldown, batching, formatting, dispatch.

    State mirrors the reference AlertsManager (stream_process_alerts.js:89-482):
    ``alerts`` maps service -> last AlertEntry (cooldown anchor), ``alert_buffer``
    holds unsent alerts; both persist via resume files.

    Thread-safety: the device-loop thread appends triggers while the alert
    timer flushes and the resume-save timer serializes, so ``alerts`` and
    ``alert_buffer`` are guarded by an internal lock (the reference is
    single-threaded per process and needs none). The email/render round-trip
    happens OUTSIDE the lock over a snapshot; sent entries are removed after
    success so a failed send retains them, and concurrent appends during the
    send are preserved.
    """

    def __init__(self, alerts_config: dict, *, logger=None, email_sender=None, grafana=None, clock=time.time):
        self.config = alerts_config
        self.logger = logger
        self.email_sender = email_sender  # callable(subject, html, image_path)
        self.grafana = grafana
        self.clock = clock
        self.alerts: dict = {}  # service -> alert dict (cooldown state)
        self.alert_buffer: List[dict] = []
        self.current_interval_s: Optional[float] = None
        self.dropped_alerts = 0  # drop-oldest evictions while dispatch is unavailable
        self._lock = threading.RLock()

    def set_config(self, alerts_config: dict) -> None:
        self.config = alerts_config

    # -- cooldown ------------------------------------------------------------
    def process_trigger(self, entry: FullStatEntry, cause_bits: int) -> Optional[AlertEntry]:
        """Apply the per-service cooldown to a device-side trigger; returns the

        AlertEntry to persist/send, or None when suppressed (:436-468)."""
        now_ms = self.clock() * 1000.0
        alert = AlertEntry(
            now_ms, entry.timestamp, entry.server, entry.service,
            cause_string(cause_bits), entry.to_csv(),
        )
        with self._lock:
            prior = self.alerts.get(entry.service)
            if prior is not None:
                interval_s = (alert.alert_timestamp - prior["alertTimestamp"]) / 1000.0
                cooldown_s = self.config.get("perServiceAlertCooldownInMinutes", 15) * 60
                if interval_s <= cooldown_s:
                    return None
            self.alerts[entry.service] = {"alertTimestamp": alert.alert_timestamp}
        return alert

    MAX_BUFFERED = 1000  # drop-oldest cap: with emails disabled (the shipped
    # default) flush() retains the buffer, so without a cap alert dicts would
    # accumulate without bound and persist into the resume file

    def add_to_buffer(self, alert: AlertEntry) -> None:
        with self._lock:
            self.dropped_alerts += capped_append(
                self.alert_buffer,
                {
                    "alertTimestamp": alert.alert_timestamp,
                    "entryTimestamp": alert.entry_timestamp,
                    "server": alert.server,
                    "service": alert.service,
                    "cause": alert.cause,
                    "entry": alert.entry,
                },
                self.MAX_BUFFERED,
            )
        if self.dropped_alerts and self.logger and self.dropped_alerts % 100 == 1:
            self.logger.warning(
                f"Alert buffer at {self.MAX_BUFFERED}-entry cap; "
                f"{self.dropped_alerts} oldest alerts dropped so far"
            )

    # -- batched send with interval doubling (:269-333) ----------------------
    def flush(self, interval_s: Optional[float] = None) -> Tuple[int, float]:
        """Send buffered alerts (if any); returns (sent_count, next_interval_s).

        The collection interval doubles after a batch went out, up to
        maxCollectionIntervalInSeconds, then resets once a quiet flush happens.
        """
        base = float(self.config.get("alertCollectionIntervalInSeconds", 60))
        if interval_s is None:
            interval_s = self.current_interval_s or base
        # The whole send/clear/double block is gated on having alerts AND a
        # live dispatch path (reference gates on emailsEnabled,
        # stream_process_alerts.js:273); otherwise the buffer is retained so
        # alerts are not lost, and the interval resets to base.
        can_send = self.email_sender is not None and bool(self.config.get("emailsEnabled"))
        with self._lock:
            if not self.alert_buffer or not can_send:
                self.current_interval_s = base
                return 0, base
            batch = list(self.alert_buffer)  # snapshot: render/send unlocked
        count = len(batch)
        if self.config.get("increaseCollectionIntervalAfterAlert"):
            # clamp: doubling from a non-power-of-two base must not overshoot
            # the configured cap
            interval_s = min(
                interval_s * 2, float(self.config.get("maxCollectionIntervalInSeconds", 960))
            )
        html = self.format_alerts_html(batch)
        image_path = None
        if self.grafana is not None:
            try:
                _url, render_url = self.grafana.alert_urls(batch)
                image_path = self.grafana.render(render_url)
            except Exception as e:  # render failure falls back to plain email
                if self.logger:
                    self.logger.error(f"Error while trying to render graph: {e}")
        self.email_sender("APM Alerts Triggered!", html, image_path)
        with self._lock:
            # a failed send (exception above) retains the batch. Remove the
            # SENT OBJECTS by identity, not a prefix slice: a cap eviction
            # during the unlocked send shifts the list, and a prefix delete
            # would then swallow an unsent alert appended mid-send.
            sent = {id(el) for el in batch}
            self.alert_buffer = [el for el in self.alert_buffer if id(el) not in sent]
        self.current_interval_s = interval_s
        return count, interval_s

    def format_alerts_html(self, batch: Optional[List[dict]] = None) -> str:
        """Two-row-per-alert HTML table (:208-267)."""
        css = (
            '<style type="text/css" media="all"> table { border-collapse: collapse; }'
            ' td { font-family: "Calibri"; font-size: 11pt; white-space: nowrap; }'
            " td, th { padding: 7px; }"
            " td.bb, th.bb { border-bottom: 2px solid black }"
            " td.center { text-align: center; } </style>"
        )
        head = (
            '<table><tr bgcolor="#1ab2ff"><th>Server</th><th>Service</th><th>Timestamp</th>'
            '<th>Lag</th><th>Cause</th></tr><tr bgcolor="#94DBFF"><th class="bb">TPM</th>'
            '<th class="bb">Avg</th><th class="bb">Avg UB</th><th class="bb">75%</th>'
            '<th class="bb">75% UB</th></tr>'
        )
        rows = []
        fac = EntryFactory()
        if batch is None:
            with self._lock:
                batch = list(self.alert_buffer)
        for el in batch:
            en = fac.from_csv(el["entry"], delim="&")
            if en is None:  # corrupted resume data must not poison the flush path
                if self.logger:
                    self.logger.error(f"Unparseable buffered alert entry skipped: {el['entry']!r}")
                continue
            ts = time.strftime("%Y-%m-%d %H:%M:%S", time.localtime(en.timestamp / 1000.0))

            def fx(v):
                return "NaN" if (isinstance(v, float) and math.isnan(v)) else f"{v:.1f}"

            rows.append(
                f'<tr bgcolor="white"><td>{en.server}</td><td>{en.service}</td><td>{ts}</td>'
                f'<td class="center">{en.lag}</td><td>{el["cause"]}</td></tr>'
                f'<tr bgcolor="#e5f8ff"><td class="bb">{fx(en.tpm)}</td><td class="bb">{fx(en.average)}</td>'
                f'<td class="bb">{fx(en.average_ub)}</td><td class="bb">{fx(en.per75)}</td>'
                f'<td class="bb">{fx(en.per75_ub)}</td></tr>'
            )
        return css + head + "".join(rows) + "</table>"

    # -- resume (:111-142) ---------------------------------------------------
    def save_resume(self, path: str, quiet: bool = True) -> None:
        with self._lock:  # snapshot: the device loop appends concurrently
            payload = {"alerts": dict(self.alerts), "alertBuffer": list(self.alert_buffer)}
        save_resume_file(path, payload, logger=self.logger, quiet=quiet)

    def load_resume(self, path: str) -> None:
        data = load_resume_file(path, logger=self.logger)
        if data:
            with self._lock:
                self.alerts = data.get("alerts", {}) or {}
                self.alert_buffer = data.get("alertBuffer", []) or []
