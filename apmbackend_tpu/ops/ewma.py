"""EWMA + seasonal baselining channels (multi-window extension, SURVEY.md §7.2
step 10; BASELINE.json configs[4]).

The reference's only baselining algorithm is the fixed-lag smoothed z-score
(stream_calc_z_score.js:66-104). These channels add the classic EWMA control
chart and seasonal (time-of-day / day-of-week) baselines as *additional lag
channels* beside the lag windows, sharing the engine's tick cadence, alert
rule ladder, and emission shapes — but with O(1) state per key instead of an
O(lag) ring:

- state is ``mean/var [S, 3, K]`` + ``count [S, K]`` where ``K`` is the number
  of season slots. ``K = 1`` is a plain EWMA channel; ``K = 24`` with
  ``slot_intervals = 360`` (10 s cadence) keeps one baseline per hour-of-day;
  ``K = 168`` per hour-of-week. Memory: a 24 h seasonal channel costs
  ``24 × 3`` floats/row vs the 8640-lag window's ``3 × 8640`` — ~360× less.
- update is the exponentially weighted moving mean/variance recursion
  (incremental form of West 1979): ``delta = x - mean``,
  ``mean += alpha·delta``, ``var = (1 - alpha)·(var + alpha·delta²)``; the
  first observation of a slot seeds ``mean = x, var = 0``.
- ``trend_beta > 0`` upgrades a channel to Holt's double exponential
  smoothing (Holt-Winters without the multiplicative season — the additive
  season is already covered by the slot axis): the baseline becomes
  ``level + trend`` and the recursion tracks both, so a service whose
  latency is *legitimately ramping* (deploy rollout, cache warm-up, organic
  load growth) is judged against the extrapolated ramp rather than a lagging
  flat mean — the flat EWMA's systematic false-positive mode. ``trend_beta
  = 0`` is bit-for-bit the plain EWMA recursion (trend stays 0).
- signal semantics mirror the z-score channel's quirks so the downstream alert
  ladder treats the channels identically: warm-up gating on per-slot update
  count (the lag-length analog), zero variance -> std undefined -> no bounds
  and no signal, NaN input -> no signal and no state update.

Influence damping carries over from the reference (stream_calc_z_score.js:96-97):
a signalling value enters the recursion damped as ``infl·x + (1-infl)·mean``,
preventing an anomaly from inflating the EWMA variance and masking its own
successors.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax.numpy as jnp

N_METRICS = 3  # average, per75, per95 (in that order on axis 1)


class EwmaSpec(NamedTuple):
    """Static per-channel settings (hashable: part of the jitted EngineConfig).

    ``channel_id`` is the wire identifier emitted in the FullStatEntry ``lag``
    field for this channel (negative by convention, so dashboards can
    distinguish EWMA/seasonal rows from true lag windows).
    """

    alpha: float  # smoothing factor in (0, 1]
    threshold: float  # signal at |x - mean| > threshold * std
    warmup: int  # min per-slot updates before signalling
    season_slots: int = 1  # K; 1 = plain EWMA
    slot_intervals: int = 1  # bucket labels per season slot
    channel_id: int = -1
    suppressed: bool = False  # like suppressedLags for this channel
    # influence damping, same semantic as stream_calc_z_score.js:96-97: a
    # signalling value enters the recursion as infl·x + (1-infl)·mean, so an
    # anomaly can't immediately inflate the EWMA variance and mask itself
    # (the classic EWMA control-chart weakness). 1.0 = no damping.
    influence: float = 1.0
    # Holt trend smoothing factor in [0, 1): 0 disables the trend term (plain
    # EWMA, the default); > 0 makes the channel a double-exponential
    # (level + trend) baseline judged against the extrapolated value.
    trend_beta: float = 0.0


class EwmaState(NamedTuple):
    mean: jnp.ndarray  # [S, 3, K] level
    var: jnp.ndarray  # [S, 3, K] residual variance
    count: jnp.ndarray  # [S, K] int32 per-slot update count
    # per-slot Holt trend; zeros() for trend_beta == 0 channels, so plain-EWMA
    # snapshots/states stay shape-compatible and the recursion is unchanged.
    # No default: omitting it must fail at the construction site, not as a
    # NoneType subscript inside the jitted step.
    trend: jnp.ndarray  # [S, 3, K]


class EwmaResult(NamedTuple):
    # each [S, 3] on the metric axis, matching ZScoreResult shapes
    window_avg: jnp.ndarray  # NaN = undefined (cold slot)
    lower_bound: jnp.ndarray
    upper_bound: jnp.ndarray
    signal: jnp.ndarray  # int32 in {-1, 0, 1}


def init_state(capacity: int, spec: EwmaSpec, dtype=jnp.float32) -> EwmaState:
    S, K = capacity, spec.season_slots
    return EwmaState(
        mean=jnp.full((S, N_METRICS, K), jnp.nan, dtype),
        var=jnp.zeros((S, N_METRICS, K), dtype),
        count=jnp.zeros((S, K), jnp.int32),
        trend=jnp.zeros((S, N_METRICS, K), dtype),
    )


def slot_for_label(label, spec: EwmaSpec):
    """Season slot owning a bucket label: (label // slot_intervals) % K.

    Labels are 10 s-granular epoch buckets (stream_calc_stats.js:89-96), so
    with the stock cadence ``slot_intervals = 360`` gives hour-of-day slots
    when ``K = 24`` (epoch hour 0 is slot 0 = 00:00 UTC).
    """
    return (jnp.asarray(label, jnp.int32) // spec.slot_intervals) % spec.season_slots


def step(
    state: EwmaState,
    spec: EwmaSpec,
    new_values: jnp.ndarray,  # [S, 3]: this tick's average/per75/per95 per row
    label,  # int32 scalar: the tick's bucket label (selects the season slot)
    threshold: jnp.ndarray = None,  # [S] per-row override; None = spec.threshold
    influence: jnp.ndarray = None,  # [S] per-row override; None = spec.influence
) -> Tuple[EwmaResult, EwmaState]:
    # per-row parameter vectors (service overrides, registry.ewma_params);
    # scalars broadcast from the spec when the caller has no overrides
    thr_v = spec.threshold if threshold is None else threshold[:, None]
    infl_v = spec.influence if influence is None else influence[:, None]
    k = slot_for_label(label, spec)
    mean_k = state.mean[:, :, k]  # [S, 3] level
    var_k = state.var[:, :, k]
    cnt_k = state.count[:, k]  # [S]
    trend_k = state.trend[:, :, k]  # [S, 3] (all-zero for trend_beta == 0)

    # the baseline the new value is judged against: the Holt one-step
    # prediction level + trend. For trend_beta == 0 trend is identically 0,
    # so pred == mean and every expression below reduces to the plain EWMA.
    pred_k = mean_k + trend_k

    warm = cnt_k >= spec.warmup  # [S]
    has_avg = warm[:, None] & ~jnp.isnan(mean_k)
    has_std = has_avg & (var_k > 0)  # zero variance -> undefined, like zscore
    std = jnp.where(has_std, jnp.sqrt(var_k), jnp.nan)

    lb = jnp.where(has_std, pred_k - thr_v * std, jnp.nan)
    ub = jnp.where(has_std, pred_k + thr_v * std, jnp.nan)

    new_ok = ~jnp.isnan(new_values)
    exceeds = has_std & new_ok & (jnp.abs(new_values - pred_k) > thr_v * std)
    signal = jnp.where(exceeds, jnp.where(new_values > pred_k, 1, -1), 0).astype(jnp.int32)

    # Holt level/trend/var update (skip NaN inputs; first observation seeds
    # the slot: level = x, trend = 0, var = 0). Signalling values are
    # influence-damped against the prediction before entering the recursion.
    pushed = jnp.where(exceeds, infl_v * new_values + (1.0 - infl_v) * pred_k, new_values)
    seeded = ~jnp.isnan(mean_k)
    delta = jnp.where(new_ok & seeded, pushed - pred_k, 0)  # one-step residual
    incr = spec.alpha * delta
    new_level = pred_k + incr  # == alpha*pushed + (1-alpha)*(level+trend)
    upd_mean = jnp.where(new_ok, jnp.where(seeded, new_level, new_values), mean_k)
    upd_trend = jnp.where(
        new_ok & seeded,
        spec.trend_beta * (new_level - mean_k) + (1.0 - spec.trend_beta) * trend_k,
        jnp.where(new_ok, 0.0, trend_k),  # seeding resets trend
    )
    # seeding resets var to 0 (not just mean): a NaN var — e.g. rows grown
    # past a resume snapshot's capacity — must not poison the recursion forever
    upd_var = jnp.where(
        new_ok,
        jnp.where(seeded, (1.0 - spec.alpha) * (var_k + delta * incr), 0.0),
        var_k,
    )

    dtype = state.mean.dtype
    new_mean = state.mean.at[:, :, k].set(upd_mean.astype(dtype))
    new_var = state.var.at[:, :, k].set(upd_var.astype(dtype))
    new_trend = state.trend.at[:, :, k].set(upd_trend.astype(dtype))
    # per-slot count advances when any metric updated (all 3 share the tick)
    any_ok = jnp.any(new_ok, axis=1)
    new_count = state.count.at[:, k].add(jnp.where(any_ok, 1, 0).astype(jnp.int32))

    result = EwmaResult(
        window_avg=jnp.where(has_avg, pred_k, jnp.nan).astype(dtype),
        lower_bound=lb.astype(dtype),
        upper_bound=ub.astype(dtype),
        signal=signal,
    )
    return result, EwmaState(new_mean, new_var, new_count, new_trend)


def grow_state(state: EwmaState, new_capacity: int) -> EwmaState:
    S_old = state.count.shape[0]
    if new_capacity < S_old:
        raise ValueError("cannot shrink")
    pad = new_capacity - S_old
    return EwmaState(
        mean=jnp.pad(state.mean, ((0, pad), (0, 0), (0, 0)), constant_values=jnp.nan),
        var=jnp.pad(state.var, ((0, pad), (0, 0), (0, 0))),
        count=jnp.pad(state.count, ((0, pad), (0, 0))),
        trend=jnp.pad(state.trend, ((0, pad), (0, 0), (0, 0))),
    )


def specs_from_config(eng_config: dict) -> Tuple[EwmaSpec, ...]:
    """Parse ``tpuEngine.ewmaChannels`` into EwmaSpec tuples.

    Config shape (keys uppercase like the z-score defaults block,
    apm_config.json:136-145):

        "ewmaChannels": [
          {"ALPHA": 0.05, "THRESHOLD": 3.0, "WARMUP": 60},
          {"ALPHA": 0.2, "THRESHOLD": 3.0, "WARMUP": 3,
           "SEASON_SLOTS": 24, "SLOT_INTERVALS": 360, "CHANNEL_ID": -24}
        ]
    """
    out = []
    seen = set()
    for i, d in enumerate(eng_config.get("ewmaChannels", []) or []):
        spec = EwmaSpec(
            alpha=float(d["ALPHA"]),
            threshold=float(d["THRESHOLD"]),
            warmup=int(d.get("WARMUP", 1)),
            season_slots=int(d.get("SEASON_SLOTS", 1)),
            slot_intervals=int(d.get("SLOT_INTERVALS", 1)),
            channel_id=int(d.get("CHANNEL_ID", -(i + 1))),
            suppressed=bool(d.get("SUPPRESSED", False)),
            influence=float(d.get("INFLUENCE", 1.0)),
            trend_beta=float(d.get("TREND_BETA", 0.0)),
        )
        if not (0.0 <= spec.trend_beta < 1.0):
            raise ValueError(
                f"ewmaChannels[{i}]: TREND_BETA must be in [0, 1), got {spec.trend_beta}"
            )
        # channel_id is the wire 'lag' and the resume-snapshot key: it must be
        # negative (so it can't collide with a real lag window) and unique
        # (a collision would silently merge two channels' resume state)
        if spec.channel_id >= 0:
            raise ValueError(f"ewmaChannels[{i}]: CHANNEL_ID must be negative, got {spec.channel_id}")
        if spec.channel_id in seen:
            raise ValueError(f"ewmaChannels[{i}]: duplicate CHANNEL_ID {spec.channel_id}")
        seen.add(spec.channel_id)
        out.append(spec)
    return tuple(out)
