"""Service registry: dynamic (server, service) keys onto static device rows.

The reference keeps all per-key state in nested dicts
``servers[server].services[service]`` that grow as keys appear
(stream_calc_stats.js:124-129, stream_calc_z_score.js:200-208) and are never
removed. On TPU, state lives in dense ``[S, ...]`` tensors with static shapes,
so this registry maps each key to a stable row index. When capacity is
exhausted the caller grows to the next power-of-two capacity and re-jits
(growth-by-recompile, SURVEY.md §7.3 "dynamic key space on static shapes").

Also materializes per-row parameter vectors from config (z-score
threshold/influence per lag, alert overrides, suppression flags) so the device
step reads them as gathered arrays instead of dict lookups per message.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from ..config import (
    service_alert_overrides,
    service_ewma_overrides,
    service_zscore_settings,
)


class CapacityExceeded(Exception):
    def __init__(self, needed: int, capacity: int):
        super().__init__(f"Service registry needs {needed} rows but capacity is {capacity}")
        self.needed = needed
        self.capacity = capacity


class ServiceRegistry:
    def __init__(self, capacity: int):
        self.capacity = capacity
        self._index: Dict[Tuple[str, str], int] = {}
        self._rows: List[Tuple[str, str]] = []

    def __len__(self) -> int:
        return len(self._rows)

    @property
    def count(self) -> int:
        return len(self._rows)

    def key_of(self, row: int) -> Tuple[str, str]:
        return self._rows[row]

    def rows(self) -> List[Tuple[str, str]]:
        return list(self._rows)

    def lookup(self, server: str, service: str) -> Optional[int]:
        return self._index.get((server, service))

    def lookup_or_add(self, server: str, service: str) -> int:
        key = (server, service)
        row = self._index.get(key)
        if row is not None:
            return row
        if len(self._rows) >= self.capacity:
            raise CapacityExceeded(len(self._rows) + 1, self.capacity)
        row = len(self._rows)
        self._rows.append(key)
        self._index[key] = row
        return row

    def lookup_or_add_batch(self, keys: Iterable[Tuple[str, str]]) -> np.ndarray:
        return np.fromiter(
            (self.lookup_or_add(srv, svc) for srv, svc in keys), dtype=np.int32
        )

    def grown(self, new_capacity: Optional[int] = None) -> "ServiceRegistry":
        """A copy with doubled (or given) capacity; row assignments preserved."""
        if new_capacity is None:
            new_capacity = max(2 * self.capacity, 1)
        if new_capacity < len(self._rows):
            raise ValueError("new capacity below current row count")
        out = ServiceRegistry(new_capacity)
        out._rows = list(self._rows)
        out._index = dict(self._index)
        return out

    # -- per-row parameter vectors ------------------------------------------

    def zscore_params(self, zscore_config: dict, lags: Sequence[int], dtype=np.float32) -> Dict[int, dict]:
        """Per-lag {threshold: [S], influence: [S]} vectors in the engine dtype

        (float64 in parity mode: 0.1 differs between f32 and f64, and the
        influence constant enters the stored history). Rows beyond the
        registered count carry the defaults. Overrides follow
        stream_calc_z_score.js:106-132 (keyed by service name only).
        """
        defaults = {int(d["LAG"]): d for d in zscore_config.get("defaults", [])}
        out = {}
        for lag in lags:
            d = defaults.get(int(lag), {"THRESHOLD": 0.0, "INFLUENCE": 0.0})
            thr = np.full(self.capacity, float(d["THRESHOLD"]), dtype=dtype)
            infl = np.full(self.capacity, float(d["INFLUENCE"]), dtype=dtype)
            out[int(lag)] = {"threshold": thr, "influence": infl}
        for row, (_server, service) in enumerate(self._rows):
            for setting in service_zscore_settings(zscore_config, service):
                lag = int(setting["LAG"])
                if lag in out:
                    out[lag]["threshold"][row] = float(setting["THRESHOLD"])
                    out[lag]["influence"][row] = float(setting["INFLUENCE"])
        return out

    def ewma_params(self, eng_config: dict, specs, dtype=np.float32) -> Dict[int, dict]:
        """Per-channel {threshold: [S], influence: [S]} vectors for the
        EWMA-family channels, with per-service overrides.

        Overrides live at ``tpuEngine.ewmaChannelOverrides.services.<service>.
        <channel_id>`` with THRESHOLD/INFLUENCE keys — the same
        service-name-keyed shape AND truthiness semantics as
        streamCalcZScore.overrides (config.service_ewma_overrides resolves
        the shape, like its zscore/alert siblings). Rows beyond the
        registered count carry the channel defaults.
        """
        out = {}
        for spec in specs:
            thr = np.full(self.capacity, float(spec.threshold), dtype=dtype)
            infl = np.full(self.capacity, float(spec.influence), dtype=dtype)
            out[spec.channel_id] = {"threshold": thr, "influence": infl}
        for row, (_server, service) in enumerate(self._rows):
            for chan_id, ov in service_ewma_overrides(eng_config, service).items():
                if chan_id in out:
                    if "THRESHOLD" in ov:
                        out[chan_id]["threshold"][row] = float(ov["THRESHOLD"])
                    if "INFLUENCE" in ov:
                        out[chan_id]["influence"][row] = float(ov["INFLUENCE"])
        return out

    def alert_params(self, alerts_config: dict, dtype=np.float32) -> dict:
        """Per-row alert vectors: hard-max override and service suppression.

        Mirrors stream_process_alerts.js:395-398: a service override of
        hardMaxMsAlertThreshold applies when set and non-zero.
        """
        hard_max_default = float(alerts_config.get("hardMaxMsAlertThreshold", np.inf))
        hard_max = np.full(self.capacity, hard_max_default, dtype=dtype)
        suppressed = np.zeros(self.capacity, dtype=bool)
        suppressed_services = set(alerts_config.get("suppressedServices", []))
        for row, (_server, service) in enumerate(self._rows):
            ov = service_alert_overrides(alerts_config, service)
            if ov and ov.get("hardMaxMsAlertThreshold"):
                hard_max[row] = float(ov["hardMaxMsAlertThreshold"])
            if service in suppressed_services:
                suppressed[row] = True
        return {"hard_max_ms": hard_max, "suppressed": suppressed}
