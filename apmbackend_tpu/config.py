"""Configuration system for the TPU-native APM backend.

Reproduces the reference's config semantics (see /root/reference/util_methods.js:253-348
and /root/reference/config/apm_config.json):

- A single JSON file shared by every module, allowing ``//`` line comments that are
  stripped before parsing unless preceded by ``:`` (so URLs like ``amqp://`` survive).
- Hard failure (exit code 2) when the file is missing on first load.
- Hot reload: the file is watched; a change is debounced, then md5+size compared,
  and only a *parseable* new config is applied — a broken edit keeps the old config
  live until corrected (util_methods.js:297-348).
- ``restart_required_vars``: dotted paths that only warn when changed at runtime.
- Hierarchical per-service overrides (e.g. z-score THRESHOLD/INFLUENCE per lag,
  apm_config.json:152-172) are resolved by :func:`resolve_path` helpers.

Unlike the reference the watcher here is polling-based (mtime+md5), which behaves
identically on NFS where inotify is unreliable — the same reason the reference
shipped a patched Perl File::Tail.
"""

from __future__ import annotations

import copy
import hashlib
import json
import os
import re
import sys
import threading
from typing import Any, Callable, Iterable, Optional

# Strip // comments unless preceded by ':' (keeps URLs intact). Mirrors
# JSONstrip (util_methods.js:265-268) which removes '[^:]//...' to end of line.
_COMMENT_RE = re.compile(r"(?<!:)//[^\n]*")


def strip_json_comments(text: str) -> str:
    """Remove ``//`` comments (not ``://``) from JSON text."""
    out_lines = []
    for line in text.splitlines():
        stripped = line.lstrip()
        if stripped.startswith("//"):
            out_lines.append("")
            continue
        out_lines.append(_COMMENT_RE.sub("", line))
    return "\n".join(out_lines)


def resolve_path(obj: Any, path: str, separator: str = ".") -> Any:
    """Resolve a dotted path into nested dicts, returning None when absent.

    Mirrors ``resolve`` (util_methods.js:248-251).
    """
    cur = obj
    for part in path.split(separator):
        if cur is None:
            return None
        if isinstance(cur, dict):
            cur = cur.get(part)
        else:
            cur = getattr(cur, part, None)
    return cur


class ConfigError(Exception):
    pass


def load_config(path: str, *, logger=None, exit_on_missing: bool = False) -> dict:
    """Read + parse the APM config file.

    Mirrors ``readAPMConfig`` (util_methods.js:253-295): missing file is fatal
    (exit 2) when ``exit_on_missing``; unparseable content returns None-equivalent
    (raises ConfigError) so a watcher can keep the previous config.
    """
    if not os.path.exists(path):
        msg = f"APM config file does not exist, can't continue: {path}"
        if logger:
            logger.warning(msg)
        if exit_on_missing:
            sys.exit(2)
        raise ConfigError(msg)
    with open(path, "r", encoding="utf-8") as fh:
        content = fh.read()
    try:
        config = json.loads(strip_json_comments(content))
    except json.JSONDecodeError as e:
        raise ConfigError(f"Could not parse JSON content from APM config file: {path}: {e}") from e
    config["apmConfigFilePath"] = path
    return config


class ConfigWatcher:
    """Poll a config file and invoke a callback when its content changes.

    Debounce + md5/size change detection per util_methods.js:301-316. A parse
    failure keeps the previous config and waits for a correction. Vars listed in
    ``restart_required_vars`` only produce a warning when changed.
    """

    def __init__(
        self,
        path: str,
        update_callback: Callable[[dict], None],
        restart_required_vars: Iterable[str] = (),
        *,
        poll_interval: float = 0.5,
        logger=None,
    ):
        self.path = path
        self.update_callback = update_callback
        self.restart_required_vars = list(restart_required_vars)
        self.poll_interval = poll_interval
        self.logger = logger
        self._prev_md5 = self._digest()
        self._current = load_config(path, logger=logger)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    @property
    def current(self) -> dict:
        return self._current

    def _digest(self) -> str:
        try:
            with open(self.path, "rb") as fh:
                return hashlib.md5(fh.read()).hexdigest()
        except OSError:
            return ""

    def check_once(self) -> Optional[dict]:
        """Single poll step; returns the new config if one was applied."""
        digest = self._digest()
        if digest == self._prev_md5 or not digest:
            return None
        self._prev_md5 = digest
        try:
            new_config = load_config(self.path, logger=self.logger)
        except ConfigError:
            if self.logger:
                self.logger.warning(
                    "The config file JSON could not be processed, proceeding with NO "
                    "config changes. Future config corrections will be picked up."
                )
            return None
        prev = self._current
        for var in self.restart_required_vars:
            old_val = resolve_path(prev, var)
            new_val = resolve_path(new_config, var)
            if json.dumps(old_val, sort_keys=True) != json.dumps(new_val, sort_keys=True):
                if self.logger:
                    self.logger.warning(
                        f"{var} was changed on settings reload, but this will not take "
                        f"effect without a restart. Old={old_val!r} New={new_val!r}"
                    )
        self._current = new_config
        self.update_callback(new_config)
        return new_config

    def start(self) -> None:
        if self._thread is not None:
            return

        def _loop():
            while not self._stop.wait(self.poll_interval):
                try:
                    self.check_once()
                except Exception as e:  # watcher must never die
                    if self.logger:
                        self.logger.error(f"Config watcher error: {e}")

        self._thread = threading.Thread(target=_loop, name="apm-config-watcher", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None


def service_zscore_settings(zscore_config: dict, service: str) -> list[dict]:
    """Resolve per-service z-score lag settings with overrides applied.

    Mirrors ``getServiceSettingsFromConfig`` (stream_calc_z_score.js:106-132):
    defaults is a list of {LAG, THRESHOLD, INFLUENCE}; overrides.services.<name>
    maps lag-string -> partial {THRESHOLD, INFLUENCE}.
    """
    settings = [dict(s) for s in zscore_config.get("defaults", [])]
    overrides = (zscore_config.get("overrides", {}) or {}).get("services", {}) or {}
    service_overrides = overrides.get(service)
    if service_overrides:
        for setting in settings:
            for lag_key, vals in service_overrides.items():
                if int(setting["LAG"]) == int(lag_key):
                    if vals.get("THRESHOLD"):
                        setting["THRESHOLD"] = vals["THRESHOLD"]
                    if vals.get("INFLUENCE"):
                        setting["INFLUENCE"] = vals["INFLUENCE"]
    return settings


def service_alert_overrides(alerts_config: dict, service: str) -> Optional[dict]:
    """Per-service alert threshold overrides (stream_process_alerts.js:335-346)."""
    overrides = (alerts_config.get("overrides", {}) or {}).get("services", {}) or {}
    return overrides.get(service)


def service_ewma_overrides(eng_config: dict, service: str) -> dict:
    """Per-service EWMA-channel overrides: channel-id-string -> partial
    {THRESHOLD, INFLUENCE}, null-safe and truthiness-filtered exactly like
    :func:`service_zscore_settings` (falsy values are ignored, matching
    stream_calc_z_score.js:106-132 — a 0 override is a no-op, not a
    signal-on-everything threshold)."""
    overrides = (eng_config.get("ewmaChannelOverrides", {}) or {}).get("services", {}) or {}
    chans = overrides.get(service) or {}
    out = {}
    for chan_key, vals in chans.items():
        kept = {
            k: vals[k] for k in ("THRESHOLD", "INFLUENCE") if vals.get(k)
        }
        if kept:
            out[int(chan_key)] = kept
    return out


def default_config() -> dict:
    """A complete default config mirroring the reference's shipped apm_config.json

    (structure and defaults from /root/reference/config/apm_config.json), with
    paths relative to the repo and TPU-engine settings added under ``tpuEngine``.
    """
    return copy.deepcopy(_DEFAULT_CONFIG)


_DEFAULT_CONFIG: dict = {
    "appDirectory": ".",
    "amqpConnectionString": "amqp://localhost:5672",
    "brokerBackend": "memory",  # "memory" | "amqp" | "redis" | "spool" | "shmring"
    # consumer prefetch for at-least-once (manual-ack) AMQP consumers: the
    # broker bound on in-flight unacked deliveries per connection — also the
    # worst-case redelivery span a dedup window must cover
    "amqpPrefetchCount": 1000,
    # End-to-end flow control (transport/base.py, DESIGN.md §7.1): the
    # producer pause buffer — what write_line holds while the broker refuses
    # — is capped; past the cap the oldest lines are evicted under
    # producerOverflowPolicy ("drop-oldest": counted loss, the at-least-once
    # layer's dedup absorbs any overlap; "spill-spool": evictions land in a
    # durable spool under spillDirectory for offline replay) and the episode
    # degrades loudly (flight bundle + decision record + counter).
    "transport": {
        # broker selection override; None defers to top-level brokerBackend
        # (kept for config compatibility with pre-ISSUE-15 deployments)
        "broker": None,
        "producerBufferMaxLines": 100000,  # 0 = legacy unbounded
        "producerOverflowPolicy": "drop-oldest",  # | "spill-spool"
        "spillDirectory": "spool/overflow",
        # brokerBackend "spool": directory of the shared durable spool fabric
        "spoolDirectory": "spool/broker",
        # /healthz flow-control provider degrades when any producer buffer
        # reaches this fraction of the cap (pages BEFORE eviction starts)
        "producerBufferDegradedRatio": 0.8,
        # Zero-object byte spine (transport/frames.py, DESIGN.md §4.1):
        # frameMode makes the parser emit packed APF1 frame batches — one
        # write_frames per batch, headers stamped once per batch — instead
        # of one write_line per record. OFF keeps the wire bit-identical to
        # the pre-frame backend; APM_NO_FRAMES=1 is the runtime kill
        # switch, APM_FRAMES_NO_NATIVE=1 forces the Python encoder.
        "frameMode": False,
        "frameMaxRecords": 512,  # records per batch before a forced flush
        # brokerBackend "shmring": mmap'd SPSC shared-memory rings (one
        # file per queue under shmRingDirectory, shmRingBytes each) for the
        # parser->worker hop — at-most-once, in-host, zero broker process.
        "shmRingDirectory": "spool/shmring",
        "shmRingBytes": 8 * 1024 * 1024,
    },
    # Redis Streams backend (transport/redis_streams.py): consumer groups
    # give manual-ack/redelivery via the PEL + XAUTOCLAIM; send refuses while
    # the group backlog is at streamMaxlen (retention trims at 2x, so only
    # the acked prefix is ever dropped). claimIdleMs is how long a delivery
    # may sit unacked before another consumer may steal it — the redis
    # analog of the AMQP redelivery-on-connection-death span.
    "redis": {
        "connectionString": "redis://localhost:6379/0",
        "streamMaxlen": 100000,
        "group": "apm",
        "claimIdleMs": 5000,
        "prefetchCount": 1000,
    },
    "logDir": "logs",
    "statLogIntervalInSeconds": 60,
    "dbInsertQueue": "db_insert",
    # Telemetry plane (apmbackend_tpu.obs): per-stage tick tracing, queue/
    # parser/DB counters, and — when a module section sets "metricsPort"
    # (0 = ephemeral) — a per-module HTTP exporter serving Prometheus
    # /metrics, JSON /healthz, and on-demand /profile. "enabled": false
    # removes every instrument from the hot paths.
    "observability": {
        "enabled": True,
        "metricsHost": "127.0.0.1",
        # Distributed trace plane (obs/trace): head-sample every Nth message
        # at transport entry (trace_id header + per-hop spans served by the
        # exporter's /trace; histograms gain bucket exemplars). 0 disables
        # sampling entirely — the wire and the hot path are then bit-identical
        # to the pre-trace backend.
        "traceSampleRate": 64,
        "traceRingSize": 512,
        # Crash flight recorder (obs/flight): triage-bundle directory (None =
        # disabled). Bundles are dumped on healthz degradation, SIGTERM/SIGINT,
        # worker feed exceptions, and on demand via /flight; a journal +
        # alive-sentinel shadow rewritten every flightJournalSeconds survives
        # kill−9 and is promoted to a crash bundle on the next boot.
        "flightDir": None,
        "flightJournalSeconds": 5.0,
        "flightMaxBundles": 16,
        # Durable telemetry spine (obs/store + obs/recorder, DESIGN.md §8.4).
        # recorderDir enables the MANAGER-side fleet recorder: every child's
        # /metrics, /trace, and /decisions scraped each recorderIntervalSeconds
        # and persisted shard-labeled into an append-only segmented store, so
        # a kill−9'd shard's last telemetry stays queryable via /query.
        "recorderDir": None,
        "recorderIntervalSeconds": 2.0,
        "recorderRetentionSeconds": 3600.0,
        "recorderDownsampleAfterSeconds": 900.0,
        "recorderDownsampleStepSeconds": 60.0,
        # Per-module store behind each exporter's /query: registry snapshots
        # every selfSampleSeconds (0 disables /query + the local store);
        # storeDir=None keeps it in-memory (volatile, still queryable).
        "storeDir": None,
        "selfSampleSeconds": 2.0,
        "storeRetentionSeconds": 900.0,
    },
    # Fleet query plane (obs/queryplane, DESIGN.md §10.5): the manager
    # replaces its per-process /query /trace /decisions /attrib with a
    # fleet-wide router — single-service queries go to the owning shard
    # via the pinned service_partition hash + live owner map, everything
    # else scatter-gathers (counters summed, histogram buckets merged
    # before the quantile, spans/decisions deduped) and a dead shard is
    # served from the recorder store with explicit partial/stale marking.
    "queryPlane": {
        "enabled": True,
        # TTL read-through cache for dashboard-repeated queries; 0 disables
        "cacheTtlSeconds": 2.0,
        # bounded shard fan-out concurrency per request
        "fanoutConcurrency": 8,
        # per-shard sub-request timeout; a slower shard degrades to the store
        "timeoutSeconds": 2.0,
        # bounded requeries when the owner map seq moves mid-fanout
        "moveRetries": 2,
        # owner-map refresh cadence (manager re-derives it from shard
        # scrapes; the standalone CLI polls /fleet at this cadence)
        "ownerRefreshSeconds": 5.0,
    },
    # SLO burn-rate engine (obs/slo, DESIGN.md §8.4): Google-SRE multi-window
    # burn rates evaluated over the telemetry store. A "fast" burn (both
    # windows >= fastBurnThreshold) pages through the alert/decision path and
    # degrades /healthz to 503; "slow" burns ticket at slowBurnThreshold.
    # objectives=None uses the built-in four (detection latency p95, alert
    # latency, per-queue wait/lag, epoch age — obs.slo.DEFAULT_OBJECTIVES);
    # override with a list of {name, kind: latency|gauge, series,
    # thresholdSeconds|threshold, target, per}.
    "slo": {
        "enabled": True,
        "evaluationIntervalSeconds": 10.0,
        "shortWindowSeconds": 300.0,
        "longWindowSeconds": 3600.0,
        "fastBurnThreshold": 14.4,
        "slowBurnThreshold": 6.0,
        "alertCooldownSeconds": 300.0,
        "objectives": None,
    },
    "statistics": [
        {"type": "average"},
        {"type": "percentile", "percentileValue": 75},
        {"type": "percentile", "percentileValue": 95},
    ],
    "applicationManager": {
        "logFilePrefix": "apm_manager",
        "fromEmail": "apm@example.com",
        "emailsEnabled": False,
        "emailList": "admin@example.com",
        "alertCollectionIntervalInSeconds": 60,
        "increaseCollectionIntervalAfterAlert": True,
        "maxCollectionIntervalInSeconds": 3840,
        "queueMessageAlertThreshold": 1000000,
        "queueMemoryAlertThreshold": 150,
        "moduleMemoryAlertThreshold": 350,
        "moduleSwapAlertThreshold": 200,
        "diskSpaceGBAvailableThreshold": 100,
        "diskSpacePercentageUsedThreshold": 80,
        "inspectionFrequencySeconds": 60,
        # hung-tick watchdog: a child whose /healthz answers 503 (or times
        # out) this many consecutive inspection cycles is force-restarted
        # through the crash-loop-damped path (0 disables; only children with
        # a metricsPort are watchable)
        "healthzFailureThreshold": 3,
        "healthzTimeoutSeconds": 2,
        "sendAlertOnUnexpectedScriptEnd": True,
        "triggerGCThreshold": 500,
        "appLogRetentionDays": 7,
        # disk inspection mount point (None = appDirectory) and the RabbitMQ
        # sbin dir for broker admin commands ("" = resolve from PATH)
        "diskInspectionMount": None,
        "rabbitSbinPath": "",
        # full child teardown on manager shutdown (default: children keep
        # running so a manager restart is non-disruptive)
        "stopChildrenOnShutdown": False,
        # per-child "metricsPort" makes the child a /fleet scrape target of
        # the manager's exporter (see tools.qstat --metrics-url, DESIGN.md)
        "moduleSettings": [
            {"module": "apmbackend_tpu.ingest.parser_main"},
            {"module": "apmbackend_tpu.runtime.worker", "moduleMemoryAlertThreshold": 700},
            {"module": "apmbackend_tpu.sinks.insert_db_main"},
            {"module": "apmbackend_tpu.ingest.jmx_main"},
        ],
        "metricsPort": None,  # the manager's own /metrics + /fleet exporter
    },
    "streamParseTransactions": {
        "logFilePrefix": "stream_parse_transactions",
        "outQueue": "transactions",
        "verboseQueueWrite": False,
        "tailPauseFileFullPath": "state/PAUSE_TAILS.switch",
        "appLogDirMaskPrefix": "fixtures/logs",
        "maskSuffixes": ["app*log", "server.log", "soap_io*log"],
        # server-name extraction from a log path: regex (group 1), else path
        # component (reference: split('/')[2]), else fixed default, else basename
        "serverFromPathPattern": None,
        "serverPathComponentIndex": 2,
        "defaultServerName": None,
        # optional path to the native C++ tail binary (native/apm_tail);
        # Python tailer threads are used when absent
        # per-file tail process: "auto" builds native/tailer.cpp via make and
        # spawns it per file (perl_tail.pl role); an explicit path uses that
        # binary; None uses in-process Python tail threads
        "nativeTailBinary": "auto",
        "metricsPort": None,  # telemetry exporter port (0 = ephemeral)
    },
    "streamCalcStats": {
        "logFilePrefix": "stream_calc_stats",
        "logDebug": False,
        "inQueue": "transactions",
        "outQueue": "stats",
        "consumeQueue": True,
        "verboseQueueWrite": False,
        "resumeFileFullPath": "save/stream_calc_stats.resume",
        "resumeFileSaveFrequencyInSeconds": 60,
        "intervalLengthInSeconds": 10,
        "windowSizeInIntervals": 30,
        "bufferSizeInIntervals": 6,
    },
    "streamCalcZScore": {
        "logFilePrefix": "stream_calc_z_score",
        "inQueue": "stats",
        "outQueue": "z_score",
        "consumeQueue": True,
        "verboseQueueWrite": False,
        "resumeFileFullPath": "save/stream_calc_z_score.resume",
        "resumeFileSaveFrequencyInSeconds": 60,
        # Per-lag baselining windows (apm_config.json:136-145 shape). Each
        # entry may also set "ROBUST": true to baseline with median/MAD
        # (1.4826 consistency scaling) instead of mean/std — immune to the
        # classic z-score's self-contamination, where an outlier burst
        # inflates the window std and masks later anomalies until it ages
        # out (no reference equivalent; per-lag static, recompiles on change).
        "defaults": [
            {"LAG": 360, "THRESHOLD": 20.0, "INFLUENCE": 0.1},
            {"LAG": 8640, "THRESHOLD": 15.0, "INFLUENCE": 0.0},
        ],
        "overrides": {"services": {}},
    },
    "streamProcessAlerts": {
        "logFilePrefix": "stream_process_alerts",
        "inQueue": "z_score",
        "consumeQueue": True,
        "verboseQueueWrite": False,
        "alertsResumeFileFullPath": "save/stream_process_alerts.resume",
        "resumeFileSaveFrequencyInSeconds": 60,
        "ignoreOldAlertsDuringCatchupLimitInMinutes": 60,
        "hardMinMsAlertThreshold": 200,
        "hardMaxMsAlertThreshold": 10000,
        "hardMinTpmAlertThreshold": 1.0,
        "alertOnBothOnly": True,
        "overrides": {"services": {}},
        "suppressedLags": [],
        "rollingAlertWindowSizeInIntervals": 60,
        "requiredNumberBadIntervalsInAlertWindowToTrigger": 45,
        "suppressedServices": [],
        "perServiceAlertCooldownInMinutes": 15,
        "alertCollectionIntervalInSeconds": 60,
        "increaseCollectionIntervalAfterAlert": True,
        "maxCollectionIntervalInSeconds": 960,
        "fromEmail": "apm@example.com",
        "emailsEnabled": False,
        "emailList": "oncall@example.com",
        "testEmailList": "admin@example.com",
    },
    "streamInsertDb": {
        "logFilePrefix": "stream_insert_db",
        "consumeQueue": True,
        "bufferResumeFileFullPath": "save/stream_insert_db_buffer.resume",
        "dbBackend": "fake",  # "fake" | "postgres" | "sqlite"
        "dbUser": "prod",
        "dbHost": "localhost",
        "dbDatabase": "apm",
        "dbTxTable": "tx",
        "dbStatTable": "stats",
        "dbAlertTable": "alerts",
        "dbJmxTable": "jmx",
        "dbInsertBufferLimit": 1000,
        "dbMaxTimeBetweenInsertsMs": 5000,
        # sqlite backend file (":memory:" = ephemeral); postgres credentials
        "dbFileFullPath": ":memory:",
        "dbPassword": None,
        "dbPort": 5432,
        "metricsPort": None,  # telemetry exporter port (0 = ephemeral)
    },
    "pullJvmStats": {
        "logFilePrefix": "pull_jvm_stats",
        "verboseQueueWrite": False,
        "clientJarFullPath": None,  # path to jboss-cli-client.jar; None => polling disabled
        "jvmHosts": [],
        "shortenHostname": True,
        "adminUser": "",
        "adminPass": "",
        "jmxPort": 9990,
        "clientTimeoutMs": 2000,
        "pollingIntervalSeconds": 60,
        "metricsPort": None,  # telemetry exporter port (0 = ephemeral)
        # resource label -> jboss-cli command; order defines blob labeling
        # (config/apm_config.json:246-254)
        "statCmdMap": {
            "ds": "/subsystem=datasources/data-source=DefaultDS/statistics=pool:read-resource(include-runtime=true,recursive=true)",
            "heap": "/core-service=platform-mbean/type=memory :read-attribute(name=heap-memory-usage)",
            "meta": "/core-service=platform-mbean/type=memory :read-attribute(name=non-heap-memory-usage)",
            "sysload": "/core-service=platform-mbean/type=operating-system :read-attribute(name=system-load-average)",
            "classcnt": "/core-service=platform-mbean/type=class-loading :read-attribute(name=loaded-class-count)",
            "threading": "/core-service=platform-mbean/type=threading :read-resource",
            "bean": "/deployment=App.ear/subdeployment=*/subsystem=ejb3/stateless-session-bean=MainBean :read-resource(recursive=true,include-runtime=true)",
        },
        # device multivariate anomaly detector over the poll stream (a TPU
        # capability beyond the reference; ops/multivariate.py). Absent block
        # or "enabled": false disables; an empty {} means enabled-with-defaults.
        "multivariateDetector": {
            "enabled": False,
            "alpha": 0.05,  # EW smoothing factor for mean/covariance
            "threshold": 3.0,  # signal at normalized Mahalanobis > threshold
            "warmup": 22,  # polls before a host may signal; keep >= 2x feature count
            "influence": 0.25,  # damping for signalling samples (1 = none)
            # baseline snapshot (None disables); avoids a full re-warmup
            # (~warmup polls of blindness) on every module restart
            "resumeFileFullPath": None,
            "resumeFileSaveFrequencyInSeconds": 60,
        },
    },
    "grafana": {
        "grafanaURL": "",
        "grafanaHostname": "",
        "alertInspectorRelativeURL": "/d/alert-inspector",
        "grafanaNowDelayIntervalMs": 90000,
        "bearerToken": "",
        "renderDir": "renders",
        "renderWidth": 1800,
        "renderHeightMultiple": 750,
        "renderExtraParams": "&autofitpanels",
        "renderTimeout": 90000,
    },
    # Pod-scale sharded serving spine (parallel/fleet.py, DESIGN.md §10):
    # shards > 0 switches the producer side to service-hash partitioning
    # (the `transactions` queue becomes one `transactions.p<K>` channel per
    # partition, partition id stamped in headers) and the worker side to
    # per-partition epoch cycles — each shard process (identity from
    # APM_SHARD_ID, or fleet.shardId for embedders) consumes the partition
    # queues it owns with a per-queue dedup window and its own delta chain.
    # partitionKey picks the stable-hash routing key field of a tx line
    # ("service" | "server"). epochStallSeconds: a shard that has intake
    # (unacked/pending) but no committed epoch for this long reports
    # healthz 503 (the manager /fleet plane degrades with it). Rebalance
    # is the quiesced handoff verified by analysis/protocol/shardmodel.py:
    # ownership of a partition moves only with an empty unacked ledger and
    # carries the partition queue's dedup-window ids + the partition's
    # state rows (WorkerApp.release_partition / adopt_partition).
    # partitions decouples the keyspace grain from the process count
    # (P >= N): 0 means auto (4x shards, min 1 per shard), so a rebalance
    # moves a fine slice instead of half a shard's keyspace. Boot
    # ownership is striped (partition p -> shard p % N). controlDir, when
    # set, makes each fleet worker poll a durable per-shard control file
    # (shard<k>.ctl.json, tmp+rename, seq-numbered) for release/adopt
    # commands — the channel the rebalance controller drives; commands
    # survive kill -9 of either side and are re-executed on restart.
    # rebalance.* is the automatic controller policy (parallel/
    # rebalancer.py, pre-verified as a transition system in
    # analysis/protocol/shardmodel.py policy mode): enabled freezes/
    # unfreezes the whole plane (moves stop, observation continues);
    # highWatermark/lowWatermark bound donor/recipient lag (messages) for
    # a move to qualify; the hysteresis band requires the donor-recipient
    # gap to STRICTLY exceed the moved partition's lag; cooldownSeconds
    # enforces at most one move per window (the anti-storm clause);
    # movesPerPartition is the per-partition budget between touches (the
    # anti-oscillation clause); intervalSeconds is the observe/decide
    # cadence; moveTimeoutSeconds bounds one release->adopt handoff
    # before the controller aborts it (the releaser re-adopts its own
    # export).
    "fleet": {
        "shards": 0,
        "partitions": 0,
        "partitionKey": "service",
        "shardId": None,
        "epochStallSeconds": 300.0,
        "controlDir": None,
        "rebalance": {
            "enabled": False,
            "highWatermark": 64,
            "lowWatermark": 16,
            "cooldownSeconds": 30.0,
            "movesPerPartition": 1,
            "intervalSeconds": 5.0,
            "moveTimeoutSeconds": 60.0,
        },
    },
    # TPU-native engine settings (no reference equivalent: this is the device
    # configuration for the batched step function that replaces the per-message
    # stream_calc_stats/z_score/process_alerts event loops).
    "tpuEngine": {
        "logFilePrefix": "tpu_worker",
        "serviceCapacity": 1024,  # static [S] rows; grows by power-of-2 recompile
        "samplesPerBucket": 128,  # per-key per-bucket elapsed sample capacity
        "meshAxis": "services",
        "dtype": "float32",
        # Storage dtype of the z-score lag rings — the engine's dominant HBM
        # buffer. "bfloat16" halves that read traffic per tick (statistics
        # still accumulate in `dtype`; ~0.4% relative rounding on stored
        # values). "" / unset = same as `dtype`.
        "zscoreRingDtype": "",
        # HBM watchdog (device-side analog of the manager's RSS watchdog):
        # manager-alert when bytes_in_use/bytes_limit crosses this fraction
        "deviceMemoryAlarmFraction": 0.9,
        # z-score window variance: "auto" (one ring pass via shifted sumsq in
        # f32 — ~1.4x the dominant reduce, <=1e-5 relative var error; f64
        # parity mode always keeps the exact two-pass), "one", or "two".
        "zscoreVariancePass": "auto",
        "checkpointDir": "save/tpu_engine",
        "resumeFileFullPath": "save/tpu_engine.resume.npz",
        # Checkpoint representation (DESIGN.md §7.4): "full" = one atomic
        # npz snapshot per save (state-size-proportional, the pre-delta
        # behavior); "delta" = incremental delta-chain commits under
        # checkpointChainDir — each epoch appends only the rows/columns
        # touched since the last commit (ingest-rate-proportional, the
        # sub-second-epoch mode), with a full-snapshot compaction rewritten
        # off the hot path every checkpointCompactEveryEpochs commits.
        # checkpointFsync hardens segment/manifest renames against power
        # loss (SIGKILL safety needs only the atomic rename). Write failures
        # (ENOSPC/EIO) retry with decorrelated jitter between
        # checkpointWriteRetryBaseSeconds and checkpointWriteRetryMaxSeconds;
        # after checkpointWriteMaxRetries consecutive failures the worker
        # degrades: flight bundle, operator alert, intake paused until a
        # write lands (healthz 503, apm_checkpoint_degraded). In fleet mode
        # (fleet.shards > 0) checkpointChainDir / resumeFileFullPath /
        # protocolEventLog may carry a "{shard}" placeholder, substituted
        # with the shard id so N shards of one shared config file get
        # disjoint chains (per-shard chain dirs are the handoff unit the
        # rebalance protocol moves ownership between).
        "checkpointMode": "full",
        "checkpointChainDir": "save/tpu_engine.chain",
        "checkpointCompactEveryEpochs": 64,
        "checkpointFsync": True,
        "checkpointWriteMaxRetries": 5,
        "checkpointWriteRetryBaseSeconds": 0.5,
        "checkpointWriteRetryMaxSeconds": 30.0,
        "microBatchSize": 65536,
        # Tick executor selection (DESIGN.md §1): "auto" size-gates the fused
        # single-dispatch program vs the staged pipeline; force with
        # "fused"/"staged". percentileImpl "auto" uses the native radix/
        # nth_element host kernel when the toolchain built it ("native"/
        # "device" force). zscoreRebuildEvery is the staggered sliding-agg
        # rebuild cadence in ticks.
        "tickExecutor": "auto",
        "percentileImpl": "auto",
        "zscoreRebuildEvery": 64,
        # host intake: C++ TxDecoder batch CSV decode (nativeDecode), SPSC
        # byte ring between transport consumer and device loop
        # (useNativeRing/ringBytes), bounded Python-list overflow when the
        # ring is full (intakeOverflowMaxLines after blocking up to
        # ringFullMaxBlockSeconds)
        "nativeDecode": True,
        "useNativeRing": True,
        "ringBytes": 4194304,
        "intakeOverflowMaxLines": 200000,
        "ringFullMaxBlockSeconds": 2.0,
        # frame intake (transport.frameMode producers): True decodes APF1
        # frame batches straight into the columnar ingest path
        # (PipelineDriver.feed_frames — no per-line Python); False unfolds
        # each batch back into lines at the feed boundary (compat path,
        # same records either way)
        "feedFrames": True,
        # double-buffered emission overlap (catch-up aware; r6)
        "asyncEmission": False,
        # per-module profiling harness keys (honored in EVERY module section,
        # like metricsPort; listed once here for the schema): SIGUSR2 /
        # MemoryError heap snapshots into heapSnapshotDir, optional JAX
        # profiler server on profilerPort, tracemalloc via traceAllocations
        "heapSnapshotDir": "logs",
        "profilerPort": None,
        "traceAllocations": False,
        # Delivery guarantee (DESIGN.md §7): "atMostOnce" = reference parity,
        # ack on receipt, in-flight loss bounded by the resume cadence.
        # "atLeastOnce" = manual acks committed only after the engine
        # checkpoint that absorbed them (epoch cycle, runtime/worker.py);
        # redeliveries deduped by msg_id against a window of this many
        # recently absorbed ids persisted inside every snapshot.
        "deliveryMode": "atMostOnce",
        "dedupWindowSize": 65536,
        # Protocol event log (DESIGN.md §9.4 trace conformance): when set
        # to a path, the worker appends one JSONL event per protocol step
        # (recover/deliver/feed/checkpoint/ack/compact); the model
        # checker's conformance tier replays the log as a path of the
        # at-least-once + delta-chain models. Off in production unless a
        # protocol flight log is wanted — cost is one json.dumps + write
        # per delivery.
        "protocolEventLog": None,
        # at-least-once intake batching: accepted deliveries buffer up to
        # this many lines and reach the engine as one bulk feed (the native
        # CSV decode path) instead of per-message object feeds; drained on
        # batch-full, on deliveryFeedMaxDelaySeconds, and always before an
        # epoch checkpoint (token<->effect alignment preserved).
        "deliveryBatchSize": 256,
        "deliveryFeedMaxDelaySeconds": 0.25,
        # mirror StatEntry/FullStatEntry lines onto the reference's 'stats' /
        # 'z_score' queues for per-stage inspection and interop (SURVEY.md §4)
        "emitStatsQueue": False,
        "emitZScoreQueue": False,
        "metricsPort": None,  # telemetry exporter port (0 = ephemeral)
        # Multi-window EWMA/seasonal baselining channels beside the lag
        # windows (no reference equivalent; SURVEY.md §7.2 step 10). Keys are
        # uppercase like streamCalcZScore.defaults. SEASON_SLOTS=24 +
        # SLOT_INTERVALS=360 keeps one baseline per UTC hour-of-day at the
        # stock 10 s cadence; CHANNEL_ID is the (negative) wire 'lag';
        # TREND_BETA > 0 upgrades the channel to a Holt (level+trend)
        # baseline that tracks legitimately-ramping services instead of
        # letting the flat EWMA's variance inflate around the ramp residual
        # and mask real regressions.
        "ewmaChannels": [],
        # Per-service THRESHOLD/INFLUENCE overrides for the EWMA-family
        # channels, keyed service -> channel id (the streamCalcZScore
        # .overrides shape extended to these channels):
        #   {"services": {"getOffers": {"-1": {"THRESHOLD": 2.0}}}}
        "ewmaChannelOverrides": {"services": {}},
    },
}


def main(argv=None) -> int:
    """``python -m apmbackend_tpu config [path]``: print (or write) the full
    default config as ``//``-commented JSON — the starting point a reference
    deployment edits, schema-compatible with its apm_config.json."""
    import argparse
    import sys

    ap = argparse.ArgumentParser(prog="apmbackend_tpu config")
    ap.add_argument("path", nargs="?", help="write here instead of stdout")
    args = ap.parse_args(argv)
    text = (
        "// apmbackend_tpu configuration (apm_config.json schema).\n"
        "// JSON with //-comment lines; hot-reloaded with debounce while the\n"
        "// pipeline runs. TPU-engine settings live under \"tpuEngine\".\n"
        + json.dumps(default_config(), indent=2)
        + "\n"
    )
    if args.path:
        with open(args.path, "w", encoding="utf-8") as fh:
            fh.write(text)
    else:
        sys.stdout.write(text)
    return 0
