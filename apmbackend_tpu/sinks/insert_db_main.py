"""DB sink module process (stream_insert_db.js role).

Consumes the ``db_insert`` queue, buffers per entry type, batch-inserts via
the configured executor. Honors the pause/resume backpressure events (stops
and restarts consumption like the reference's qm 'pause'/'resume' handlers),
saves un-inserted buffers to a resume file on shutdown and loads them on boot.
"""

from __future__ import annotations

from typing import Optional

from ..transport.memory import MemoryBroker
from ..utils.counters import DBStats
from .db import DBWriter, make_executor


def build(runtime) -> DBWriter:
    """Wire the sink onto an existing ModuleRuntime (shared by main() and the
    single-process standalone pipeline)."""
    cfg = runtime.module_config
    db_stats = DBStats()
    writer = DBWriter(
        make_executor(cfg),
        cfg,
        db_stats=db_stats,
        logger=runtime.logger,
    )
    from ..obs import telemetry_active

    if getattr(runtime, "telemetry", None) is not None or telemetry_active():
        from ..obs.views import register_db_stats

        register_db_stats(db_stats, "streamInsertDb")
    resume_path = cfg.get("bufferResumeFileFullPath")
    if resume_path:
        writer.load_resume(resume_path)

    in_queue = runtime.qm.get_queue(
        runtime.config.get("dbInsertQueue", "db_insert"), "c", writer.consume_line
    )
    if cfg.get("consumeQueue", True):
        in_queue.start_consume()

    runtime.qm.on("pause", in_queue.stop_consume)
    runtime.qm.on("resume", lambda: in_queue.start_consume() if cfg.get("consumeQueue", True) else None)

    interval = int(runtime.config.get("statLogIntervalInSeconds", 60))
    runtime.every(interval, lambda: runtime.logger.info(db_stats.snapshot_and_reset()),
                  name="dbstats-log", align=True)

    def _exit():
        writer.close(flush=True)
        if resume_path:
            writer.save_resume(resume_path)

    runtime.on_exit(_exit)
    return writer


def main(config_path: Optional[str] = None, broker: Optional[MemoryBroker] = None) -> None:
    from ..runtime.module_base import ModuleRuntime

    runtime = ModuleRuntime("streamInsertDb", config_path=config_path, broker=broker)
    build(runtime)
    runtime.logger.info("DB sink started")
    runtime.run_forever()


if __name__ == "__main__":
    main()
