"""Batched DB sink.

Role parity with the reference's terminal stage (stream_insert_db.js):

- per-entry-type buffers keyed by the 2-char tag (``tx``/``fs``/``al``/``jx``;
  plain ``st`` entries are rejected just like consumeMsg does,
  stream_insert_db.js:355-376),
- flush when a buffer reaches ``dbInsertBufferLimit`` records or when
  ``dbMaxTimeBetweenInsertsMs`` elapses since the first record entered an
  empty buffer (stream_insert_db.js:329-353; config/apm_config.json:230-231),
- one multi-row INSERT per flush (the pg-promise ``helpers.insert`` role,
  stream_insert_db.js:298-302),
- on insert failure the drained rows are pushed back onto the FRONT of the
  live buffer — ahead of anything that arrived during the attempt — giving the
  same retry-forever, order-preserving semantics as the unshift loop at
  stream_insert_db.js:310-320,
- un-inserted buffers survive restarts via a JSON resume file
  (stream_insert_db.js:166, 225; SURVEY.md §5.4).

The executor is pluggable: a fake (in-memory, for tests — the seam the
reference never had), SQLite (stdlib, always available), or Postgres (gated on
a driver being installed; the reference's production target). Executors own
value adaptation (datetime -> ISO-8601, dict -> JSON for the jsonb columns).
"""

from __future__ import annotations

import json
import math
import threading
import time
from datetime import datetime, timezone
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..entries import Entry, EntryFactory
from ..utils.counters import DBStats
from ..utils.resume import load_resume_file, save_resume_file


class ColumnSet:
    """Table name + ordered column list for one entry type
    (pg-promise ColumnSet role, stream_insert_db.js:149-160)."""

    def __init__(self, table: str, columns: Sequence[str]):
        self.table = table
        self.columns = tuple(columns)


def column_sets_from_config(db_config: dict) -> Dict[str, ColumnSet]:
    """The four column sets of getColumnSets (stream_insert_db.js:149-160)."""
    return {
        "tx": ColumnSet(
            db_config.get("dbTxTable", "tx"),
            ("endts", "startts", "server", "service", "logid", "acctnum", "elapsed", "toplevel"),
        ),
        "fs": ColumnSet(
            db_config.get("dbStatTable", "stats"),
            ("timestamp", "server", "service", "tpm", "lag", "stats"),
        ),
        "al": ColumnSet(
            db_config.get("dbAlertTable", "alerts"),
            ("entrytimestamp", "alerttimestamp", "server", "service", "cause", "entry"),
        ),
        "jx": ColumnSet(
            db_config.get("dbJmxTable", "jmx"),
            (
                "timestamp", "server", "dsinusenodes", "dsactivenodes", "dsavailablenodes",
                "heapused", "heapcommitted", "heapmax", "metaused", "metacommitted",
                "metamax", "sysload", "classcnt", "threadcnt", "daemonthreadcnt",
                "beanpoolavailablecnt", "beanpoolcurrentsize", "beanpoolmaxsize",
            ),
        ),
    }


def _iso_z(dt: datetime) -> str:
    return dt.astimezone(timezone.utc).isoformat(timespec="milliseconds").replace("+00:00", "Z")


def _json_datetime_only(o):
    """json.dumps default for jsonb columns: datetimes serialize like
    JSON.stringify'd Dates; anything else stays a LOUD TypeError so corrupt
    objects fail the flush (and re-queue) instead of persisting as reprs."""
    if isinstance(o, datetime):
        return _iso_z(o)
    raise TypeError(f"Object of type {type(o).__name__} is not JSON serializable")


def _adapt(value):
    """Common scalar adaptation: datetime -> ISO-8601 Z (JS Date.toJSON shape),
    dict -> compact JSON (jsonb columns), NaN -> None. Nested dicts may carry
    datetimes of their own (AlertEntry embeds the full triggering entry,
    entries.js:210) — they serialize to the same ISO-Z shape JSON.stringify
    gives a Date."""
    if isinstance(value, datetime):
        return _iso_z(value)
    if isinstance(value, dict):
        return json.dumps(
            value, separators=(",", ":"), allow_nan=False,
            default=_json_datetime_only,
        )
    if isinstance(value, float) and math.isnan(value):
        return None
    return value


class FakeExecutor:
    """In-memory executor for tests: records every batch; can be told to fail."""

    def __init__(self):
        self.tables: Dict[str, List[tuple]] = {}
        self.batches: List[Tuple[str, int]] = []
        self.scripts: List[str] = []
        self.fail = False

    def insert_many(self, cs: ColumnSet, rows: List[dict]) -> None:
        if self.fail:
            raise RuntimeError("injected insert failure")
        table = self.tables.setdefault(cs.table, [])
        for row in rows:
            table.append(tuple(_adapt(row.get(c)) for c in cs.columns))
        self.batches.append((cs.table, len(rows)))

    def execute_script(self, sql: str) -> None:
        self.scripts.append(sql)

    def close(self) -> None:
        pass


class SQLiteExecutor:
    """SQLite executor (stdlib). Tables are created on demand with TEXT-affinity
    columns — SQLite's dynamic typing keeps numerics numeric."""

    def __init__(self, path: str = ":memory:"):
        import sqlite3

        # The writer may flush from its timer thread while the consumer thread
        # adds rows; a single connection guarded by the writer's buffer lock
        # would serialize anyway, but check_same_thread must be off for the
        # timer-thread flush path.
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._created: set = set()
        self._lock = threading.Lock()

    def insert_many(self, cs: ColumnSet, rows: List[dict]) -> None:
        cols = ", ".join(cs.columns)
        ph = ", ".join("?" for _ in cs.columns)
        with self._lock:
            if cs.table not in self._created:
                self._conn.execute(f"CREATE TABLE IF NOT EXISTS {cs.table} ({cols})")
                self._created.add(cs.table)
            self._conn.executemany(
                f"INSERT INTO {cs.table} ({cols}) VALUES ({ph})",
                [tuple(_adapt(r.get(c)) for c in cs.columns) for r in rows],
            )
            self._conn.commit()

    def execute_script(self, sql: str) -> None:
        """Run provisioning DDL (tools/schema.py) on this backend."""
        with self._lock:
            self._conn.executescript(sql)
            self._conn.commit()

    def close(self) -> None:
        with self._lock:
            self._conn.close()


class PostgresExecutor:  # pragma: no cover - requires a driver + live server
    """Postgres executor (the production target, stream_insert_db.js:133-143).

    Gated: constructed only when a driver (psycopg2 or pg8000) is importable.
    One multi-row INSERT per call, matching the pg-promise helpers.insert path.
    """

    def __init__(self, *, user: str, host: str, database: str, password: Optional[str] = None, port: int = 5432):
        driver = None
        try:
            import psycopg2  # type: ignore

            driver = "psycopg2"
            self._conn = psycopg2.connect(
                user=user, host=host, dbname=database, password=password, port=port
            )
        except ImportError:
            try:
                import pg8000.native  # type: ignore

                driver = "pg8000"
                self._conn = pg8000.native.Connection(
                    user, host=host, database=database, password=password, port=port
                )
            except ImportError:
                raise RuntimeError(
                    "No Postgres driver available (psycopg2/pg8000); "
                    "use dbBackend 'sqlite' or 'fake'"
                )
        self._driver = driver
        self._lock = threading.Lock()

    def insert_many(self, cs: ColumnSet, rows: List[dict]) -> None:
        cols = ", ".join(cs.columns)
        values = [tuple(_adapt(r.get(c)) for c in cs.columns) for r in rows]
        with self._lock:
            if self._driver == "psycopg2":
                ph = ", ".join("%s" for _ in cs.columns)
                with self._conn.cursor() as cur:
                    cur.executemany(f"INSERT INTO {cs.table} ({cols}) VALUES ({ph})", values)
                self._conn.commit()
            else:
                ph = ", ".join(f":p{i}" for i in range(len(cs.columns)))
                for row in values:
                    self._conn.run(
                        f"INSERT INTO {cs.table} ({cols}) VALUES ({ph})",
                        **{f"p{i}": v for i, v in enumerate(row)},
                    )

    def execute_script(self, sql: str) -> None:
        """Run provisioning DDL (tools/schema.py); driver differences stay here."""
        with self._lock:
            if self._driver == "psycopg2":
                with self._conn.cursor() as cur:
                    cur.execute(sql)
                self._conn.commit()
            else:  # pg8000.native: one statement per run()
                for stmt in sql.split(";"):
                    if stmt.strip():
                        self._conn.run(stmt)

    def close(self) -> None:
        self._conn.close()


def make_executor(db_config: dict):
    """Executor from config ``dbBackend``: fake | sqlite | postgres."""
    backend = db_config.get("dbBackend", "fake")
    if backend == "fake":
        return FakeExecutor()
    if backend == "sqlite":
        return SQLiteExecutor(db_config.get("dbFileFullPath", ":memory:"))
    if backend == "postgres":
        return PostgresExecutor(
            user=db_config.get("dbUser", "prod"),
            host=db_config.get("dbHost", "localhost"),
            database=db_config.get("dbDatabase", "apm"),
            password=db_config.get("dbPassword"),
            port=int(db_config.get("dbPort", 5432)),
        )
    raise ValueError(f"Unknown dbBackend: {backend!r}")


class DBWriter:
    """Per-type buffering + batch flush around a pluggable executor.

    Thread model: ``add``/``consume_line`` may be called from a consumer
    thread while the flush timer fires on the writer's own daemon thread; a
    single lock guards the buffers, and flushes drain to a temp list first so
    concurrent adds never interleave into a half-written batch (the async race
    the reference comments on at stream_insert_db.js:288-301).
    """

    REJECTED_TYPES = ("st",)

    def __init__(
        self,
        executor,
        db_config: dict,
        *,
        db_stats: Optional[DBStats] = None,
        logger=None,
        clock: Callable[[], float] = time.monotonic,
        start_timer: bool = True,
    ):
        self.executor = executor
        self.column_sets = column_sets_from_config(db_config)
        self.buffer_limit = int(db_config.get("dbInsertBufferLimit", 1000))
        self.max_ms = float(db_config.get("dbMaxTimeBetweenInsertsMs", 5000))
        self.db_stats = db_stats
        self.logger = logger
        self.clock = clock
        self._factory = EntryFactory()
        self._lock = threading.RLock()
        self._buffers: Dict[str, List[dict]] = {t: [] for t in self.column_sets}
        # Deadline per type, armed on first insert into an empty buffer
        # (stream_insert_db.js:332-343); None = disarmed.
        self._deadlines: Dict[str, Optional[float]] = {t: None for t in self.column_sets}
        self._stop = threading.Event()
        self._wake = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # trace plane: close sampled db_insert-queue trace contexts with a
        # "sink" span at buffer absorb (the last hop of the db_insert leg)
        from ..obs.attrib import STAGE_SINK_ABSORB, get_attrib
        from ..obs.trace import get_tracer

        self._obs_tracer = get_tracer()
        # wall-clock attribution (obs.attrib): insert flushes double as the
        # sink_absorb stage's busy time — same perf_counter pair DBStats
        # already pays
        self._att_absorb = get_attrib().clock(STAGE_SINK_ABSORB)
        if start_timer:
            self._thread = threading.Thread(target=self._timer_loop, daemon=True, name="dbwriter-timer")
            self._thread.start()

    # -- intake --------------------------------------------------------------
    def consume_line(self, line: str, headers: Optional[dict] = None) -> None:
        """CSV line off the db_insert queue (consumeMsg, stream_insert_db.js:355-376)."""
        entry = self._factory.from_csv(line)
        if entry is None:
            if self.logger:
                self.logger.info(f"Entry undefined: {line}")
            return
        if headers:
            tid = headers.get("trace_id")
            if tid is not None:
                # sampled message: mark the sink absorb under its trace_id
                now = time.time()
                start = headers.get("ingest_ts")
                self._obs_tracer.span(
                    tid, "sink",
                    now if start is None else float(start), now,
                    entry_type=entry.type,
                )
        self.add_entry(entry)

    def add_entry(self, entry: Entry) -> None:
        if entry.type not in self.column_sets:
            if self.logger:
                self.logger.info(f"Not a tx, fs, al, or jx: {entry}")
            return
        try:
            obj = entry.to_postgres()
        except Exception as e:
            if self.logger:
                self.logger.error(f"to_postgres for type:{entry.type} threw an error: {e}")
            return
        self.add(entry.type, obj)

    def add(self, etype: str, obj: dict) -> None:
        with self._lock:
            flush_now = len(self._buffers[etype]) >= self.buffer_limit
        # Reference order: flush the full buffer first, then append
        # (stream_insert_db.js:345-352).
        if flush_now:
            self.process_buffer(etype)
        with self._lock:
            self._buffers[etype].append(obj)
            # arm whenever no deadline is pending — covers both the
            # first-insert-into-empty-buffer case and the row that lands
            # right after a limit-triggered flush disarmed the timer
            if self._deadlines[etype] is None:
                self._deadlines[etype] = self.clock() + self.max_ms / 1000.0
                self._wake.set()

    # -- flush ---------------------------------------------------------------
    def process_buffer(self, etype: str) -> bool:
        """Flush one type's buffer. Returns True when the insert succeeded
        (or the buffer was empty)."""
        with self._lock:
            drained = self._buffers[etype]
            if not drained:
                return True
            self._buffers[etype] = []
            self._deadlines[etype] = None
        start = time.perf_counter()
        try:
            self.executor.insert_many(self.column_sets[etype], drained)
        except Exception as e:
            if self.logger:
                self.logger.error(f"Error during insert attempt: {e}")
            with self._lock:
                # Drained rows go back in FRONT of anything newer
                # (stream_insert_db.js:310-320) and the timeout re-arms so
                # retry happens even if no new rows arrive.
                self._buffers[etype] = drained + self._buffers[etype]
                if self._deadlines[etype] is None:
                    self._deadlines[etype] = self.clock() + self.max_ms / 1000.0
                    self._wake.set()
            return False
        elapsed = time.perf_counter() - start
        if self.db_stats is not None:
            self.db_stats.add_inserted(len(drained))
            self.db_stats.add_elapsed_ms(elapsed * 1000.0)
        self._att_absorb.add_busy(elapsed)
        return True

    def process_all(self) -> None:
        """Flush everything (processAllBuffers, on shutdown)."""
        for etype in self.column_sets:
            self.process_buffer(etype)

    def process_due(self, now: Optional[float] = None) -> List[str]:
        """Flush every buffer whose deadline has passed; returns flushed types."""
        now = self.clock() if now is None else now
        due = []
        with self._lock:
            for etype, deadline in self._deadlines.items():
                if deadline is not None and now >= deadline:
                    due.append(etype)
        for etype in due:
            self.process_buffer(etype)
        return due

    def _timer_loop(self) -> None:
        while not self._stop.is_set():
            with self._lock:
                deadlines = [d for d in self._deadlines.values() if d is not None]
            if not deadlines:
                self._wake.wait(timeout=1.0)
                self._wake.clear()
                continue
            wait = min(deadlines) - self.clock()
            if wait > 0:
                self._wake.wait(timeout=wait)
                self._wake.clear()
                continue
            self.process_due()

    # -- resume (§5.4) -------------------------------------------------------
    def buffered_counts(self) -> Dict[str, int]:
        with self._lock:
            return {t: len(b) for t, b in self._buffers.items()}

    def save_resume(self, path: str) -> None:
        with self._lock:
            payload = {t: [self._resume_row(r) for r in b] for t, b in self._buffers.items()}
        save_resume_file(path, payload, logger=self.logger)

    @classmethod
    def _resume_row(cls, value):
        """Recursive datetime -> ISO adaptation ('al' rows nest an entry dict
        that itself contains datetimes)."""
        if isinstance(value, datetime):
            return _adapt(value)
        if isinstance(value, dict):
            return {k: cls._resume_row(v) for k, v in value.items()}
        if isinstance(value, list):
            return [cls._resume_row(v) for v in value]
        return value

    def load_resume(self, path: str) -> bool:
        data = load_resume_file(path, logger=self.logger)
        if not isinstance(data, dict):
            return False
        with self._lock:
            for etype in self.column_sets:
                rows = data.get(etype)
                if isinstance(rows, list) and rows:
                    self._buffers[etype] = list(rows) + self._buffers[etype]
                    if self._deadlines[etype] is None:
                        self._deadlines[etype] = self.clock() + self.max_ms / 1000.0
            self._wake.set()
        return True

    # -- lifecycle -----------------------------------------------------------
    def close(self, *, flush: bool = True) -> None:
        self._stop.set()
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
        if flush:
            self.process_all()
        self.executor.close()
