"""Terminal sinks: batched DB writer (stream_insert_db.js role) and the
outbound adapters it feeds (Postgres/SQLite/fake executors)."""

from .db import (  # noqa: F401
    ColumnSet,
    DBWriter,
    FakeExecutor,
    PostgresExecutor,
    SQLiteExecutor,
    column_sets_from_config,
    make_executor,
)
