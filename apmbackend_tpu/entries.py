"""Typed record schema + pipe-delimited CSV wire format.

Wire-compatible with the reference's entries.js so the two systems interoperate
on the same broker queues:

- ``TxEntry``      ``tx|server|service|logId|acctNum|startTs|endTs|elapsed|topLevel``
  (entries.js:19)
- ``StatEntry``    ``st|ts|server|service|tpm|avg|p75|p95`` (entries.js:72)
- ``FullStatEntry````fs|ts|server|service|lag|tpm|avg:avgAvg:avgLB:avgUB:sig|...``
  (entries.js:117) — note the *average* signal is serialized as a bare int while
  the per75/per95 signals go through nf() and render as ``1.0``/``0.0``; kept.
- ``AlertEntry``   ``al|alertTs|entryTs|server|service|cause|entry-with-&``
  (entries.js:215) — the nested entry's pipes are re-delimited to ``&``.
- ``JmxEntry``     ``jx|ts|server|<16 numeric fields>`` (entries.js:307)

Numeric-quirk parity: JS ``parseInt``/``parseFloat`` return NaN for empty or
non-numeric strings, and the reference's ``nf()`` (entries.js:65-69) formats
NaN/undefined as the literal string ``undefined`` — which parses back to NaN.
``js_to_fixed`` mirrors Number.prototype.toFixed (round-half-toward-+inf on the
exact binary value) so CSV output is byte-identical to the reference's.
"""

from __future__ import annotations

import math
import re
from dataclasses import dataclass
from datetime import datetime, timezone
from decimal import Decimal, ROUND_HALF_UP, ROUND_HALF_DOWN
from typing import Optional, Union

NAN = float("nan")

_NUM_PREFIX_INT = re.compile(r"^\s*[+-]?\d+")
_NUM_PREFIX_FLOAT = re.compile(r"^\s*[+-]?(\d+\.?\d*|\.\d+)([eE][+-]?\d+)?")


def js_parse_int(value) -> float:
    """JS parseInt: leading integer prefix or NaN. Returns float to carry NaN.

    Exact-type fast paths first: entry construction runs these parsers on
    every numeric field at intake rates (~1.5M calls per replay run), and
    the common case is an already-numeric Python float/int. ``type() is``
    beats the isinstance chain and — unlike isinstance(int) — cannot be
    fooled by bool (a bool subclasses int but must parse to NaN)."""
    t = type(value)
    if t is float:
        if value != value or math.isinf(value):  # NaN or +-inf
            return NAN
        return float(int(value))
    if t is int:
        return float(value)
    if value is None or t is bool:
        return NAN
    if t is str:
        if value.isascii() and value.isdigit():
            # whole-string ASCII digit run: the regex would match all of it
            # and compute this same float(int(...)) — skip the match
            return float(int(value))
        m = _NUM_PREFIX_INT.match(value)
        return float(int(m.group(0))) if m else NAN
    if isinstance(value, (int, float)):  # numpy scalars & friends
        if isinstance(value, float) and (math.isnan(value) or math.isinf(value)):
            return NAN
        return float(int(value))
    m = _NUM_PREFIX_INT.match(str(value))
    return float(int(m.group(0))) if m else NAN


def js_parse_float(value) -> float:
    """JS parseFloat: leading float prefix or NaN (same fast-path note as
    js_parse_int)."""
    t = type(value)
    if t is float:
        return value
    if t is int:
        return float(value)
    if value is None or t is bool:
        return NAN
    if isinstance(value, (int, float)):  # numpy scalars & friends
        return float(value)
    s = str(value)
    m = _NUM_PREFIX_FLOAT.match(s)
    if m:
        return float(m.group(0))
    m = re.match(r"^\s*([+-]?)Infinity", s)
    if m:
        return float("-inf") if m.group(1) == "-" else float("inf")
    return NAN


def js_to_fixed(num: float, digits: int = 1) -> str:
    """Number.prototype.toFixed: nearest decimal with f digits; on an exact tie
    the *larger* n is chosen (ECMA-262 Number.prototype.toFixed step 10.c)."""
    if math.isnan(num):
        return "NaN"
    if math.isinf(num):
        return "Infinity" if num > 0 else "-Infinity"
    d = Decimal(num)  # exact binary value
    q = Decimal(1).scaleb(-digits)
    rounding = ROUND_HALF_UP if num >= 0 else ROUND_HALF_DOWN  # "larger n" => toward +inf
    out = d.quantize(q, rounding=rounding)
    if out == 0 and num == 0:
        out = abs(out)  # (0).toFixed/( -0).toFixed give "0.0"; keep "-0.0" for x<0
    return f"{out:.{digits}f}"


def nf(num: Optional[float], digits: int = 1) -> str:
    """Reference nf(): falsy-but-not-zero (NaN/None) -> 'undefined' (entries.js:65-69)."""
    if num is None or (isinstance(num, float) and math.isnan(num)):
        return "undefined"
    return js_to_fixed(float(num), digits)


def _num_str(value: float) -> str:
    """Bare `${num}` interpolation: NaN -> 'NaN', integral floats without '.0'."""
    if isinstance(value, float):
        if math.isnan(value):
            return "NaN"
        if value.is_integer():
            return str(int(value))
    return str(value)


def _ms_to_dt(ms: float) -> Optional[datetime]:
    if ms is None or (isinstance(ms, float) and math.isnan(ms)):
        return None
    return datetime.fromtimestamp(ms / 1000.0, tz=timezone.utc)


@dataclass
class TxEntry:
    """One completed transaction (entries.js:1-43)."""

    server: str
    service: str
    log_id: str
    acct_num: float  # NaN when unknown
    start_ts: float  # ms
    end_ts: float  # ms
    elapsed: float  # ms
    top_level: str  # 'Y' | 'N'
    type: str = "tx"

    def __post_init__(self):
        self.acct_num = js_parse_int(self.acct_num)
        self.start_ts = js_parse_int(self.start_ts)
        self.end_ts = js_parse_int(self.end_ts)
        self.elapsed = js_parse_int(self.elapsed)

    def to_csv(self) -> str:
        return (
            f"tx|{self.server}|{self.service}|{self.log_id}|{_num_str(self.acct_num)}|"
            f"{_num_str(self.start_ts)}|{_num_str(self.end_ts)}|{_num_str(self.elapsed)}|{self.top_level}"
        )

    def to_postgres(self) -> dict:
        return {
            "endts": _ms_to_dt(self.end_ts),
            "startts": _ms_to_dt(self.start_ts),
            "server": self.server,
            "service": self.service,
            "logid": self.log_id,
            "acctnum": None if math.isnan(self.acct_num) else int(self.acct_num),
            "elapsed": None if math.isnan(self.elapsed) else int(self.elapsed),
            "toplevel": self.top_level,
        }


_MAX_SAFE_INT = 1 << 53  # beyond this, float(int) rounds and str(v) would drift


def _csv_num(v) -> str:
    """``_num_str(js_parse_int(v))`` with exact-type fast paths for the two
    shapes the frame emitter actually passes: Python ints inside the float53
    window render as themselves, and short ASCII digit strings (<= 15 digits
    stays exact through the float round-trip) render as the zero-stripped
    run. Anything else — signs, whitespace, huge digit runs, NaN — takes
    the full coercion so the bytes cannot drift from TxEntry.to_csv."""
    t = type(v)
    if t is int:
        if -_MAX_SAFE_INT <= v <= _MAX_SAFE_INT:
            return str(v)
    elif t is str and 0 < len(v) <= 15 and v.isascii() and v.isdigit():
        return v.lstrip("0") or "0"
    return _num_str(js_parse_int(v))


def format_tx_line(server, service, log_id, acct_num,
                   start_ts, end_ts, elapsed, top_level) -> str:
    """``TxEntry(...).to_csv()`` without the TxEntry — the frame-emission
    fast path of the zero-object byte spine. Byte-identical to the
    dataclass route (pinned by tests/test_parser_native_diff.py): every
    numeric field takes the same js_parse_int coercion (or a proven-equal
    fast path, see _csv_num), then the same bare `${num}` rendering."""
    return (
        f"tx|{server}|{service}|{log_id}|{_csv_num(acct_num)}|"
        f"{_csv_num(start_ts)}|{_csv_num(end_ts)}|"
        f"{_csv_num(elapsed)}|{top_level}"
    )


@dataclass
class StatEntry:
    """Windowed TPM/avg/p75/p95 for one (server, service) (entries.js:52-84)."""

    timestamp: float
    server: str
    service: str
    tpm: float
    average: float
    per75: float
    per95: float
    type: str = "st"

    def __post_init__(self):
        self.timestamp = js_parse_int(self.timestamp)
        self.tpm = js_parse_float(self.tpm)
        self.average = js_parse_float(self.average)
        self.per75 = js_parse_float(self.per75)
        self.per95 = js_parse_float(self.per95)

    def to_csv(self) -> str:
        return (
            f"st|{_num_str(self.timestamp)}|{self.server}|{self.service}|"
            f"{nf(self.tpm, 2)}|{nf(self.average)}|{nf(self.per75)}|{nf(self.per95)}"
        )


@dataclass
class FullStatEntry:
    """StatEntry + per-lag z-score bands/signals (entries.js:86-152)."""

    timestamp: float
    server: str
    service: str
    tpm: float
    lag: Union[int, str]
    average: float
    average_avg: float
    average_lb: float
    average_ub: float
    average_signal: float
    per75: float
    per75_avg: float
    per75_lb: float
    per75_ub: float
    per75_signal: float
    per95: float
    per95_avg: float
    per95_lb: float
    per95_ub: float
    per95_signal: float
    type: str = "fs"

    def __post_init__(self):
        # unrolled (no setattr/getattr loop): FullStatEntry construction is
        # the per-row hot path of every tick's emission fan-out
        self.timestamp = js_parse_int(self.timestamp)
        self.tpm = js_parse_float(self.tpm)
        self.average = js_parse_float(self.average)
        self.average_avg = js_parse_float(self.average_avg)
        self.average_lb = js_parse_float(self.average_lb)
        self.average_ub = js_parse_float(self.average_ub)
        self.per75 = js_parse_float(self.per75)
        self.per75_avg = js_parse_float(self.per75_avg)
        self.per75_lb = js_parse_float(self.per75_lb)
        self.per75_ub = js_parse_float(self.per75_ub)
        self.per95 = js_parse_float(self.per95)
        self.per95_avg = js_parse_float(self.per95_avg)
        self.per95_lb = js_parse_float(self.per95_lb)
        self.per95_ub = js_parse_float(self.per95_ub)
        self.average_signal = js_parse_int(self.average_signal)
        self.per75_signal = js_parse_int(self.per75_signal)
        self.per95_signal = js_parse_int(self.per95_signal)

    def _sig_str(self, v: float) -> str:
        return "NaN" if math.isnan(v) else str(int(v))

    def to_csv(self) -> str:
        # average signal bare; per75/per95 signals via nf() => "1.0"/"0.0"
        # (entries.js:117 interpolates nf(per75Signal) but averageSignal raw).
        return (
            f"fs|{_num_str(self.timestamp)}|{self.server}|{self.service}|{self.lag}|{nf(self.tpm, 2)}|"
            f"{nf(self.average)}:{nf(self.average_avg)}:{nf(self.average_lb)}:{nf(self.average_ub)}:{self._sig_str(self.average_signal)}|"
            f"{nf(self.per75)}:{nf(self.per75_avg)}:{nf(self.per75_lb)}:{nf(self.per75_ub)}:{nf(self.per75_signal)}|"
            f"{nf(self.per95)}:{nf(self.per95_avg)}:{nf(self.per95_lb)}:{nf(self.per95_ub)}:{nf(self.per95_signal)}"
        )

    def to_postgres(self) -> dict:
        def _n(v):
            return None if (isinstance(v, float) and math.isnan(v)) else v

        def _sig(v):
            # Signals are ints in the reference's stats jsonb (entries.js:95-105).
            return None if (isinstance(v, float) and math.isnan(v)) else int(v)

        return {
            "timestamp": _ms_to_dt(self.timestamp),
            "server": self.server,
            "service": self.service,
            "tpm": _n(self.tpm),
            "lag": self.lag,
            "stats": {
                "average": _n(self.average),
                "averageavg": _n(self.average_avg),
                "averagelb": _n(self.average_lb),
                "averageub": _n(self.average_ub),
                "averagesignal": _sig(self.average_signal),
                "per75": _n(self.per75),
                "per75avg": _n(self.per75_avg),
                "per75lb": _n(self.per75_lb),
                "per75ub": _n(self.per75_ub),
                "per75signal": _sig(self.per75_signal),
                "per95": _n(self.per95),
                "per95avg": _n(self.per95_avg),
                "per95lb": _n(self.per95_lb),
                "per95ub": _n(self.per95_ub),
                "per95signal": _sig(self.per95_signal),
            },
        }


@dataclass
class AlertEntry:
    """A raised alert wrapping the offending entry (entries.js:202-241)."""

    alert_timestamp: float
    entry_timestamp: float
    server: str
    service: str
    cause: str
    entry: str  # CSV string of nested entry; pipes re-delimited to '&'

    type: str = "al"

    def __post_init__(self):
        self.alert_timestamp = js_parse_int(self.alert_timestamp)
        self.entry_timestamp = js_parse_int(self.entry_timestamp)
        self.entry = self.entry.replace("|", "&")

    def to_csv(self) -> str:
        return (
            f"al|{_num_str(self.alert_timestamp)}|{_num_str(self.entry_timestamp)}|"
            f"{self.server}|{self.service}|{self.cause}|{self.entry}"
        )

    def to_postgres(self) -> dict:
        nested = EntryFactory().from_csv(self.entry, delim="&")
        return {
            "alerttimestamp": _ms_to_dt(self.alert_timestamp),
            "entrytimestamp": _ms_to_dt(self.entry_timestamp),
            "server": self.server,
            "service": self.service,
            "cause": self.cause,
            "entry": nested.to_postgres() if nested is not None else None,
        }


_JMX_FIELDS = (
    "ds_in_use_nodes", "ds_active_nodes", "ds_available_nodes",
    "heap_used", "heap_committed", "heap_max",
    "meta_used", "meta_committed", "meta_max",
    "sys_load", "class_cnt", "thread_cnt", "daemon_thread_cnt",
    "bean_pool_available_count", "bean_pool_current_size", "bean_pool_max_size",
)


@dataclass
class JmxEntry:
    """One JMX poll sample for a JVM host (entries.js:243-332)."""

    timestamp: float
    server: str
    ds_in_use_nodes: float = NAN
    ds_active_nodes: float = NAN
    ds_available_nodes: float = NAN
    heap_used: float = NAN
    heap_committed: float = NAN
    heap_max: float = NAN
    meta_used: float = NAN
    meta_committed: float = NAN
    meta_max: float = NAN
    sys_load: float = NAN
    class_cnt: float = NAN
    thread_cnt: float = NAN
    daemon_thread_cnt: float = NAN
    bean_pool_available_count: float = NAN
    bean_pool_current_size: float = NAN
    bean_pool_max_size: float = NAN
    type: str = "jx"

    def __post_init__(self):
        self.timestamp = js_parse_int(self.timestamp)
        for name in _JMX_FIELDS:
            parse = js_parse_float if name == "sys_load" else js_parse_int
            setattr(self, name, parse(getattr(self, name)))

    @classmethod
    def from_jmx_stats(cls, timestamp, server: str, stats: dict) -> "JmxEntry":
        """Build from the raw jboss-cli JSON blobs (entries.js:246-273)."""
        return cls(
            timestamp=timestamp,
            server=server,
            ds_in_use_nodes=stats["ds"]["result"]["InUseCount"],
            ds_active_nodes=stats["ds"]["result"]["ActiveCount"],
            ds_available_nodes=stats["ds"]["result"]["AvailableCount"],
            heap_used=stats["heap"]["result"]["used"],
            heap_committed=stats["heap"]["result"]["committed"],
            heap_max=stats["heap"]["result"]["max"],
            meta_used=stats["meta"]["result"]["used"],
            meta_committed=stats["meta"]["result"]["committed"],
            meta_max=stats["meta"]["result"]["max"],
            sys_load=stats["sysload"]["result"],
            class_cnt=stats["classcnt"]["result"],
            thread_cnt=stats["threading"]["result"]["thread-count"],
            daemon_thread_cnt=stats["threading"]["result"]["daemon-thread-count"],
            bean_pool_available_count=stats["bean"]["result"][0]["result"]["pool-available-count"],
            bean_pool_current_size=stats["bean"]["result"][0]["result"]["pool-current-size"],
            bean_pool_max_size=stats["bean"]["result"][0]["result"]["pool-max-size"],
        )

    def to_csv(self) -> str:
        parts = ["jx", _num_str(self.timestamp), self.server]
        parts += [_num_str(getattr(self, name)) for name in _JMX_FIELDS]
        return "|".join(parts)

    def to_postgres(self) -> dict:
        def _n(v):
            if isinstance(v, float) and math.isnan(v):
                return None
            return int(v) if isinstance(v, float) and v.is_integer() else v

        return {
            "timestamp": _ms_to_dt(self.timestamp),
            "server": self.server,
            "dsinusenodes": _n(self.ds_in_use_nodes),
            "dsactivenodes": _n(self.ds_active_nodes),
            "dsavailablenodes": _n(self.ds_available_nodes),
            "heapused": _n(self.heap_used),
            "heapcommitted": _n(self.heap_committed),
            "heapmax": _n(self.heap_max),
            "metaused": _n(self.meta_used),
            "metacommitted": _n(self.meta_committed),
            "metamax": _n(self.meta_max),
            "sysload": self.sys_load if not math.isnan(self.sys_load) else None,
            "classcnt": _n(self.class_cnt),
            "threadcnt": _n(self.thread_cnt),
            "daemonthreadcnt": _n(self.daemon_thread_cnt),
            "beanpoolavailablecnt": _n(self.bean_pool_available_count),
            "beanpoolcurrentsize": _n(self.bean_pool_current_size),
            "beanpoolmaxsize": _n(self.bean_pool_max_size),
        }


Entry = Union[TxEntry, StatEntry, FullStatEntry, AlertEntry, JmxEntry]


class EntryFactory:
    """CSV -> typed entry dispatch on the 2-char tag (entries.js:174-193)."""

    def from_csv(self, line: str, delim: str = "|") -> Optional[Entry]:
        arr = line.split(delim)
        tag = arr[0]
        try:
            if tag == "tx":
                return TxEntry(arr[1], arr[2], arr[3], arr[4], arr[5], arr[6], arr[7], arr[8])
            if tag == "st":
                return StatEntry(arr[1], arr[2], arr[3], arr[4], arr[5], arr[6], arr[7])
            if tag == "fs":
                avg = arr[6].split(":")
                p75 = arr[7].split(":")
                p95 = arr[8].split(":")
                return FullStatEntry(
                    arr[1], arr[2], arr[3], arr[5], arr[4],
                    avg[0], avg[1], avg[2], avg[3], avg[4],
                    p75[0], p75[1], p75[2], p75[3], p75[4],
                    p95[0], p95[1], p95[2], p95[3], p95[4],
                )
            if tag == "al":
                return AlertEntry(arr[1], arr[2], arr[3], arr[4], arr[5], arr[6])
            if tag == "jx":
                return JmxEntry(arr[1], arr[2], *arr[3:19])
        except (IndexError, ValueError):
            return None
        return None
