"""Transport fault injection (chaos) — the testing seam the reference lacked.

The reference's failure story was only ever exercised in production
(SURVEY.md §5.3: "No fault injection exists"); its manual-test seams were
consume toggles and destructive queue peeks. This module makes broker
misbehavior a first-class, DETERMINISTIC test input: wrap any
:class:`~apmbackend_tpu.transport.base.Channel` in a :class:`ChaosChannel`
and inject

- **forced-full windows** (``force_full()`` / ``release()``): ``send()``
  refuses like a broker under memory/disk alarm, driving the real
  pause → buffer → drain → resume stack (queue.js:245-263, 88-106 contract)
  on demand instead of by luck;
- **message drops** (``drop_p``): delivery loss after the ack — the
  at-most-once window the reference accepts (queue.js:277-283);
- **duplicate deliveries** (``dup_p``): broker redelivery, which
  ack-on-receipt consumers see as double-processing.

Randomness is a seeded ``random.Random``: a failing chaos test replays
bit-identically. Counters (:class:`ChaosStats`) expose exactly what was
injected so assertions can account for every message.

This is a *testing* module: production code never constructs it. Wire it by
wrapping the backend factory handed to ``QueueManager``::

    broker = MemoryBroker()
    chaos = ChaosChannel(MemoryChannel(broker), drop_p=0.1, seed=7)
    qm = QueueManager(lambda direction: chaos if direction == "p" else ...)

**Process-level harness** (the kill−9 tier): :class:`SpoolChannel` is a
durable file-backed broker whose consumer cursor only advances on ``ack()``
— at-least-once semantics that survive SIGKILL of the consumer process —
and :class:`ChaosWorkerHarness` spawns a REAL worker subprocess (the
production ``WorkerApp`` epoch cycle, ``deliveryMode: atLeastOnce``) over
such a spool, kills it −9 mid-stream, restarts it, and exposes the final
engine snapshot + delivery stats so tests can assert the recovered run is
EQUAL to a crash-free golden run. The chaos seams compose: the harness can
inject duplicate deliveries (``dup_p``) on top of the kill/restart cycle.
"""

from __future__ import annotations

import json
import os
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from ..transport.base import Channel


@dataclass
class ChaosStats:
    refused_sends: int = 0
    dropped: int = 0
    duplicated: int = 0
    delivered: int = 0
    sent: int = 0
    _lock: threading.Lock = field(default_factory=threading.Lock, repr=False)

    def _bump(self, name: str, n: int = 1) -> None:
        with self._lock:
            setattr(self, name, getattr(self, name) + n)


class ChaosChannel(Channel):
    """Fault-injecting decorator around a real transport Channel."""

    def __init__(
        self,
        inner: Channel,
        *,
        drop_p: float = 0.0,
        dup_p: float = 0.0,
        seed: int = 0,
    ):
        if not (0.0 <= drop_p <= 1.0 and 0.0 <= dup_p <= 1.0):
            raise ValueError("drop_p/dup_p must be probabilities")
        self.inner = inner
        self.drop_p = drop_p
        self.dup_p = dup_p
        self.stats = ChaosStats()
        self._rng = random.Random(seed)
        self._forced_full = False
        self._drain_cbs: List[Callable[[], None]] = []
        # real backend drains propagate through the same callback list the
        # chaos-released drains use
        inner.on_drain(self._fire_drain)

    # -- producer-side faults -------------------------------------------------
    def force_full(self) -> None:
        """Subsequent ``send()`` calls refuse (broker alarm engaged)."""
        self._forced_full = True

    def release(self) -> None:
        """End the forced-full window and fire the drain event, exactly like
        a broker clearing its alarm (connection.unblocked -> drain)."""
        self._forced_full = False
        self._fire_drain()

    def send(self, name: str, payload: bytes, headers=None) -> bool:
        if self._forced_full:
            self.stats._bump("refused_sends")
            return False
        ok = self.inner.send(name, payload, headers)
        if ok:
            self.stats._bump("sent")
        return ok

    # -- consumer-side faults -------------------------------------------------
    def consume(self, name: str, callback: Callable[[bytes], None], consumer_tag: str,
                manual_ack: bool = False) -> None:
        from ..transport.base import accepts_headers

        if manual_ack:

            def chaotic(payload: bytes, headers=None, token=None) -> None:
                # manual-ack semantics under chaos: a DROP leaves the token
                # unacked on the broker ledger (delivery loss before
                # processing — redelivered on close/restart, nothing is
                # silently gone); a DUP replays the same payload+msg_id+token
                # (the consumer's dedup window must catch it; double-acking
                # one token is idempotent by the Channel contract)
                if self.drop_p and self._rng.random() < self.drop_p:
                    self.stats._bump("dropped")
                    return
                self.stats._bump("delivered")
                callback(payload, headers, token)
                if self.dup_p and self._rng.random() < self.dup_p:
                    self.stats._bump("duplicated")
                    self.stats._bump("delivered")
                    callback(payload, headers, token)

            self.inner.consume(name, chaotic, consumer_tag, manual_ack=True)
            return

        wants_headers = accepts_headers(callback)

        def chaotic(payload: bytes, headers=None) -> None:
            # the backend already removed the message (ack-on-receipt): a
            # drop here IS the at-most-once loss window
            deliver = (
                (lambda: callback(payload, headers)) if wants_headers
                else (lambda: callback(payload))
            )
            if self.drop_p and self._rng.random() < self.drop_p:
                self.stats._bump("dropped")
                return
            self.stats._bump("delivered")
            deliver()
            if self.dup_p and self._rng.random() < self.dup_p:
                self.stats._bump("duplicated")
                self.stats._bump("delivered")
                deliver()

        self.inner.consume(name, chaotic, consumer_tag)

    # -- passthrough ----------------------------------------------------------
    def ack(self, tokens) -> None:
        self.inner.ack(tokens)

    def assert_queue(self, name: str) -> None:
        self.inner.assert_queue(name)

    def cancel(self, consumer_tag: str) -> None:
        self.inner.cancel(consumer_tag)

    def on_drain(self, callback: Callable[[], None]) -> None:
        self._drain_cbs.append(callback)

    def close(self) -> None:
        self.inner.close()

    def _fire_drain(self) -> None:
        for cb in list(self._drain_cbs):
            cb()


# ---------------------------------------------------------------------------
# Process-level harness: durable spool broker + kill−9 worker driver
# ---------------------------------------------------------------------------


class _SpoolQueue:
    """Consumer-side view of one spool file: incremental record parsing plus
    the acked-cursor bookkeeping."""

    def __init__(self, directory: str, name: str):
        self.path = os.path.join(directory, f"{name}.spool")
        self.cursor_path = os.path.join(directory, f"{name}.cursor")
        self.records: List[Tuple[bytes, Optional[dict]]] = []
        self._buf = b""
        self._read_off = 0
        self.acked_upto = 0  # records [0, acked_upto) are committed
        self._acked_set: set = set()
        self.next_deliver = 0
        if os.path.exists(self.cursor_path):
            try:
                with open(self.cursor_path, "r", encoding="utf-8") as fh:
                    self.acked_upto = int(json.load(fh)["acked"])
            except Exception:
                self.acked_upto = 0  # torn cursor: redeliver from zero (safe)
        self.next_deliver = self.acked_upto

    def poll(self) -> None:
        """Parse any newly appended COMPLETE records (a concurrently writing
        producer may leave a partial trailing line — it stays buffered)."""
        if not os.path.exists(self.path):
            return
        with open(self.path, "rb") as fh:
            fh.seek(self._read_off)
            chunk = fh.read()
        if not chunk:
            return
        self._read_off += len(chunk)
        self._buf += chunk
        *lines, self._buf = self._buf.split(b"\n")
        for line in lines:
            if not line:
                continue
            try:
                rec = json.loads(line)
                self.records.append((rec["p"].encode("utf-8"), rec.get("h")))
            except Exception:
                # a mangled record is a poison message: skip it rather than
                # wedging the queue forever
                self.records.append((b"", None))

    def ack(self, index: int) -> bool:
        """Mark one record committed; returns True when the contiguous
        cursor advanced (caller persists it)."""
        if index < self.acked_upto:
            return False  # idempotent re-ack
        self._acked_set.add(index)
        advanced = False
        while self.acked_upto in self._acked_set:
            self._acked_set.discard(self.acked_upto)
            self.acked_upto += 1
            advanced = True
        return advanced

    def persist_cursor(self) -> None:
        tmp = self.cursor_path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump({"acked": self.acked_upto}, fh)
        os.replace(tmp, self.cursor_path)


class SpoolChannel(Channel):
    """Durable file-backed broker channel — the kill−9 fabric.

    One append-only JSON-lines spool per queue under ``directory``; the
    consumer's committed cursor lives in ``<queue>.cursor`` and is advanced
    ONLY by ``ack()`` (atomic tmp+rename). SIGKILL the consumer process at
    any instant and a fresh SpoolChannel resumes delivery from the last
    committed cursor — everything delivered-but-unacked is redelivered, the
    exact contract a durable AMQP queue with manual acks provides, minus the
    network. ``send`` appends with flush (the producer/harness process
    survives the chaos, so line-buffered append is durable enough).

    Delivery is pumped (``deliver()`` / ``start_pump_thread``) like the
    memory broker. Ack-on-receipt consumers advance the cursor at delivery;
    manual-ack consumers receive ``(queue, index)`` tokens.
    """

    def __init__(self, directory: str, *, prefetch: int = 100000):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.prefetch = prefetch
        self._queues: Dict[str, _SpoolQueue] = {}
        # (tag, callback, manual) per queue
        self._consumers: Dict[str, Tuple[str, Callable, bool]] = {}
        self._send_fhs: Dict[str, object] = {}
        self._lock = threading.RLock()
        self._drain_cbs: List[Callable[[], None]] = []
        self._pump_thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # -- Channel contract ----------------------------------------------------
    def assert_queue(self, name: str) -> None:
        with self._lock:
            if name not in self._queues:
                self._queues[name] = _SpoolQueue(self.directory, name)

    def send(self, name: str, payload: bytes, headers: Optional[dict] = None) -> bool:
        with self._lock:
            self.assert_queue(name)
            fh = self._send_fhs.get(name)
            if fh is None:
                fh = open(os.path.join(self.directory, f"{name}.spool"), "ab")
                self._send_fhs[name] = fh
            rec = json.dumps({"p": payload.decode("utf-8"), "h": headers})
            fh.write(rec.encode("utf-8") + b"\n")
            fh.flush()
        return True

    def consume(self, name: str, callback: Callable[[bytes], None], consumer_tag: str,
                manual_ack: bool = False) -> None:
        from ..transport.base import accepts_headers

        if not manual_ack and not accepts_headers(callback):
            inner = callback
            callback = lambda payload, _h=None, _cb=inner: _cb(payload)  # noqa: E731
        with self._lock:
            self.assert_queue(name)
            self._consumers[name] = (consumer_tag, callback, manual_ack)

    def cancel(self, consumer_tag: str) -> None:
        with self._lock:
            self._consumers = {
                q: c for q, c in self._consumers.items() if c[0] != consumer_tag
            }

    def ack(self, tokens) -> None:
        with self._lock:
            advanced: set = set()
            for name, index in tokens:
                q = self._queues.get(name)
                if q is not None and q.ack(index):
                    advanced.add(name)
            for name in advanced:
                self._queues[name].persist_cursor()

    def on_drain(self, callback: Callable[[], None]) -> None:
        self._drain_cbs.append(callback)

    def close(self) -> None:
        self.stop()
        with self._lock:
            for fh in self._send_fhs.values():
                try:
                    fh.close()
                except Exception:
                    pass
            self._send_fhs.clear()

    # -- delivery ------------------------------------------------------------
    def deliver(self, max_messages: Optional[int] = None) -> int:
        delivered = 0
        while max_messages is None or delivered < max_messages:
            batch = []
            with self._lock:
                for name, (tag, cb, manual) in self._consumers.items():
                    q = self._queues[name]
                    q.poll()
                    if q.next_deliver >= len(q.records):
                        continue
                    if manual and q.next_deliver - q.acked_upto >= self.prefetch:
                        continue  # unacked ledger at the prefetch bound
                    payload, headers = q.records[q.next_deliver]
                    index = q.next_deliver
                    q.next_deliver += 1
                    if not manual and q.ack(index):
                        q.persist_cursor()
                    batch.append((cb, payload, headers, manual, (name, index)))
            if not batch:
                break
            for cb, payload, headers, manual, token in batch:
                if manual:
                    cb(payload, headers, token)
                else:
                    cb(payload, headers)
                delivered += 1
        return delivered

    def acked_count(self, name: str) -> int:
        with self._lock:
            q = self._queues.get(name)
            return q.acked_upto if q else 0

    def delivered_count(self, name: str) -> int:
        with self._lock:
            q = self._queues.get(name)
            return q.next_deliver if q else 0

    def start_pump_thread(self, poll_s: float = 0.005) -> None:
        if self._pump_thread is not None:
            return

        def _loop():
            while not self._stop.is_set():
                if self.deliver() == 0:
                    self._stop.wait(poll_s)

        self._pump_thread = threading.Thread(target=_loop, name="spool-pump", daemon=True)
        self._pump_thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._pump_thread is not None:
            self._pump_thread.join(timeout=2.0)
            self._pump_thread = None


def read_spool_cursor(directory: str, queue: str) -> int:
    """Committed (acked) record count for ``queue`` — the harness's view of
    a (possibly dead) worker's progress, read straight off disk."""
    path = os.path.join(os.path.abspath(directory), f"{queue}.cursor")
    if not os.path.exists(path):
        return 0
    try:
        with open(path, "r", encoding="utf-8") as fh:
            return int(json.load(fh)["acked"])
    except Exception:
        return 0


class ChaosWorkerHarness:
    """Drive a REAL worker subprocess through kill−9 chaos.

    The child is the production stack — ``WorkerApp`` with ``deliveryMode:
    atLeastOnce`` over a :class:`SpoolChannel` (optionally chaos-wrapped with
    duplicate injection) — launched via ``python -m
    apmbackend_tpu.testing.chaos --child``. The harness appends tx lines to
    the durable spool, watches the committed cursor, SIGKILLs / restarts the
    child at will, and collects the final engine snapshot + delivery stats.

    Crash-equivalence protocol (tests/test_chaos_harness.py): run one
    harness to completion with no kills (golden), another over the same line
    stream with kills + dup chaos, then compare the two final resume
    snapshots array-for-array.
    """

    QUEUE = "transactions"

    def __init__(self, workdir: str, *, dup_p: float = 0.0, seed: int = 0,
                 capacity: int = 64, save_every_s: float = 0.4):
        import sys

        self.workdir = os.path.abspath(workdir)
        self.spool_dir = os.path.join(self.workdir, "spool")
        os.makedirs(self.spool_dir, exist_ok=True)
        self.resume_path = os.path.join(self.workdir, "engine.resume.npz")
        self.stats_path = os.path.join(self.workdir, "stats.json")
        self.done_path = os.path.join(self.workdir, "DONE")
        self.log_path = os.path.join(self.workdir, "child.log")
        self.dup_p = dup_p
        self.seed = seed
        self.capacity = capacity
        self.save_every_s = save_every_s
        # crash flight-recorder bundles (obs/flight): the child journals
        # here on a fast cadence; a kill−9 leaves journal+sentinel behind
        # and the RESTARTED child promotes them into a ...-crash.json bundle
        self.flight_dir = os.path.join(self.workdir, "flight")
        self.python = sys.executable
        self.proc = None
        self.generation = 0
        self._seq = 0
        self._producer = SpoolChannel(self.spool_dir)

    def flight_bundles(self) -> list:
        """(path, parsed body) for every flight bundle the child produced —
        parse errors raise (an unreadable bundle is the bug this asserts on)."""
        from ..obs.flight import list_bundles

        return list_bundles(self.flight_dir)

    # -- stream --------------------------------------------------------------
    def send_line(self, line: str) -> None:
        self._seq += 1
        self._producer.send(
            self.QUEUE, line.encode("utf-8"),
            {"ingest_ts": time.time(), "msg_id": f"h-{self._seq}"},
        )

    @property
    def sent(self) -> int:
        return self._seq

    # -- child lifecycle -----------------------------------------------------
    def start(self):
        import subprocess

        assert self.proc is None or self.proc.poll() is not None
        self.generation += 1
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        env.pop("PYTHONPATH", None)  # no TPU-relay sitecustomize in children
        log_fh = open(self.log_path, "ab")
        self.proc = subprocess.Popen(
            [
                self.python, "-m", "apmbackend_tpu.testing.chaos", "--child",
                "--spool-dir", self.spool_dir,
                "--resume", self.resume_path,
                "--queue", self.QUEUE,
                "--stats-out", self.stats_path,
                "--done-file", self.done_path,
                "--capacity", str(self.capacity),
                "--save-every-s", str(self.save_every_s),
                "--dup-p", str(self.dup_p),
                "--seed", str(self.seed + self.generation),
                "--flight-dir", self.flight_dir,
            ],
            stdout=log_fh, stderr=log_fh, stdin=subprocess.DEVNULL,
            cwd=os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
            env=env,
        )
        log_fh.close()
        return self.proc

    def kill9(self) -> None:
        """SIGKILL: no atexit, no signal handler, no flush — the real thing."""
        import signal as _signal

        if self.proc is not None and self.proc.poll() is None:
            os.kill(self.proc.pid, _signal.SIGKILL)
            self.proc.wait(timeout=30)

    def acked(self) -> int:
        return read_spool_cursor(self.spool_dir, self.QUEUE)

    def wait_acked(self, n: int, timeout_s: float = 120.0) -> int:
        """Block until the committed cursor reaches ``n`` (or timeout); the
        kill-point selector for mid-stream SIGKILLs."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            got = self.acked()
            if got >= n:
                return got
            if self.proc is not None and self.proc.poll() is not None:
                raise RuntimeError(
                    f"chaos child exited rc={self.proc.returncode} before acking {n} "
                    f"(got {got}); see {self.log_path}"
                )
            time.sleep(0.02)
        raise TimeoutError(f"cursor stuck at {self.acked()} < {n}; see {self.log_path}")

    def finish(self, timeout_s: float = 180.0) -> dict:
        """Signal end-of-stream, wait for the child's final epoch commit and
        graceful exit, and return its stats JSON."""
        tmp = self.done_path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump({"total": self._seq}, fh)
        os.replace(tmp, self.done_path)
        rc = self.proc.wait(timeout=timeout_s)
        if rc != 0:
            raise RuntimeError(f"chaos child exit rc={rc}; see {self.log_path}")
        with open(self.stats_path, "r", encoding="utf-8") as fh:
            return json.load(fh)

    def close(self) -> None:
        self.kill9()
        self._producer.close()


def _child_main(argv=None) -> int:
    """The harness child: the production worker epoch cycle over a spool.

    Everything between SpoolChannel and the engine snapshot is the REAL
    production code path — WorkerApp's at-least-once consume/dedup/epoch
    logic, PipelineDriver's checkpoint — not a test double. The only
    harness-specific parts are the spool transport and the DONE/stats
    files."""
    import argparse

    ap = argparse.ArgumentParser(prog="apmbackend_tpu.testing.chaos --child")
    ap.add_argument("--spool-dir", required=True)
    ap.add_argument("--resume", required=True)
    ap.add_argument("--queue", default="transactions")
    ap.add_argument("--stats-out", required=True)
    ap.add_argument("--done-file", required=True)
    ap.add_argument("--capacity", type=int, default=64)
    ap.add_argument("--save-every-s", type=float, default=0.4)
    ap.add_argument("--dup-p", type=float, default=0.0)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--flight-dir", default=None)
    args = ap.parse_args(argv)

    from ..config import default_config
    from ..runtime.module_base import ModuleRuntime
    from ..runtime.worker import WorkerApp
    from ..transport.base import QueueManager

    cfg = default_config()
    eng = cfg["tpuEngine"]
    eng["serviceCapacity"] = args.capacity
    eng["samplesPerBucket"] = 64
    eng["deliveryMode"] = "atLeastOnce"
    eng["resumeFileFullPath"] = args.resume
    eng["metricsPort"] = None
    cfg["streamCalcZScore"]["defaults"] = [{"LAG": 6, "THRESHOLD": 3.0, "INFLUENCE": 0.1}]
    cfg["streamCalcStats"]["inQueue"] = args.queue
    # the resume-save timer IS the epoch cadence: short, so SIGKILLs land at
    # arbitrary points relative to commits
    cfg["streamCalcStats"]["resumeFileSaveFrequencyInSeconds"] = args.save_every_s
    cfg["streamProcessAlerts"]["alertsResumeFileFullPath"] = None
    cfg["logDir"] = None
    if args.flight_dir:
        # crash flight recorder under kill−9: journal on a sub-second
        # cadence so a SIGKILL at any instant leaves a fresh shadow; the
        # restarted child promotes it to a crash bundle at boot. The
        # recorder only READS pipeline state and writes under its own
        # directory — the bit-identical golden comparison is untouched.
        cfg["observability"]["flightDir"] = args.flight_dir
        cfg["observability"]["flightJournalSeconds"] = 0.2

    runtime = ModuleRuntime(
        "tpuEngine", config=cfg, install_signals=True, console_log=True
    )
    spools = {}

    def factory(direction: str):
        ch = SpoolChannel(args.spool_dir)
        spools[direction] = ch
        if direction == "c" and args.dup_p > 0:
            return ChaosChannel(ch, dup_p=args.dup_p, seed=args.seed)
        return ch

    runtime.qm = QueueManager(factory, 3600, logger=runtime.logger)
    worker = WorkerApp(runtime)
    consumer = spools["c"]
    consumer.start_pump_thread()

    total = None
    while True:
        if total is None and os.path.exists(args.done_file):
            try:
                with open(args.done_file, "r", encoding="utf-8") as fh:
                    total = int(json.load(fh)["total"])
            except Exception:
                total = None
        if total is not None and consumer.delivered_count(args.queue) >= total:
            # stream fully delivered: force the final epoch commit and stop
            # once every record is acked (committed)
            worker.save_state()
            if consumer.acked_count(args.queue) >= total:
                break
        time.sleep(0.02)

    consumer.stop()
    worker.shutdown()  # final save_state + ack inside
    stats = {
        "epoch": worker._delivery_epoch,
        "deduped_total": worker._deduped_total,
        "unacked": len(worker._epoch_tokens),
        "acked": consumer.acked_count(args.queue),
        "services": worker.driver.registry.count,
        "latest_label": worker.driver._latest_label,
    }
    tmp = args.stats_out + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(stats, fh)
    os.replace(tmp, args.stats_out)
    runtime.stop_timers()
    return 0


if __name__ == "__main__":
    import sys

    if "--child" in sys.argv:
        sys.argv.remove("--child")
        sys.exit(_child_main(sys.argv[1:]))
    raise SystemExit("usage: python -m apmbackend_tpu.testing.chaos --child ...")
