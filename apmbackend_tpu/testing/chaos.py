"""Transport fault injection (chaos) — the testing seam the reference lacked.

The reference's failure story was only ever exercised in production
(SURVEY.md §5.3: "No fault injection exists"); its manual-test seams were
consume toggles and destructive queue peeks. This module makes broker
misbehavior a first-class, DETERMINISTIC test input: wrap any
:class:`~apmbackend_tpu.transport.base.Channel` in a :class:`ChaosChannel`
and inject

- **forced-full windows** (``force_full()`` / ``release()``): ``send()``
  refuses like a broker under memory/disk alarm, driving the real
  pause → buffer → drain → resume stack (queue.js:245-263, 88-106 contract)
  on demand instead of by luck;
- **message drops** (``drop_p``): delivery loss after the ack — the
  at-most-once window the reference accepts (queue.js:277-283);
- **duplicate deliveries** (``dup_p``): broker redelivery, which
  ack-on-receipt consumers see as double-processing.

Randomness is a seeded ``random.Random``: a failing chaos test replays
bit-identically. Counters (:class:`ChaosStats`) expose exactly what was
injected so assertions can account for every message.

This is a *testing* module: production code never constructs it. Wire it by
wrapping the backend factory handed to ``QueueManager``::

    broker = MemoryBroker()
    chaos = ChaosChannel(MemoryChannel(broker), drop_p=0.1, seed=7)
    qm = QueueManager(lambda direction: chaos if direction == "p" else ...)

**Process-level harness** (the kill−9 tier): :class:`SpoolChannel` is a
durable file-backed broker whose consumer cursor only advances on ``ack()``
— at-least-once semantics that survive SIGKILL of the consumer process —
and :class:`ChaosWorkerHarness` spawns a REAL worker subprocess (the
production ``WorkerApp`` epoch cycle, ``deliveryMode: atLeastOnce``) over
such a spool, kills it −9 mid-stream, restarts it, and exposes the final
engine snapshot + delivery stats so tests can assert the recovered run is
EQUAL to a crash-free golden run. The chaos seams compose: the harness can
inject duplicate deliveries (``dup_p``) on top of the kill/restart cycle.
"""

from __future__ import annotations

import json
import os
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, List

from ..transport.base import Channel


@dataclass
class ChaosStats:
    refused_sends: int = 0
    dropped: int = 0
    duplicated: int = 0
    delivered: int = 0
    sent: int = 0
    _lock: threading.Lock = field(default_factory=threading.Lock, repr=False)

    def _bump(self, name: str, n: int = 1) -> None:
        with self._lock:
            setattr(self, name, getattr(self, name) + n)


class ChaosChannel(Channel):
    """Fault-injecting decorator around a real transport Channel."""

    def __init__(
        self,
        inner: Channel,
        *,
        drop_p: float = 0.0,
        dup_p: float = 0.0,
        seed: int = 0,
    ):
        if not (0.0 <= drop_p <= 1.0 and 0.0 <= dup_p <= 1.0):
            raise ValueError("drop_p/dup_p must be probabilities")
        self.inner = inner
        self.drop_p = drop_p
        self.dup_p = dup_p
        self.stats = ChaosStats()
        self._rng = random.Random(seed)
        self._forced_full = False
        self._drain_cbs: List[Callable[[], None]] = []
        # real backend drains propagate through the same callback list the
        # chaos-released drains use
        inner.on_drain(self._fire_drain)

    # -- producer-side faults -------------------------------------------------
    def force_full(self) -> None:
        """Subsequent ``send()`` calls refuse (broker alarm engaged)."""
        self._forced_full = True

    def release(self) -> None:
        """End the forced-full window and fire the drain event, exactly like
        a broker clearing its alarm (connection.unblocked -> drain)."""
        self._forced_full = False
        self._fire_drain()

    def send(self, name: str, payload: bytes, headers=None) -> bool:
        if self._forced_full:
            self.stats._bump("refused_sends")
            return False
        ok = self.inner.send(name, payload, headers)
        if ok:
            self.stats._bump("sent")
        return ok

    # -- consumer-side faults -------------------------------------------------
    def consume(self, name: str, callback: Callable[[bytes], None], consumer_tag: str,
                manual_ack: bool = False) -> None:
        from ..transport.base import accepts_headers

        if manual_ack:

            def chaotic(payload: bytes, headers=None, token=None) -> None:
                # manual-ack semantics under chaos: a DROP leaves the token
                # unacked on the broker ledger (delivery loss before
                # processing — redelivered on close/restart, nothing is
                # silently gone); a DUP replays the same payload+msg_id+token
                # (the consumer's dedup window must catch it; double-acking
                # one token is idempotent by the Channel contract)
                if self.drop_p and self._rng.random() < self.drop_p:
                    self.stats._bump("dropped")
                    return
                self.stats._bump("delivered")
                callback(payload, headers, token)
                if self.dup_p and self._rng.random() < self.dup_p:
                    self.stats._bump("duplicated")
                    self.stats._bump("delivered")
                    callback(payload, headers, token)

            self.inner.consume(name, chaotic, consumer_tag, manual_ack=True)
            return

        wants_headers = accepts_headers(callback)

        def chaotic(payload: bytes, headers=None) -> None:
            # the backend already removed the message (ack-on-receipt): a
            # drop here IS the at-most-once loss window
            deliver = (
                (lambda: callback(payload, headers)) if wants_headers
                else (lambda: callback(payload))
            )
            if self.drop_p and self._rng.random() < self.drop_p:
                self.stats._bump("dropped")
                return
            self.stats._bump("delivered")
            deliver()
            if self.dup_p and self._rng.random() < self.dup_p:
                self.stats._bump("duplicated")
                self.stats._bump("delivered")
                deliver()

        self.inner.consume(name, chaotic, consumer_tag)

    # -- passthrough ----------------------------------------------------------
    def ack(self, tokens) -> None:
        self.inner.ack(tokens)

    def assert_queue(self, name: str) -> None:
        self.inner.assert_queue(name)

    def cancel(self, consumer_tag: str) -> None:
        self.inner.cancel(consumer_tag)

    def on_drain(self, callback: Callable[[], None]) -> None:
        self._drain_cbs.append(callback)

    def close(self) -> None:
        self.inner.close()

    def _fire_drain(self) -> None:
        for cb in list(self._drain_cbs):
            cb()


# ---------------------------------------------------------------------------
# Process-level harness: durable spool broker + kill−9 worker driver
# ---------------------------------------------------------------------------


# SpoolChannel moved to transport/spool.py (it is a real transport backend,
# not a test double — the production worker runs over it in the chaos and
# hostile-storage tiers); re-exported here for compatibility.
from ..transport.spool import SpoolChannel, read_spool_cursor  # noqa: E402
from ..transport.spool import _SpoolQueue as _SpoolQueue  # noqa: E402 (re-export)


class ChaosWorkerHarness:
    """Drive a REAL worker subprocess through kill−9 chaos.

    The child is the production stack — ``WorkerApp`` with ``deliveryMode:
    atLeastOnce`` over a :class:`SpoolChannel` (optionally chaos-wrapped with
    duplicate injection) — launched via ``python -m
    apmbackend_tpu.testing.chaos --child``. The harness appends tx lines to
    the durable spool, watches the committed cursor, SIGKILLs / restarts the
    child at will, and collects the final engine snapshot + delivery stats.

    Crash-equivalence protocol (tests/test_chaos_harness.py): run one
    harness to completion with no kills (golden), another over the same line
    stream with kills + dup chaos, then compare the two final resume
    snapshots array-for-array.

    ``checkpoint_mode="delta"`` runs the child on the incremental delta
    chain (deltachain.py) under ``<workdir>/chain``; at clean exit the child
    exports a FULL snapshot to ``resume_path``, so the same array-for-array
    comparison covers delta runs — including cross-mode comparisons (a delta
    chaos run vs a full-snapshot golden run must still be bit-identical).
    ``fault_env`` injects hostile-storage faults (deltachain.StorageFaultPlan
    grammar) via ``APM_CHAOS_FS``: a string applies to every child
    generation, a ``{generation: spec}`` dict targets specific restarts
    (e.g. kill-during-compaction only in generation 1).
    """

    QUEUE = "transactions"

    def __init__(self, workdir: str, *, dup_p: float = 0.0, seed: int = 0,
                 capacity: int = 64, save_every_s: float = 0.4,
                 checkpoint_mode: str = "full", compact_every: int = 0,
                 fault_env=None, event_log: bool = False):
        import sys

        self.workdir = os.path.abspath(workdir)
        self.spool_dir = os.path.join(self.workdir, "spool")
        os.makedirs(self.spool_dir, exist_ok=True)
        self.resume_path = os.path.join(self.workdir, "engine.resume.npz")
        self.stats_path = os.path.join(self.workdir, "stats.json")
        self.done_path = os.path.join(self.workdir, "DONE")
        self.log_path = os.path.join(self.workdir, "child.log")
        self.dup_p = dup_p
        self.seed = seed
        self.capacity = capacity
        self.save_every_s = save_every_s
        self.checkpoint_mode = checkpoint_mode
        self.chain_dir = os.path.join(self.workdir, "chain")
        self.compact_every = compact_every
        self.fault_env = fault_env
        # crash flight-recorder bundles (obs/flight): the child journals
        # here on a fast cadence; a kill−9 leaves journal+sentinel behind
        # and the RESTARTED child promotes them into a ...-crash.json bundle
        self.flight_dir = os.path.join(self.workdir, "flight")
        # protocol event log (analysis/protocol conformance): the child
        # appends worker events; the harness appends crash/corrupt markers
        # at its injection points so the replay knows what was done to it
        self.event_log_path = (
            os.path.join(self.workdir, "events.jsonl") if event_log else None)
        self.python = sys.executable
        self.proc = None
        self.generation = 0
        self._seq = 0
        self._producer = SpoolChannel(self.spool_dir)

    def _mark_event(self, ev: str, **fields) -> None:
        if self.event_log_path is None:
            return
        fields["ev"] = ev
        fields["ts"] = time.time()
        with open(self.event_log_path, "a", encoding="utf-8") as fh:
            fh.write(json.dumps(fields, separators=(",", ":")) + "\n")

    def events(self) -> list:
        """The protocol event log so far (torn tail tolerated)."""
        from ..analysis.protocol.conformance import read_event_log

        assert self.event_log_path is not None, "harness built without event_log"
        return read_event_log(self.event_log_path)

    def flight_bundles(self) -> list:
        """(path, parsed body) for every flight bundle the child produced —
        parse errors raise (an unreadable bundle is the bug this asserts on)."""
        from ..obs.flight import list_bundles

        return list_bundles(self.flight_dir)

    def wait_rearmed(self, n_bundles: int, timeout_s: float = 60.0) -> None:
        """Block until the restarted child has promoted the previous
        generation's journal+sentinel shadow into crash bundle ``n_bundles``
        (boot-time ``recover_crash``) AND its OWN live journal carries the
        worker sources again (WorkerApp registered + a journal tick ran).

        The spool cursor can race far past the nominal kill points, so
        without this the next SIGKILL can land mid-boot — before the
        recorder re-arms (two crashes legitimately collapse into one
        promotion) or before the journal is source-populated. Crucially the
        journal must be the *current* generation's: ``recover_crash``
        consumes only the sentinel, so the dead generation's journal (which
        already had ``engine_health``) stays on disk until the new child's
        first tick overwrites it. The journal's ``pid`` stamp (obs/flight
        ``snapshot``) is matched against the live child to reject that
        stale read.
        """
        assert self.proc is not None and self.proc.poll() is None, \
            "wait_rearmed needs a live child (call start() first)"
        journal = os.path.join(self.flight_dir, "tpu_worker.journal.json")
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            promoted = sum(
                1 for _p, b in self.flight_bundles() if b.get("recovered")
            )
            if promoted >= n_bundles:
                try:
                    with open(journal, "r", encoding="utf-8") as fh:
                        body = json.load(fh)
                except Exception:
                    body = {}
                if ("engine_health" in body
                        and body.get("pid") == self.proc.pid):
                    return
            time.sleep(0.05)
        raise TimeoutError(
            f"crash bundle {n_bundles} / re-armed journal (pid "
            f"{self.proc.pid}) never appeared; see {self.log_path}"
        )

    # -- stream --------------------------------------------------------------
    def send_line(self, line: str) -> None:
        self._seq += 1
        self._producer.send(
            self.QUEUE, line.encode("utf-8"),
            {"ingest_ts": time.time(), "msg_id": f"h-{self._seq}"},
        )

    @property
    def sent(self) -> int:
        return self._seq

    # -- child lifecycle -----------------------------------------------------
    def start(self):
        import subprocess

        assert self.proc is None or self.proc.poll() is not None
        self.generation += 1
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        env.pop("PYTHONPATH", None)  # no TPU-relay sitecustomize in children
        env.pop("APM_CHAOS_FS", None)
        fault = self.fault_env
        if isinstance(fault, dict):
            fault = fault.get(self.generation)
        if fault:
            env["APM_CHAOS_FS"] = fault
        argv = [
            self.python, "-m", "apmbackend_tpu.testing.chaos", "--child",
            "--spool-dir", self.spool_dir,
            "--resume", self.resume_path,
            "--queue", self.QUEUE,
            "--stats-out", self.stats_path,
            "--done-file", self.done_path,
            "--capacity", str(self.capacity),
            "--save-every-s", str(self.save_every_s),
            "--dup-p", str(self.dup_p),
            "--seed", str(self.seed + self.generation),
            "--flight-dir", self.flight_dir,
            "--checkpoint-mode", self.checkpoint_mode,
            "--chain-dir", self.chain_dir,
            "--compact-every", str(self.compact_every),
        ]
        if self.event_log_path:
            argv += ["--event-log", self.event_log_path]
        log_fh = open(self.log_path, "ab")
        self.proc = subprocess.Popen(
            argv,
            stdout=log_fh, stderr=log_fh, stdin=subprocess.DEVNULL,
            cwd=os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
            env=env,
        )
        log_fh.close()
        return self.proc

    def kill9(self) -> None:
        """SIGKILL: no atexit, no signal handler, no flush — the real thing."""
        import signal as _signal

        if self.proc is not None and self.proc.poll() is None:
            os.kill(self.proc.pid, _signal.SIGKILL)
            self.proc.wait(timeout=30)
            self._mark_event("crash", gen=self.generation)

    def wait_child_death(self, timeout_s: float = 120.0) -> int:
        """Block until the child dies on its own — the fault-plan SIGKILL
        scenarios (kill:compact=...) where the child, not the harness, picks
        the crash instant. Returns the (negative-signal) exit code."""
        rc = self.proc.wait(timeout=timeout_s)
        if rc != 0:
            self._mark_event("crash", gen=self.generation)
        return rc

    def acked(self) -> int:
        return read_spool_cursor(self.spool_dir, self.QUEUE)

    def chain_tail_segment(self):
        """Path of the newest delta segment on disk (None when no deltas)."""
        segs = sorted(
            n for n in os.listdir(self.chain_dir)
            if n.startswith("delta-") and n.endswith(".seg")
        )
        return os.path.join(self.chain_dir, segs[-1]) if segs else None

    def corrupt_chain_tail(self, mode: str) -> str:
        """Damage the chain tail between child generations — the hostile-
        storage matrix rows a SIGKILL alone cannot produce on a journaling
        filesystem: ``truncate`` (torn final write: half the segment),
        ``garbage`` (bit rot in the payload), ``header`` (truncated inside
        the header framing), ``stale-dup`` (a leftover same-name future
        segment from a dead incarnation: the tail copied to epoch+1, which
        recovery must reject via the uid linkage, never replay)."""
        seg = self.chain_tail_segment()
        assert seg is not None, "no delta segment to corrupt"
        blob = open(seg, "rb").read()
        if mode == "truncate":
            # apm: allow(durability-discipline): deliberately torn bytes — this IS the hostile-storage injector
            open(seg, "wb").write(blob[: max(1, len(blob) // 2)])
        elif mode == "header":
            # apm: allow(durability-discipline): deliberately torn header framing — hostile-storage injector
            open(seg, "wb").write(blob[: len(b"APMDCSG1") + 5])
        elif mode == "garbage":
            mid = len(blob) // 2  # 0xA5: never a no-op over real segment bytes
            # apm: allow(durability-discipline): deliberate bit rot — hostile-storage injector
            open(seg, "wb").write(blob[:mid] + b"\xa5" * 16 + blob[mid + 16:])
        elif mode == "stale-dup":
            epoch = int(os.path.basename(seg)[6:-4])
            dup = os.path.join(self.chain_dir, f"delta-{epoch + 1:012d}.seg")
            open(dup, "wb").write(blob)
            self._mark_event("corrupt", mode=mode)
            return dup
        else:
            raise ValueError(f"unknown corruption mode {mode!r}")
        self._mark_event("corrupt", mode=mode)
        return seg

    def wait_acked(self, n: int, timeout_s: float = 120.0) -> int:
        """Block until the committed cursor reaches ``n`` (or timeout); the
        kill-point selector for mid-stream SIGKILLs."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            got = self.acked()
            if got >= n:
                return got
            if self.proc is not None and self.proc.poll() is not None:
                raise RuntimeError(
                    f"chaos child exited rc={self.proc.returncode} before acking {n} "
                    f"(got {got}); see {self.log_path}"
                )
            time.sleep(0.02)
        raise TimeoutError(f"cursor stuck at {self.acked()} < {n}; see {self.log_path}")

    def finish(self, timeout_s: float = 180.0) -> dict:
        """Signal end-of-stream, wait for the child's final epoch commit and
        graceful exit, and return its stats JSON."""
        tmp = self.done_path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump({"total": self._seq}, fh)
        os.replace(tmp, self.done_path)
        rc = self.proc.wait(timeout=timeout_s)
        if rc != 0:
            raise RuntimeError(f"chaos child exit rc={rc}; see {self.log_path}")
        with open(self.stats_path, "r", encoding="utf-8") as fh:
            return json.load(fh)

    def close(self) -> None:
        self.kill9()
        self._producer.close()


class QueryLoad:
    """Concurrent dashboard-load generator against query-plane URLs —
    the read-side chaos instrument (ISSUE 20). Seeded like every chaos
    seam: thread ``i`` walks its own ``Random(seed + i)`` URL sequence,
    so a failing drill replays the same request mix. Collects status
    codes, transport errors, and latencies; ``stop()`` returns the
    summary the kill−9 drill asserts on (zero 5xx, p95 bound).

    Degraded-serving honesty is the point: an HTTP error status is
    recorded under its code (a 5xx mid-drill is a FINDING), while a
    transport-level failure (connection refused while the front door
    itself restarts) counts separately as an error, not a 5xx.
    """

    def __init__(self, urls: List[str], *, threads: int = 4,
                 timeout_s: float = 5.0, seed: int = 0):
        if not urls:
            raise ValueError("QueryLoad needs at least one URL")
        self.urls = list(urls)
        self.threads = max(1, int(threads))
        self.timeout_s = float(timeout_s)
        self.seed = int(seed)
        self._stop = threading.Event()
        self._workers: List[threading.Thread] = []
        self._lock = threading.Lock()
        self._codes: dict = {}  # guarded-by: _lock
        self._latencies_ms: List[float] = []  # guarded-by: _lock
        self._errors = 0  # guarded-by: _lock

    def _one(self, rng: random.Random) -> None:
        import urllib.error
        import urllib.request

        url = rng.choice(self.urls)
        t0 = time.monotonic()
        try:
            with urllib.request.urlopen(url, timeout=self.timeout_s) as resp:
                resp.read()
                code = resp.status
        except urllib.error.HTTPError as e:
            code = e.code
        except Exception:
            with self._lock:
                self._errors += 1
            return
        ms = (time.monotonic() - t0) * 1000.0
        with self._lock:
            self._codes[code] = self._codes.get(code, 0) + 1
            self._latencies_ms.append(ms)

    def start(self) -> "QueryLoad":
        def run(i):
            rng = random.Random(self.seed + i)
            while not self._stop.is_set():
                self._one(rng)

        self._workers = [
            threading.Thread(target=run, args=(i,), daemon=True,
                             name=f"query-load-{i}")
            for i in range(self.threads)
        ]
        for t in self._workers:
            t.start()
        return self

    def stop(self) -> dict:
        self._stop.set()
        for t in self._workers:
            t.join(timeout=self.timeout_s + 1.0)
        with self._lock:
            lats = sorted(self._latencies_ms)
            codes = dict(self._codes)
            errors = self._errors

        def pct(p):
            if not lats:
                return None
            return lats[min(len(lats) - 1, int(p * (len(lats) - 1)))]

        return {
            "requests": sum(codes.values()),
            "codes": codes,
            "five_xx": sum(n for c, n in codes.items() if 500 <= c < 600),
            "errors": errors,
            "p50_ms": pct(0.50),
            "p95_ms": pct(0.95),
        }


def _child_main(argv=None) -> int:
    """The harness child: the production worker epoch cycle over a spool.

    Everything between SpoolChannel and the engine snapshot is the REAL
    production code path — WorkerApp's at-least-once consume/dedup/epoch
    logic, PipelineDriver's checkpoint — not a test double. The only
    harness-specific parts are the spool transport and the DONE/stats
    files."""
    import argparse

    ap = argparse.ArgumentParser(prog="apmbackend_tpu.testing.chaos --child")
    ap.add_argument("--spool-dir", required=True)
    ap.add_argument("--resume", required=True)
    ap.add_argument("--queue", default="transactions")
    ap.add_argument("--stats-out", required=True)
    ap.add_argument("--done-file", required=True)
    ap.add_argument("--capacity", type=int, default=64)
    ap.add_argument("--save-every-s", type=float, default=0.4)
    ap.add_argument("--dup-p", type=float, default=0.0)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--flight-dir", default=None)
    ap.add_argument("--checkpoint-mode", default="full", choices=("full", "delta"))
    ap.add_argument("--chain-dir", default=None)
    ap.add_argument("--compact-every", type=int, default=0)
    ap.add_argument("--event-log", default=None)
    args = ap.parse_args(argv)

    from ..config import default_config
    from ..runtime.module_base import ModuleRuntime
    from ..runtime.worker import WorkerApp
    from ..transport.base import QueueManager

    cfg = default_config()
    eng = cfg["tpuEngine"]
    eng["serviceCapacity"] = args.capacity
    eng["samplesPerBucket"] = 64
    eng["deliveryMode"] = "atLeastOnce"
    eng["metricsPort"] = None
    if args.checkpoint_mode == "delta":
        # delta-chain epoch commits; the full `--resume` npz is written only
        # as a clean-exit EXPORT so the harness's array-for-array comparison
        # (and cross-mode full-vs-delta comparisons) keep working
        eng["checkpointMode"] = "delta"
        eng["checkpointChainDir"] = args.chain_dir
        eng["resumeFileFullPath"] = None
        eng["checkpointCompactEveryEpochs"] = args.compact_every
        # fast retry cadence: the ENOSPC scenarios must clear inside a test
        eng["checkpointWriteRetryBaseSeconds"] = 0.05
        eng["checkpointWriteRetryMaxSeconds"] = 0.5
    else:
        eng["resumeFileFullPath"] = args.resume
    if args.event_log:
        # protocol event log for the trace-conformance tier: the REAL
        # worker's deliver/feed/checkpoint/ack stream, replayed against
        # the models by tests/test_protocol_conformance.py
        eng["protocolEventLog"] = args.event_log
    cfg["streamCalcZScore"]["defaults"] = [{"LAG": 6, "THRESHOLD": 3.0, "INFLUENCE": 0.1}]
    cfg["streamCalcStats"]["inQueue"] = args.queue
    # the resume-save timer IS the epoch cadence: short, so SIGKILLs land at
    # arbitrary points relative to commits
    cfg["streamCalcStats"]["resumeFileSaveFrequencyInSeconds"] = args.save_every_s
    cfg["streamProcessAlerts"]["alertsResumeFileFullPath"] = None
    cfg["logDir"] = None
    if args.flight_dir:
        # crash flight recorder under kill−9: journal on a sub-second
        # cadence so a SIGKILL at any instant leaves a fresh shadow; the
        # restarted child promotes it to a crash bundle at boot. The
        # recorder only READS pipeline state and writes under its own
        # directory — the bit-identical golden comparison is untouched.
        cfg["observability"]["flightDir"] = args.flight_dir
        cfg["observability"]["flightJournalSeconds"] = 0.2

    runtime = ModuleRuntime(
        "tpuEngine", config=cfg, install_signals=True, console_log=True
    )
    spools = {}

    def factory(direction: str):
        ch = SpoolChannel(args.spool_dir)
        spools[direction] = ch
        if direction == "c" and args.dup_p > 0:
            return ChaosChannel(ch, dup_p=args.dup_p, seed=args.seed)
        return ch

    runtime.qm = QueueManager(factory, 3600, logger=runtime.logger)
    worker = WorkerApp(runtime)
    consumer = spools["c"]
    consumer.start_pump_thread()

    total = None
    while True:
        if total is None and os.path.exists(args.done_file):
            try:
                with open(args.done_file, "r", encoding="utf-8") as fh:
                    total = int(json.load(fh)["total"])
            except Exception:
                total = None
        if total is not None and consumer.delivered_count(args.queue) >= total:
            # stream fully delivered: force the final epoch commit and stop
            # once every record is acked (committed)
            worker.save_state()
            if consumer.acked_count(args.queue) >= total:
                break
        time.sleep(0.02)

    consumer.stop()
    worker.shutdown()  # final save_state + ack inside
    if args.checkpoint_mode == "delta":
        # clean-exit export: the comparison snapshot (NOT a checkpoint — the
        # chain is the durable state; this npz exists for the harness's
        # bit-identical assertions against full-mode/golden runs)
        with worker._driver_lock:
            worker.driver.save_resume(args.resume)
    stats = {
        "epoch": worker._delivery_epoch,
        "deduped_total": worker._deduped_total,
        "unacked": len(worker._epoch_tokens),
        "acked": consumer.acked_count(args.queue),
        "services": worker.driver.registry.count,
        "latest_label": worker.driver._latest_label,
        "checkpoint_mode": args.checkpoint_mode,
        "checkpoint_write_failures": worker._ckpt_failures_total,
        "chain_epoch": (
            worker._ckpt_chain.tail_epoch if worker._ckpt_chain is not None else None
        ),
        "compactions": (
            worker._ckpt_chain.compactions if worker._ckpt_chain is not None else 0
        ),
    }
    tmp = args.stats_out + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(stats, fh)
    os.replace(tmp, args.stats_out)
    runtime.stop_timers()
    return 0


if __name__ == "__main__":
    import sys

    if "--child" in sys.argv:
        sys.argv.remove("--child")
        sys.exit(_child_main(sys.argv[1:]))
    raise SystemExit("usage: python -m apmbackend_tpu.testing.chaos --child ...")
