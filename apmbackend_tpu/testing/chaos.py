"""Transport fault injection (chaos) — the testing seam the reference lacked.

The reference's failure story was only ever exercised in production
(SURVEY.md §5.3: "No fault injection exists"); its manual-test seams were
consume toggles and destructive queue peeks. This module makes broker
misbehavior a first-class, DETERMINISTIC test input: wrap any
:class:`~apmbackend_tpu.transport.base.Channel` in a :class:`ChaosChannel`
and inject

- **forced-full windows** (``force_full()`` / ``release()``): ``send()``
  refuses like a broker under memory/disk alarm, driving the real
  pause → buffer → drain → resume stack (queue.js:245-263, 88-106 contract)
  on demand instead of by luck;
- **message drops** (``drop_p``): delivery loss after the ack — the
  at-most-once window the reference accepts (queue.js:277-283);
- **duplicate deliveries** (``dup_p``): broker redelivery, which
  ack-on-receipt consumers see as double-processing.

Randomness is a seeded ``random.Random``: a failing chaos test replays
bit-identically. Counters (:class:`ChaosStats`) expose exactly what was
injected so assertions can account for every message.

This is a *testing* module: production code never constructs it. Wire it by
wrapping the backend factory handed to ``QueueManager``::

    broker = MemoryBroker()
    chaos = ChaosChannel(MemoryChannel(broker), drop_p=0.1, seed=7)
    qm = QueueManager(lambda direction: chaos if direction == "p" else ...)
"""

from __future__ import annotations

import random
import threading
from dataclasses import dataclass, field
from typing import Callable, List

from ..transport.base import Channel


@dataclass
class ChaosStats:
    refused_sends: int = 0
    dropped: int = 0
    duplicated: int = 0
    delivered: int = 0
    sent: int = 0
    _lock: threading.Lock = field(default_factory=threading.Lock, repr=False)

    def _bump(self, name: str, n: int = 1) -> None:
        with self._lock:
            setattr(self, name, getattr(self, name) + n)


class ChaosChannel(Channel):
    """Fault-injecting decorator around a real transport Channel."""

    def __init__(
        self,
        inner: Channel,
        *,
        drop_p: float = 0.0,
        dup_p: float = 0.0,
        seed: int = 0,
    ):
        if not (0.0 <= drop_p <= 1.0 and 0.0 <= dup_p <= 1.0):
            raise ValueError("drop_p/dup_p must be probabilities")
        self.inner = inner
        self.drop_p = drop_p
        self.dup_p = dup_p
        self.stats = ChaosStats()
        self._rng = random.Random(seed)
        self._forced_full = False
        self._drain_cbs: List[Callable[[], None]] = []
        # real backend drains propagate through the same callback list the
        # chaos-released drains use
        inner.on_drain(self._fire_drain)

    # -- producer-side faults -------------------------------------------------
    def force_full(self) -> None:
        """Subsequent ``send()`` calls refuse (broker alarm engaged)."""
        self._forced_full = True

    def release(self) -> None:
        """End the forced-full window and fire the drain event, exactly like
        a broker clearing its alarm (connection.unblocked -> drain)."""
        self._forced_full = False
        self._fire_drain()

    def send(self, name: str, payload: bytes, headers=None) -> bool:
        if self._forced_full:
            self.stats._bump("refused_sends")
            return False
        ok = self.inner.send(name, payload, headers)
        if ok:
            self.stats._bump("sent")
        return ok

    # -- consumer-side faults -------------------------------------------------
    def consume(self, name: str, callback: Callable[[bytes], None], consumer_tag: str) -> None:
        from ..transport.base import accepts_headers

        wants_headers = accepts_headers(callback)

        def chaotic(payload: bytes, headers=None) -> None:
            # the backend already removed the message (ack-on-receipt): a
            # drop here IS the at-most-once loss window
            deliver = (
                (lambda: callback(payload, headers)) if wants_headers
                else (lambda: callback(payload))
            )
            if self.drop_p and self._rng.random() < self.drop_p:
                self.stats._bump("dropped")
                return
            self.stats._bump("delivered")
            deliver()
            if self.dup_p and self._rng.random() < self.dup_p:
                self.stats._bump("duplicated")
                self.stats._bump("delivered")
                deliver()

        self.inner.consume(name, chaotic, consumer_tag)

    # -- passthrough ----------------------------------------------------------
    def assert_queue(self, name: str) -> None:
        self.inner.assert_queue(name)

    def cancel(self, consumer_tag: str) -> None:
        self.inner.cancel(consumer_tag)

    def on_drain(self, callback: Callable[[], None]) -> None:
        self._drain_cbs.append(callback)

    def close(self) -> None:
        self.inner.close()

    def _fire_drain(self) -> None:
        for cb in list(self._drain_cbs):
            cb()
