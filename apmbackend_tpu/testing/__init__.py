"""Test affordances: fault injection + crash chaos for the transport fabric."""

from .chaos import (  # noqa: F401
    ChaosChannel,
    ChaosStats,
    ChaosWorkerHarness,
    SpoolChannel,
    read_spool_cursor,
)
