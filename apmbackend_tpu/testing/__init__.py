"""Test affordances: fault injection for the transport fabric."""

from .chaos import ChaosChannel, ChaosStats  # noqa: F401
