"""Transaction parser module process (stream_parse_transactions.js role).

Tails the configured log masks (PyTailer threads or the native C++ tail
binary), correlates entry/exit lines into TxEntry records, and produces them
onto the ``transactions`` queue (audit records straight to ``db_insert``).
Backpressure: a queue 'pause' event creates the shared pause file that stalls
every tailer at the source; 'resume' deletes it (stream_parse_transactions.js:
170-176, 834-897).

``--replay <dir>`` feeds fixture/captured logs through the same parser and
exits — the deterministic replay driver (SURVEY.md §7.2 step 3).
"""

from __future__ import annotations

import argparse
import os
from typing import Optional

from ..transport.memory import MemoryBroker
from .parser import TransactionParser
from .replay import ReplayDriver
from .tailer import TailManager


def server_extractor(cfg: dict):
    """Server name from a log path, config-driven.

    Order: ``serverFromPathPattern`` (regex, group 1) -> path component
    ``serverPathComponentIndex`` (the reference's ``split('/')[2]``,
    stream_parse_transactions.js:319) -> ``defaultServerName`` -> basename.
    """
    import re as _re

    pattern = cfg.get("serverFromPathPattern")
    compiled = _re.compile(pattern) if pattern else None
    component = cfg.get("serverPathComponentIndex")
    default = cfg.get("defaultServerName")

    def extract(fp: str) -> str:
        if compiled is not None:
            m = compiled.search(fp)
            if m:
                return m.group(1)
        if component is not None:
            parts = fp.split("/")
            if len(parts) > component:
                return parts[component]
        return default or fp.rsplit("/", 1)[-1]

    return extract


def build(runtime, *, tail: bool = True):
    cfg = runtime.module_config
    verbose = bool(cfg.get("verboseQueueWrite"))
    out_queue = runtime.qm.get_queue(cfg.get("outQueue", "transactions"), "p")
    db_queue = runtime.qm.get_queue(runtime.config.get("dbInsertQueue", "db_insert"), "p")

    def on_record(tx, insert_to_db: bool) -> None:
        # Provider records go down the pipeline; non-Provider audit records go
        # straight to the DB queue (outputRecord, stream_parse_transactions.js:264-290).
        if insert_to_db:
            db_queue.write_line(tx.to_csv(), verbose)
        else:
            out_queue.write_line(tx.to_csv(), verbose)

    # transport.frameMode: queue-bound records leave the parser as packed
    # APF1 frame batches (one write_frames per batch — the zero-object byte
    # spine) instead of one write_line per record. db-direct audit records
    # keep the per-record path either way. APM_NO_FRAMES=1 overrides to OFF
    # inside TransactionParser (the kill switch); OFF is bit-identical to
    # the pre-frame wire by construction.
    tcfg = runtime.config.get("transport", {}) or {}
    frame_sink = None
    if tcfg.get("frameMode"):
        def frame_sink(blob: bytes, n_records: int) -> None:
            out_queue.write_frames(blob, n_records, verbose)

    parser = TransactionParser(
        on_record, logger=runtime.logger, server_from_path=server_extractor(cfg),
        frame_sink=frame_sink,
        frame_max_records=int(tcfg.get("frameMaxRecords", 512) or 512),
    )
    # parser-stage counters as a /metrics view, gated like the worker's
    # collector so throwaway test runtimes do not pile up dead collectors
    from ..obs import telemetry_active

    if getattr(runtime, "telemetry", None) is not None or telemetry_active():
        from ..obs.views import register_parser

        register_parser(parser, "streamParseTransactions")

    manager = None
    if tail:
        native = cfg.get("nativeTailBinary")
        if native == "auto":
            # build the in-repo C++ tailer (native/tailer.cpp) on demand;
            # falls back to Python threads when no toolchain is available
            from ..native import tail_binary_path

            native = tail_binary_path()
            if native is None:
                runtime.logger.warning(
                    "nativeTailBinary=auto but native build unavailable; using Python tailers"
                )
        elif native and not os.path.exists(native):
            runtime.logger.warning(f"nativeTailBinary not found, using Python tailers: {native}")
            native = None

        def on_tail_exit(path, rc):
            # any tail death kills the parser; the manager restarts it
            # (fail-fast, stream_parse_transactions.js:919-922)
            runtime.logger.error(f"Tail exited (rc={rc}) for {path}; exiting parser")
            runtime.exit(1)

        manager = TailManager(
            cfg, parser.read_line, logger=runtime.logger,
            native_binary=native, on_tail_exit=on_tail_exit,
            # batch delivery: each poll's complete lines reach the parser as
            # one chunk through the native ingest fast path (read_lines)
            on_lines=parser.read_lines,
        )
        manager.start()
        runtime.qm.on("pause", manager.pause_reads)
        runtime.qm.on("resume", manager.resume_reads)
        runtime.on_exit(manager.stop)

    # TTL cache sweeps (expired partials emit incomplete records,
    # stream_parse_transactions.js:213-239) + hit/miss stat logging (:329-335)
    runtime.every(1.0, parser.sweep, name="cache-sweep")
    interval = int(runtime.config.get("statLogIntervalInSeconds", 60))
    runtime.every(
        interval,
        lambda: runtime.logger.info(f"Cache stats: {parser.cache_stats()}"),
        name="cache-stats", align=True,
    )
    runtime.on_exit(parser.drain)
    return parser, manager


def main(config_path: Optional[str] = None, broker: Optional[MemoryBroker] = None) -> None:
    from ..runtime.module_base import ModuleRuntime

    ap = argparse.ArgumentParser()
    ap.add_argument("--replay", help="replay a directory of logs then exit")
    args, _ = ap.parse_known_args()

    runtime = ModuleRuntime("streamParseTransactions", config_path=config_path, broker=broker)
    parser, _manager = build(runtime, tail=not args.replay)
    if args.replay:
        driver = ReplayDriver(parser)
        fed = driver.feed_dir(args.replay)
        driver.finish()
        runtime.logger.info(f"Replay complete: {fed} lines")
        runtime.exit(0)
    runtime.logger.info("Transaction parser started")
    runtime.run_forever()


if __name__ == "__main__":
    main()
