"""TTL cache with expiry callbacks.

Role parity with the reference's NodeCache usage
(stream_parse_transactions.js:211-239): per-key TTL, periodic sweep, an
``expired`` callback that lets the parser salvage or discard incomplete
correlation state, and hit/miss statistics (logged every 60 s, :329-335).
The clock is injectable so log replay is deterministic.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, Optional, Tuple


class TTLCache:
    def __init__(
        self,
        ttl_s: float,
        *,
        on_expired: Optional[Callable[[str, Any], None]] = None,
        clock: Callable[[], float] = time.monotonic,
        sweep_interval_s: Optional[float] = None,
    ):
        self.ttl_s = ttl_s
        self.on_expired = on_expired
        self.clock = clock
        self.sweep_interval_s = sweep_interval_s if sweep_interval_s is not None else max(ttl_s / 4, 1)
        self._store: Dict[str, Tuple[float, Any]] = {}  # key -> (expires_at, value)
        self._last_sweep = clock()
        self.hits = 0
        self.misses = 0

    def __len__(self) -> int:
        return len(self._store)

    def set(self, key: str, value: Any) -> None:
        self._store[key] = (self.clock() + self.ttl_s, value)

    def get(self, key: str) -> Optional[Any]:
        # one clock() read serves both the sweep check and the expiry test:
        # get() runs twice per emitted record on the frame fast path
        now = self.clock()
        if now - self._last_sweep >= self.sweep_interval_s:
            self.sweep()
        item = self._store.get(key)
        if item is None:
            self.misses += 1
            return None
        expires_at, value = item
        if now >= expires_at:
            del self._store[key]
            if self.on_expired:
                self.on_expired(key, value)
            self.misses += 1
            return None
        self.hits += 1
        return value

    def has(self, key: str) -> bool:
        return self.get(key) is not None

    def delete(self, key: str) -> None:
        self._store.pop(key, None)

    def maybe_sweep(self) -> None:
        now = self.clock()
        if now - self._last_sweep >= self.sweep_interval_s:
            self.sweep()

    def sweep(self) -> int:
        """Expire all overdue entries, firing callbacks. Returns count."""
        now = self.clock()
        self._last_sweep = now
        expired = [(k, v) for k, (exp, v) in self._store.items() if now >= exp]
        for key, value in expired:
            del self._store[key]
            if self.on_expired:
                self.on_expired(key, value)
        return len(expired)

    def clear(self) -> int:
        """Drop everything WITHOUT firing callbacks (end-of-replay partial
        discard; the native record cache exposes the same method)."""
        n = len(self._store)
        self._store.clear()
        return n

    def flush_all(self) -> int:
        """Expire everything regardless of TTL (end-of-replay drain)."""
        items = list(self._store.items())
        self._store.clear()
        for key, (_exp, value) in items:
            if self.on_expired:
                self.on_expired(key, value)
        return len(items)

    def stats(self) -> dict:
        return {"keys": len(self._store), "hits": self.hits, "misses": self.misses}
