"""JMX poller module process (pull_jvm_stats.js role)."""

from __future__ import annotations

import threading
from typing import Optional

from ..transport.memory import MemoryBroker
from .jmx import JmxPoller


def build(runtime) -> JmxPoller:
    cfg = runtime.module_config
    db_queue = runtime.qm.get_queue(runtime.config.get("dbInsertQueue", "db_insert"), "p")
    verbose = bool(cfg.get("verboseQueueWrite"))
    poller = JmxPoller(
        cfg,
        lambda line: db_queue.write_line(line, verbose),
        logger=runtime.logger,
    )
    runtime.on_reload(lambda new_cfg: poller.set_config(new_cfg.get("pullJvmStats", {})))

    # Second-aligned recursion; the first (immediate) tick never polls
    # (pullAllJvmStatsRecurs(false), pull_jvm_stats.js:141-149).
    def schedule(not_first_time: bool) -> None:
        if runtime._stop.is_set():
            return
        if not_first_time:
            try:
                poller.pull_all()
            except Exception as e:
                runtime.logger.error(f"JMX poll error: {e}")
        t = threading.Timer(poller.seconds_until_next_poll(), schedule, args=(True,))
        t.daemon = True
        t.start()

    if cfg.get("jvmHosts") and cfg.get("clientJarFullPath"):
        schedule(False)
    else:
        runtime.logger.warning("JMX polling disabled: no jvmHosts/clientJarFullPath configured")
    return poller


def main(config_path: Optional[str] = None, broker: Optional[MemoryBroker] = None) -> None:
    from ..runtime.module_base import ModuleRuntime

    runtime = ModuleRuntime("pullJvmStats", config_path=config_path, broker=broker)
    build(runtime)
    runtime.logger.info("JMX poller started")
    runtime.run_forever()


if __name__ == "__main__":
    main()
