"""JMX poller module process (pull_jvm_stats.js role)."""

from __future__ import annotations

import threading
from typing import Optional

from ..transport.memory import MemoryBroker
from .jmx import JmxPoller


def _make_detector(cfg: dict, logger):
    """Optional device multivariate detector over the poll stream — a new
    capability beyond the reference (which only persists JMX rows). Enabled by
    ``pullJvmStats.multivariateDetector`` config block."""
    mv_cfg = cfg.get("multivariateDetector")
    # an empty {} block means "enabled with defaults" — only an absent block
    # or an explicit enabled=false disables
    if mv_cfg is None or not mv_cfg.get("enabled", True):
        return None
    from ..ops import multivariate as mv

    spec = mv.MvSpec(
        n_features=mv.JMX_FEATURE_COUNT,
        alpha=float(mv_cfg.get("alpha", 0.05)),
        threshold=float(mv_cfg.get("threshold", 3.0)),
        warmup=int(mv_cfg.get("warmup", 2 * mv.JMX_FEATURE_COUNT)),
        influence=float(mv_cfg.get("influence", 0.25)),
    )
    return mv.MvDriver(spec, logger=logger)


def build(runtime) -> JmxPoller:
    cfg = runtime.module_config
    db_queue = runtime.qm.get_queue(runtime.config.get("dbInsertQueue", "db_insert"), "p")
    verbose = bool(cfg.get("verboseQueueWrite"))
    poller = JmxPoller(
        cfg,
        lambda line: db_queue.write_line(line, verbose),
        logger=runtime.logger,
    )
    # detector holder so hot reload can swap/disable it (a spec change rebuilds
    # the detector — its EW baselines restart, like the z-score stale-lag purge
    # on reload, stream_calc_z_score.js:370-371)
    det = {"driver": _make_detector(cfg, runtime.logger), "block": cfg.get("multivariateDetector")}

    # -- detector resume (§5.4 parity: periodic snapshot + load on boot) -----
    mv_block = cfg.get("multivariateDetector") or {}
    resume_path = mv_block.get("resumeFileFullPath")
    if det["driver"] is not None and resume_path:
        if det["driver"].load_resume(resume_path):
            runtime.logger.info(f"JMX detector baselines resumed from {resume_path}")

        def save_detector():
            if det["driver"] is not None:
                det["driver"].save_resume(resume_path)

        runtime.every(
            int(mv_block.get("resumeFileSaveFrequencyInSeconds", 60)),
            save_detector, name="jmx-detector-resume",
        )
        runtime.on_exit(save_detector)

    def on_reload(new_cfg: dict) -> None:
        block = new_cfg.get("pullJvmStats", {})
        poller.set_config(block)
        mv_block = block.get("multivariateDetector")
        if mv_block != det["block"]:
            det["block"] = mv_block
            det["driver"] = _make_detector(block, runtime.logger)
            runtime.logger.warning(
                "multivariateDetector config changed: detector "
                + ("rebuilt (baselines reset)" if det["driver"] else "disabled")
            )

    runtime.on_reload(on_reload)

    # Second-aligned recursion; the first (immediate) tick never polls
    # (pullAllJvmStatsRecurs(false), pull_jvm_stats.js:141-149).
    def schedule(not_first_time: bool) -> None:
        if runtime._stop.is_set():
            return
        if not_first_time:
            try:
                entries = poller.pull_all()
                detector = det["driver"]
                if detector is not None and entries:
                    for verdict in detector.feed(entries):
                        if verdict["signal"]:
                            runtime.logger.warning(
                                "JMX multivariate anomaly on "
                                f"{verdict['server']}: score={verdict['score']:.2f} "
                                f"over {verdict['observed']} metrics"
                            )
            except Exception as e:
                runtime.logger.error(f"JMX poll error: {e}")
        t = threading.Timer(poller.seconds_until_next_poll(), schedule, args=(True,))
        t.daemon = True
        t.start()

    if cfg.get("jvmHosts") and cfg.get("clientJarFullPath"):
        schedule(False)
    else:
        runtime.logger.warning("JMX polling disabled: no jvmHosts/clientJarFullPath configured")
    return poller


def main(config_path: Optional[str] = None, broker: Optional[MemoryBroker] = None) -> None:
    from ..runtime.module_base import ModuleRuntime

    runtime = ModuleRuntime("pullJvmStats", config_path=config_path, broker=broker)
    build(runtime)
    runtime.logger.info("JMX poller started")
    runtime.run_forever()


if __name__ == "__main__":
    main()
