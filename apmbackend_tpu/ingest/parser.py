"""Log-correlation parser: raw JVM log lines -> complete TxEntry records.

Reproduces the correlation semantics of stream_parse_transactions.js (the
reference's design notes :3-44):

- **SOAP logs** build a logId -> accountNumber map: an ``IO=I`` header opens a
  per-file context carrying the logId; a later ``<accountNumber>`` (or the
  riskid two-line ``<key>AccountNumber</key>`` / ``<value>`` form) saves the
  account number (:352-376).
- **CommonTiming entry/exit join**: entry lines park a partial record keyed
  (logId, service) in a TTL cache; the exit line joins it with the account
  cache into a full record (:378-446 EJB form, :451-565 standard form). A
  missing account number parks the joined record in a second, shorter-TTL
  cache that is flushed when the SOAP parser later finds the number
  (saveAcctNum backfill :294-327) or emitted without it on expiry (:226-239).
- **BAF salvage**: exit lines on BAF logs may carry the account number inside
  bracketed metadata before INFO; used as a last resort (:486-504).
- **Audit-trail state machine** (APP logs): a mapping line links autrId ->
  logId; the "Audit Trail id :" line activates a per-file context; the
  RequestTrace elapsed section collects per-subservice elapsed arrays (same
  subservice can repeat, consumed FIFO); the stopWatchList XML supplies
  start/stop timestamps per subservice; each completed subservice emits a
  record, with non-Provider records routed straight to the DB queue
  (insert_to_db) to skip stats processing (:578-731).
- Emitted records may lack logId/acctNum/startTs; startTs falls back to
  endTs - elapsed (:264-290). ``Provider[...]`` is normalized to
  ``Provider:...`` and ``S:`` marks top-level (:258,274,282).

Output is roughly ordered only (cache timeouts) — downstream re-orders via the
min-heap, like the reference (:7, stream_calc_stats.js:136-155).
"""

from __future__ import annotations

import re
import time
from dataclasses import dataclass, field
from datetime import datetime
from typing import Callable, Dict, List, Optional

from ..entries import TxEntry
from .ttlcache import TTLCache

_TOPLEVEL_RE = re.compile(r"^S:")
_PROVIDER_RE = re.compile(r"Provider\[", re.IGNORECASE)

_SOAP_IN_RE = re.compile(r"^=== jbossId.*IO=I")
_SOAP_OUT_RE = re.compile(r"^=== jbossId.*IO=O")
_SOAP_ACCT_RE = re.compile(r"<accountNumber>", re.IGNORECASE)
_SOAP_ALT_KEY_RE = re.compile(r"<key>AccountNumber</key>", re.IGNORECASE)
_SOAP_ALT_VALUE_RE = re.compile(r"<value>")

_EJB_ENTRY_RE = re.compile(r"INFO *\[CommonTiming] The EJB")
_EJB_EXIT_RE = re.compile(r"INFO *\[CommonTiming] Total time")
_CT_ENTRY_RE = re.compile(r"INFO *CommonTiming::Start")
_CT_EXIT_RE = re.compile(r"INFO *CommonTiming::Stop")

_BAF_META_RE = re.compile(r"\[[^ ]+] +INFO ")

_AUTR_MAP_RE = re.compile(r"INFO  auditTrailId=")
_AUTR_LINE_RE = re.compile(r"^Audit Trail id *:")
_ELAPSED_START_RE = re.compile(r": RequestTrace \[stopWatchList=")
_ELAPSED_END_RE = re.compile(r"^]")
_SW_XML_START_RE = re.compile(r"<stopWatchList>")
_SW_XML_END_RE = re.compile(r"</stopWatchList>")
_SW_NAME_RE = re.compile(r"<name>")
_SW_START_RE = re.compile(r"<startTime>")
_SW_STOP_RE = re.compile(r"<stopTime>")

_SOAP_FILE_RE = re.compile(r"soap_io")
_SERVER_FILE_RE = re.compile(r"server\.log")

# one alternation pass as a PRE-FILTER: most lines carry no timing marker
# at all (payload/noise), and for them a single scan replaces up to four
# sequential searches. Lines that DO match re-run the original sequential
# ladder (stream_parse_transactions.js:741-812 priority) — regex
# alternation picks the LEFTMOST occurrence, not the ladder's first-pattern
# priority, so on a line where markers co-occur the ladder must decide.
_SERVER_DISPATCH_RE = re.compile(
    r"INFO *\[CommonTiming] The EJB"
    r"|INFO *\[CommonTiming] Total time"
    r"|INFO *CommonTiming::Start"
    r"|INFO *CommonTiming::Stop"
)

_ISO_TZ_RE = re.compile(r"T.*-")
_DIGITS_RE = re.compile(r"^[0-9]+$")


class ConsumerError(Exception):
    """A downstream on_record consumer raised — NOT a malformed log line."""


def convert_log_date_to_ms(date_str: str) -> str:
    """'' for falsy; audit ISO-with-offset or 'YYYY-MM-DD HH:MM:SS,mmm' (local
    time) -> epoch ms (stream_parse_transactions.js:242-256)."""
    if not date_str:
        return ""
    if _ISO_TZ_RE.search(date_str):
        return str(int(datetime.fromisoformat(date_str).timestamp() * 1000))
    parts = re.split(r"-|\s+|:|,", date_str.strip())
    dt = datetime(
        int(parts[0]), int(parts[1]), int(parts[2]),
        int(parts[3]), int(parts[4]), int(parts[5]), int(parts[6]) * 1000,
    )
    return str(int(dt.timestamp() * 1000))


def _strip_brackets(token: str) -> str:
    return token.replace("[", "").replace("]", "")


def _xml_text(line: str) -> str:
    """Text content of a single-tag XML line: strip the closing tag FIRST,

    then everything through the remaining (opening) '>' — order matters with
    greedy matching (stream_parse_transactions.js:669,677,682)."""
    return re.sub(r".*>", "", re.sub(r"</.*", "", line), count=1)


@dataclass
class _AutrContext:
    """Per-file audit-trail state (the reference's context map entry :579-731)."""

    autr_id_map: Dict[str, dict] = field(default_factory=dict)
    active_autr_id: Optional[str] = None
    active_log_id: Optional[str] = None
    active_alt_acct: Optional[str] = None
    elapsed_flag: bool = False
    sw_flag: bool = False
    active_service: Optional[str] = None
    service_map: Optional[Dict[str, List[dict]]] = None


@dataclass
class _SoapContext:
    log_id: str
    pull_next_value: bool = False


class TransactionParser:
    """Stateful multi-file log parser. Feed lines via read_line(file_path, line);

    completed records arrive at ``on_record(tx, insert_to_db)``."""

    def __init__(
        self,
        on_record: Callable[[TxEntry, bool], None],
        *,
        logger=None,
        clock: Callable[[], float] = time.monotonic,
        server_from_path: Optional[Callable[[str], str]] = None,
        record_ttl_s: float = 120.0,
        need_num_ttl_s: float = 30.0,
        acct_ttl_s: float = 120.0,
    ):
        self.on_record = on_record
        self.logger = logger
        # stage counters (ROADMAP "replay is parser-bound" quantification;
        # exported by obs.views.register_parser, surfaced by bench_replay):
        # plain dict ints — this is the per-line hot loop, registry
        # instruments stay out of it
        self.counters = {
            "lines_in": 0,      # raw lines through read_line
            "tx_out": 0,        # complete TxEntry records emitted
            "db_direct_out": 0, # records routed straight to the DB queue
            "parse_ns": 0,      # wall ns inside _read_line
        }
        self.server_from_path = server_from_path or (lambda fp: fp.split("/")[2] if len(fp.split("/")) > 2 else fp)
        # per-file dispatch cache: (kind, server) resolved ONCE per file
        # path, not per line — the filename classification and server
        # extraction are pure functions of the path, and read_line runs at
        # intake rates where two regex searches per line were ~15% of the
        # parser's whole budget
        self._file_info: Dict[str, tuple] = {}
        # per-file contexts: SOAP logId tracking + audit-trail state machines
        self._soap_ctx: Dict[str, _SoapContext] = {}
        self._autr_ctx: Dict[str, _AutrContext] = {}
        # logId -> acctNum (backfill source)
        self.acct_cache = TTLCache(acct_ttl_s, clock=clock)
        # logId -> {service: partial record}; expiry = no exit line found
        self.record_cache = TTLCache(record_ttl_s, clock=clock, on_expired=self._on_partial_expired)
        # logId -> {service: joined-but-numberless record}; expiry = emit anyway
        self.need_num_cache = TTLCache(need_num_ttl_s, clock=clock, on_expired=self._on_neednum_expired)

    # -- cache expiry --------------------------------------------------------
    def _on_partial_expired(self, log_id: str, service_map: dict) -> None:
        for service, rec in service_map.items():
            if self.logger:
                self.logger.error(
                    f"Partial record expired! No matching timing exit found. "
                    f"Discarding. Service: {service} logId: {log_id}"
                )

    def _on_neednum_expired(self, log_id: str, need_map: dict) -> None:
        for service, rec in need_map.items():
            self._output(
                rec.get("server", ""), service, log_id,
                rec.get("alt_acct") or "",
                rec.get("start_ts", ""), rec["end_ts"], rec["elapsed"],
                rec.get("insert_to_db", False),
            )

    def sweep(self) -> None:
        self.acct_cache.sweep()
        self.record_cache.sweep()
        self.need_num_cache.sweep()

    def drain(self) -> None:
        """End-of-replay: flush numberless records out, drop partials."""
        self.need_num_cache.flush_all()
        self.record_cache._store.clear()

    def cache_stats(self) -> dict:
        return {
            "acct": self.acct_cache.stats(),
            "record": self.record_cache.stats(),
            "need": self.need_num_cache.stats(),
        }

    # -- record emission -----------------------------------------------------
    def _output(self, server, service, log_id, acct_num, start_ts, end_ts, elapsed, insert_to_db=False):
        start_ms = convert_log_date_to_ms(start_ts)
        end_ms = convert_log_date_to_ms(end_ts)
        service = _PROVIDER_RE.sub("Provider:", service).replace("]", "")
        if not start_ms and end_ms:
            try:
                start_ms = str(int(end_ms) - int(elapsed))
            except (TypeError, ValueError):
                start_ms = ""
        top = "Y" if _TOPLEVEL_RE.match(service) else "N"
        tx = TxEntry(server, service, log_id, acct_num, start_ms, end_ms, elapsed, top)
        c = self.counters
        c["tx_out"] += 1
        if insert_to_db:
            c["db_direct_out"] += 1
        try:
            self.on_record(tx, insert_to_db)
        except Exception as e:
            raise ConsumerError(e) from e

    # -- account numbers -----------------------------------------------------
    def _save_acct_num(self, acct_num: str, file_path: str, source: str, alt_log_id: Optional[str] = None):
        acct_num = acct_num.strip()
        if not _DIGITS_RE.match(acct_num):
            if self.logger:
                self.logger.error(f"Invalid acctNum (SRC={source}): {acct_num!r} from {file_path}")
            return
        if source == "bafmetainfo":
            log_id = alt_log_id
            if not log_id:
                return
        else:
            ctx = self._soap_ctx.get(file_path)
            if ctx is None:
                return
            log_id = ctx.log_id
        self.acct_cache.set(log_id, acct_num)
        if source != "bafmetainfo":
            self._soap_ctx.pop(file_path, None)
        # backfill: release any parked numberless records for this logId
        need_map = self.need_num_cache.get(log_id)
        if need_map:
            server = self.server_from_path(file_path)
            for service in list(need_map):
                rec = need_map.pop(service)
                self._output(
                    rec.get("server") or server, service, log_id, acct_num,
                    rec.get("start_ts", ""), rec["end_ts"], rec["elapsed"],
                    rec.get("insert_to_db", False),
                )

    def _baf_meta_acct(self, line: str, file_path: str, log_id: str, tokens: List[str]) -> str:
        """Account number from BAF bracketed metadata, '' if absent (:486-497)."""
        if not _BAF_META_RE.search(line) or len(tokens) < 4:
            return ""
        info = re.sub(r".*]\[", "", tokens[3])
        info = _strip_brackets(info)
        acct = info.split(":")[-1]
        if acct:
            self._save_acct_num(acct, file_path, "bafmetainfo", log_id)
        return acct

    # -- SOAP ----------------------------------------------------------------
    def _parse_soap(self, line: str, file_path: str) -> None:
        if _SOAP_IN_RE.match(line):
            token = line.split()[1]
            self._soap_ctx[file_path] = _SoapContext(log_id=token.split("=")[1])
        elif _SOAP_OUT_RE.match(line):
            self._soap_ctx.pop(file_path, None)
        else:
            ctx = self._soap_ctx.get(file_path)
            if ctx is None:
                return
            if _SOAP_ACCT_RE.search(line):
                self._save_acct_num(re.split(r"<|>", line.strip())[2], file_path, "standard")
            elif _SOAP_ALT_KEY_RE.search(line):
                ctx.pull_next_value = True
            elif _SOAP_ALT_VALUE_RE.search(line) and ctx.pull_next_value:
                self._save_acct_num(re.split(r"<|>", line.strip())[2], file_path, "riskStrategy")

    # -- CommonTiming (EJB + standard) --------------------------------------
    def _park_partial(self, log_id: str, service: str, server: str, start_ts: str) -> None:
        smap = self.record_cache.get(log_id)
        if smap is None:
            smap = {}
            self.record_cache.set(log_id, smap)
        smap[service] = {"server": server, "start_ts": start_ts}

    def _join_exit(self, line, file_path, log_id, service, server, end_ts, elapsed, tokens, salvage: bool):
        smap = self.record_cache.get(log_id)
        partial = smap.get(service) if smap else None
        if partial is None:
            if self.logger:
                self.logger.error(
                    f"CommonTiming exit had no matching entry in the record cache. "
                    f"logId: {log_id} service: {service}"
                )
            if salvage:
                acct = self._baf_meta_acct(line, file_path, log_id, tokens)
                self._output(server, service, "", acct, "", end_ts, elapsed)
            else:
                self._output(server, service, "", "", "", end_ts, elapsed)
            return
        acct = self.acct_cache.get(log_id)
        if acct:
            self._output(server, service, log_id, acct, partial["start_ts"], end_ts, elapsed)
        else:
            alt = self._baf_meta_acct(line, file_path, log_id, tokens) if salvage else ""
            need = self.need_num_cache.get(log_id)
            if need is None:
                need = {}
                self.need_num_cache.set(log_id, need)
            need[service] = {
                "server": partial["server"], "start_ts": partial["start_ts"],
                "end_ts": end_ts, "elapsed": elapsed, "alt_acct": alt,
            }
        smap.pop(service, None)

    def _parse_ejb_entry(self, line: str, server: str) -> None:
        arr = line.split()
        log_id = _strip_brackets(arr[0])
        if not log_id:
            return
        self._park_partial(log_id, f"S:{arr[13]}", server, f"{arr[1]} {arr[2]}")

    def _parse_ejb_exit(self, line: str, file_path: str, server: str) -> None:
        arr = line.split()
        log_id = _strip_brackets(arr[0])
        end_ts = f"{arr[1]} {arr[2]}"
        service = f"S:{arr[9]}"
        elapsed = arr[11]
        if not log_id:
            self._output(server, service, "", "", "", end_ts, elapsed)
            return
        self._join_exit(line, file_path, log_id, service, server, end_ts, elapsed, arr, salvage=False)

    def _parse_ct_entry(self, line: str, server: str) -> None:
        arr = line.split()
        log_id = _strip_brackets(arr[0])
        if not log_id:
            return
        # split on INFO: BAF logs interleave bracketed metadata that breaks
        # positional token counts (:459)
        half = line.split("INFO", 1)[1].strip().split()
        self._park_partial(log_id, half[1], server, f"{arr[1]} {arr[2]}")

    def _parse_ct_exit(self, line: str, file_path: str, server: str) -> None:
        arr = line.split()
        half = line.split("INFO", 1)[1].strip().split()
        log_id = _strip_brackets(arr[0])
        end_ts = f"{arr[1]} {arr[2]}"
        service, elapsed = half[1], half[5]
        if not log_id:
            acct = self._baf_meta_acct(line, file_path, log_id, arr)
            self._output(server, service, "", acct, "", end_ts, elapsed)
            return
        self._join_exit(line, file_path, log_id, service, server, end_ts, elapsed, arr, salvage=True)

    # -- audit trail ---------------------------------------------------------
    def _parse_app_line(self, line: str, file_path: str, server: str) -> None:
        if _AUTR_MAP_RE.search(line):
            arr = line.split()
            log_id = _strip_brackets(arr[0])
            autr_id = arr[5].split("=")[1]
            ctx = self._autr_ctx.setdefault(file_path, _AutrContext())
            alt = self._baf_meta_acct(line, file_path, log_id, arr)
            ctx.autr_id_map[autr_id] = {"log_id": log_id, "alt_acct": alt}
            return
        if _AUTR_LINE_RE.match(line):
            ctx = self._autr_ctx.get(file_path)
            if ctx is None:
                if self.logger:
                    self.logger.error("Missing context for audit trail id line (startup race)")
                return
            autr_id = line.split(":")[1].strip()
            mapping = ctx.autr_id_map.pop(autr_id, None)
            if mapping is None or not mapping.get("log_id"):
                if self.logger:
                    self.logger.error(f"Could not resolve autrId {autr_id} to a logId")
                return
            ctx.service_map = {}
            ctx.active_autr_id = autr_id
            ctx.active_log_id = mapping["log_id"]
            ctx.active_alt_acct = mapping.get("alt_acct")
            ctx.elapsed_flag = False
            ctx.sw_flag = False
            ctx.active_service = None
            return

        ctx = self._autr_ctx.get(file_path)
        if ctx is None or not ctx.active_log_id:
            return  # random log line

        if _ELAPSED_START_RE.search(line):
            ctx.elapsed_flag = True
        elif ctx.elapsed_flag:
            if _ELAPSED_END_RE.match(line):
                ctx.elapsed_flag = False
            else:
                arr = line.split(":")
                service = arr[0].strip()
                elapsed = _strip_brackets(arr[1].split()[0])
                ctx.service_map.setdefault(service, []).append({"elapsed": elapsed})
        elif _SW_XML_START_RE.search(line):
            ctx.sw_flag = True
        elif ctx.sw_flag:
            if _SW_XML_END_RE.search(line):
                ctx.active_autr_id = None
                ctx.active_log_id = None
                ctx.active_alt_acct = None
                ctx.elapsed_flag = False
                ctx.sw_flag = False
                ctx.active_service = None
                ctx.service_map = None
            elif _SW_NAME_RE.search(line):
                ctx.active_service = _xml_text(line)
            elif ctx.active_service:
                if _SW_START_RE.search(line):
                    entries = ctx.service_map.get(ctx.active_service)
                    if not entries:
                        if self.logger:
                            self.logger.error(
                                f"No serviceMap entry for {ctx.active_service} on startTime"
                            )
                        return
                    entries[0]["start_ts"] = _xml_text(line)
                elif _SW_STOP_RE.search(line):
                    end_ts = _xml_text(line)
                    service = ctx.active_service
                    entries = ctx.service_map.get(service)
                    if not entries:
                        if self.logger:
                            self.logger.error(f"No serviceMap entry for {service} on stopTime")
                        return
                    rec = entries.pop(0)
                    log_id = ctx.active_log_id
                    acct = self.acct_cache.get(log_id)
                    # non-Provider audit records bypass stats straight to DB (:697)
                    insert_to_db = not _PROVIDER_RE.search(service)
                    if acct:
                        self._output(
                            server, service, log_id, acct,
                            rec.get("start_ts", ""), end_ts, rec["elapsed"], insert_to_db,
                        )
                    else:
                        need = self.need_num_cache.get(log_id)
                        if need is None:
                            need = {}
                            self.need_num_cache.set(log_id, need)
                        need[service] = {
                            "server": server, "start_ts": rec.get("start_ts", ""),
                            "end_ts": end_ts, "elapsed": rec["elapsed"],
                            "alt_acct": ctx.active_alt_acct, "insert_to_db": insert_to_db,
                        }

    # -- dispatch ------------------------------------------------------------
    def read_line(self, file_path: str, line: str) -> None:
        """Per-line dispatch; malformed lines are logged and skipped, never

        fatal (JS's out-of-range indexing yields undefined where Python would
        raise — fail-open is the equivalent robustness)."""
        c = self.counters
        c["lines_in"] += 1
        t0 = time.perf_counter_ns()
        try:
            self._read_line(file_path, line)
        except ConsumerError as e:
            # downstream (engine/sink) failure, not bad input — surface loudly
            if self.logger:
                self.logger.error(
                    f"Record consumer failed (record dropped) in {file_path}: "
                    f"{e.__cause__!r}: {line[:200]!r}"
                )
        except Exception as e:
            if self.logger:
                self.logger.error(f"Unparseable log line in {file_path}: {e}: {line[:200]!r}")
        finally:
            c["parse_ns"] += time.perf_counter_ns() - t0

    def _read_line(self, file_path: str, line: str) -> None:
        if not line:
            return
        info = self._file_info.get(file_path)
        if info is None:
            name = file_path.rsplit("/", 1)[-1]
            kind = (
                0 if _SOAP_FILE_RE.search(name)
                else 1 if _SERVER_FILE_RE.search(name)
                else 2
            )
            info = (kind, self.server_from_path(file_path))
            self._file_info[file_path] = info
        kind, server = info

        if kind == 0:
            self._parse_soap(line, file_path)
            return
        has_marker = _SERVER_DISPATCH_RE.search(line) is not None
        if kind == 1:  # server.log: EJB + standard CommonTiming forms
            if not has_marker:
                return
            # the reference's sequential priority ladder, run only on
            # marker-bearing lines (prefilter above)
            if _EJB_ENTRY_RE.search(line):
                self._parse_ejb_entry(line, server)
            elif _EJB_EXIT_RE.search(line):
                self._parse_ejb_exit(line, file_path, server)
            elif _CT_ENTRY_RE.search(line):
                self._parse_ct_entry(line, server)
            elif _CT_EXIT_RE.search(line):
                self._parse_ct_exit(line, file_path, server)
        else:  # APP log: CT forms only; EJB markers fall through to app state
            if has_marker and _CT_ENTRY_RE.search(line):
                self._parse_ct_entry(line, server)
            elif has_marker and _CT_EXIT_RE.search(line):
                self._parse_ct_exit(line, file_path, server)
            else:
                self._parse_app_line(line, file_path, server)
