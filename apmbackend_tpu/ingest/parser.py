"""Log-correlation parser: raw JVM log lines -> complete TxEntry records.

Reproduces the correlation semantics of stream_parse_transactions.js (the
reference's design notes :3-44):

- **SOAP logs** build a logId -> accountNumber map: an ``IO=I`` header opens a
  per-file context carrying the logId; a later ``<accountNumber>`` (or the
  riskid two-line ``<key>AccountNumber</key>`` / ``<value>`` form) saves the
  account number (:352-376).
- **CommonTiming entry/exit join**: entry lines park a partial record keyed
  (logId, service) in a TTL cache; the exit line joins it with the account
  cache into a full record (:378-446 EJB form, :451-565 standard form). A
  missing account number parks the joined record in a second, shorter-TTL
  cache that is flushed when the SOAP parser later finds the number
  (saveAcctNum backfill :294-327) or emitted without it on expiry (:226-239).
- **BAF salvage**: exit lines on BAF logs may carry the account number inside
  bracketed metadata before INFO; used as a last resort (:486-504).
- **Audit-trail state machine** (APP logs): a mapping line links autrId ->
  logId; the "Audit Trail id :" line activates a per-file context; the
  RequestTrace elapsed section collects per-subservice elapsed arrays (same
  subservice can repeat, consumed FIFO); the stopWatchList XML supplies
  start/stop timestamps per subservice; each completed subservice emits a
  record, with non-Provider records routed straight to the DB queue
  (insert_to_db) to skip stats processing (:578-731).
- Emitted records may lack logId/acctNum/startTs; startTs falls back to
  endTs - elapsed (:264-290). ``Provider[...]`` is normalized to
  ``Provider:...`` and ``S:`` marks top-level (:258,274,282).

Output is roughly ordered only (cache timeouts) — downstream re-orders via the
min-heap, like the reference (:7, stream_calc_stats.js:136-155).
"""

from __future__ import annotations

import os
import re
import time
from dataclasses import dataclass, field
from datetime import datetime
from typing import Callable, Dict, List, Optional, Union

from ..entries import TxEntry, format_tx_line
from .ttlcache import TTLCache

# Kill switch for the native (C++) ingest fast path — same pattern as
# APM_PCT_NO_RADIX: set APM_PARSE_NO_NATIVE=1 to force the pure-Python
# reference implementation (read_lines degrades to a per-line loop and the
# correlation record cache stays a Python TTLCache). Both paths produce
# bit-identical TxEntry streams (tests/test_parser_native_diff.py).
_NO_NATIVE_ENV = "APM_PARSE_NO_NATIVE"

_TOPLEVEL_RE = re.compile(r"^S:")
_PROVIDER_RE = re.compile(r"Provider\[", re.IGNORECASE)

_SOAP_IN_RE = re.compile(r"^=== jbossId.*IO=I")
_SOAP_OUT_RE = re.compile(r"^=== jbossId.*IO=O")
_SOAP_ACCT_RE = re.compile(r"<accountNumber>", re.IGNORECASE)
_SOAP_ALT_KEY_RE = re.compile(r"<key>AccountNumber</key>", re.IGNORECASE)
_SOAP_ALT_VALUE_RE = re.compile(r"<value>")

_EJB_ENTRY_RE = re.compile(r"INFO *\[CommonTiming] The EJB")
_EJB_EXIT_RE = re.compile(r"INFO *\[CommonTiming] Total time")
_CT_ENTRY_RE = re.compile(r"INFO *CommonTiming::Start")
_CT_EXIT_RE = re.compile(r"INFO *CommonTiming::Stop")

_BAF_META_RE = re.compile(r"\[[^ ]+] +INFO ")

_AUTR_MAP_RE = re.compile(r"INFO  auditTrailId=")
_AUTR_LINE_RE = re.compile(r"^Audit Trail id *:")
_ELAPSED_START_RE = re.compile(r": RequestTrace \[stopWatchList=")
_ELAPSED_END_RE = re.compile(r"^]")
_SW_XML_START_RE = re.compile(r"<stopWatchList>")
_SW_XML_END_RE = re.compile(r"</stopWatchList>")
_SW_NAME_RE = re.compile(r"<name>")
_SW_START_RE = re.compile(r"<startTime>")
_SW_STOP_RE = re.compile(r"<stopTime>")

_SOAP_FILE_RE = re.compile(r"soap_io")
_SERVER_FILE_RE = re.compile(r"server\.log")

# one alternation pass as PRE-FILTER *and* dispatcher: most lines carry no
# timing marker at all (payload/noise), and for them a single scan replaces
# up to four sequential searches. Each alternative is a GROUP so the match
# also identifies WHICH marker won at the leftmost position (m.lastindex,
# 1-4) — the regex engine tries alternatives left-to-right per position, so
# lastindex is the highest-priority marker AT the leftmost occurrence. The
# reference ladder (stream_parse_transactions.js:741-812) wants the
# highest-priority marker occurring ANYWHERE, which can differ only when a
# higher-priority marker occurs strictly AFTER the leftmost one — so only
# the (rare) patterns ABOVE lastindex are re-searched, from the match
# position on, instead of discarding the match and re-running the whole
# ladder (the double-regex this replaces).
_SERVER_DISPATCH_RE = re.compile(
    r"(INFO *\[CommonTiming] The EJB)"
    r"|(INFO *\[CommonTiming] Total time)"
    r"|(INFO *CommonTiming::Start)"
    r"|(INFO *CommonTiming::Stop)"
)
# ladder priorities 1..3 for the above re-search (priority 4 never needs one)
_LADDER_RES = (_EJB_ENTRY_RE, _EJB_EXIT_RE, _CT_ENTRY_RE)

_ISO_TZ_RE = re.compile(r"T.*-")
_DIGITS_RE = re.compile(r"^[0-9]+$")


class ConsumerError(Exception):
    """A downstream on_record consumer raised — NOT a malformed log line."""


_date_ms_cache: Dict[str, str] = {}
_minute_ms_cache: Dict[tuple, int] = {}


def convert_log_date_to_ms(date_str: str) -> str:
    """'' for falsy; audit ISO-with-offset or 'YYYY-MM-DD HH:MM:SS,mmm' (local
    time) -> epoch ms (stream_parse_transactions.js:242-256).

    This runs twice per emitted record — one of the two dominant
    per-emission costs — so it is memoized twice over, with NO numeric
    drift between the parser backends (both share this function):

    - a string-keyed memo (audit-trail blocks chain each stopTime into the
      next startTime; entry timestamps are re-parsed at exit join);
    - for the local-time form, a minute-keyed epoch cache: the expensive
      ``datetime(...).timestamp()`` runs once per distinct minute and the
      seconds/millis are added as exact integers. A minute-aligned
      timestamp is an integral float (no mantissa rounding) and DST
      transitions land on whole minutes, so ``minute_ms + s*1000 + mmm``
      IS the exact epoch value — strictly tighter than the previous
      per-call float path, whose *1000 product could truncate one ulp shy
      of the integer."""
    if not date_str:
        return ""
    cached = _date_ms_cache.get(date_str)
    if cached is not None:
        return cached
    if _ISO_TZ_RE.search(date_str):
        out = str(int(datetime.fromisoformat(date_str).timestamp() * 1000))
    else:
        ds = date_str.strip()
        sec = -1
        if (
            len(ds) == 23 and ds[4] == "-" and ds[7] == "-"
            and ds[10] == " " and ds[13] == ":" and ds[16] == ":"
            and ds[19] == ","
        ):
            # fixed-layout scan of the canonical WildFly form: re.split was
            # the single hottest memo-miss cost, and dense streams make
            # almost every call a miss (unique millis). The minute cache is
            # keyed by the 16-char prefix, so the hot path is one slice, one
            # dict hit and two int()s. Any non-digit slice falls through to
            # the general splitter, so junk input keeps the exact legacy
            # error behaviour (the prefix of a malformed minute can never be
            # cached — only successful parses insert).
            try:
                sec, ms = int(ds[17:19]), int(ds[20:23])
                mkey = ds[:16]
                base = _minute_ms_cache.get(mkey)
                if base is None:
                    base = int(datetime(
                        int(ds[:4]), int(ds[5:7]), int(ds[8:10]),
                        int(ds[11:13]), int(ds[14:16]),
                    ).timestamp()) * 1000
                    if len(_minute_ms_cache) >= 4096:
                        _minute_ms_cache.clear()
                    _minute_ms_cache[mkey] = base
            except ValueError:
                sec = -1
        if sec < 0:
            # general form: whitespace runs, fancy widths — the legacy path
            # (str prefix vs 5-tuple keys cannot collide in the shared cache)
            parts = re.split(r"-|\s+|:|,", ds)
            mkey = (parts[0], parts[1], parts[2], parts[3], parts[4])
            sec, ms = int(parts[5]), int(parts[6])
            base = _minute_ms_cache.get(mkey)
            if base is None:
                dt = datetime(
                    int(parts[0]), int(parts[1]), int(parts[2]),
                    int(parts[3]), int(parts[4]),
                )
                base = int(dt.timestamp()) * 1000
                if len(_minute_ms_cache) >= 4096:
                    _minute_ms_cache.clear()
                _minute_ms_cache[mkey] = base
        if not (0 <= sec <= 59 and 0 <= ms <= 999):
            # datetime() would have rejected these; keep the raise
            raise ValueError(f"second/millisecond out of range: {date_str!r}")
        out = str(base + sec * 1000 + ms)
    if len(_date_ms_cache) >= 16384:  # bounded: log time advances, keys churn
        _date_ms_cache.clear()
    _date_ms_cache[date_str] = out
    return out


def _strip_brackets(token: str) -> str:
    return token.replace("[", "").replace("]", "")


def _xml_text(line: str) -> str:
    """Text content of a single-tag XML line: strip the closing tag FIRST,

    then everything through the remaining (opening) '>' — order matters with
    greedy matching (stream_parse_transactions.js:669,677,682). Implemented
    with find/rfind, exactly equivalent to the original
    ``re.sub(r".*>", "", re.sub(r"</.*", "", line), count=1)``: the inner
    sub cuts at the FIRST "</" (the greedy tail eats the rest), the outer
    strips through the LAST '>' of the remainder."""
    cut = line.find("</")
    if cut >= 0:
        line = line[:cut]
    gt = line.rfind(">")
    return line[gt + 1:] if gt >= 0 else line


@dataclass
class _AutrContext:
    """Per-file audit-trail state (the reference's context map entry :579-731)."""

    autr_id_map: Dict[str, dict] = field(default_factory=dict)
    active_autr_id: Optional[str] = None
    active_log_id: Optional[str] = None
    active_alt_acct: Optional[str] = None
    elapsed_flag: bool = False
    sw_flag: bool = False
    active_service: Optional[str] = None
    service_map: Optional[Dict[str, List[dict]]] = None


@dataclass
class _SoapContext:
    log_id: str
    pull_next_value: bool = False


class _NativeRecordCache:
    """TTLCache-shaped facade over the native (logId, service) correlation
    map (native/parser.cpp) so read_line, tests, and cache_stats() see one
    coherent cache whether lines arrived via the batch fast path or the
    per-line reference path. Hit/miss/expiry semantics replicate TTLCache
    exactly (parity pinned by tests/test_parser_native_diff.py); the expiry
    callback fires from a drained batch instead of inline, which reorders
    only log lines, never records."""

    def __init__(self, engine, clock, on_expired_pair):
        self._e = engine
        self.clock = clock
        self._on_expired_pair = on_expired_pair
        self._server_ids: Dict[str, int] = {}
        self._server_names: List[str] = []

    def server_id(self, name: str) -> int:
        sid = self._server_ids.get(name)
        if sid is None:
            sid = len(self._server_names)
            self._server_ids[name] = sid
            self._server_names.append(name)
        return sid

    def server_name(self, sid: int) -> str:
        return self._server_names[sid]

    def _drain(self) -> None:
        if self._e.expired_pending():
            for lid, svc in self._e.drain_expired():
                self._on_expired_pair(
                    lid.decode("utf-8", "replace"), svc.decode("utf-8", "replace")
                )

    def park(self, log_id: str, service: str, server: str, start_ts: str) -> None:
        self._e.park(
            log_id.encode("utf-8", "replace"), service.encode("utf-8", "replace"),
            self.server_id(server), start_ts.encode("utf-8", "replace"),
            self.clock(),
        )
        self._drain()

    def take(self, log_id: str, service: str):
        """(server, start_ts) when found+popped, else None (key missing or
        service missing — _join_exit treats both as no-partial)."""
        r = self._e.take(
            log_id.encode("utf-8", "replace"), service.encode("utf-8", "replace"),
            self.clock(),
        )
        self._drain()
        if not r:  # None (no key) or () (key without this service)
            return None
        sid, ts = r
        return self.server_name(sid), ts.decode("utf-8", "replace")

    def get(self, key: str):
        """TTLCache.get view (counts a hit/miss, lazy-expires): the live
        service map as {service: {"server", "start_ts"}} — a COPY; parser
        internals mutate through park/take, not through this."""
        m = self._e.peek(key.encode("utf-8", "replace"), self.clock())
        self._drain()
        if m is None:
            return None
        return {
            svc.decode("utf-8", "replace"): {
                "server": self.server_name(sid),
                "start_ts": ts.decode("utf-8", "replace"),
            }
            for svc, (sid, ts) in m.items()
        }

    def sweep(self) -> None:
        self._e.sweep(self.clock())
        self._drain()

    def clear(self) -> None:
        self._e.clear()

    def stats(self) -> dict:
        keys, hits, misses = self._e.stats()
        return {"keys": keys, "hits": hits, "misses": misses}

    def __len__(self) -> int:
        return self._e.stats()[0]


class TransactionParser:
    """Stateful multi-file log parser. Feed lines via read_line(file_path, line);

    completed records arrive at ``on_record(tx, insert_to_db)``."""

    def __init__(
        self,
        on_record: Callable[[TxEntry, bool], None],
        *,
        logger=None,
        clock: Callable[[], float] = time.monotonic,
        server_from_path: Optional[Callable[[str], str]] = None,
        record_ttl_s: float = 120.0,
        need_num_ttl_s: float = 30.0,
        acct_ttl_s: float = 120.0,
        use_native: Optional[bool] = None,
        frame_sink: Optional[Callable[[bytes, int], None]] = None,
        frame_max_records: int = 512,
    ):
        self.on_record = on_record
        self.logger = logger
        # frame-emission mode (the zero-object byte spine): queue-bound
        # records skip TxEntry + on_record entirely — the finished CSV line
        # goes into a buffer that is packed into APF1 frame batches
        # (transport/frames.py) and handed to frame_sink(blob, n_records)
        # at chunk/sweep/drain boundaries or when frame_max_records
        # accumulate. db-direct records (insert_to_db=True) always keep the
        # per-record on_record path. APM_NO_FRAMES=1 kills the mode (the
        # APM_PARSE_NO_NATIVE pattern); frames OFF is the default wire.
        if os.environ.get("APM_NO_FRAMES", "") in ("1", "true"):
            frame_sink = None
        self.frame_sink = frame_sink
        self._frame_buf: list = []
        self._frame_max = max(1, int(frame_max_records))
        # carriage (APC1 trailer): per-record ingest stamps + the sampled
        # batch trace_id ride IN the frame, so e2e latency and trace
        # stitching survive fabrics that strip or never carry headers (the
        # pipelined shm-ring hop). APM_NO_FRAME_CARRIAGE=1 kills it —
        # frames then ship the bit-identical pre-carriage wire.
        self._frame_carriage = (
            frame_sink is not None
            and os.environ.get("APM_NO_FRAME_CARRIAGE", "") in ("", "0")
        )
        self._frame_ts: list = []  # per-record time.time(), parallel to _frame_buf
        # stage counters (ROADMAP "replay is parser-bound" quantification;
        # exported by obs.views.register_parser, surfaced by bench_replay):
        # plain dict ints — this is the per-line hot loop, registry
        # instruments stay out of it
        self.counters = {
            "lines_in": 0,      # raw lines through read_line/read_lines
            "tx_out": 0,        # complete TxEntry records emitted
            "db_direct_out": 0, # records routed straight to the DB queue
            "parse_ns": 0,      # wall ns inside _read_line / native chunks
            "native_lines": 0,  # lines that went through the native chunk path
            "prefilter_rejected": 0,  # lines the native pre-filter dropped
            "frames_emitted": 0,      # APF1 frame batches handed to frame_sink
            "frame_records_out": 0,   # records emitted via frames (no TxEntry)
        }
        self.server_from_path = server_from_path or (lambda fp: fp.split("/")[2] if len(fp.split("/")) > 2 else fp)
        # per-file dispatch cache: (kind, server, native server id) resolved
        # ONCE per file path, not per line — the filename classification and
        # server extraction are pure functions of the path, and read_line
        # runs at intake rates where two regex searches per line were ~15%
        # of the parser's whole budget
        self._file_info: Dict[str, tuple] = {}
        # per-file contexts: SOAP logId tracking + audit-trail state
        # machines. With the native engine BOTH live in C++ (the soap dict
        # is reached through the _soap_* accessors; app lines route through
        # the native machine even from read_line) so the batch and per-line
        # APIs share one state; these dicts serve the pure-Python path.
        self._soap_ctx: Dict[str, _SoapContext] = {}
        self._autr_ctx: Dict[str, _AutrContext] = {}
        self._file_ids: Dict[str, int] = {}
        self._clock = clock
        # trace plane (obs/trace): the parser is the raw-read ingest
        # boundary — read_lines notes each chunk's wall time so the producer
        # can anchor the sampled ingest span there. One attribute load +
        # integer compare per CHUNK (never per line); rate 0 = no-op.
        from ..obs.trace import get_tracer

        self._obs_tracer = get_tracer()
        # attribution plane (obs/attrib): the parser owns two stages —
        # the scan itself (mirrors parse_ns at chunk granularity) and the
        # frame pack. Cached clock references; no-ops when the plane is off.
        from ..obs.attrib import STAGE_FRAME_PACK, STAGE_PARSER_SCAN, get_attrib

        _att = get_attrib()
        self._att_scan = _att.clock(STAGE_PARSER_SCAN)
        self._att_pack = _att.clock(STAGE_FRAME_PACK)
        # logId -> acctNum (backfill source)
        self.acct_cache = TTLCache(acct_ttl_s, clock=clock)
        # the native ingest fast path (marker pre-filter + field extraction
        # + correlation join in C++); None -> pure-Python reference path
        self._native = None
        if use_native if use_native is not None else (
            os.environ.get(_NO_NATIVE_ENV, "") not in ("1", "true")
        ):
            try:
                from ..native import ParserEngineNative

                self._native = ParserEngineNative(
                    record_ttl_s, max(record_ttl_s / 4, 1), clock()
                )
            except Exception:
                self._native = None  # no toolchain: Python fallback
        # logId -> {service: partial record}; expiry = no exit line found.
        # With the native engine the map lives in C++ (read_line and
        # read_lines share it through the park/take shims); the TTLCache
        # reference implementation is kept behind APM_PARSE_NO_NATIVE=1.
        if self._native is not None:
            self.record_cache = _NativeRecordCache(
                self._native, clock, self._on_partial_expired_pair
            )
        else:
            self.record_cache = TTLCache(record_ttl_s, clock=clock, on_expired=self._on_partial_expired)
        # logId -> {service: joined-but-numberless record}; expiry = emit anyway
        self.need_num_cache = TTLCache(need_num_ttl_s, clock=clock, on_expired=self._on_neednum_expired)

    # -- cache expiry --------------------------------------------------------
    def _on_partial_expired(self, log_id: str, service_map: dict) -> None:
        for service, rec in service_map.items():
            self._on_partial_expired_pair(log_id, service)

    def _on_partial_expired_pair(self, log_id: str, service: str) -> None:
        if self.logger:
            self.logger.error(
                f"Partial record expired! No matching timing exit found. "
                f"Discarding. Service: {service} logId: {log_id}"
            )

    def _on_neednum_expired(self, log_id: str, need_map: dict) -> None:
        for service, rec in need_map.items():
            self._output(
                rec.get("server", ""), service, log_id,
                rec.get("alt_acct") or "",
                rec.get("start_ts", ""), rec["end_ts"], rec["elapsed"],
                rec.get("insert_to_db", False),
            )

    def sweep(self) -> None:
        self.acct_cache.sweep()
        self.record_cache.sweep()
        self.need_num_cache.sweep()
        if self._frame_buf:
            self._flush_frames_safe("<sweep>")

    def drain(self) -> None:
        """End-of-replay: flush numberless records out, drop partials."""
        self.need_num_cache.flush_all()
        self.record_cache.clear()
        if self._frame_buf:
            self._flush_frames_safe("<drain>")

    # -- frame emission ------------------------------------------------------
    def flush_frames(self) -> None:
        """Pack buffered frame-mode lines into one APF1 batch — plus the
        carriage trailer (per-record ingest deltas off the batch's min
        stamp, and a head-sampled trace_id: one should_sample compare per
        BATCH, deterministic in the frames_emitted sequence) — and hand it
        to frame_sink. Called at chunk/sweep/drain boundaries and when the
        buffer reaches frame_max_records; a sink failure raises
        ConsumerError (batch dropped loudly, like a failed on_record)."""
        buf = self._frame_buf
        if not buf:
            return
        ts = self._frame_ts
        self._frame_buf = []
        self._frame_ts = []
        from ..transport import frames as _frames

        t0 = time.perf_counter()
        blob = _frames.encode_lines(buf)
        if self._frame_carriage and len(ts) == len(buf):
            base = min(ts)
            tr = self._obs_tracer
            seq = self.counters["frames_emitted"]
            trace_id = ""
            if tr.should_sample(seq):
                trace_id = f"tf-{os.getpid():x}-{seq}"
            blob = _frames.append_carriage(
                blob, base,
                [int((t - base) * 1000.0 + 0.5) for t in ts], trace_id,
            )
            if trace_id:
                # the batch's ingest span: raw-read anchor (chunk boundary)
                # -> packed and handed to the fabric
                tr.span(trace_id, "ingest", tr.ingest_start or base,
                        time.time(), records=len(buf))
        self._att_pack.add_busy(time.perf_counter() - t0)
        self.counters["frames_emitted"] += 1
        try:
            self.frame_sink(blob, len(buf))
        except Exception as e:
            raise ConsumerError(e) from e

    def _flush_frames_safe(self, where: str) -> None:
        try:
            self.flush_frames()
        except ConsumerError as e:
            if self.logger:
                self.logger.error(
                    f"Frame sink failed (batch dropped) at {where}: "
                    f"{e.__cause__!r}"
                )

    def cache_stats(self) -> dict:
        return {
            "acct": self.acct_cache.stats(),
            "record": self.record_cache.stats(),
            "need": self.need_num_cache.stats(),
        }

    # -- record emission -----------------------------------------------------
    def _output(self, server, service, log_id, acct_num, start_ts, end_ts, elapsed, insert_to_db=False):
        start_ms = convert_log_date_to_ms(start_ts)
        end_ms = convert_log_date_to_ms(end_ts)
        if "[" in service or "]" in service:
            # the sub/replace only fire on bracketed services; the gate
            # skips two regex passes on the (majority) plain names
            service = _PROVIDER_RE.sub("Provider:", service).replace("]", "")
        if not start_ms and end_ms:
            try:
                start_ms = str(int(end_ms) - int(elapsed))
            except (TypeError, ValueError):
                start_ms = ""
        top = "Y" if service.startswith("S:") else "N"  # == _TOPLEVEL_RE.match
        c = self.counters
        if self.frame_sink is not None and not insert_to_db:
            # frame mode, queue-bound record: format the CSV line directly
            # (format_tx_line == TxEntry(...).to_csv() byte for byte) and
            # buffer it for batch packing — no TxEntry, no on_record
            c["tx_out"] += 1
            c["frame_records_out"] += 1
            # start/end go in as OUR canonical str(int(...)) strings: the
            # _csv_num digit fast path renders them verbatim, which is the
            # same byte output the int(...) round trip produced ('' still
            # coerces to NaN; negatives and >15-digit strings take the full
            # js_parse_int route and agree with int()'s reading exactly)
            self._frame_buf.append(format_tx_line(
                server, service, log_id, acct_num, start_ms, end_ms,
                elapsed, top,
            ))
            if self._frame_carriage:
                self._frame_ts.append(time.time())
            if len(self._frame_buf) >= self._frame_max:
                self.flush_frames()
            return
        # start/end are OUR str(int(...)) strings (or ''): int() parses
        # them identically to js_parse_int, and TxEntry's int fast path
        # skips the per-field regex — '' stays '' and parses to NaN as
        # before. elapsed/acct_num come from the wild and keep the full
        # js_parse_int treatment inside TxEntry.
        tx = TxEntry(
            server, service, log_id, acct_num,
            int(start_ms) if start_ms else "",
            int(end_ms) if end_ms else "",
            elapsed, top,
        )
        c["tx_out"] += 1
        if insert_to_db:
            c["db_direct_out"] += 1
        try:
            self.on_record(tx, insert_to_db)
        except Exception as e:
            raise ConsumerError(e) from e

    # -- account numbers -----------------------------------------------------
    def _save_acct_num(self, acct_num: str, file_path: str, source: str, alt_log_id: Optional[str] = None):
        acct_num = acct_num.strip()
        if not _DIGITS_RE.match(acct_num):
            if self.logger:
                self.logger.error(f"Invalid acctNum (SRC={source}): {acct_num!r} from {file_path}")
            return
        if source == "bafmetainfo":
            log_id = alt_log_id
            if not log_id:
                return
        else:
            st = self._soap_state(file_path)
            if st is None:
                return
            log_id = st[0]
        self.acct_cache.set(log_id, acct_num)
        if source != "bafmetainfo":
            self._soap_close(file_path)
        self._backfill_need(log_id, acct_num, file_path)

    def _backfill_need(self, log_id: str, acct_num: str, file_path: str) -> None:
        """Release any parked numberless records for this logId."""
        need_map = self.need_num_cache.get(log_id)
        if need_map:
            server = self.server_from_path(file_path)
            for service in list(need_map):
                rec = need_map.pop(service)
                self._output(
                    rec.get("server") or server, service, log_id, acct_num,
                    rec.get("start_ts", ""), rec["end_ts"], rec["elapsed"],
                    rec.get("insert_to_db", False),
                )

    def _baf_meta_acct(self, line: str, file_path: str, log_id: str, tokens: List[str]) -> str:
        """Account number from BAF bracketed metadata, '' if absent (:486-497)."""
        if not _BAF_META_RE.search(line) or len(tokens) < 4:
            return ""
        info = re.sub(r".*]\[", "", tokens[3])
        info = _strip_brackets(info)
        acct = info.split(":")[-1]
        if acct:
            self._save_acct_num(acct, file_path, "bafmetainfo", log_id)
        return acct

    # -- SOAP ----------------------------------------------------------------
    # Context accessors: the per-file SOAP state lives in the native engine
    # when it is active (shared with the batch machine), else in _soap_ctx.
    def _soap_state(self, file_path: str):
        """(log_id, pull_next_value) of the open context, or None."""
        if self._native is not None:
            st = self._native.soap_get(self._file_info_for(file_path)[3])
            if st is None:
                return None
            return st[0].decode("utf-8", "replace"), st[1]
        ctx = self._soap_ctx.get(file_path)
        return None if ctx is None else (ctx.log_id, ctx.pull_next_value)

    def _soap_open(self, file_path: str, log_id: str) -> None:
        if self._native is not None:
            self._native.soap_set(
                self._file_info_for(file_path)[3],
                log_id.encode("utf-8", "replace"),
            )
        else:
            self._soap_ctx[file_path] = _SoapContext(log_id=log_id)

    def _soap_arm(self, file_path: str) -> None:
        if self._native is not None:
            self._native.soap_arm(self._file_info_for(file_path)[3])
        else:
            ctx = self._soap_ctx.get(file_path)
            if ctx is not None:
                ctx.pull_next_value = True

    def _soap_close(self, file_path: str) -> None:
        if self._native is not None:
            self._native.soap_close(self._file_info_for(file_path)[3])
        else:
            self._soap_ctx.pop(file_path, None)

    def _parse_soap(self, line: str, file_path: str) -> None:
        if _SOAP_IN_RE.match(line):
            token = line.split()[1]
            self._soap_open(file_path, token.split("=")[1])
        elif _SOAP_OUT_RE.match(line):
            self._soap_close(file_path)
        else:
            st = self._soap_state(file_path)
            if st is None:
                return
            if _SOAP_ACCT_RE.search(line):
                self._save_acct_num(re.split(r"<|>", line.strip())[2], file_path, "standard")
            elif _SOAP_ALT_KEY_RE.search(line):
                self._soap_arm(file_path)
            elif _SOAP_ALT_VALUE_RE.search(line) and st[1]:
                self._save_acct_num(re.split(r"<|>", line.strip())[2], file_path, "riskStrategy")

    # -- CommonTiming (EJB + standard) --------------------------------------
    # Record-cache access goes through park/take so the reference handlers
    # and the native event loop share ONE map regardless of backend. The
    # TTLCache branch reproduces the original inline get/set/pop sequence
    # byte-for-byte (incl. hit/miss accounting); the native branch defers to
    # the C++ map with identical semantics.
    def _park_partial(self, log_id: str, service: str, server: str, start_ts: str) -> None:
        rc = self.record_cache
        if self._native is not None:
            rc.park(log_id, service, server, start_ts)
            return
        smap = rc.get(log_id)
        if smap is None:
            smap = {}
            rc.set(log_id, smap)
        smap[service] = {"server": server, "start_ts": start_ts}

    def _take_partial(self, log_id: str, service: str):
        """(server, start_ts) of the parked partial — popped — or None."""
        rc = self.record_cache
        if self._native is not None:
            return rc.take(log_id, service)
        smap = rc.get(log_id)
        partial = smap.get(service) if smap else None
        if partial is None:
            return None
        smap.pop(service, None)
        return partial["server"], partial["start_ts"]

    def _join_exit(self, line, file_path, log_id, service, server, end_ts, elapsed, tokens, salvage: bool):
        partial = self._take_partial(log_id, service)
        if partial is None:
            if self.logger:
                self.logger.error(
                    f"CommonTiming exit had no matching entry in the record cache. "
                    f"logId: {log_id} service: {service}"
                )
            if salvage:
                acct = self._baf_meta_acct(line, file_path, log_id, tokens)
                self._output(server, service, "", acct, "", end_ts, elapsed)
            else:
                self._output(server, service, "", "", "", end_ts, elapsed)
            return
        p_server, p_start_ts = partial
        acct = self.acct_cache.get(log_id)
        if acct:
            self._output(server, service, log_id, acct, p_start_ts, end_ts, elapsed)
        else:
            alt = self._baf_meta_acct(line, file_path, log_id, tokens) if salvage else ""
            self._park_need_num(
                log_id, service, p_server, p_start_ts, end_ts, elapsed, alt
            )

    def _park_need_num(self, log_id, service, server, start_ts, end_ts, elapsed,
                       alt_acct, insert_to_db=None) -> None:
        need = self.need_num_cache.get(log_id)
        if need is None:
            need = {}
            self.need_num_cache.set(log_id, need)
        rec = {
            "server": server, "start_ts": start_ts,
            "end_ts": end_ts, "elapsed": elapsed, "alt_acct": alt_acct,
        }
        if insert_to_db is not None:
            rec["insert_to_db"] = insert_to_db
        need[service] = rec

    def _parse_ejb_entry(self, line: str, server: str) -> None:
        arr = line.split()
        log_id = _strip_brackets(arr[0])
        if not log_id:
            return
        self._park_partial(log_id, f"S:{arr[13]}", server, f"{arr[1]} {arr[2]}")

    def _parse_ejb_exit(self, line: str, file_path: str, server: str) -> None:
        arr = line.split()
        log_id = _strip_brackets(arr[0])
        end_ts = f"{arr[1]} {arr[2]}"
        service = f"S:{arr[9]}"
        elapsed = arr[11]
        if not log_id:
            self._output(server, service, "", "", "", end_ts, elapsed)
            return
        self._join_exit(line, file_path, log_id, service, server, end_ts, elapsed, arr, salvage=False)

    def _parse_ct_entry(self, line: str, server: str) -> None:
        arr = line.split()
        log_id = _strip_brackets(arr[0])
        if not log_id:
            return
        # split on INFO: BAF logs interleave bracketed metadata that breaks
        # positional token counts (:459)
        half = line.split("INFO", 1)[1].strip().split()
        self._park_partial(log_id, half[1], server, f"{arr[1]} {arr[2]}")

    def _parse_ct_exit(self, line: str, file_path: str, server: str) -> None:
        arr = line.split()
        half = line.split("INFO", 1)[1].strip().split()
        log_id = _strip_brackets(arr[0])
        end_ts = f"{arr[1]} {arr[2]}"
        service, elapsed = half[1], half[5]
        if not log_id:
            acct = self._baf_meta_acct(line, file_path, log_id, arr)
            self._output(server, service, "", acct, "", end_ts, elapsed)
            return
        self._join_exit(line, file_path, log_id, service, server, end_ts, elapsed, arr, salvage=True)

    # -- audit trail ---------------------------------------------------------
    def _parse_app_line(self, line: str, file_path: str, server: str) -> None:
        if _AUTR_MAP_RE.search(line):
            arr = line.split()
            log_id = _strip_brackets(arr[0])
            autr_id = arr[5].split("=")[1]
            ctx = self._autr_ctx.setdefault(file_path, _AutrContext())
            alt = self._baf_meta_acct(line, file_path, log_id, arr)
            ctx.autr_id_map[autr_id] = {"log_id": log_id, "alt_acct": alt}
            return
        if _AUTR_LINE_RE.match(line):
            ctx = self._autr_ctx.get(file_path)
            if ctx is None:
                if self.logger:
                    self.logger.error("Missing context for audit trail id line (startup race)")
                return
            autr_id = line.split(":")[1].strip()
            mapping = ctx.autr_id_map.pop(autr_id, None)
            if mapping is None or not mapping.get("log_id"):
                if self.logger:
                    self.logger.error(f"Could not resolve autrId {autr_id} to a logId")
                return
            ctx.service_map = {}
            ctx.active_autr_id = autr_id
            ctx.active_log_id = mapping["log_id"]
            ctx.active_alt_acct = mapping.get("alt_acct")
            ctx.elapsed_flag = False
            ctx.sw_flag = False
            ctx.active_service = None
            return

        ctx = self._autr_ctx.get(file_path)
        if ctx is None or not ctx.active_log_id:
            return  # random log line

        if _ELAPSED_START_RE.search(line):
            ctx.elapsed_flag = True
        elif ctx.elapsed_flag:
            if _ELAPSED_END_RE.match(line):
                ctx.elapsed_flag = False
            else:
                arr = line.split(":")
                service = arr[0].strip()
                elapsed = _strip_brackets(arr[1].split()[0])
                ctx.service_map.setdefault(service, []).append({"elapsed": elapsed})
        elif _SW_XML_START_RE.search(line):
            ctx.sw_flag = True
        elif ctx.sw_flag:
            if _SW_XML_END_RE.search(line):
                ctx.active_autr_id = None
                ctx.active_log_id = None
                ctx.active_alt_acct = None
                ctx.elapsed_flag = False
                ctx.sw_flag = False
                ctx.active_service = None
                ctx.service_map = None
            elif _SW_NAME_RE.search(line):
                ctx.active_service = _xml_text(line)
            elif ctx.active_service:
                if _SW_START_RE.search(line):
                    entries = ctx.service_map.get(ctx.active_service)
                    if not entries:
                        if self.logger:
                            self.logger.error(
                                f"No serviceMap entry for {ctx.active_service} on startTime"
                            )
                        return
                    entries[0]["start_ts"] = _xml_text(line)
                elif _SW_STOP_RE.search(line):
                    end_ts = _xml_text(line)
                    service = ctx.active_service
                    entries = ctx.service_map.get(service)
                    if not entries:
                        if self.logger:
                            self.logger.error(f"No serviceMap entry for {service} on stopTime")
                        return
                    rec = entries.pop(0)
                    log_id = ctx.active_log_id
                    acct = self.acct_cache.get(log_id)
                    # non-Provider audit records bypass stats straight to DB (:697)
                    insert_to_db = not _PROVIDER_RE.search(service)
                    if acct:
                        self._output(
                            server, service, log_id, acct,
                            rec.get("start_ts", ""), end_ts, rec["elapsed"], insert_to_db,
                        )
                    else:
                        self._park_need_num(
                            log_id, service, server, rec.get("start_ts", ""),
                            end_ts, rec["elapsed"], ctx.active_alt_acct,
                            insert_to_db,
                        )

    # -- dispatch ------------------------------------------------------------
    def read_line(self, file_path: str, line: str) -> None:
        """Per-line dispatch; malformed lines are logged and skipped, never

        fatal (JS's out-of-range indexing yields undefined where Python would
        raise — fail-open is the equivalent robustness)."""
        c = self.counters
        c["lines_in"] += 1
        t0 = time.perf_counter_ns()
        try:
            self._read_line(file_path, line)
        except ConsumerError as e:
            # downstream (engine/sink) failure, not bad input — surface loudly
            if self.logger:
                self.logger.error(
                    f"Record consumer failed (record dropped) in {file_path}: "
                    f"{e.__cause__!r}: {line[:200]!r}"
                )
        except Exception as e:
            if self.logger:
                self.logger.error(f"Unparseable log line in {file_path}: {e}: {line[:200]!r}")
        finally:
            dt = time.perf_counter_ns() - t0
            c["parse_ns"] += dt
            self._att_scan.add_busy(dt * 1e-9)

    # -- batch API (native ingest fast path) ---------------------------------
    def read_lines(self, file_path: str, data: Union[bytes, str]) -> int:
        """Feed a chunk of complete '\\n'-separated lines from one file.

        The batch counterpart of read_line and the parser's hot path: with
        the native engine the chunk takes ONE pass through C++ (marker
        pre-filter + field extraction + correlation join) and only
        marker-relevant lines ever become Python objects; without it (no
        toolchain, or APM_PARSE_NO_NATIVE=1) the chunk degrades to the
        per-line reference loop. Both produce bit-identical TxEntry streams
        and cache statistics. A trailing newline terminates the last line
        (no empty final line); interior empty lines count as (no-op) lines,
        matching read_line('') semantics. Returns the number of lines.
        """
        if isinstance(data, str):
            data = data.encode("utf-8", "replace")
        if not data:
            return 0
        self._obs_tracer.note_ingest_start()  # chunk-granular ingest anchor
        if self._native is None:
            segs = data.decode("utf-8", "replace").split("\n")
            if segs[-1] == "" and len(segs) > 1:
                segs.pop()
            for line in segs:
                self.read_line(file_path, line)
            if self._frame_buf:
                self._flush_frames_safe(file_path)
            return len(segs)
        c = self.counters
        t0 = time.perf_counter_ns()
        try:
            return self._read_lines_native(file_path, data)
        finally:
            dt = time.perf_counter_ns() - t0
            c["parse_ns"] += dt
            self._att_scan.add_busy(dt * 1e-9)
            if self._frame_buf:
                self._flush_frames_safe(file_path)

    def _read_lines_native(self, file_path: str, data: bytes) -> int:
        info = self._file_info_for(file_path)
        c = self.counters
        before = c["lines_in"]
        off = 0
        while off < len(data):
            # a RAW barrier stops the native scan mid-chunk so the Python
            # replay runs in strict line order against the shared state;
            # re-invoke on the remainder (rare: exotic/malformed lines only)
            consumed = self._native_chunk(
                file_path, info, data[off:] if off else data, count=True
            )
            off += consumed
        return c["lines_in"] - before

    def _native_chunk(self, file_path: str, info, data: bytes,
                      count: bool = False) -> int:
        """One native scan pass; processes its events. Returns bytes
        consumed (== len(data) unless a RAW barrier stopped the scan)."""
        kind, server, sid, fid = info
        eng = self._native
        ev, pool, counts = eng.chunk(data, kind, sid, fid, self._clock())
        if count:
            c = self.counters
            c["lines_in"] += counts[0]
            c["native_lines"] += counts[0]
            c["prefilter_rejected"] += counts[1]
        if eng.expired_pending():
            self.record_cache._drain()
        consumed = counts[5]
        if not len(ev):
            return consumed

        # span decode: off >= 0 -> chunk buffer, off < 0 -> pool. Every
        # non-RAW span is pure ASCII (exotic lines are routed RAW), so one
        # latin-1 decode of the whole chunk up front — 1:1 bytes->chars,
        # byte offsets stay valid — and plain str slicing per span replace
        # a bytes-slice + decode pair per field; for ASCII spans the result
        # equals the reference's errors='replace' slicing exactly.
        dstr = data.decode("latin-1")
        pstr = pool.decode("latin-1")

        def sp(off, ln):
            if off >= 0:
                return dstr[off: off + ln]
            s = -off - 1
            return pstr[s: s + ln]

        CLS_EJB_EXIT = eng.CLS_EJB_EXIT
        CLS_CT_EXIT = eng.CLS_CT_EXIT
        CLS_AUDIT_STOP = eng.CLS_AUDIT_STOP
        # field indexes into the event row (EVENT dtype order); rows are
        # indexed selectively per class — the hot classes touch a handful
        # of fields and a full 19-name unpack per event is measurable here
        for row in ev.tolist():
            cls = row[2]
            try:
                if cls == CLS_EJB_EXIT or cls == CLS_CT_EXIT:
                    baf_len = row[16]
                    self._exit_event(
                        file_path, server, cls == CLS_CT_EXIT, row[3],
                        sp(row[4], row[5]) if row[5] >= 0 else "",
                        sp(row[6], row[7]), sp(row[8], row[9]),
                        sp(row[10], row[11]),
                        row[14], sp(row[12], row[13]) if row[13] >= 0 else "",
                        sp(row[15], baf_len) if baf_len >= 0 else None,
                    )
                elif cls == CLS_AUDIT_STOP:
                    self._audit_stop_event(
                        server, sp(row[8], row[9]), sp(row[4], row[5]),
                        sp(row[6], row[7]), sp(row[12], row[13]),
                        sp(row[10], row[11]), sp(row[15], row[16]),
                        bool(row[3] & 16),  # FL_INSERT_DB
                    )
                elif cls == 12 or cls == 14:  # SOAP_ACCT / SOAP_ALT_VALUE
                    self._save_acct_event(
                        sp(row[6], row[7]), file_path, sp(row[4], row[5]),
                        "standard" if cls == 12 else "riskStrategy",
                    )
                elif cls == 21:  # CLS_ACCT_SAVE_BAF (audit map line)
                    self._save_acct_num(
                        sp(row[6], row[7]), file_path, "bafmetainfo",
                        sp(row[4], row[5]),
                    )
                elif cls == 23:  # CLS_AUDIT_LOG
                    self._audit_log_event(
                        row[17], sp(row[8], row[9]) if row[9] >= 0 else "",
                        file_path, data, row[0], row[1],
                    )
                else:  # CLS_RAW
                    # exotic / malformed line: the reference handler decides
                    # (record/soap state reached through the backend shims)
                    self._read_line_ref(
                        file_path,
                        data[row[0]: row[0] + row[1]].decode("utf-8", "replace"),
                    )
            except ConsumerError as e:
                if self.logger:
                    line = data[row[0]: row[0] + row[1]].decode("utf-8", "replace")
                    self.logger.error(
                        f"Record consumer failed (record dropped) in {file_path}: "
                        f"{e.__cause__!r}: {line[:200]!r}"
                    )
            except Exception as e:
                if self.logger:
                    line = data[row[0]: row[0] + row[1]].decode("utf-8", "replace")
                    self.logger.error(
                        f"Unparseable log line in {file_path}: {e}: {line[:200]!r}"
                    )
        return consumed

    def _save_acct_event(self, acct_num: str, file_path: str, log_id: str,
                         source: str) -> None:
        """_save_acct_num's SOAP tail with the context logId captured at
        scan time (the native machine already closed the context on a
        digits-valid number, exactly where the reference pops it)."""
        acct_num = acct_num.strip()
        if not _DIGITS_RE.match(acct_num):
            if self.logger:
                self.logger.error(f"Invalid acctNum (SRC={source}): {acct_num!r} from {file_path}")
            return
        self.acct_cache.set(log_id, acct_num)
        self._backfill_need(log_id, acct_num, file_path)

    def _audit_stop_event(self, server, service, log_id, start_ts, end_ts,
                          elapsed, alt_acct, insert_to_db: bool) -> None:
        """The stopTime emission tail of _parse_app_line (the state machine
        itself ran natively)."""
        acct = self.acct_cache.get(log_id)
        if acct:
            self._output(server, service, log_id, acct, start_ts, end_ts,
                         elapsed, insert_to_db)
        else:
            self._park_need_num(log_id, service, server, start_ts, end_ts,
                                elapsed, alt_acct, insert_to_db)

    def _audit_log_event(self, code: int, detail: str, file_path: str,
                         data: bytes, line_off: int, line_len: int) -> None:
        """Reference log lines whose branches ran natively (log text parity;
        no record/state effect)."""
        if not self.logger:
            return
        if code == 1:
            self.logger.error("Missing context for audit trail id line (startup race)")
        elif code == 2:
            self.logger.error(f"Could not resolve autrId {detail} to a logId")
        elif code == 3:
            self.logger.error(f"No serviceMap entry for {detail} on startTime")
        elif code == 4:
            self.logger.error(f"No serviceMap entry for {detail} on stopTime")
        elif code == 5:
            line = data[line_off: line_off + line_len].decode("utf-8", "replace")
            self.logger.error(
                f"Unparseable log line in {file_path}: list index out of range: {line[:200]!r}"
            )

    def _baf_salvage(self, flags: int, tok3: Optional[str], file_path: str,
                     log_id: str) -> str:
        """_baf_meta_acct with the regex gate + tokens[3] precomputed
        natively (FL_BAF iff _BAF_META_RE matched and len(tokens) >= 4)."""
        if not (flags & self._native.FL_BAF) or tok3 is None:
            return ""
        info = re.sub(r".*]\[", "", tok3)
        info = _strip_brackets(info)
        acct = info.split(":")[-1]
        if acct:
            self._save_acct_num(acct, file_path, "bafmetainfo", log_id)
        return acct

    def _exit_event(self, file_path, server, salvage, flags, log_id, end_ts,
                    service, elapsed, jserver, jts, baf_tok) -> None:
        """_parse_ejb_exit/_parse_ct_exit + _join_exit with extraction AND
        the record-cache take already done natively (keep in lockstep with
        those handlers — parity pinned by test_parser_native_diff)."""
        eng = self._native
        if flags & eng.FL_LOGID_EMPTY:
            acct = self._baf_salvage(flags, baf_tok, file_path, "") if salvage else ""
            self._output(server, service, "", acct, "", end_ts, elapsed)
            return
        if not (flags & eng.FL_JOIN_FOUND):
            if self.logger:
                self.logger.error(
                    f"CommonTiming exit had no matching entry in the record cache. "
                    f"logId: {log_id} service: {service}"
                )
            if salvage:
                acct = self._baf_salvage(flags, baf_tok, file_path, log_id)
                self._output(server, service, "", acct, "", end_ts, elapsed)
            else:
                self._output(server, service, "", "", "", end_ts, elapsed)
            return
        p_server = self.record_cache.server_name(jserver)
        acct = self.acct_cache.get(log_id)
        if acct:
            self._output(server, service, log_id, acct, jts, end_ts, elapsed)
        else:
            alt = self._baf_salvage(flags, baf_tok, file_path, log_id) if salvage else ""
            self._park_need_num(log_id, service, p_server, jts, end_ts, elapsed, alt)

    def _file_info_for(self, file_path: str) -> tuple:
        info = self._file_info.get(file_path)
        if info is None:
            name = file_path.rsplit("/", 1)[-1]
            kind = (
                0 if _SOAP_FILE_RE.search(name)
                else 1 if _SERVER_FILE_RE.search(name)
                else 2
            )
            server = self.server_from_path(file_path)
            if self._native is not None:
                sid = self.record_cache.server_id(server)
                fid = self._file_ids.setdefault(file_path, len(self._file_ids))
            else:
                sid = fid = -1
            info = (kind, server, sid, fid)
            self._file_info[file_path] = info
        return info

    def _read_line(self, file_path: str, line: str) -> None:
        if not line:
            return
        info = self._file_info_for(file_path)
        if self._native is not None and info[0] == 2:
            # app-log lines must run through the native audit machine even
            # on the per-line API — its state lives in C++ and cannot be
            # split with the Python reference context
            data = line.encode("utf-8", "replace")
            off = 0
            while off < len(data):
                consumed = self._native_chunk(
                    file_path, info, data[off:] if off else data
                )
                off += consumed
            return
        self._read_line_ref(file_path, line, info)

    def _read_line_ref(self, file_path: str, line: str, info=None) -> None:
        """The reference per-line dispatch (also the RAW-event replay path;
        record/soap state reached through the backend shims)."""
        kind, server = (info or self._file_info_for(file_path))[:2]

        if kind == 0:
            self._parse_soap(line, file_path)
            return
        m = _SERVER_DISPATCH_RE.search(line)
        if kind == 1:  # server.log: EJB + standard CommonTiming forms
            if m is None:
                return
            # the reference's sequential ladder priority, reconstructed from
            # the pre-filter match itself: lastindex is the winning marker at
            # the LEFTMOST occurrence; a higher-priority marker can only beat
            # it by occurring strictly later in the line, so only the
            # patterns above lastindex are (rarely) re-searched — the common
            # single-marker line dispatches with zero extra regex work.
            j = m.lastindex
            if j > 1:
                p = m.start() + 1
                for i in range(1, j):
                    if _LADDER_RES[i - 1].search(line, p):
                        j = i
                        break
            if j == 1:
                self._parse_ejb_entry(line, server)
            elif j == 2:
                self._parse_ejb_exit(line, file_path, server)
            elif j == 3:
                self._parse_ct_entry(line, server)
            else:
                self._parse_ct_exit(line, file_path, server)
        else:  # APP log: CT forms only; EJB markers fall through to app state
            if m is not None and (
                m.lastindex == 3 or _CT_ENTRY_RE.search(line, m.start() + 1)
            ):
                self._parse_ct_entry(line, server)
            elif m is not None and (
                m.lastindex == 4 or _CT_EXIT_RE.search(line, m.start() + 1)
            ):
                self._parse_ct_exit(line, file_path, server)
            else:
                self._parse_app_line(line, file_path, server)
