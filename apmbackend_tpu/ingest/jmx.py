"""WildFly JMX poller (pull_jvm_stats.js role).

Every ``pollingIntervalSeconds`` (second-aligned, first tick skipped —
pull_jvm_stats.js:141-149) each configured JVM host is queried through the
jboss-cli client jar for the datasource pool, heap/metaspace, system load,
class/thread counts and EJB bean pool; the resulting :class:`JmxEntry` rows go
to the db_insert queue.

The CLI prints one bare JSON blob per command plus free-text warnings;
:func:`cli_to_json` reshapes that concatenation into a single labeled JSON
object exactly like cliToJSON (pull_jvm_stats.js:15-33). The command runner is
injectable so polling is testable without Java/WildFly.
"""

from __future__ import annotations

import json
import re
import subprocess
import time
from typing import Callable, List, Optional

from ..entries import JmxEntry

_LETTER_LINE = re.compile(r"^[a-zA-Z]")
_BLOB_BOUNDARY = re.compile(r"\n}\n{")


def cli_to_json(resources: List[str], output: str) -> dict:
    """Concatenated jboss-cli JSON blobs -> one dict keyed by resource name."""
    res_copy = list(resources)
    fixed = _BLOB_BOUNDARY.sub("\n},\n{", str(output))
    lines = []
    for line in fixed.split("\n"):
        if _LETTER_LINE.match(line):
            continue  # discard warning messages
        if line.startswith("{"):
            lines.append(f'"{res_copy.pop(0)}" : {{')
        else:
            lines.append(line)
    return json.loads("{" + "\n".join(lines) + "}")


def default_runner(cmd: str, timeout_s: float) -> str:
    """Run the CLI command, stderr ignored (execSync stdio pipe/pipe/ignore,
    pull_jvm_stats.js:42)."""
    out = subprocess.run(
        cmd, shell=True, stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
        timeout=timeout_s, check=True,
    )
    return out.stdout.decode("utf-8", errors="replace")


class JmxPoller:
    def __init__(
        self,
        jvm_config: dict,
        write_line: Callable[[str], None],
        *,
        logger=None,
        runner: Callable[[str, float], str] = default_runner,
        clock: Callable[[], float] = time.time,
    ):
        self.config = jvm_config
        self.write_line = write_line
        self.logger = logger
        self.runner = runner
        self.clock = clock

    def set_config(self, jvm_config: dict) -> None:
        self.config = jvm_config

    # -- command construction (pull_jvm_stats.js:38-43) ----------------------
    def build_command(self, jvm_host: str, cmd_list: str) -> str:
        c = self.config
        return (
            f"java -jar {c['clientJarFullPath']} --output-json "
            f"--timeout={c.get('clientTimeoutMs', 2000)} "
            f"--controller={jvm_host}:{c.get('jmxPort', 9990)} "
            f"--user={c.get('adminUser', '')} --password={c.get('adminPass', '')} "
            f'--connect commands="{cmd_list}"'
        )

    def stat_names_and_commands(self) -> tuple:
        stat_names: List[str] = []
        cmds: List[str] = []
        for stat_name, stat_cmd in (self.config.get("statCmdMap") or {}).items():
            stat_names.append(stat_name)
            cmds.append(stat_cmd)
        return stat_names, ",".join(cmds)

    # -- polling -------------------------------------------------------------
    def pull_host(self, jvm_host: str, stat_names: List[str], cmd_list: str) -> Optional[dict]:
        try:
            raw = self.runner(self.build_command(jvm_host, cmd_list),
                              float(self.config.get("clientTimeoutMs", 2000)) / 1000.0 + 30.0)
            stats = cli_to_json(stat_names, raw)
            stats["server"] = jvm_host
            return stats
        except Exception:
            # connection errors are silently skipped like the bare `return`
            # at pull_jvm_stats.js:54-56 — a down JVM is a normal condition
            return None

    def pull_all(self, ts: Optional[float] = None) -> List[JmxEntry]:
        ts = self.clock() * 1000.0 if ts is None else ts
        stat_names, cmd_list = self.stat_names_and_commands()
        entries: List[JmxEntry] = []
        for jvm_host in self.config.get("jvmHosts", []) or []:
            stats = self.pull_host(jvm_host, stat_names, cmd_list)
            if stats is None:
                continue
            server = stats["server"]
            if self.config.get("shortenHostname"):
                server = re.sub(r"\..*", "", server)
            try:
                entry = JmxEntry.from_jmx_stats(ts, server, stats)
            except (KeyError, IndexError, TypeError) as e:
                if self.logger:
                    self.logger.error(f"Malformed JMX stats from {jvm_host}: {e}")
                continue
            entries.append(entry)
            self.write_line(entry.to_csv())
        return entries

    def seconds_until_next_poll(self) -> float:
        """Second-aligned cadence: fire on the :00 of each interval
        (pull_jvm_stats.js:145-147)."""
        interval = int(self.config.get("pollingIntervalSeconds", 60))
        current_sec = int(self.clock()) % 60
        return interval - (current_sec % interval)
