"""Synthetic WildFly log fixtures + replay driver.

The reference was only ever tested against live NFS-mounted JVM logs
(SURVEY.md §4); this module provides what it never had: a deterministic
fixture generator producing coherent soap_io / server.log / app log triples
(SOAP account headers, EJB + standard CommonTiming entry/exit pairs,
audit-trail RequestTrace sections), and a replay driver that feeds them
through the parser — BASELINE.json config[0] ("WildFly log replay ->
stream_parse_transactions -> stream_calc_z_score").
"""

from __future__ import annotations

import os
import random
from datetime import datetime, timedelta
from typing import Dict, List, Optional, Tuple

from .parser import TransactionParser


def _log_ts(dt: datetime) -> str:
    return dt.strftime("%Y-%m-%d %H:%M:%S,") + f"{dt.microsecond // 1000:03d}"


def _iso_ts(dt: datetime) -> str:
    # audit-trail style ISO with offset (parser detects via 'T.*-')
    return dt.strftime("%Y-%m-%dT%H:%M:%S.") + f"{dt.microsecond // 1000:03d}" + "-00:00"


class FixtureGenerator:
    """Emit (file_name, line) streams describing synthetic transactions."""

    def __init__(self, server: str = "jvmhost1", seed: int = 0, start: Optional[datetime] = None):
        self.server = server
        self.rng = random.Random(seed)
        self.t = start or datetime(2024, 1, 10, 9, 0, 0)
        self._next_id = 0

    def _log_id(self) -> str:
        self._next_id += 1
        return f"jb{self._next_id:08d}"

    def advance(self, seconds: float) -> None:
        self.t += timedelta(seconds=seconds)

    def soap_transaction(
        self, service: str, elapsed_ms: int, acct: Optional[int] = None, riskid: bool = False
    ) -> List[Tuple[str, str]]:
        """A SOAP-correlated EJB transaction: soap_io header with account
        number + server.log EJB CommonTiming entry/exit pair."""
        log_id = self._log_id()
        start = self.t
        end = start + timedelta(milliseconds=elapsed_ms)
        out: List[Tuple[str, str]] = []
        soap = f"soap_io_{self.server}.log"
        out.append((soap, f"=== jbossId={log_id} ts={_log_ts(start)} IO=I ==="))
        if acct is not None:
            if riskid:
                out.append((soap, "    <key>AccountNumber</key>"))
                out.append((soap, f"    <value>{acct}</value>"))
            else:
                out.append((soap, f"    <accountNumber>{acct}</accountNumber>"))
        out.append((soap, "  <payload>...</payload>"))
        out.append((soap, f"=== jbossId={log_id} ts={_log_ts(end)} IO=O ==="))
        srv = "server.log"
        out.append(
            (srv, f"[{log_id}] {_log_ts(start)} INFO [CommonTiming] The EJB timing entry has begun for method {service}")
        )
        out.append(
            (srv, f"[{log_id}] {_log_ts(end)} INFO [CommonTiming] Total time for EJB {service} call: {elapsed_ms} ms")
        )
        return out

    def standard_ct_transaction(
        self, service: str, elapsed_ms: int, acct: Optional[int] = None,
        baf_meta: bool = False, app_file: Optional[str] = None,
    ) -> List[Tuple[str, str]]:
        """A standard CommonTiming pair on an app log; optional BAF metadata
        carries the account number for the salvage path."""
        log_id = self._log_id()
        start = self.t
        end = start + timedelta(milliseconds=elapsed_ms)
        fname = app_file or f"app_{self.server}.log"
        meta = f"[ch:7:{acct}] " if (baf_meta and acct is not None) else ""
        out = [
            (fname, f"[{log_id}] {_log_ts(start)} {meta}INFO CommonTiming::Start {service} begin"),
            (fname, f"[{log_id}] {_log_ts(end)} {meta}INFO CommonTiming::Stop {service} completed in time: {elapsed_ms} ms"),
        ]
        return out

    def audit_trail(
        self, subservices: List[Tuple[str, int]], acct: Optional[int] = None,
        app_file: Optional[str] = None,
    ) -> List[Tuple[str, str]]:
        """An audit-trail block: map line, id line, RequestTrace elapsed
        section, stopWatchList XML with per-subservice timestamps."""
        log_id = self._log_id()
        autr_id = f"AUTR{self._next_id:06d}"
        fname = app_file or f"app_{self.server}.log"
        meta = f"[ch:9:{acct}] " if acct is not None else "[ch:9:x] "
        out = [(fname, f"[{log_id}] {_log_ts(self.t)} {meta}INFO  auditTrailId={autr_id} begin")]
        out.append((fname, f"Audit Trail id : {autr_id}"))
        out.append((fname, "summary: RequestTrace [stopWatchList="))
        for svc, ms in subservices:
            out.append((fname, f"{svc} :[{ms} millis] step"))
        out.append((fname, "]"))
        out.append((fname, "<stopWatchList>"))
        t = self.t
        for svc, ms in subservices:
            t_end = t + timedelta(milliseconds=ms)
            out.append((fname, f"  <name>{svc}</name>"))
            out.append((fname, f"  <startTime>{_iso_ts(t)}</startTime>"))
            out.append((fname, f"  <stopTime>{_iso_ts(t_end)}</stopTime>"))
            t = t_end
        out.append((fname, "</stopWatchList>"))
        return out


def write_fixture_logs(
    out_dir: str,
    *,
    n_transactions: int = 200,
    services: Tuple[str, ...] = ("getAccountInfo", "getOffers", "Provider[credit-check]"),
    seed: int = 0,
    server: str = "jvmhost1",
    anomaly: Optional[dict] = None,
    tx_per_bucket: Optional[float] = None,
) -> Dict[str, str]:
    """Generate a mixed fixture log directory; returns {file_name: path}.

    ``anomaly`` injects a latency regression for end-to-end detection tests
    and demos: ``{"service": name, "start_frac": 0.75, "factor": 8.0}``
    multiplies that service's elapsed times by ``factor`` for every
    transaction past ``start_frac`` of the stream (the other services stay
    healthy — the detector must single it out).

    ``tx_per_bucket`` sets the PRODUCTION DENSITY of the fixture: the mean
    number of transactions per 10 s stats bucket (log time advances
    ~10/tx_per_bucket seconds per transaction, ±50% jitter). The default
    (None) keeps the legacy sparse cadence — ~1 s of log time per tx, i.e.
    ~10 tx/bucket — which forces a full detection tick every ~10 records
    when replayed: a time-compression artifact that benchmarks nothing a
    production replay would see (VERDICT r5 weak 1). ~1,000 tx/bucket
    matches a production-heavy JVM's correlation stream; the replay bench's
    headline number runs at that density.
    """
    gen = FixtureGenerator(server=server, seed=seed)
    rng = random.Random(seed + 1)
    lines_by_file: Dict[str, List[str]] = {}

    def put(pairs):
        for fname, line in pairs:
            lines_by_file.setdefault(fname, []).append(line)

    a_service = (anomaly or {}).get("service")
    a_start = int((anomaly or {}).get("start_frac", 0.75) * n_transactions)
    a_factor = float((anomaly or {}).get("factor", 8.0))

    for i in range(n_transactions):
        service = services[rng.randrange(len(services))]
        elapsed = rng.randint(50, 1200)
        if a_service is not None and service == a_service and i >= a_start:
            elapsed = int(elapsed * a_factor)
        acct = rng.randint(10**8, 10**9 - 1)
        kind = rng.random()
        if kind < 0.5:
            put(gen.soap_transaction(service, elapsed, acct, riskid=rng.random() < 0.2))
        elif kind < 0.8:
            put(gen.standard_ct_transaction(service, elapsed, acct, baf_meta=True))
        else:
            put(gen.audit_trail([(service, elapsed), ("bcottag", rng.randint(5, 50))], acct))
        if tx_per_bucket is None:
            gen.advance(rng.uniform(0.05, 2.0))  # legacy sparse cadence
        else:
            mean_s = 10.0 / float(tx_per_bucket)
            gen.advance(rng.uniform(0.5 * mean_s, 1.5 * mean_s))

    os.makedirs(out_dir, exist_ok=True)
    paths = {}
    for fname, lines in lines_by_file.items():
        p = os.path.join(out_dir, fname)
        with open(p, "w", encoding="utf-8") as fh:
            fh.write("\n".join(lines) + "\n")
        paths[fname] = p
    return paths


class ReplayDriver:
    """Feed fixture (or captured production) logs through the parser.

    Interleaves lines across files in generation order when given explicit
    (file, line) pairs, or round-robins whole files from disk. Drains the
    numberless-record cache at the end so replay is loss-free.
    """

    def __init__(self, parser: TransactionParser):
        self.parser = parser
        self.lines_fed = 0

    def feed_pairs(self, pairs) -> int:
        for file_name, line in pairs:
            self.parser.read_line(file_name, line)
            self.lines_fed += 1
        return self.lines_fed

    def feed_dir(self, log_dir: str, *, chunk_bytes: int = 1 << 18) -> int:
        """Round-robin byte chunks across the directory's files through the
        parser's batch API (read_lines): the whole chunk takes one native
        pass, and noise lines never become Python strings. Chunks are
        carved at the last newline; the partial tail is prepended to the
        file's next chunk. Cross-file interleaving is now chunk-granular
        instead of 64-line-granular — correlation is unaffected (the TTL
        windows dwarf any replay skew) and emission totals are identical.
        """
        files = sorted(
            os.path.join(log_dir, f) for f in os.listdir(log_dir) if not f.startswith(".")
        )
        handles = [(p, open(p, "rb")) for p in files]
        tails = {p: b"" for p, _ in handles}
        live = list(handles)
        while live:
            nxt = []
            for path, fh in live:
                blob = fh.read(chunk_bytes)
                if not blob:
                    if tails[path]:  # unterminated final line
                        self.lines_fed += self.parser.read_lines(path, tails[path])
                        tails[path] = b""
                    continue
                blob = tails[path] + blob
                cut = blob.rfind(b"\n")
                if cut >= 0:
                    self.lines_fed += self.parser.read_lines(path, blob[: cut + 1])
                    tails[path] = blob[cut + 1:]
                else:
                    tails[path] = blob
                nxt.append((path, fh))
            live = nxt
        for _p, fh in handles:
            fh.close()
        return self.lines_fed

    def finish(self) -> None:
        self.parser.drain()
