from .parser import TransactionParser, convert_log_date_to_ms  # noqa: F401
from .replay import FixtureGenerator, ReplayDriver, write_fixture_logs  # noqa: F401
from .tailer import NativeTailer, PauseFile, PyTailer, TailManager, discover_log_files  # noqa: F401
from .ttlcache import TTLCache  # noqa: F401
