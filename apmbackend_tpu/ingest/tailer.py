"""File tailing with the pause-file backpressure protocol.

Role parity with perl_tail.pl: one tailer per log file, robust against
truncation/rotation, holding its read position while a shared pause file
exists (perl_tail.pl:36-41) — the pause file IS the cross-process
backpressure signal created by the parser when downstream queues fill
(stream_parse_transactions.js:834-897).

Two implementations:
- :class:`PyTailer` — in-process thread, used by default and in tests.
- :class:`NativeTailer` — spawns the C++ ``apm_tail`` binary (native/tailer.cpp)
  per file like the reference spawns perl, reading its stdout; preferred in
  production for the ~70-file fan-in.
Both deliver lines to a callback as (file_path, line).
"""

from __future__ import annotations

import glob as globlib
import os
import subprocess
import threading
import time
from typing import Callable, List, Optional, Sequence


def discover_log_files(mask_prefix: str, mask_suffixes: Sequence[str]) -> List[str]:
    """Glob the configured masks (streamParseTransactions.appLogDirMaskPrefix /
    maskSuffixes, config parity with stream_parse_transactions.js:814-825)."""
    files: List[str] = []
    for suffix in mask_suffixes:
        files.extend(globlib.glob(os.path.join(mask_prefix, suffix)))
    return sorted(set(files))


class PauseFile:
    """The shared pause switch (tailPauseFileFullPath)."""

    def __init__(self, path: str):
        self.path = path

    def create(self) -> None:
        os.makedirs(os.path.dirname(os.path.abspath(self.path)), exist_ok=True)
        with open(self.path, "a", encoding="utf-8"):
            pass

    def delete(self) -> None:
        try:
            os.unlink(self.path)
        except FileNotFoundError:
            pass

    def exists(self) -> bool:
        return os.path.exists(self.path)


class PyTailer:
    """Polling tailer for one file: start at EOF, follow appends, re-open on

    truncation (size shrink) — net-mount-safe (no inode assumptions, the
    reason the reference patched File::Tail).

    ``on_lines`` (optional) switches to batch delivery: each poll's
    complete lines are handed over as ONE newline-joined str chunk, the
    shape TransactionParser.read_lines wants for the native ingest fast
    path — per-line callback overhead disappears from the tail loop."""

    def __init__(
        self,
        file_path: str,
        on_line: Callable[[str, str], None],
        pause_file: Optional[PauseFile] = None,
        *,
        poll_interval_s: float = 0.2,
        from_start: bool = False,
        on_exit: Optional[Callable[[str, Optional[int]], None]] = None,
        on_lines: Optional[Callable[[str, str], object]] = None,
    ):
        self.file_path = file_path
        self.on_line = on_line
        self.on_lines = on_lines
        self.pause_file = pause_file
        self.poll_interval_s = poll_interval_s
        self.from_start = from_start
        self.on_exit = on_exit
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # wall-clock attribution (obs.attrib): delivery busy, poll idle,
        # pause-file waits blocked (the pause file IS downstream backpressure)
        from ..obs.attrib import STAGE_TAILER_READ, get_attrib

        self._att_read = get_attrib().clock(STAGE_TAILER_READ)

    def _deliver(self, buf: str) -> str:
        """Push complete lines from ``buf``; returns the partial tail."""
        t0 = time.perf_counter() if self._att_read.enabled else 0.0
        try:
            if self.on_lines is not None:
                cut = buf.rfind("\n")
                if cut < 0:
                    return buf
                try:
                    self.on_lines(self.file_path, buf[: cut + 1])
                except Exception:
                    pass  # consumer bug must not kill the tail
                return buf[cut + 1:]
            while "\n" in buf:
                line, buf = buf.split("\n", 1)
                try:
                    self.on_line(self.file_path, line)
                except Exception:
                    pass
            return buf
        finally:
            if self._att_read.enabled:
                self._att_read.add_busy(time.perf_counter() - t0)

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run, name=f"tail-{os.path.basename(self.file_path)}", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=2.0)

    def _run(self) -> None:
        try:
            pos = 0
            fh = None
            buf = ""
            inode = None
            while not self._stop.is_set():
                if fh is None:
                    # open BEFORE honoring pause so the EOF anchor is
                    # established at startup — lines written while paused must
                    # be delivered after resume, not skipped
                    try:
                        fh = open(self.file_path, "r", encoding="utf-8", errors="replace")
                    except FileNotFoundError:
                        # a file that appears later is all new content
                        self.from_start = True
                        time.sleep(self.poll_interval_s)
                        continue
                    if not self.from_start:
                        fh.seek(0, os.SEEK_END)
                    pos = fh.tell()
                    try:
                        inode = os.fstat(fh.fileno()).st_ino
                    except OSError:
                        inode = None
                if self.pause_file is not None and self.pause_file.exists():
                    # hold position while paused (perl_tail.pl:36-41)
                    time.sleep(self.poll_interval_s)
                    self._att_read.add_blocked(self.poll_interval_s)
                    continue
                try:
                    st = os.stat(self.file_path)
                    size, cur_inode = st.st_size, st.st_ino
                except OSError:
                    size, cur_inode = 0, inode
                if size < pos or (inode is not None and cur_inode != inode):
                    # truncated, or rename-rotation swapped the inode: reopen
                    # the new file from the start (but drain the old handle
                    # first so nothing written pre-rotation is lost)
                    tail_chunk = fh.read()
                    if tail_chunk:
                        buf = self._deliver(buf + tail_chunk)
                    fh.close()
                    fh = None
                    self.from_start = True  # new file: read from beginning
                    continue
                chunk = fh.read()
                if chunk:
                    pos = fh.tell()
                    # consumer bugs are swallowed inside _deliver; fail-fast
                    # (on_exit) is reserved for file-level problems
                    buf = self._deliver(buf + chunk)
                else:
                    time.sleep(self.poll_interval_s)
                    self._att_read.add_idle(self.poll_interval_s)
            if fh:
                fh.close()
            # graceful stop() is not a tail death: fail-fast on_exit fires
            # only for unexpected termination
            if self.on_exit and not self._stop.is_set():
                self.on_exit(self.file_path, 0)
        except Exception:
            if self.on_exit and not self._stop.is_set():
                self.on_exit(self.file_path, 1)


class NativeTailer:
    """Spawn the C++ tail binary (one process per file, stdout line stream),

    mirroring the reference's per-file perl spawn
    (stream_parse_transactions.js:902-975). Tail process death is fail-fast:
    on_exit is invoked so the supervisor can restart the whole parser
    (:919-922 semantics)."""

    def __init__(
        self,
        binary_path: str,
        file_path: str,
        pause_file_path: str,
        on_line: Callable[[str, str], None],
        on_exit: Optional[Callable[[str, Optional[int]], None]] = None,
        on_lines: Optional[Callable[[str, bytes], object]] = None,
    ):
        self.binary_path = binary_path
        self.file_path = file_path
        self.pause_file_path = pause_file_path
        self.on_line = on_line
        self.on_lines = on_lines
        self.on_exit = on_exit
        self._proc: Optional[subprocess.Popen] = None
        self._thread: Optional[threading.Thread] = None
        self._stopping = False
        from ..obs.attrib import STAGE_TAILER_READ, get_attrib

        self._att_read = get_attrib().clock(STAGE_TAILER_READ)

    def _deliver(self, complete: bytes) -> None:
        t0 = time.perf_counter() if self._att_read.enabled else 0.0
        try:
            if self.on_lines is not None:
                # raw byte chunk straight into the parser's batch API (the
                # native ingest fast path takes it without str-ifying lines)
                self.on_lines(self.file_path, complete)
            else:
                for line in complete.split(b"\n")[:-1]:
                    self.on_line(self.file_path, line.decode("utf-8", "replace"))
        except Exception:
            pass  # consumer bug must not kill the pump
        finally:
            if self._att_read.enabled:
                self._att_read.add_busy(time.perf_counter() - t0)

    def start(self, from_start: bool = False) -> None:
        argv = [self.binary_path, self.file_path, self.pause_file_path]
        if from_start:
            argv.append("--from-start")
        self._proc = subprocess.Popen(
            argv, stdout=subprocess.PIPE, stderr=subprocess.DEVNULL
        )

        def _pump():
            assert self._proc is not None and self._proc.stdout is not None
            stdout = self._proc.stdout
            buf = b""
            while True:
                # read1: whatever the pipe has (>=1 byte, blocking) — batch
                # naturally under load, line-latency when idle
                chunk = stdout.read1(1 << 16)
                if not chunk:
                    break
                buf += chunk
                cut = buf.rfind(b"\n")
                if cut >= 0:
                    self._deliver(buf[: cut + 1])
                    buf = buf[cut + 1:]
            if buf:  # unterminated final line at tail death
                self._deliver(buf + b"\n")
            rc = self._proc.wait()
            if self.on_exit and not self._stopping:
                self.on_exit(self.file_path, rc)

        self._thread = threading.Thread(target=_pump, name=f"ntail-{os.path.basename(self.file_path)}", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stopping = True
        if self._proc and self._proc.poll() is None:
            self._proc.terminate()
            try:
                self._proc.wait(timeout=2.0)
            except subprocess.TimeoutExpired:
                self._proc.kill()
                try:  # reap: a killed child must not linger as a zombie
                    self._proc.wait(timeout=2.0)
                except subprocess.TimeoutExpired:
                    pass
        if self._thread:
            self._thread.join(timeout=2.0)


class TailManager:
    """All tails for the configured log masks + the pause switch."""

    def __init__(
        self,
        config: dict,
        on_line: Callable[[str, str], None],
        *,
        logger=None,
        native_binary: Optional[str] = None,
        on_tail_exit: Optional[Callable[[str, Optional[int]], None]] = None,
        from_start: bool = False,
        on_lines: Optional[Callable[[str, object], object]] = None,
    ):
        self.config = config
        self.on_line = on_line
        self.on_lines = on_lines  # batch delivery (parser.read_lines shape)
        self.logger = logger
        self.native_binary = native_binary
        self.on_tail_exit = on_tail_exit
        self.from_start = from_start
        self.pause = PauseFile(config["tailPauseFileFullPath"])
        self.tailers: List = []

    def start(self) -> int:
        self.pause.delete()  # clear stale pause on boot (:899-900)
        files = discover_log_files(self.config["appLogDirMaskPrefix"], self.config["maskSuffixes"])
        for f in files:
            if self.native_binary:
                t = NativeTailer(
                    self.native_binary, f, self.pause.path, self.on_line,
                    self.on_tail_exit, on_lines=self.on_lines,
                )
                t.start(from_start=self.from_start)
            else:
                t = PyTailer(
                    f, self.on_line, self.pause,
                    from_start=self.from_start, on_exit=self.on_tail_exit,
                    on_lines=self.on_lines,
                )
                t.start()
            self.tailers.append(t)
        if self.logger:
            self.logger.info(f"Started {len(self.tailers)} tails")
        return len(self.tailers)

    def pause_reads(self) -> None:
        self.pause.create()

    def resume_reads(self) -> None:
        self.pause.delete()

    def stop(self) -> None:
        for t in self.tailers:
            t.stop()
        self.tailers.clear()
