"""Crash flight-recorder bundles: post-mortems without grepping logs.

A watchdog restart or a kill−9 used to mean reconstructing the module's
final moments from interleaved log lines. The :class:`FlightRecorder`
keeps a bounded, always-current triage picture of one module process —
registered *sources* (tick-span ring, recent traces + decisions, metrics
snapshot, config hash, backlog depths) sampled on demand — and writes it
to ``observability.flightDir`` as a JSON **bundle** on the paths a
process can still act on:

- healthz degradation (the exporter dumps, rate-limited);
- SIGTERM / SIGINT (ModuleRuntime's handler, before exit handlers run);
- an unhandled worker feed exception (the device loop's crash-damping);
- on demand via the exporter's ``GET /flight?reason=...`` (the manager's
  hung-tick watchdog requests one from a wedged-but-serving child right
  before force-restarting it).

**kill−9 has no handler**, so the recorder also maintains an on-disk
shadow: a *journal* (atomic snapshot of the same sources, rewritten on a
timer) plus an *alive sentinel* (written at boot, removed on clean
shutdown). A SIGKILLed process leaves both behind; the NEXT boot finds
the sentinel, promotes the last journal into a ``...-crash.json`` bundle
(:meth:`recover_crash`), and re-arms. The chaos harness asserts this end
to end: kill−9 produces a parseable bundle while the run stays
bit-identical to the golden run — the recorder only ever *reads* pipeline
state and writes files under its own directory.

Bundles are bounded (``max_bundles``, oldest pruned) and every source is
guarded: a broken source degrades to an error string, never a failed
dump. Stdlib only, like the rest of ``obs/``.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

# a runaway metrics render must not balloon bundles: cap any single
# string-valued source (journals rewrite frequently)
MAX_SOURCE_CHARS = 262_144


def config_hash(config: dict) -> str:
    """Stable digest of the live config — ties a bundle to the exact
    settings the process was running under."""
    import hashlib

    try:
        blob = json.dumps(config, sort_keys=True, default=repr)
    except Exception:
        blob = repr(config)
    return hashlib.sha256(blob.encode("utf-8")).hexdigest()[:16]


class FlightRecorder:
    def __init__(
        self,
        directory: str,
        module: str,
        *,
        max_bundles: int = 16,
        min_interval_s: float = 30.0,
        logger=None,
    ):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.module = module
        self.max_bundles = int(max_bundles)
        self.min_interval_s = float(min_interval_s)
        self.logger = logger
        self._sources: Dict[str, Callable[[], object]] = {}  # guarded-by: _lock
        self._lock = threading.Lock()
        self._last_dump = 0.0  # guarded-by: _lock
        self._seq = 0  # guarded-by: _lock (bundle counter: unique names within one second)
        self.journal_path = os.path.join(self.directory, f"{module}.journal.json")
        self.sentinel_path = os.path.join(self.directory, f"{module}.alive")

    # -- sources --------------------------------------------------------------
    def add_source(self, name: str, fn: Callable[[], object]) -> None:
        """``fn() -> JSON-serializable`` sampled at snapshot time; a broken
        source contributes its error string instead of failing the dump.
        Locked: wiring can race the journal timer's first snapshot."""
        with self._lock:
            self._sources[name] = fn

    def snapshot(self, reason: str = "") -> dict:
        body: dict = {
            "module": self.module,
            "ts": time.time(),
            "reason": reason,
            "pid": os.getpid(),
        }
        with self._lock:
            sources = list(self._sources.items())
        for name, fn in sources:
            try:
                value = fn()
                if isinstance(value, str) and len(value) > MAX_SOURCE_CHARS:
                    value = value[:MAX_SOURCE_CHARS] + "...[truncated]"
                json.dumps(value, default=repr)  # serializability gate per source
            except Exception as e:
                value = f"source error: {e!r}"
            body[name] = value
        return body

    # -- direct bundles -------------------------------------------------------
    def dump(self, reason: str, *, force: bool = False) -> Optional[str]:
        """Write one bundle; rate-limited unless ``force`` (a flapping
        healthz must not churn the directory). Returns the path or None."""
        with self._lock:
            now = time.time()
            if not force and now - self._last_dump < self.min_interval_s:
                return None
            self._last_dump = now
            self._seq += 1
            seq = self._seq
        body = self.snapshot(reason)
        stamp = time.strftime("%Y%m%d-%H%M%S", time.localtime(body["ts"]))
        safe_reason = "".join(c if c.isalnum() or c in "-_" else "_" for c in reason)[:48]
        path = os.path.join(
            self.directory,
            f"flight-{self.module}-{stamp}-{os.getpid()}-{seq}-{safe_reason or 'manual'}.json",
        )
        try:
            self._write_atomic(path, body)
        except Exception as e:
            if self.logger:
                self.logger.error(f"Flight bundle write failed: {e}")
            return None
        if self.logger:
            self.logger.warning(f"Flight bundle written ({reason}): {path}")
        self._prune()
        return path

    def bundles(self) -> List[str]:
        """Bundle paths, oldest first."""
        try:
            names = [
                n for n in os.listdir(self.directory)
                if n.startswith("flight-") and n.endswith(".json")
            ]
        except OSError:
            return []
        paths = [os.path.join(self.directory, n) for n in names]
        paths.sort(key=lambda p: (os.path.getmtime(p), p))
        return paths

    def _prune(self) -> None:
        paths = self.bundles()
        for path in paths[: max(0, len(paths) - self.max_bundles)]:
            try:
                os.unlink(path)
            except OSError:
                pass

    # -- the kill−9 shadow ----------------------------------------------------
    def journal(self) -> None:
        """Rewrite the on-disk journal (atomic) — the state a SIGKILL leaves
        behind for the next boot to promote. Runs on a timer; cheap enough
        for sub-second cadences (one JSON dump of bounded sources)."""
        try:
            self._write_atomic(self.journal_path, self.snapshot("journal"))
        except Exception as e:
            if self.logger:
                self.logger.error(f"Flight journal write failed: {e}")

    def mark_alive(self) -> None:
        """Write the alive sentinel (+ an initial journal so even an
        immediate SIGKILL leaves something to promote)."""
        self.journal()
        try:
            self._write_atomic(
                self.sentinel_path, {"pid": os.getpid(), "start_ts": time.time()}
            )
        except Exception as e:
            if self.logger:
                self.logger.error(f"Flight sentinel write failed: {e}")

    def mark_clean_exit(self) -> None:
        try:
            os.unlink(self.sentinel_path)
        except OSError:
            pass

    def recover_crash(self) -> Optional[str]:
        """Boot-time check: a leftover sentinel means the previous process
        died without a clean shutdown (kill−9, OOM, power). Promote its last
        journal into a crash bundle; returns the bundle path or None."""
        if not os.path.exists(self.sentinel_path):
            return None
        crash: dict = {"module": self.module, "recovered": True,
                       "crash_detected_ts": time.time()}
        try:
            with open(self.sentinel_path, "r", encoding="utf-8") as fh:
                crash["previous_process"] = json.load(fh)
        except Exception:
            crash["previous_process"] = None
        try:
            with open(self.journal_path, "r", encoding="utf-8") as fh:
                crash["journal"] = json.load(fh)
        except Exception as e:
            crash["journal"] = None
            crash["journal_error"] = repr(e)
        stamp = time.strftime("%Y%m%d-%H%M%S")
        with self._lock:
            self._seq += 1
            seq = self._seq
        path = os.path.join(
            self.directory,
            f"flight-{self.module}-{stamp}-{os.getpid()}-{seq}-crash.json",
        )
        try:
            self._write_atomic(path, crash)
        except Exception as e:
            if self.logger:
                self.logger.error(f"Crash bundle write failed: {e}")
            return None
        self.mark_clean_exit()  # consume the sentinel: one crash, one bundle
        self._prune()
        if self.logger:
            self.logger.warning(f"Crash flight bundle recovered: {path}")
        return path

    # -- io -------------------------------------------------------------------
    @staticmethod
    def _write_atomic(path: str, body: dict) -> None:
        tmp = f"{path}.{os.getpid()}.tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(body, fh, indent=1, default=repr)
        os.replace(tmp, path)


def read_bundle(path: str) -> dict:
    """Parse one bundle (tests, triage tooling)."""
    with open(path, "r", encoding="utf-8") as fh:
        return json.load(fh)


def list_bundles(directory: str, module: Optional[str] = None) -> List[Tuple[str, dict]]:
    """(path, parsed body) for every bundle in ``directory``, oldest first.
    Unparseable files raise — a bundle that cannot be read is a bug the
    chaos harness exists to catch."""
    directory = os.path.abspath(directory)
    try:
        names = sorted(
            n for n in os.listdir(directory)
            if n.startswith("flight-") and n.endswith(".json")
            and (module is None or n.startswith(f"flight-{module}-"))
        )
    except OSError:
        return []
    out = []
    for name in names:
        path = os.path.join(directory, name)
        out.append((path, read_bundle(path)))
    return out
