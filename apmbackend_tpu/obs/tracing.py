"""Per-tick span tracing for the host loop around the fused device step.

The r6 dispatch-floor work (CHANGES.md) proved the tick budget is won or
lost in fixed per-tick overhead; this module keeps that measurable in
production instead of only in ``benchmarks/bench_dispatch.py``. The
PipelineDriver calls :meth:`TickTracer.record` once per tick with the
stage durations it already has the boundaries for — NO new device syncs
are added (the cost model of DESIGN.md §4 is sacred):

- ``dispatch``: the executor call — program enqueue + any in-step host
  work (the native percentile kernel's dlpack views block here, so on the
  fused-native path this includes the device wait for program A),
- ``rebuild``: the separate staggered-rebuild scheduler step (0 when the
  fused executor folds the chunk into the tick program),
- ``tx_drain``: the ordered-tx heap/backlog drain to the DB queue,
- ``emit``: emission readback + host fan-out (``np.asarray`` of the
  emission blocks on the remaining device compute — the blocking sync
  point we already pay; in async-emission mode this is the PREVIOUS
  tick's drain, making pipelining overlap directly visible as
  emit << dispatch+compute).

Each tick also lands in a host-side ring of recent spans (the flight
recorder the /healthz handler and post-mortems read) and feeds the
``apm_tick_stage_seconds`` histograms plus catch-up depth (labels
advanced per tick — the megatick/backfill signal).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, Optional

from .registry import DEFAULT_COUNT_BUCKETS, MetricsRegistry

STAGES = ("dispatch", "rebuild", "tx_drain", "emit")


class TickTracer:
    def __init__(
        self,
        registry: MetricsRegistry,
        *,
        ring_size: int = 256,
    ):
        self.ring: deque = deque(maxlen=ring_size)
        self._lock = threading.Lock()
        self._ticks = registry.counter(
            "apm_ticks_total", "Detection ticks executed by this process"
        )
        self._last_tick = registry.gauge(
            "apm_tick_last_unixtime", "Wall time of the most recent tick"
        )
        self._tick_seconds = registry.histogram(
            "apm_tick_seconds", "Whole-tick host wall time (all stages)"
        )
        self._stage = {
            s: registry.histogram(
                "apm_tick_stage_seconds",
                "Per-stage tick wall time (see obs.tracing docstring)",
                labels={"stage": s},
            )
            for s in STAGES
        }
        self._catchup = registry.histogram(
            "apm_tick_catchup_labels",
            "Interval labels advanced per tick (>1 = catch-up/backfill)",
            buckets=DEFAULT_COUNT_BUCKETS,
        )

    def record(
        self,
        label: int,
        stages: Dict[str, float],
        *,
        catchup_labels: Optional[int] = None,
    ) -> None:
        now = time.time()
        total = 0.0
        for name, dur in stages.items():
            hist = self._stage.get(name)
            if hist is not None:
                hist.observe(dur)
            total += dur
        self._tick_seconds.observe(total)
        self._ticks.inc()
        self._last_tick.set(now)
        if catchup_labels is not None and catchup_labels > 0:
            self._catchup.observe(catchup_labels)
        with self._lock:
            self.ring.append(
                {"label": int(label), "wall_ts": now, "stages": dict(stages)}
            )

    # -- introspection (healthz, post-mortems) --------------------------------
    @property
    def ticks_total(self) -> int:
        return int(self._ticks.value)

    @property
    def last_tick_ts(self) -> float:
        return self._last_tick.value

    def recent(self, n: int = 16) -> list:
        with self._lock:
            items = list(self.ring)
        return items[-n:]

    def summary(self) -> dict:
        """Healthz-sized digest: tick count, age of the last tick, and the
        mean of each stage over the span ring."""
        with self._lock:
            items = list(self.ring)
        out = {
            "ticks_total": self.ticks_total,
            "last_tick_age_s": (
                round(time.time() - self.last_tick_ts, 3) if items else None
            ),
            "ring_depth": len(items),
        }
        if items:
            means: Dict[str, float] = {}
            for span in items:
                for k, v in span["stages"].items():
                    means[k] = means.get(k, 0.0) + v
            out["stage_mean_ms"] = {
                k: round(v / len(items) * 1000, 4) for k, v in means.items()
            }
        return out
