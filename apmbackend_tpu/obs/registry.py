"""Thread-safe metrics registry rendering Prometheus text format 0.0.4.

Three instrument kinds (counter, gauge, fixed-bucket histogram) plus
*collectors* — callables sampled at scrape time — which is how mutable
pre-existing telemetry (QueueStats/DBStats interval counters, the memory
broker's queue depths, parser cache stats) is absorbed as views without
changing its log-and-reset behavior.

Design constraints, in order:

1. **Hot-path cost.** ``Counter.inc``/``Histogram.observe`` run inside the
   per-tick loop (~0.5 ms budget) and the per-line parser loop; they are a
   lock acquire + a float add / bisect. No string formatting, no label
   dict hashing per call — instruments are resolved once at wire-up and
   held by the caller.
2. **Idempotent wire-up.** ``registry.counter(name, ..., labels=...)`` is
   get-or-create keyed on (name, sorted label items): two PipelineDrivers
   in one process share the same series (process totals), matching
   Prometheus client semantics.
3. **stdlib only.**
"""

from __future__ import annotations

import bisect
import re
import threading
import time
from typing import Callable, Dict, Iterable, List, NamedTuple, Optional, Tuple

# latency buckets in SECONDS: 100 µs .. 10 s, tuned so the ~0.5 ms tick
# floor and the 10 s interval cadence both land mid-range
DEFAULT_TIME_BUCKETS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
    0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)
# count-shaped buckets (catch-up depth, batch sizes)
DEFAULT_COUNT_BUCKETS = (1, 2, 5, 10, 25, 50, 100, 250, 1000, 10000)

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')
_SAMPLE_RE = re.compile(r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{([^}]*)\})?\s+(\S+)")


class Sample(NamedTuple):
    """One scrape-time sample emitted by a collector view."""

    name: str
    labels: Dict[str, str]
    value: float
    mtype: str = "gauge"  # "counter" | "gauge"
    help: str = ""


def _escape(value: str) -> str:
    return value.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt_labels(labels: Dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{_escape(str(v))}"' for k, v in sorted(labels.items()))
    return "{" + inner + "}"


def _fmt_value(v: float) -> str:
    if v == float("inf"):
        return "+Inf"
    f = float(v)
    return repr(int(f)) if f.is_integer() and abs(f) < 2**53 else repr(f)


class Counter:
    __slots__ = ("labels", "_value", "_lock")

    def __init__(self, labels: Dict[str, str]):
        self.labels = labels
        self._value = 0.0  # guarded-by: _lock
        self._lock = threading.Lock()

    def inc(self, value: float = 1.0) -> None:
        with self._lock:
            self._value += value

    @property
    def value(self) -> float:
        # apm: allow(lock-guard): GIL-atomic float read at scrape time — a torn logical value only skews one scrape, never the counter
        return self._value


class Gauge:
    __slots__ = ("labels", "_value", "_fn", "_lock")

    def __init__(self, labels: Dict[str, str]):
        self.labels = labels
        self._value = 0.0  # guarded-by: _lock
        self._fn: Optional[Callable[[], float]] = None  # guarded-by: _lock
        self._lock = threading.Lock()

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)
            self._fn = None

    def set_fn(self, fn: Callable[[], float]) -> None:
        """Sample ``fn`` at scrape time (live views: ring bytes, RSS, ...)."""
        with self._lock:
            self._fn = fn

    @property
    def value(self) -> float:
        # apm: allow(lock-guard): one volatile-style read of the fn slot; set()/set_fn() order is irrelevant to a single scrape
        fn = self._fn
        if fn is not None:
            try:
                return float(fn())
            except Exception:
                return float("nan")  # a broken view must not kill the scrape
        # apm: allow(lock-guard): GIL-atomic float read at scrape time (same contract as Counter.value)
        return self._value


class Histogram:
    """Fixed-bucket histogram (cumulative render, prometheus semantics).

    Buckets optionally carry OpenMetrics **exemplars** — the last sampled
    trace_id whose observation landed in each bucket
    (:meth:`observe_exemplar`), rendered only when the scrape asks for the
    OpenMetrics exposition — the bridge from "the p99 moved" to "here is a
    transaction that lived in that bucket" (the trace plane's ``/trace``).
    """

    __slots__ = ("labels", "bounds", "_counts", "_sum", "_count", "_lock", "_exemplars")

    def __init__(self, labels: Dict[str, str], buckets: Tuple[float, ...]):
        self.labels = labels
        self.bounds = tuple(sorted(float(b) for b in buckets))
        self._counts = [0] * (len(self.bounds) + 1)  # guarded-by: _lock
        self._sum = 0.0  # guarded-by: _lock
        self._count = 0  # guarded-by: _lock
        self._lock = threading.Lock()
        # bucket index -> (trace_id, observed value, unix ts); populated only
        # by observe_exemplar, so unsampled traffic pays nothing extra
        self._exemplars: Dict[int, Tuple[str, float, float]] = {}  # guarded-by: _lock

    def observe(self, value: float) -> None:
        idx = bisect.bisect_left(self.bounds, value)
        with self._lock:
            self._counts[idx] += 1
            self._sum += value
            self._count += 1

    def observe_exemplar(self, value: float, trace_id: str) -> None:
        """observe() + remember this trace as the bucket's exemplar."""
        idx = bisect.bisect_left(self.bounds, value)
        with self._lock:
            self._counts[idx] += 1
            self._sum += value
            self._count += 1
            self._exemplars[idx] = (str(trace_id), float(value), time.time())

    def exemplars_snapshot(self) -> Dict[int, Tuple[str, float, float]]:
        with self._lock:
            return dict(self._exemplars)

    @property
    def count(self) -> int:
        # apm: allow(lock-guard): GIL-atomic int read for tests/summaries; the consistent triple goes through snapshot()
        return self._count

    @property
    def sum(self) -> float:
        # apm: allow(lock-guard): GIL-atomic float read for tests/summaries; the consistent triple goes through snapshot()
        return self._sum

    def snapshot(self) -> Tuple[List[int], float, int]:
        with self._lock:
            return list(self._counts), self._sum, self._count


class _Family:
    __slots__ = ("name", "mtype", "help", "metrics", "buckets")

    def __init__(self, name: str, mtype: str, help: str, buckets=None):
        self.name = name
        self.mtype = mtype
        self.help = help
        self.buckets = buckets
        self.metrics: Dict[Tuple[Tuple[str, str], ...], object] = {}


class MetricsRegistry:
    def __init__(self):
        self._lock = threading.Lock()
        self._families: Dict[str, _Family] = {}  # guarded-by: _lock
        self._collectors: List[Callable[[], Iterable[Sample]]] = []  # guarded-by: _lock

    # -- instrument wire-up (get-or-create) ----------------------------------
    def _get(self, name: str, mtype: str, help: str, labels, factory):
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name: {name!r}")
        labels = dict(labels or {})
        key = tuple(sorted((str(k), str(v)) for k, v in labels.items()))
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = _Family(name, mtype, help)
                self._families[name] = fam
            elif fam.mtype != mtype:
                raise ValueError(
                    f"metric {name} already registered as {fam.mtype}, not {mtype}"
                )
            inst = fam.metrics.get(key)
            if inst is None:
                inst = factory(labels)
                fam.metrics[key] = inst
            return inst

    def counter(self, name: str, help: str = "", labels: Optional[dict] = None) -> Counter:
        return self._get(name, "counter", help, labels, Counter)

    def gauge(self, name: str, help: str = "", labels: Optional[dict] = None) -> Gauge:
        return self._get(name, "gauge", help, labels, Gauge)

    def histogram(
        self,
        name: str,
        help: str = "",
        labels: Optional[dict] = None,
        buckets: Tuple[float, ...] = DEFAULT_TIME_BUCKETS,
    ) -> Histogram:
        return self._get(
            name, "histogram", help, labels, lambda lb: Histogram(lb, buckets)
        )

    def add_collector(self, fn: Callable[[], Iterable[Sample]]) -> None:
        """Register a scrape-time view; ``fn`` returns Samples. Exceptions
        are swallowed per-collector — a broken view must not 500 /metrics."""
        with self._lock:
            self._collectors.append(fn)

    # -- introspection (tests) -----------------------------------------------
    def get_sample(self, name: str, labels: Optional[dict] = None):
        """Instrument lookup without creation; None when absent."""
        labels = dict(labels or {})
        key = tuple(sorted((str(k), str(v)) for k, v in labels.items()))
        with self._lock:
            fam = self._families.get(name)
            return fam.metrics.get(key) if fam else None

    # -- render --------------------------------------------------------------
    def render(self, *, exemplars: bool = False) -> str:
        """Prometheus text 0.0.4; ``exemplars=True`` appends OpenMetrics
        exemplar suffixes (``# {trace_id="..."} value ts``) to histogram
        bucket lines that have one — served when the scrape opts into the
        OpenMetrics exposition (exporter ``/metrics?exemplars=1``)."""
        out: List[str] = []
        with self._lock:
            families = list(self._families.values())
            family_names = set(self._families)
            collectors = list(self._collectors)
        for fam in families:
            if not fam.metrics:
                continue
            if fam.help:
                out.append(f"# HELP {fam.name} {fam.help}")
            out.append(f"# TYPE {fam.name} {fam.mtype}")
            for inst in fam.metrics.values():
                if isinstance(inst, Histogram):
                    counts, total, count = inst.snapshot()
                    ex = inst.exemplars_snapshot() if exemplars else {}
                    cum = 0
                    for i, (bound, c) in enumerate(zip(inst.bounds, counts)):
                        cum += c
                        lb = dict(inst.labels)
                        lb["le"] = _fmt_value(bound)
                        line = f"{fam.name}_bucket{_fmt_labels(lb)} {cum}"
                        if i in ex:
                            tid, val, ts = ex[i]
                            line += (
                                f' # {{trace_id="{_escape(tid)}"}} '
                                f"{_fmt_value(val)} {ts:.3f}"
                            )
                        out.append(line)
                    lb = dict(inst.labels)
                    lb["le"] = "+Inf"
                    line = f"{fam.name}_bucket{_fmt_labels(lb)} {count}"
                    if len(inst.bounds) in ex:
                        tid, val, ts = ex[len(inst.bounds)]
                        line += (
                            f' # {{trace_id="{_escape(tid)}"}} '
                            f"{_fmt_value(val)} {ts:.3f}"
                        )
                    out.append(line)
                    out.append(
                        f"{fam.name}_sum{_fmt_labels(inst.labels)} {_fmt_value(total)}"
                    )
                    out.append(f"{fam.name}_count{_fmt_labels(inst.labels)} {count}")
                else:
                    out.append(
                        f"{fam.name}{_fmt_labels(inst.labels)} {_fmt_value(inst.value)}"
                    )
        seen_types: Dict[str, str] = {}
        for fn in collectors:
            try:
                samples = list(fn())
            except Exception:
                continue
            for s in samples:
                # membership against the locked snapshot: _families can grow
                # concurrently (another thread wiring an instrument mid-render)
                if s.name not in seen_types and s.name not in family_names:
                    if s.help:
                        out.append(f"# HELP {s.name} {s.help}")
                    out.append(f"# TYPE {s.name} {s.mtype}")
                    seen_types[s.name] = s.mtype
                out.append(f"{s.name}{_fmt_labels(s.labels)} {_fmt_value(s.value)}")
        return "\n".join(out) + "\n"


def histogram_quantile(buckets: List[Tuple[float, float]], q: float) -> float:
    """Estimate quantile ``q`` from cumulative histogram buckets
    ``[(le, cumulative_count)]`` — prometheus ``histogram_quantile``
    semantics (linear interpolation inside the winning bucket; the +Inf
    bucket clamps to the highest finite bound). NaN when empty."""
    if not buckets:
        return float("nan")
    pts = sorted(buckets, key=lambda p: p[0])
    total = pts[-1][1]
    if total <= 0:
        return float("nan")
    rank = q * total
    prev_bound, prev_cum = 0.0, 0.0
    for bound, cum in pts:
        if cum >= rank:
            if bound == float("inf"):
                # open-ended tail: clamp to the highest finite bound
                return prev_bound if len(pts) > 1 else float("nan")
            if cum == prev_cum:
                return bound
            return prev_bound + (bound - prev_bound) * (rank - prev_cum) / (cum - prev_cum)
        prev_bound, prev_cum = bound, cum
    return pts[-1][0]


# -- text-format helpers (qstat --metrics-url, manager fleet merge, tests) ----

def parse_prom_text(text: str) -> List[Tuple[str, Dict[str, str], float]]:
    """Prometheus text -> [(name, labels, value)]. Lenient: unparseable
    lines are skipped (a CLI reading a live endpoint must not crash on a
    format corner)."""
    out = []
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if not m:
            continue
        name, _, labelstr, value = m.groups()
        labels = {}
        if labelstr:
            for lk, lv in _LABEL_RE.findall(labelstr):
                labels[lk] = lv.replace('\\"', '"').replace("\\n", "\n").replace("\\\\", "\\")
        try:
            out.append((name, labels, float(value)))
        except ValueError:
            continue
    return out


def relabel_metrics(text: str, extra_labels: Dict[str, str]) -> str:
    """Inject labels into every sample line of a Prometheus text body —
    the manager's fleet aggregation stamps ``module=<child>`` so scraped
    children merge into one exposition without series collisions."""
    if not extra_labels:
        return text
    inject = ",".join(f'{k}="{_escape(str(v))}"' for k, v in sorted(extra_labels.items()))
    out = []
    for line in text.splitlines():
        stripped = line.strip()
        m = _SAMPLE_RE.match(stripped) if stripped and not stripped.startswith("#") else None
        if not m:
            out.append(line)
            continue
        name, braced, labelstr, _value = m.groups()
        rest = stripped[m.end(2) if braced else m.end(1):]
        if braced:
            merged = f"{{{labelstr},{inject}}}" if labelstr else f"{{{inject}}}"
            out.append(f"{name}{merged}{rest}")
        else:
            out.append(f"{name}{{{inject}}}{rest}")
    return "\n".join(out) + ("\n" if text.endswith("\n") else "")


# -- the process-global registry ---------------------------------------------

_global_registry = MetricsRegistry()
_global_lock = threading.Lock()


def get_registry() -> MetricsRegistry:
    """The process-wide default registry every module wires into."""
    return _global_registry


def set_registry(registry: MetricsRegistry) -> MetricsRegistry:
    """Swap the process-global registry (test isolation); returns the old."""
    global _global_registry
    with _global_lock:
        old, _global_registry = _global_registry, registry
    return old
