"""Pipeline-wide wall-clock attribution: where does a second of wall go?

The telemetry plane answers "how fast" (throughput counters) and "how
long" (latency histograms); the tick tracer decomposes one tick. None of
them answer the ROADMAP's standing question — *which stage owns the
wall-clock* — so every bottleneck diagnosis (replay is parser-bound,
fleet e2e is tick-cadence-bound) had to be reconstructed by hand across
bench reports. This module makes that attribution a first-class,
queryable signal:

- :class:`StageClock` — per-stage busy / blocked-on-downstream / idle
  second accumulators. Cost discipline is the PR 2 rule: deltas are taken
  from ``time.perf_counter()`` values the call sites already have at
  existing sync boundaries (tick t0..t3, parser parse_ns, ring spin
  deadlines) — no new device syncs, no hot-path locks. Each clock is
  written by the one thread that owns its stage (the shm-ring SPSC
  discipline); readers take a snapshot of plain floats, so a torn read
  costs at most one in-flight delta, never a crash.
- :class:`Occupancy` — time-weighted occupancy for the buffered resources
  (producer pause buffer, worker intake ring, frame FIFO, shm ring):
  ``sample(level)`` integrates ``level`` over the time it was held, which
  generalizes the instantaneous ``apm_shmring_occupancy_bytes`` gauge
  into "how full was it *on average*, and at peak".
- :class:`AttributionPlane` — the process-wide table of clocks +
  occupancies, exported to the registry (``apm_stage_*_seconds_total``,
  ``apm_occupancy_*``) so the PR 12 TimeSeriesStore's self-sample
  persists stage shares for ``/query`` range plots, and served by the
  exporter's ``GET /attrib`` with a critical-path verdict.

The bottleneck estimator (:func:`estimate`): every stage contributes its
busy share and blocked share of the observation window; the wall the
instrumented stages do NOT account for is the pipeline waiting for the
next tick boundary to arrive in the stream (ticks fire on data labels —
``feed`` only ticks when a record's 10 s label advances), reported as the
implicit ``tick_cadence`` candidate. The verdict is the argmax share:
``{"bottleneck": "tick_cadence", "reason": "drain_wait 71% of window"}``
for a cadence-dominated fleet, ``parser_scan`` for a parser-bound replay.
bench_replay/bench_rolling certify both namings under reproducible
inputs, and bench_rolling's ON-vs-OFF A/B gates accounting overhead <2%.

Kill switch: ``APM_NO_ATTRIB=1`` (or ``configure(enabled=False)``) makes
:meth:`AttributionPlane.clock` hand out a shared no-op clock — call
sites keep their single cached reference and pay one dead method call.
Stdlib + numpy-free like the rest of ``obs/``.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, Dict, List, Optional

from .registry import MetricsRegistry, Sample, get_registry

# canonical stage names (call sites may mint others; these keep the
# table, the docs, and the bench assertions in one vocabulary)
STAGE_TAILER_READ = "tailer_read"
STAGE_PARSER_SCAN = "parser_scan"
STAGE_FRAME_PACK = "frame_pack"
STAGE_TRANSPORT_SEND = "transport_send"
STAGE_TRANSPORT_PUMP = "transport_pump"
STAGE_SHMRING_PUSH = "shmring_push"
STAGE_SHMRING_POP = "shmring_pop"
STAGE_INTAKE_PUSH = "intake_push"
STAGE_WORKER_FEED = "worker_feed"
STAGE_TICK_DISPATCH = "tick_dispatch"
STAGE_TICK_REBUILD = "tick_rebuild"
STAGE_TICK_TX_DRAIN = "tick_tx_drain"
STAGE_TICK_EMIT = "tick_emit"
STAGE_SINK_ABSORB = "sink_absorb"

# the implicit candidate: wall no instrumented stage accounts for —
# waiting for the stream to reach the next tick boundary
CADENCE = "tick_cadence"


class StageClock:
    """Busy/blocked/idle accumulators for ONE stage.

    Single-writer: the thread that owns the stage adds deltas; plain
    float adds under the GIL, no lock on the hot path. ``enabled`` lets
    call sites skip even the perf_counter pair when the plane is off.
    """

    __slots__ = ("stage", "busy_s", "blocked_s", "idle_s", "events")

    enabled = True

    def __init__(self, stage: str):
        self.stage = stage
        self.busy_s = 0.0
        self.blocked_s = 0.0
        self.idle_s = 0.0
        self.events = 0

    def add_busy(self, dt: float) -> None:
        if dt > 0.0:
            self.busy_s += dt
            self.events += 1

    def add_blocked(self, dt: float) -> None:
        if dt > 0.0:
            self.blocked_s += dt

    def add_idle(self, dt: float) -> None:
        if dt > 0.0:
            self.idle_s += dt

    def snapshot(self) -> dict:
        return {
            "busy_s": self.busy_s,
            "blocked_s": self.blocked_s,
            "idle_s": self.idle_s,
            "events": self.events,
        }


class _NullClock(StageClock):
    """The disabled plane's shared clock: same API, zero accumulation."""

    __slots__ = ()

    enabled = False

    def add_busy(self, dt: float) -> None:
        pass

    def add_blocked(self, dt: float) -> None:
        pass

    def add_idle(self, dt: float) -> None:
        pass


_NULL_CLOCK = _NullClock("_disabled")


class Occupancy:
    """Time-weighted occupancy of one bounded resource.

    ``sample(level)`` charges the PREVIOUS level for the time it was
    held; the average is the integral over elapsed time, so a buffer
    that spikes for 1 ms out of 10 s averages near zero instead of
    whatever the scrape happened to catch. Single-writer like
    :class:`StageClock`."""

    __slots__ = ("resource", "capacity", "_level", "_last", "_integral",
                 "peak", "_t0")

    enabled = True

    def __init__(self, resource: str, capacity: Optional[float] = None):
        self.resource = resource
        self.capacity = capacity
        self._level = 0.0
        self._t0 = self._last = time.perf_counter()
        self._integral = 0.0
        self.peak = 0.0

    def sample(self, level: float) -> None:
        now = time.perf_counter()
        self._integral += self._level * (now - self._last)
        self._last = now
        self._level = float(level)
        if level > self.peak:
            self.peak = float(level)

    def time_avg(self) -> float:
        now = time.perf_counter()
        elapsed = now - self._t0
        if elapsed <= 0.0:
            return 0.0
        return (self._integral + self._level * (now - self._last)) / elapsed

    def snapshot(self) -> dict:
        out = {
            "avg": self.time_avg(),
            "peak": self.peak,
            "level": self._level,
        }
        if self.capacity:
            out["capacity"] = self.capacity
            out["utilization"] = out["avg"] / self.capacity
        return out


class _NullOccupancy(Occupancy):
    __slots__ = ()

    enabled = False

    def sample(self, level: float) -> None:
        pass


_NULL_OCC = _NullOccupancy("_disabled")


def estimate(stages: Dict[str, dict], window_s: float) -> dict:
    """The critical-path verdict over a stage table.

    Every stage candidates twice — its busy share (it IS the work) and
    its blocked share (it is starved BY downstream; rendered as
    ``<stage>_wait``). The unaccounted wall candidates as the implicit
    ``tick_cadence``/``drain_wait`` (ticks fire on stream labels, so
    un-attributed wall is the pipeline waiting for the next boundary).
    Stages may run on parallel threads, so shares can sum past 1.0; the
    unaccounted remainder is clamped at zero, which only ever
    *understates* cadence wait — the conservative direction."""
    window_s = max(float(window_s), 1e-9)
    candidates = []  # (stage, mode, share)
    accounted = 0.0
    for stage, st in stages.items():
        busy = float(st.get("busy_s", 0.0))
        blocked = float(st.get("blocked_s", 0.0))
        accounted += busy + blocked
        candidates.append((stage, "busy", busy / window_s))
        if blocked > 0.0:
            candidates.append((stage, "blocked", blocked / window_s))
    cadence_share = max(0.0, 1.0 - accounted / window_s)
    candidates.append((CADENCE, "drain_wait", cadence_share))
    stage, mode, share = max(candidates, key=lambda c: c[2])
    if mode == "busy":
        reason = f"busy {share * 100.0:.0f}% of window"
    elif mode == "blocked":
        reason = f"{stage}_wait {share * 100.0:.0f}% of window"
    else:
        reason = f"drain_wait {share * 100.0:.0f}% of window"
    return {
        "bottleneck": stage,
        "mode": mode,
        "share": round(share, 4),
        "reason": reason,
        "verdict": f"bottleneck: {stage}, confidence: {reason}",
        "window_s": round(window_s, 3),
    }


class AttributionPlane:
    """The process-wide attribution table (one per process; see
    :func:`get_attrib`). Creation of clocks/occupancies is locked; the
    accumulators themselves are single-writer lock-free."""

    def __init__(self, module: str = "apm", enabled: Optional[bool] = None):
        if enabled is None:
            enabled = os.environ.get("APM_NO_ATTRIB", "") in ("", "0")
        self.module = module
        self.enabled = bool(enabled)
        self._lock = threading.Lock()
        self._clocks: Dict[str, StageClock] = {}  # guarded-by: _lock (creation; accumulation is single-writer)
        self._occ: Dict[str, Occupancy] = {}  # guarded-by: _lock (creation)
        self._t0 = time.perf_counter()
        self._wall0 = time.time()

    # -- wiring ---------------------------------------------------------------
    def configure(self, *, module: Optional[str] = None,
                  enabled: Optional[bool] = None) -> "AttributionPlane":
        if module is not None:
            self.module = module
        if enabled is not None:
            self.enabled = bool(enabled)
        return self

    def clock(self, stage: str) -> StageClock:
        """Create-or-get the stage's clock; the shared no-op when the
        plane is disabled (call sites cache the reference either way)."""
        if not self.enabled:
            return _NULL_CLOCK
        with self._lock:
            clock = self._clocks.get(stage)
            if clock is None:
                clock = self._clocks[stage] = StageClock(stage)
            return clock

    def occupancy(self, resource: str,
                  capacity: Optional[float] = None) -> Occupancy:
        if not self.enabled:
            return _NULL_OCC
        with self._lock:
            occ = self._occ.get(resource)
            if occ is None:
                occ = self._occ[resource] = Occupancy(resource, capacity)
            elif capacity is not None and occ.capacity is None:
                occ.capacity = capacity
            return occ

    def reset(self) -> None:
        """Restart the observation window (bench phase boundaries)."""
        with self._lock:
            self._clocks.clear()
            self._occ.clear()
            self._t0 = time.perf_counter()
            self._wall0 = time.time()

    def window_s(self) -> float:
        return time.perf_counter() - self._t0

    # -- views ----------------------------------------------------------------
    def stage_table(self) -> Dict[str, dict]:
        with self._lock:
            clocks = list(self._clocks.values())
        return {c.stage: c.snapshot() for c in clocks}

    def occupancy_table(self) -> Dict[str, dict]:
        with self._lock:
            occs = list(self._occ.values())
        return {o.resource: o.snapshot() for o in occs}

    def snapshot(self) -> dict:
        """The full attribution picture: the /attrib body, the flight
        recorder's ``attribution`` source, and the bench certification
        input — one shape everywhere."""
        window = self.window_s()
        stages = self.stage_table()
        body = {
            "module": self.module,
            "enabled": self.enabled,
            "window_s": round(window, 3),
            "window_start_unixtime": self._wall0,
            "stages": {
                s: dict(
                    st,
                    busy_share=round(st["busy_s"] / max(window, 1e-9), 4),
                    blocked_share=round(st["blocked_s"] / max(window, 1e-9), 4),
                )
                for s, st in stages.items()
            },
            "occupancy": self.occupancy_table(),
        }
        body["estimate"] = estimate(stages, window)
        return body

    def bottleneck(self) -> dict:
        return estimate(self.stage_table(), self.window_s())

    # -- registry export ------------------------------------------------------
    def collect(self) -> List[Sample]:
        """Scrape-time samples — the store's self-sample persists these,
        so ``/query`` can plot ``rate(apm_stage_busy_seconds_total[60s])``
        stage shares over time."""
        out: List[Sample] = []
        labels = {"module": self.module}
        for stage, st in self.stage_table().items():
            sl = dict(labels, stage=stage)
            out.append(Sample(
                "apm_stage_busy_seconds_total", sl, st["busy_s"], "counter",
                "Wall seconds the stage spent doing its own work",
            ))
            out.append(Sample(
                "apm_stage_blocked_seconds_total", sl, st["blocked_s"],
                "counter",
                "Wall seconds the stage spent blocked on downstream",
            ))
            out.append(Sample(
                "apm_stage_idle_seconds_total", sl, st["idle_s"], "counter",
                "Wall seconds the stage spent idle (no input pending)",
            ))
            out.append(Sample(
                "apm_stage_events_total", sl, st["events"], "counter",
                "Busy intervals the stage accumulated",
            ))
        for resource, oc in self.occupancy_table().items():
            rl = dict(labels, resource=resource)
            out.append(Sample(
                "apm_occupancy_avg", rl, oc["avg"], "gauge",
                "Time-weighted average occupancy of the buffered resource",
            ))
            out.append(Sample(
                "apm_occupancy_peak", rl, oc["peak"], "gauge",
                "Peak occupancy of the buffered resource",
            ))
            out.append(Sample(
                "apm_occupancy_level", rl, oc["level"], "gauge",
                "Most recently sampled occupancy of the buffered resource",
            ))
        return out

    _registered_into: Optional[int] = None

    def install(self, registry: Optional[MetricsRegistry] = None) -> None:
        """Idempotently register the collector (the views.py _MARK
        discipline: standalone mode builds several runtimes over one
        process registry — one collector, not four)."""
        reg = registry if registry is not None else get_registry()
        if self._registered_into == id(reg):
            return
        self._registered_into = id(reg)
        reg.add_collector(self.collect)


# -- the process-global plane -------------------------------------------------

_plane = AttributionPlane()


def get_attrib() -> AttributionPlane:
    """The process-wide attribution plane every stage records into."""
    return _plane


def configure(**kwargs) -> AttributionPlane:
    """Configure the process plane in place (ModuleRuntime wiring; tests)."""
    return _plane.configure(**kwargs)


def set_attrib(plane: AttributionPlane) -> AttributionPlane:
    """Swap the process-global plane (test/bench isolation); returns the
    old one. Call sites cache clock references at construction, so a swap
    takes effect for components built AFTER it — the bench A/B pattern."""
    global _plane
    old, _plane = _plane, plane
    return old


def merge_snapshots(snapshots: List[dict]) -> dict:
    """Fleet-merge child /attrib bodies: stage seconds sum across
    children (stages run in parallel processes — the estimator's
    parallel-threads caveat already covers this), occupancy keeps each
    child's row under ``<module>:<resource>``, and the verdict is
    recomputed over the merged table with the widest child window."""
    stages: Dict[str, dict] = {}
    occupancy: Dict[str, dict] = {}
    window = 0.0
    children = []
    for snap in snapshots:
        if not snap:
            continue
        children.append(snap.get("module", "?"))
        window = max(window, float(snap.get("window_s", 0.0)))
        for stage, st in (snap.get("stages") or {}).items():
            agg = stages.setdefault(
                stage, {"busy_s": 0.0, "blocked_s": 0.0, "idle_s": 0.0,
                        "events": 0})
            agg["busy_s"] += float(st.get("busy_s", 0.0))
            agg["blocked_s"] += float(st.get("blocked_s", 0.0))
            agg["idle_s"] += float(st.get("idle_s", 0.0))
            agg["events"] += int(st.get("events", 0))
        for resource, oc in (snap.get("occupancy") or {}).items():
            occupancy[f"{snap.get('module', '?')}:{resource}"] = oc
    body = {
        "children": children,
        "window_s": round(window, 3),
        "stages": stages,
        "occupancy": occupancy,
    }
    body["estimate"] = estimate(stages, window)
    return body


def make_attrib_route(plane_fn: Optional[Callable[[], AttributionPlane]] = None):
    """``GET /attrib`` route body for :meth:`TelemetryServer.add_route`."""
    import json

    def route(_query):
        plane = plane_fn() if plane_fn is not None else get_attrib()
        return 200, "application/json", json.dumps(
            plane.snapshot(), indent=1, default=repr)

    return route
