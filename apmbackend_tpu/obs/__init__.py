"""Process-wide telemetry plane (SURVEY.md §5.5 made first-class).

The reference APM backend watched a 70-JVM fleet but was itself nearly
blind: its self-telemetry was log-and-reset strings (QueueStats/DBStats)
and on-demand heap dumps. This package gives every module the measurement
discipline the stream-processing literature treats as prerequisite to
optimization (PAPERS.md: arxiv 1712.08285 per-stage timing, arxiv
2511.14894 streaming-DAQ monitoring):

- :mod:`.registry` — a thread-safe metrics registry (counters, gauges,
  fixed-bucket histograms, collector views) rendering Prometheus text
  format; one process-global instance via :func:`get_registry`.
- :mod:`.exporter` — a stdlib-HTTP exporter thread per module serving
  ``/metrics``, ``/healthz`` and on-demand ``/profile`` capture.
- :mod:`.tracing` — the per-tick span ring + stage histograms the
  PipelineDriver records so "where did this tick's 0.56 ms go" is
  answerable in production, not just in bench_dispatch.py.
- :mod:`.trace` — sampled per-transaction trace propagation: head-sampled
  trace contexts stamped at transport entry, spans per hop (ingest →
  queue → feed → tick → emit → alert) in a ring served by ``/trace``,
  histograms linking back via OpenMetrics exemplars.
- :mod:`.decisions` — alert decision provenance: the z-score inputs
  behind every page, keyed by trace_id, served by ``/decisions``.
- :mod:`.flight` — crash flight-recorder bundles: bounded triage dumps on
  healthz degradation / SIGTERM / watchdog restart, plus a journal +
  sentinel shadow that survives kill−9 and is promoted to a crash bundle
  on the next boot.
- :mod:`.store` — the durable telemetry spine (DESIGN.md §8.4): an
  append-only segmented on-disk time-series store with CRC'd records,
  retention + downsample-on-compact, and ``rate()``/
  ``histogram_quantile`` range queries behind ``/query``.
- :mod:`.recorder` — the manager-side fleet recorder persisting every
  child's ``/metrics``, ``/trace``, and ``/decisions`` shard-labeled into
  the store, so a kill−9'd shard's telemetry survives into triage.
- :mod:`.slo` — Google-SRE multi-window burn-rate evaluation over the
  store (detection latency, per-queue lag/wait, epoch age), paging
  through the decision ring and degrading ``/healthz`` on fast burn.
- :mod:`.queryplane` — the fleet read front door (DESIGN.md §10.5):
  hash-routed single-service queries, scatter-gather merges with
  sum-then-quantile histogram semantics, and a durable degraded read
  path through the recorder store with per-shard freshness marking.

Everything here is stdlib-only and import-light: no jax at import time
(the /profile route imports it lazily), no hard dependency from any hot
path — a driver with telemetry disabled never touches this package.
"""

from .attrib import AttributionPlane, StageClock, get_attrib, merge_snapshots, set_attrib
from .decisions import DecisionRing, get_decisions
from .exporter import TelemetryServer, telemetry_active
from .flight import FlightRecorder
from .registry import (
    MetricsRegistry,
    Sample,
    get_registry,
    histogram_quantile,
    parse_prom_text,
    relabel_metrics,
    set_registry,
)
from .queryplane import QueryPlane
from .recorder import FleetRecorder
from .slo import SLOEngine
from .store import TimeSeriesStore, eval_range, make_query_route, matrix_doc
from .trace import SpanRing, Tracer, get_tracer
from .tracing import TickTracer

__all__ = [
    "AttributionPlane",
    "DecisionRing",
    "FleetRecorder",
    "FlightRecorder",
    "MetricsRegistry",
    "QueryPlane",
    "SLOEngine",
    "Sample",
    "SpanRing",
    "StageClock",
    "TelemetryServer",
    "TickTracer",
    "TimeSeriesStore",
    "Tracer",
    "eval_range",
    "get_attrib",
    "get_decisions",
    "get_registry",
    "get_tracer",
    "histogram_quantile",
    "make_query_route",
    "matrix_doc",
    "merge_snapshots",
    "parse_prom_text",
    "relabel_metrics",
    "set_attrib",
    "set_registry",
    "telemetry_active",
]
