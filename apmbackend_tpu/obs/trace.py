"""Sampled per-transaction trace propagation (the distributed trace plane).

The telemetry plane (PR 2) observes the pipeline in aggregate —
``apm_e2e_ingest_to_alert_seconds`` says *how long*, never *which
transaction*. This module adds the missing axis: a **head-sampled trace
context** stamped where a record enters the transport fabric
(``ProducerQueue.write_line``, the parser/tailer ingest boundary for the
``transactions`` queue), carried in transport headers alongside the
existing ``ingest_ts``/``msg_id`` (memory-broker tuples, AMQP message
properties, spool JSON — wire payloads untouched, reference interop
intact), and closed span by span as the record crosses each hop:

- ``ingest``  — raw-line read (chunk granular, :meth:`Tracer.note_ingest_start`)
                → transport entry, recorded by the producer;
- ``queue``   — producer ingest stamp → consumer delivery (ConsumerQueue);
- ``feed``    — delivery → the device driver absorbing the line (worker);
- ``tick``    — the device tick that closed the transaction's bucket;
- ``emit``    — that tick's emission readback + host fan-out;
- ``alert``   — alert dispatch for the transaction's service (when fired).

Redelivered messages keep their ORIGINAL trace_id (the broker retains
headers, exactly like ``msg_id``), so an at-least-once crash/redeliver
cycle extends a trace instead of splitting it.

Cost discipline (the hot path is sacred): the sampling decision is ONE
integer compare per produced message (``seq % rate``); an unsampled
message pays nothing else, and ``sample_rate <= 0`` (tracing OFF) skips
even that — behavior is bit-identical to the pre-trace wire. Sampling is
deterministic in the producer's message sequence, so a replayed stream
samples the same positions every run.

Spans land in a process-wide bounded :class:`SpanRing` served by the
exporter's ``/trace`` endpoint; the manager stitches cross-module spans
by trace_id on its own ``/trace`` route. Histograms link back into the
plane via OpenMetrics exemplars (``registry.Histogram.observe_exemplar``).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import List, Optional

# transport header key carrying the trace context end to end
TRACE_HEADER = "trace_id"


class SpanRing:
    """Thread-safe bounded ring of finished spans (plain dicts)."""

    def __init__(self, maxlen: int = 512):
        self._ring: deque = deque(maxlen=int(maxlen))  # guarded-by: _lock
        self._lock = threading.Lock()

    def add(self, span: dict) -> None:
        with self._lock:
            self._ring.append(span)

    def spans(self, trace_id: Optional[str] = None, n: Optional[int] = None) -> List[dict]:
        with self._lock:
            items = list(self._ring)
        if trace_id is not None:
            items = [s for s in items if s.get("trace_id") == trace_id]
        if n is not None and n > 0:
            items = items[-n:]
        return items

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()

    @property
    def maxlen(self) -> int:
        # apm: allow(lock-guard): deque.maxlen is immutable after construction — no torn read possible
        return self._ring.maxlen or 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)


class Tracer:
    """Head-sampled trace recorder for one process.

    One instance per process (see :func:`get_tracer`), mutated in place by
    :func:`configure` so transport objects may cache the reference at
    construction regardless of wiring order. ``rate == 0`` disables the
    plane entirely — producers then stamp no header and record no span.
    """

    def __init__(self, module: str = "apm", sample_rate: int = 0, ring_size: int = 512):
        self.module = module
        self.rate = int(sample_rate)
        self.ring = SpanRing(ring_size)
        # chunk-granular ingest anchor: the tailer/replay/parser note when a
        # chunk of raw lines was read; the producer's ingest span starts
        # there (or at transport entry when no boundary noted one)
        self._ingest_start: Optional[float] = None

    # -- wiring ---------------------------------------------------------------
    def configure(
        self,
        *,
        sample_rate: Optional[int] = None,
        module: Optional[str] = None,
        ring_size: Optional[int] = None,
    ) -> "Tracer":
        if sample_rate is not None:
            self.rate = int(sample_rate)
        if module is not None:
            self.module = module
        if ring_size is not None and int(ring_size) != self.ring.maxlen:
            self.ring = SpanRing(int(ring_size))
        return self

    # -- sampling -------------------------------------------------------------
    def should_sample(self, seq: int) -> bool:
        """The head-sampling decision: one integer compare. Deterministic in
        the producer's sequence number (message N of every run samples the
        same way), so replay/chaos runs are reproducible."""
        r = self.rate
        return r > 0 and seq % r == 0

    # -- ingest boundary ------------------------------------------------------
    def note_ingest_start(self) -> None:
        """Mark 'a chunk of raw input was just read' — the start anchor of
        the next ingest span. Called once per chunk (never per line)."""
        if self.rate > 0:
            self._ingest_start = time.time()

    @property
    def ingest_start(self) -> Optional[float]:
        return self._ingest_start

    # -- spans ----------------------------------------------------------------
    def span(
        self,
        trace_id: str,
        name: str,
        start: float,
        end: float,
        *,
        module: Optional[str] = None,
        **attrs,
    ) -> dict:
        """Record one finished span into the ring."""
        span = {
            "trace_id": trace_id,
            "name": name,
            "module": module or self.module,
            "start": start,
            "end": end,
            "duration_ms": round((end - start) * 1000.0, 3),
        }
        if attrs:
            span["attrs"] = attrs
        self.ring.add(span)
        return span


# -- the process-global tracer ------------------------------------------------

_tracer = Tracer()


def get_tracer() -> Tracer:
    """The process-wide tracer every transport/driver hop records into."""
    return _tracer


def configure(**kwargs) -> Tracer:
    """Configure the process tracer in place (ModuleRuntime wiring; tests)."""
    return _tracer.configure(**kwargs)


def set_tracer(tracer: Tracer) -> Tracer:
    """Swap the process-global tracer (test isolation); returns the old."""
    global _tracer
    old, _tracer = _tracer, tracer
    return old
