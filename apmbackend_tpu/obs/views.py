"""Collector views absorbing pre-existing telemetry into the registry.

QueueStats/DBStats keep their reference-parity log-and-reset behavior
(the ``IN<q: n - OUT>q: m`` lines); these views read the CUMULATIVE
totals those classes now also maintain, so /metrics exports proper
monotonic counters while the legacy log lines stay byte-identical.
Registration helpers are idempotent per underlying object (standalone
mode builds four ModuleRuntimes over one broker in one process — the
depth gauges must not export four copies of the same series).
"""

from __future__ import annotations

from typing import Optional

from .registry import MetricsRegistry, Sample, get_registry

_MARK = "_apm_obs_registered"


def register_queue_stats(qs, module: str, registry: Optional[MetricsRegistry] = None) -> None:
    """QueueStats cumulative totals -> apm_queue_messages_total{queue,direction,module}."""
    if getattr(qs, _MARK, False):
        return
    setattr(qs, _MARK, True)
    reg = registry if registry is not None else get_registry()

    def collect():
        for name, ctype, total in qs.totals():
            yield Sample(
                "apm_queue_messages_total",
                {"queue": name, "direction": "in" if ctype == "c" else "out", "module": module},
                total,
                "counter",
                "Messages through each queue handle (cumulative; QueueStats view)",
            )

    reg.add_collector(collect)


def register_db_stats(db, module: str, registry: Optional[MetricsRegistry] = None) -> None:
    """DBStats cumulative totals -> rows-inserted / insert-time counters."""
    if getattr(db, _MARK, False):
        return
    setattr(db, _MARK, True)
    reg = registry if registry is not None else get_registry()

    def collect():
        rows, ms = db.totals()
        labels = {"module": module}
        yield Sample(
            "apm_db_rows_inserted_total", labels, rows, "counter",
            "Rows batch-inserted by the DB sink (cumulative; DBStats view)",
        )
        yield Sample(
            "apm_db_insert_seconds_total", labels, ms / 1000.0, "counter",
            "Wall time spent in DB inserts (cumulative; DBStats view)",
        )

    reg.add_collector(collect)


def register_memory_broker(broker, registry: Optional[MetricsRegistry] = None) -> None:
    """Live queue depth/bytes gauges over the in-process broker — the
    rabbitmqctl-list_queues role (apm_manager.js:429-453) as a scrape."""
    if getattr(broker, _MARK, False):
        return
    setattr(broker, _MARK, True)
    reg = registry if registry is not None else get_registry()

    def collect():
        for name in broker.queue_names():
            yield Sample(
                "apm_queue_depth", {"queue": name}, broker.queue_depth(name),
                "gauge", "Messages waiting in the queue (memory broker view)",
            )
            yield Sample(
                "apm_queue_memory_bytes", {"queue": name}, broker.queue_memory_bytes(name),
                "gauge", "Payload bytes waiting in the queue (memory broker view)",
            )

    reg.add_collector(collect)


def register_attribution(module: Optional[str], registry: Optional[MetricsRegistry] = None) -> None:
    """Export the process attribution plane (obs.attrib) into the registry:
    ``apm_stage_{busy,blocked,idle}_seconds_total`` + occupancy gauges.
    Idempotent inside the plane itself (one collector per registry) — the
    standalone four-runtimes-one-registry topology registers once. A None
    ``module`` installs without claiming the label (non-exporter runtimes,
    mirroring the tracer's module rule)."""
    from .attrib import get_attrib

    plane = get_attrib()
    if module is not None:
        plane.configure(module=module)
    plane.install(registry)


def register_parser(parser, module: str, registry: Optional[MetricsRegistry] = None) -> None:
    """Correlation-parser stage counters (the ROADMAP "replay is
    parser-bound" quantification): line/record throughput, parse time,
    and correlation/account cache hit rates."""
    if getattr(parser, _MARK, False):
        return
    setattr(parser, _MARK, True)
    reg = registry if registry is not None else get_registry()
    labels = {"module": module}

    def collect():
        c = parser.counters
        yield Sample("apm_parser_lines_total", labels, c["lines_in"], "counter",
                     "Raw log lines fed to the correlation parser")
        yield Sample("apm_parser_tx_total", labels, c["tx_out"], "counter",
                     "Complete TxEntry records emitted by the parser")
        yield Sample("apm_parser_db_direct_total", labels, c["db_direct_out"], "counter",
                     "Records routed straight to the DB queue (non-Provider audit rows)")
        yield Sample("apm_parser_parse_seconds_total", labels, c["parse_ns"] / 1e9, "counter",
                     "Wall time inside TransactionParser.read_line/read_lines")
        yield Sample("apm_parser_native_lines_total", labels, c.get("native_lines", 0),
                     "counter",
                     "Lines processed by the native (C++) ingest fast path")
        yield Sample("apm_parser_prefilter_rejected_total", labels,
                     c.get("prefilter_rejected", 0), "counter",
                     "Lines dropped by the native marker pre-filter with zero Python work")
        yield Sample("apm_frames_emitted_total", labels,
                     c.get("frames_emitted", 0), "counter",
                     "APF1 frame batches emitted by the parser's frame mode")
        yield Sample("apm_frame_records_total", labels,
                     c.get("frame_records_out", 0), "counter",
                     "Records emitted via frame batches (no TxEntry, no on_record)")
        for cache, st in parser.cache_stats().items():
            cl = dict(labels, cache=cache)
            yield Sample("apm_parser_cache_hits_total", cl, st["hits"], "counter",
                         "Correlation cache hits (TTLCache view)")
            yield Sample("apm_parser_cache_misses_total", cl, st["misses"], "counter",
                         "Correlation cache misses (TTLCache view)")
            yield Sample("apm_parser_cache_keys", cl, st["keys"], "gauge",
                         "Live keys in the correlation cache")

    reg.add_collector(collect)
