"""Alert decision provenance: why did this page fire?

The reference's alert was a bare wire line — a number crossed a band, an
email went out, and the on-call replayed logs to reconstruct why. Every
anomaly alert the PipelineDriver dispatches now also emits a **decision
record**: the z-score inputs per channel at trigger time — per-metric
triggering value, rolling window mean, derived std, the lower/upper bands
actually compared, the smoothed signal, the configured threshold and
influence, window occupancy (ring fill for lag channels / sample count
for EWMA channels), and the device cause bits — keyed by the sampled
trace_id when the triggering bucket contained one. A page is thereby
*replayable* instead of a bare number.

Records are plain dicts in a process-wide bounded ring (same discipline
as the trace SpanRing) served by the exporter's ``/decisions`` endpoint
and folded into flight-recorder bundles. Recording happens on the ALERT
path only — never per message or per tick — so the hot path is untouched;
``observability.enabled: false`` removes it entirely.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import List, Optional, Tuple


class DecisionRing:
    """Thread-safe bounded ring of alert decision records (plain dicts)."""

    def __init__(self, maxlen: int = 256):
        self._ring: deque = deque(maxlen=int(maxlen))  # guarded-by: _lock
        self._lock = threading.Lock()
        self.total = 0  # guarded-by: _lock (monotonic count ever recorded)

    def record(self, decision: dict) -> None:
        with self._lock:
            self._ring.append(decision)
            self.total += 1

    def snapshot(self, n: Optional[int] = None) -> Tuple[int, List[dict]]:
        """Atomic ``(total, last n items)`` under one lock hold — a caller
        tracking a seen-counter against ``total`` cannot race a concurrent
        record() landing between the counter read and the item read."""
        with self._lock:
            total = self.total
            items = list(self._ring)
        if n is not None and 0 < n < len(items):
            items = items[-n:]
        return total, items

    def recent(self, n: Optional[int] = None, trace_id: Optional[str] = None) -> List[dict]:
        with self._lock:
            items = list(self._ring)
        if trace_id is not None:
            items = [d for d in items if d.get("trace_id") == trace_id]
        if n is not None and n > 0:
            items = items[-n:]
        return items

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)


# -- the process-global ring --------------------------------------------------

_decisions = DecisionRing()


def get_decisions() -> DecisionRing:
    """The process-wide decision ring the driver records into."""
    return _decisions


def set_decisions(ring: DecisionRing) -> DecisionRing:
    """Swap the process-global ring (test isolation); returns the old."""
    global _decisions
    old, _decisions = _decisions, ring
    return old
