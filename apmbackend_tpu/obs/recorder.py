"""Manager-side fleet recorder: scrape every child's ``/metrics``,
``/trace``, and ``/decisions`` on a cadence and persist them shard-labeled
into a :class:`~.store.TimeSeriesStore` (DESIGN.md §8.4).

This closes the PR-5 durable-sink follow-up: a kill−9'd shard's last
scraped series, spans, and alert decisions survive in the store and stay
queryable through ``/query`` after the process (and its rings) are gone.

Failure discipline: a scrape error is counted and skipped — the loop
never raises, never blocks past the per-target timeout, and a full disk
degrades inside the store (drop-and-count), so the recorder can never
take down the manager's monitor cadence or a child's hot path.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.request
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

from .registry import MetricsRegistry, Sample, parse_prom_text
from .store import TimeSeriesStore

Targets = Callable[[], List[Tuple[str, str]]]


def _fetch(url: str, timeout: float) -> bytes:
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.read()


class FleetRecorder:
    """Scrapes ``targets()`` -> ``[(module_name, base_url)]`` into a store.

    Drive it either with :meth:`start`/:meth:`stop` (own daemon thread —
    tests, benches, standalone) or by calling :meth:`scrape_once` from an
    existing timer (the manager wires ``runtime.every``).
    """

    def __init__(
        self,
        store: TimeSeriesStore,
        targets: Targets,
        *,
        interval_s: float = 2.0,
        timeout_s: float = 2.0,
        trace_n: int = 256,
        decision_n: int = 256,
        self_registry: Optional[MetricsRegistry] = None,
        self_module: str = "manager",
        registry: Optional[MetricsRegistry] = None,
        logger=None,
    ):
        self.store = store
        self.targets = targets
        self.interval_s = float(interval_s)
        self.timeout_s = float(timeout_s)
        self.trace_n = int(trace_n)
        self.decision_n = int(decision_n)
        self.self_registry = self_registry
        self.self_module = self_module
        self._logger = logger
        self._lock = threading.Lock()
        self._seen: Dict[str, Tuple[set, deque]] = {}  # guarded-by: _lock
        self._counts = {  # guarded-by: _lock
            "scrapes_total": 0,
            "scrape_errors_total": 0,
            "rows_total": 0,
            "span_rows_total": 0,
            "decision_rows_total": 0,
        }
        self._errors_by_module: Dict[str, int] = {}  # guarded-by: _lock
        self._last = {"ts": 0.0, "targets": 0, "ok": 0}  # guarded-by: _lock
        # wall time of the last SUCCESSFUL scrape per target — the query
        # plane's per-shard freshness source (how stale is the durable
        # fallback for a dead shard)
        self._last_ok_by_module: Dict[str, float] = {}  # guarded-by: _lock
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        if registry is not None:
            registry.add_collector(self._collect)

    # -- metrics about the recorder itself ---------------------------------

    def _collect(self):
        with self._lock:
            counts = dict(self._counts)
            errs = dict(self._errors_by_module)
            last = dict(self._last)
        yield Sample("apm_recorder_scrapes_total", {}, counts["scrapes_total"],
                     "counter", "Fleet recorder scrape passes completed")
        for mod, n in sorted(errs.items()):
            yield Sample("apm_recorder_scrape_errors_total", {"module": mod}, n,
                         "counter",
                         "Failed child endpoint fetches (skipped, drop-and-count)")
        yield Sample("apm_recorder_rows_total", {"kind": "sample"},
                     counts["rows_total"], "counter",
                     "Metric sample rows persisted by the fleet recorder")
        yield Sample("apm_recorder_rows_total", {"kind": "span"},
                     counts["span_rows_total"], "counter",
                     "Trace span rows persisted by the fleet recorder")
        yield Sample("apm_recorder_rows_total", {"kind": "decision"},
                     counts["decision_rows_total"], "counter",
                     "Alert decision rows persisted by the fleet recorder")
        yield Sample("apm_recorder_last_scrape_unixtime", {}, last["ts"],
                     "gauge", "Wall time of the last completed scrape pass")
        yield Sample("apm_recorder_targets", {}, last["targets"], "gauge",
                     "Targets seen on the last scrape pass")

    # -- dedup bookkeeping --------------------------------------------------

    def _fresh(self, target: str, kind: str, keys: List[tuple],
               rows: List[dict]) -> List[dict]:
        """Rows whose (kind, key) was not persisted for this target yet —
        /trace and /decisions return rings, so every pass re-sends history;
        bounded memory (the ring sizes bound what can ever come back)."""
        out = []
        with self._lock:
            seen, order = self._seen.setdefault(target, (set(), deque()))
            for key, row in zip(keys, rows):
                k = (kind,) + key
                if k in seen:
                    continue
                seen.add(k)
                order.append(k)
                while len(order) > 8192:
                    seen.discard(order.popleft())
                out.append(row)
        return out

    # -- one pass ------------------------------------------------------------

    def _scrape_target(self, name: str, base: str, now: float) -> None:
        extra = {"module": name}
        text = _fetch(f"{base}/metrics", self.timeout_s).decode("utf-8", "replace")
        n = self.store.append_samples(parse_prom_text(text), ts=now,
                                      extra_labels=extra)
        with self._lock:
            self._counts["rows_total"] += n
        try:
            doc = json.loads(_fetch(f"{base}/trace?n={self.trace_n}",
                                    self.timeout_s))
            spans = [s for s in doc.get("spans", []) if isinstance(s, dict)]
            keys = [(s.get("trace_id"), s.get("name"), s.get("start"))
                    for s in spans]
            fresh = self._fresh(name, "t", keys, spans)
            if fresh:
                n = self.store.append_spans(fresh, extra=extra)
                with self._lock:
                    self._counts["span_rows_total"] += n
        except Exception:
            self._note_error(name)
        try:
            doc = json.loads(_fetch(f"{base}/decisions?n={self.decision_n}",
                                    self.timeout_s))
            decs = [d for d in doc.get("decisions", []) if isinstance(d, dict)]
            keys = [(d.get("trace_id"), d.get("ts"), d.get("service"),
                     d.get("channel")) for d in decs]
            fresh = self._fresh(name, "d", keys, decs)
            if fresh:
                n = self.store.append_decisions(fresh, extra=extra)
                with self._lock:
                    self._counts["decision_rows_total"] += n
        except Exception:
            self._note_error(name)

    def _note_error(self, module: str) -> None:
        with self._lock:
            self._counts["scrape_errors_total"] += 1
            self._errors_by_module[module] = \
                self._errors_by_module.get(module, 0) + 1

    def scrape_once(self, now: Optional[float] = None) -> dict:
        """One pass over every target; never raises. Returns a summary."""
        now = time.time() if now is None else float(now)
        try:
            targets = list(self.targets() or [])
        except Exception:
            targets = []
        ok = 0
        for name, base in targets:
            try:
                self._scrape_target(name, base.rstrip("/"), now)
                ok += 1
                with self._lock:
                    self._last_ok_by_module[name] = now
            except Exception as e:
                self._note_error(name)
                if self._logger:
                    self._logger.debug("recorder: scrape %s failed: %s", name, e)
        if self.self_registry is not None:
            try:
                n = self.store.ingest_registry(
                    self.self_registry, ts=now,
                    extra_labels={"module": self.self_module})
                with self._lock:
                    self._counts["rows_total"] += n
            except Exception:
                self._note_error(self.self_module)
        try:
            self.store.compact(now)
        except Exception:
            pass
        with self._lock:
            self._counts["scrapes_total"] += 1
            self._last = {"ts": now, "targets": len(targets), "ok": ok}
            return {"ts": now, "targets": len(targets), "ok": ok,
                    "errors_total": self._counts["scrape_errors_total"]}

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()

        def loop():
            while not self._stop.is_set():
                self.scrape_once()
                self._stop.wait(self.interval_s)

        self._thread = threading.Thread(target=loop, name="apm-recorder",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=self.timeout_s + self.interval_s + 1.0)

    def freshness(self) -> Dict[str, float]:
        """{target name: unixtime of its last successful scrape} — what the
        query plane reports as per-shard staleness when serving a dead
        shard from the durable store."""
        with self._lock:
            return dict(self._last_ok_by_module)

    def status(self) -> dict:
        with self._lock:
            out = {"last": dict(self._last), "counts": dict(self._counts),
                   "errors_by_module": dict(self._errors_by_module),
                   "freshness": dict(self._last_ok_by_module)}
        out["store"] = self.store.stats()
        return out
