"""Config-schema'd SLO engine: Google-SRE multi-window burn rates over the
time-series store (DESIGN.md §8.4).

An *objective* declares a target fraction of good events for one series:

- ``kind: "latency"`` — histogram objective; an event is bad when it lands
  above ``thresholdSeconds``. Bad fraction over a window is computed from
  the increase of the cumulative ``<series>_bucket`` counters across the
  window (the bucket with the smallest bound >= threshold vs ``+Inf``),
  so it works from recorder scrapes alone, no per-event stream needed.
- ``kind: "gauge"`` — point objective; a sampled point is bad when its
  value exceeds ``threshold`` (per-queue lag, epoch age). Bad fraction =
  bad points / points.

``per: "<label>"`` fans one objective out over every observed value of a
label (the ROADMAP's per-queue lag SLOs: one burn rate per queue).

Burn rate = bad_fraction / (1 - target): burning the whole error budget
over the window is exactly 1.0. Multi-window alerting (SRE workbook ch.5):
page ("fast") when BOTH the short and long window burn >= fastBurnThreshold
(14.4 ~ 2% of a 30-day budget in one hour); ticket ("slow") at
slowBurnThreshold (6.0). Fast burn degrades ``/healthz`` to 503 through
the engine's :meth:`health` provider.

Every alert is recorded with full provenance — the windows, bad
fractions, thresholds, and point counts that produced it — into the
process decision ring (the same ring ``_dispatch_alert`` records into, so
``/decisions`` resolves SLO pages exactly like anomaly pages) and handed
to the ``on_alert`` sink (the manager wires ``ManagerAlerts``).
"""

from __future__ import annotations

import math
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from .registry import MetricsRegistry, Sample
from .store import TimeSeriesStore

# Default objectives: the four budgets the ISSUE names. Series fed by the
# worker histograms, the transport lag gauge, and the fleet epoch gauge.
DEFAULT_OBJECTIVES = [
    {
        "name": "detection_latency_p95",
        "kind": "latency",
        "series": "apm_e2e_ingest_to_emit_seconds",
        "thresholdSeconds": 0.1,
        "target": 0.95,
    },
    {
        "name": "alert_latency",
        "kind": "latency",
        "series": "apm_e2e_ingest_to_alert_seconds",
        "thresholdSeconds": 0.25,
        "target": 0.99,
    },
    {
        "name": "queue_wait",
        "kind": "latency",
        "series": "apm_queue_wait_seconds",
        "thresholdSeconds": 0.5,
        "target": 0.99,
        "per": "queue",
    },
    {
        "name": "queue_lag",
        "kind": "gauge",
        "series": "apm_queue_lag",
        "threshold": 10000.0,
        "target": 0.99,
        "per": "queue",
    },
    {
        "name": "epoch_age",
        "kind": "gauge",
        "series": "apm_delivery_epoch_age_seconds",
        "threshold": 60.0,
        "target": 0.99,
    },
    {
        # the rebalance controller's emergency signal (ISSUE 18): one
        # burn rate per KEYSPACE partition (P > N grain), fed by the
        # worker's apm_partition_lag gauge. A fast burn here qualifies
        # the owning shard as a rebalance donor even below the high
        # watermark (parallel/rebalancer.py reads it via
        # burning_partitions()).
        "name": "partition_lag",
        "kind": "gauge",
        "series": "apm_partition_lag",
        "threshold": 10000.0,
        "target": 0.99,
        "per": "partition",
    },
]


def burning_partitions(results: List[dict]) -> set:
    """Partition ids currently under FAST burn of the ``partition_lag``
    objective — the SLO → rebalance-policy bridge. Accepts the engine's
    last evaluation (``SLOEngine.status()["results"]`` or the list
    returned by ``evaluate()``); tolerates absent/foreign objectives."""
    out = set()
    for r in results or []:
        if (r.get("objective") == "partition_lag"
                and r.get("severity") == "fast"
                and str(r.get("key", "")).isdigit()):
            out.add(int(r["key"]))
    return out


def _delta(points: List[Tuple[float, float]]) -> float:
    """Reset-aware counter increase over a point list (first..last)."""
    if len(points) < 2:
        return 0.0
    inc = 0.0
    for (_, a), (_, b) in zip(points, points[1:]):
        inc += (b - a) if b >= a else b
    return max(0.0, inc)


class SLOEngine:
    """Evaluates objectives over a store; thread-safe."""

    def __init__(
        self,
        store: TimeSeriesStore,
        objectives: Optional[List[dict]] = None,
        *,
        short_window_s: float = 300.0,
        long_window_s: float = 3600.0,
        fast_burn: float = 14.4,
        slow_burn: float = 6.0,
        cooldown_s: float = 300.0,
        on_alert: Optional[Callable[[str, dict], None]] = None,
        decisions=None,
        registry: Optional[MetricsRegistry] = None,
        logger=None,
    ):
        self.store = store
        self.objectives = list(DEFAULT_OBJECTIVES if objectives is None
                               else objectives)
        self.short_window_s = float(short_window_s)
        self.long_window_s = float(long_window_s)
        self.fast_burn = float(fast_burn)
        self.slow_burn = float(slow_burn)
        self.cooldown_s = float(cooldown_s)
        self.on_alert = on_alert
        self._decisions = decisions
        self._logger = logger
        self._lock = threading.Lock()
        self._last_alert: Dict[tuple, float] = {}  # guarded-by: _lock
        self._last_eval: List[dict] = []  # guarded-by: _lock
        self._last_eval_ts = 0.0  # guarded-by: _lock
        self._alerts_total: Dict[tuple, int] = {}  # guarded-by: _lock
        self._evals_total = 0  # guarded-by: _lock
        if registry is not None:
            registry.add_collector(self._collect)

    @classmethod
    def from_config(cls, store: TimeSeriesStore, config: dict, **kw) -> "SLOEngine":
        """Build from the ``slo.*`` config section (config.py schema)."""
        slo_cfg = (config or {}).get("slo") or {}
        return cls(
            store,
            slo_cfg.get("objectives"),
            short_window_s=slo_cfg.get("shortWindowSeconds", 300.0),
            long_window_s=slo_cfg.get("longWindowSeconds", 3600.0),
            fast_burn=slo_cfg.get("fastBurnThreshold", 14.4),
            slow_burn=slo_cfg.get("slowBurnThreshold", 6.0),
            cooldown_s=slo_cfg.get("alertCooldownSeconds", 300.0),
            **kw,
        )

    # -- window math ---------------------------------------------------------

    def _bad_fraction_latency(self, obj: dict, start: float, end: float,
                              key_label: Optional[str]) -> Dict[str, dict]:
        threshold = float(obj.get("thresholdSeconds", 0.1))
        groups = self.store.series_points(
            str(obj["series"]) + "_bucket", start, end, obj.get("labels"))
        # one point list PER ORIGINAL LABELSET under each (key, le): the
        # reset-aware delta must run per counter series — interleaving two
        # shards' counters (shard0=100, shard1=5, ...) would read every
        # cross-shard transition as a reset and inflate the increase
        by_key: Dict[str, Dict[float, List[List[Tuple[float, float]]]]] = {}
        for lblkey, pts in groups.items():
            lbl = dict(lblkey)
            le_raw = lbl.pop("le", None)
            if le_raw is None:
                continue
            le = math.inf if le_raw in ("+Inf", "inf", "Inf") else float(le_raw)
            key = str(lbl.get(key_label, "")) if key_label else ""
            by_key.setdefault(key, {}).setdefault(le, []).append(pts)
        out: Dict[str, dict] = {}
        for key, by_le in by_key.items():
            total = sum(_delta(s) for s in by_le.get(math.inf, []))
            finite = sorted(b for b in by_le if not math.isinf(b))
            good_le = next((b for b in finite if b >= threshold), None)
            good = sum(_delta(s) for s in by_le[good_le]) \
                if good_le is not None else 0.0
            bad = max(0.0, total - good)
            out[key] = {
                "bad_fraction": (bad / total) if total > 0 else 0.0,
                "events": total,
                "bad_events": bad,
                "bucket_le": good_le,
            }
        return out

    def _bad_fraction_gauge(self, obj: dict, start: float, end: float,
                            key_label: Optional[str]) -> Dict[str, dict]:
        threshold = float(obj.get("threshold", 0.0))
        groups = self.store.series_points(
            str(obj["series"]), start, end, obj.get("labels"))
        by_key: Dict[str, List[float]] = {}
        for lblkey, pts in groups.items():
            lbl = dict(lblkey)
            key = str(lbl.get(key_label, "")) if key_label else ""
            by_key.setdefault(key, []).extend(v for _, v in pts)
        out: Dict[str, dict] = {}
        for key, values in by_key.items():
            bad = sum(1 for v in values if v > threshold)
            out[key] = {
                "bad_fraction": (bad / len(values)) if values else 0.0,
                "events": len(values),
                "bad_events": bad,
                "bucket_le": None,
            }
        return out

    # -- evaluation ----------------------------------------------------------

    def evaluate(self, now: Optional[float] = None) -> List[dict]:
        """Evaluate every objective over both windows; dispatch alerts for
        fast/slow burns (cooldown-limited); never raises."""
        now = time.time() if now is None else float(now)
        results: List[dict] = []
        for obj in self.objectives:
            try:
                results.extend(self._evaluate_objective(obj, now))
            except Exception as e:
                if self._logger:
                    self._logger.warning("slo: objective %s failed: %s",
                                         obj.get("name"), e)
        with self._lock:
            self._last_eval = results
            self._last_eval_ts = now
            self._evals_total += 1
        return results

    def _evaluate_objective(self, obj: dict, now: float) -> List[dict]:
        kind = obj.get("kind", "gauge")
        key_label = obj.get("per")
        target = float(obj.get("target", 0.99))
        budget = max(1e-9, 1.0 - target)
        frac = self._bad_fraction_latency if kind == "latency" \
            else self._bad_fraction_gauge
        windows = {"short": self.short_window_s, "long": self.long_window_s}
        per_window = {
            w: frac(obj, now - seconds, now, key_label)
            for w, seconds in windows.items()
        }
        keys = set()
        for d in per_window.values():
            keys.update(d)
        out = []
        for key in sorted(keys):
            win = {
                w: per_window[w].get(
                    key, {"bad_fraction": 0.0, "events": 0, "bad_events": 0,
                          "bucket_le": None})
                for w in windows
            }
            burn_short = win["short"]["bad_fraction"] / budget
            burn_long = win["long"]["bad_fraction"] / budget
            if burn_short >= self.fast_burn and burn_long >= self.fast_burn:
                severity = "fast"
            elif burn_short >= self.slow_burn and burn_long >= self.slow_burn:
                severity = "slow"
            else:
                severity = None
            res = {
                "objective": obj.get("name", obj.get("series")),
                "kind": kind,
                "series": obj.get("series"),
                "key": key,
                "per": key_label,
                "target": target,
                "threshold": obj.get("thresholdSeconds", obj.get("threshold")),
                "burn_short": burn_short,
                "burn_long": burn_long,
                "severity": severity,
                "windows": {
                    w: dict(win[w], window_s=windows[w]) for w in windows
                },
                "ts": now,
            }
            out.append(res)
            if severity is not None:
                self._maybe_alert(res, now)
        return out

    def _maybe_alert(self, res: dict, now: float) -> None:
        akey = (res["objective"], res["key"])
        with self._lock:
            last = self._last_alert.get(akey, 0.0)
            if now - last < self.cooldown_s:
                return
            self._last_alert[akey] = now
            ck = (res["objective"], res["severity"])
            self._alerts_total[ck] = self._alerts_total.get(ck, 0) + 1
        record = dict(res, decision="slo_burn_rate")
        ring = self._decisions
        if ring is None:
            from .decisions import get_decisions
            ring = get_decisions()
        try:
            ring.record(record)
        except Exception:
            pass
        key_part = f" [{res['per']}={res['key']}]" if res["key"] else ""
        msg = (
            f"SLO {res['severity']}-burn: {res['objective']}{key_part} "
            f"burn_short={res['burn_short']:.1f} burn_long={res['burn_long']:.1f} "
            f"(target={res['target']}, threshold={res['threshold']})"
        )
        if self.on_alert is not None:
            try:
                self.on_alert(msg, record)
            except Exception:
                pass
        if self._logger:
            self._logger.warning("%s", msg)

    # -- providers -----------------------------------------------------------

    def health(self) -> dict:
        """``add_health`` provider: fast burn degrades /healthz to 503."""
        with self._lock:
            results = list(self._last_eval)
            ts = self._last_eval_ts
        fast = [f"{r['objective']}:{r['key']}" if r["key"] else r["objective"]
                for r in results if r["severity"] == "fast"]
        slow = [f"{r['objective']}:{r['key']}" if r["key"] else r["objective"]
                for r in results if r["severity"] == "slow"]
        return {"ok": not fast, "fast_burning": fast, "slow_burning": slow,
                "objectives": len(self.objectives), "last_eval": ts}

    def status(self) -> dict:
        """Flight-bundle / qstat view: the full last evaluation."""
        with self._lock:
            return {"last_eval_ts": self._last_eval_ts,
                    "results": list(self._last_eval),
                    "windows": {"short_s": self.short_window_s,
                                "long_s": self.long_window_s},
                    "thresholds": {"fast": self.fast_burn,
                                   "slow": self.slow_burn}}

    def _collect(self):
        with self._lock:
            results = list(self._last_eval)
            alerts = dict(self._alerts_total)
            evals = self._evals_total
        yield Sample("apm_slo_evaluations_total", {}, evals, "counter",
                     "SLO engine evaluation passes")
        for (objective, severity), n in sorted(alerts.items()):
            yield Sample("apm_slo_alerts_total",
                         {"objective": objective, "severity": severity}, n,
                         "counter", "Burn-rate alerts dispatched (post-cooldown)")
        for r in results:
            lbl = {"objective": r["objective"]}
            if r["key"]:
                lbl["key"] = r["key"]
            yield Sample("apm_slo_burn_rate", dict(lbl, window="short"),
                         r["burn_short"], "gauge",
                         "Error-budget burn rate over the short window")
            yield Sample("apm_slo_burn_rate", dict(lbl, window="long"),
                         r["burn_long"], "gauge",
                         "Error-budget burn rate over the long window")
            yield Sample("apm_slo_fast_burn_active", lbl,
                         1.0 if r["severity"] == "fast" else 0.0, "gauge",
                         "1 while the objective is fast-burning (healthz 503)")
