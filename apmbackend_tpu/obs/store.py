"""Append-only segmented on-disk time-series store (the durable telemetry
spine, DESIGN.md §8.4).

Three record kinds share one journal: metric ``samples`` (registry
snapshots, shard-labeled by the fleet recorder), trace ``spans``, and
alert ``decisions`` — so a kill−9'd shard's last telemetry survives into
triage instead of dying with its process rings.

Durability discipline (two sanctioned idioms, analysis/durability.py):

- The ACTIVE segment is an append-mode journal: ``magic | per-record
  (u32 len | u32 crc32 | JSON batch)``. Append + flush is the commit; a
  torn tail is detected by the READER (length bounds + CRC) and recovery
  stops at the last valid record. No rename dance on the hot path.
- Compaction rewrites (downsample) and nothing else go through the
  tmp + ``os.replace`` atomic writer.

Hostile storage reuses the ``APM_CHAOS_FS`` seam from deltachain
(``StorageFaultPlan.on_segment_write`` — torn prefix then OSError).
Failed disk writes DEGRADE, never raise: the rows stay queryable from
the in-memory index, the drop is counted, and the writer backs off and
retries on a fresh segment. A full disk must not take down the scrape
loop or the hot path.

Retention is time-based (whole aged-out segments are unlinked);
segments older than ``downsample_after_s`` are compacted in place to
one sample per ``downsample_step_s`` bucket per series (LAST value per
bucket — cumulative counters stay correct for ``rate()``).

``directory=None`` gives a volatile in-memory store with the identical
query surface (the per-module ``/query`` default).
"""

from __future__ import annotations

import json
import math
import os
import re
import struct
import threading
import time
import zlib
from typing import Callable, Dict, Iterable, List, Optional, Tuple

from .registry import MetricsRegistry, Sample, histogram_quantile, parse_prom_text


def _faults():
    """The deltachain ``APM_CHAOS_FS`` fault plan (shared seam) — imported
    lazily so the obs package stays stdlib-only at import time (deltachain
    pulls numpy)."""
    from ..deltachain import _faults as dc_faults

    return dc_faults()

_MAGIC = b"APMTSDB1"
_REC = struct.Struct("<II")  # payload_len, payload_crc32
_MAX_RECORD = 16 << 20  # bounds check against bit-rotted length fields

SEGMENT_GLOB_RE = re.compile(r"^tseries-(\d{8})\.seg$")


def _seg_name(seq: int) -> str:
    return f"tseries-{seq:08d}.seg"


def _labelkey(labels: Dict[str, str]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _write_segment_atomic(path: str, blob: bytes) -> None:
    """Sanctioned atomic writer for compaction outputs: pid-suffixed tmp,
    flush+fsync, then ``os.replace`` — the rename IS the commit."""
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "wb") as fh:
            plan = _faults()
            if plan is not None:
                plan.on_segment_write(fh, blob)
            fh.write(blob)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, path)
    except OSError:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


class _Segment:
    """One on-disk (or in-memory) segment: decoded record batches plus the
    bookkeeping compaction and retention need. Mutated only under the
    owning store's lock."""

    __slots__ = ("seq", "path", "records", "min_ts", "max_ts", "bytes",
                 "downsampled", "created")

    def __init__(self, seq: int, path: Optional[str], created: float):
        self.seq = seq
        self.path = path
        self.records: List[dict] = []
        self.min_ts = math.inf
        self.max_ts = -math.inf
        self.bytes = 0
        self.downsampled = 0.0  # step already applied; 0 = raw
        self.created = created

    def note(self, record: dict, nbytes: int) -> None:
        self.records.append(record)
        self.bytes += nbytes
        ts = float(record.get("t", 0.0))
        self.min_ts = min(self.min_ts, ts)
        self.max_ts = max(self.max_ts, ts)


def _encode_record(record: dict) -> bytes:
    payload = json.dumps(record, separators=(",", ":")).encode("utf-8")
    return _REC.pack(len(payload), zlib.crc32(payload) & 0xFFFFFFFF) + payload


def _encode_segment_blob(header: dict, records: Iterable[dict]) -> bytes:
    parts = [_MAGIC, _encode_record({"k": "h", "t": header.get("created", 0.0),
                                     "h": header})]
    for rec in records:
        parts.append(_encode_record(rec))
    return b"".join(parts)


def _decode_records(blob: bytes) -> Tuple[List[dict], bool, int]:
    """Decode framed records; returns (records, clean, good_off). ``clean``
    is False when the walk stopped early — torn tail, bit-rot, or bounds —
    in which case everything before the first invalid frame is kept and
    ``good_off`` is the byte offset of the first invalid frame (the length
    a repair pass may truncate the file to)."""
    out: List[dict] = []
    if not blob.startswith(_MAGIC):
        return out, False, 0
    off = len(_MAGIC)
    n = len(blob)
    while off < n:
        if off + _REC.size > n:
            return out, False, off  # torn tail inside a frame header
        length, crc = _REC.unpack_from(blob, off)
        if length > _MAX_RECORD or off + _REC.size + length > n:
            return out, False, off  # bit-rotted length or truncated payload
        payload = blob[off + _REC.size:off + _REC.size + length]
        if (zlib.crc32(payload) & 0xFFFFFFFF) != crc:
            return out, False, off  # bit-rot
        try:
            rec = json.loads(payload.decode("utf-8"))
        except (ValueError, UnicodeDecodeError):
            return out, False, off
        out.append(rec)
        off += _REC.size + length
    return out, True, off


class TimeSeriesStore:
    """Append-only segmented time-series store with range queries.

    Thread-safe; every public method may be called from scrape threads,
    HTTP handler threads, and timer threads concurrently.
    """

    def __init__(
        self,
        directory: Optional[str] = None,
        *,
        retention_s: float = 3600.0,
        segment_max_bytes: int = 4 << 20,
        segment_max_age_s: float = 300.0,
        downsample_after_s: Optional[float] = 900.0,
        downsample_step_s: float = 60.0,
        reopen_backoff_s: float = 5.0,
        read_only: bool = False,
        registry: Optional[MetricsRegistry] = None,
        logger=None,
    ):
        self.directory = directory
        # read_only: post-mortem reader mode (qstat --store). Recovery
        # reads the valid prefix but never repairs in place — no truncate,
        # no quarantine rename — so pointing a CLI at a LIVE recorder
        # directory cannot mutate segments out from under the running
        # writer's open file handle. Appends and compaction are refused.
        self.read_only = bool(read_only)
        self.retention_s = float(retention_s)
        self.segment_max_bytes = int(segment_max_bytes)
        self.segment_max_age_s = float(segment_max_age_s)
        self.downsample_after_s = (
            None if downsample_after_s in (None, 0) else float(downsample_after_s)
        )
        self.downsample_step_s = max(1.0, float(downsample_step_s))
        self.reopen_backoff_s = float(reopen_backoff_s)
        self._logger = logger
        self._lock = threading.Lock()
        self._segments: List[_Segment] = []  # guarded-by: _lock
        self._active: Optional[_Segment] = None  # guarded-by: _lock
        self._fh = None  # guarded-by: _lock
        self._seq = 0  # guarded-by: _lock
        self._reopen_at = 0.0  # guarded-by: _lock
        self._closed = False  # guarded-by: _lock
        self._counts = {  # guarded-by: _lock
            "rows_total": 0,
            "dropped_rows_total": 0,
            "write_errors_total": 0,
            "recovered_rows": 0,
            "corrupt_segments_total": 0,
            "compactions_total": 0,
            "retention_drops_total": 0,
        }
        if directory is not None:
            os.makedirs(directory, exist_ok=True)
            with self._lock:
                self._recover_locked()
        if registry is not None:
            registry.add_collector(self._collect)

    # -- telemetry about the telemetry store -------------------------------

    def _collect(self):
        st = self.stats()
        yield Sample("apm_tsdb_rows_total", {}, st["rows_total"], "counter",
                     "Rows (samples/spans/decisions) appended to the time-series store")
        yield Sample("apm_tsdb_dropped_rows_total", {}, st["dropped_rows_total"],
                     "counter",
                     "Rows whose durable write failed (kept in memory, drop-and-count)")
        yield Sample("apm_tsdb_write_errors_total", {}, st["write_errors_total"],
                     "counter", "Segment write failures (ENOSPC/EIO degradation)")
        yield Sample("apm_tsdb_corrupt_segments_total", {},
                     st["corrupt_segments_total"], "counter",
                     "Segments whose recovery walk stopped early (torn tail / bit-rot)")
        yield Sample("apm_tsdb_compactions_total", {}, st["compactions_total"],
                     "counter", "Downsample-on-compact rewrites")
        yield Sample("apm_tsdb_segments", {}, st["segments"], "gauge",
                     "Live segments in the time-series store")
        yield Sample("apm_tsdb_bytes", {}, st["bytes"], "gauge",
                     "Total bytes across live store segments")

    # -- recovery ----------------------------------------------------------

    # apm: holds(_lock): called from __init__ under the lock
    def _recover_locked(self) -> None:
        names = []
        for fn in os.listdir(self.directory):
            m = SEGMENT_GLOB_RE.match(fn)
            if m:
                names.append((int(m.group(1)), fn))
        names.sort()
        stop = False
        for seq, fn in names:
            self._seq = max(self._seq, seq)
            path = os.path.join(self.directory, fn)
            if stop:
                # past the last valid segment: quarantine (rename aside,
                # content preserved for forensics) so the NEXT recovery sees
                # fresh appends — which land on higher seqs — as a clean
                # readable prefix instead of an unreachable tail
                if not self.read_only:
                    self._quarantine(path)
                continue
            try:
                with open(path, "rb") as fh:
                    blob = fh.read()
            except OSError:
                self._counts["corrupt_segments_total"] += 1
                stop = True
                if not self.read_only:
                    self._quarantine(path)
                continue
            records, clean, good_off = _decode_records(blob)
            if not records:
                # nothing valid (bad magic / empty / rotted header):
                # recovery stops at the last valid segment before this one
                self._counts["corrupt_segments_total"] += 1
                stop = True
                if not self.read_only:
                    self._quarantine(path)
                continue
            if records[0].get("k") == "h":
                header, body = records[0].get("h", {}), records[1:]
            else:
                header, body = {}, records
            seg = _Segment(seq, path, float(header.get("created", 0.0)))
            seg.downsampled = float(header.get("ds", 0.0))
            for rec in body:
                seg.note(rec, 0)
                self._counts["recovered_rows"] += len(rec.get("rows", ()))
            seg.bytes = len(blob)
            self._segments.append(seg)
            if not clean:
                self._counts["corrupt_segments_total"] += 1
                stop = True  # torn/rotted mid-file: later segments stay unread
                if self.read_only:
                    continue
                # repair in place: drop the rotted suffix so the segment
                # reads clean next time and doesn't re-poison recovery
                try:
                    with open(path, "r+b") as fh:
                        fh.truncate(good_off)
                    seg.bytes = good_off
                except OSError:
                    self._counts["write_errors_total"] += 1

    def _quarantine(self, path: str) -> None:
        try:
            os.replace(path, path + ".quarantine")
        except OSError:
            if self._logger:
                self._logger.warning("tsdb: quarantine failed for %s", path)

    # -- segment lifecycle -------------------------------------------------

    # apm: holds(_lock): callers append under the lock
    def _open_segment_locked(self, now: float) -> bool:
        if self.directory is None:
            self._seq += 1
            self._active = _Segment(self._seq, None, now)
            self._segments.append(self._active)
            return True
        if now < self._reopen_at:
            return False
        self._seq += 1
        path = os.path.join(self.directory, _seg_name(self._seq))
        header_blob = _MAGIC + _encode_record(
            {"k": "h", "t": now, "h": {"created": now, "ds": 0.0}})
        try:
            fh = open(path, "ab")
            plan = _faults()
            if plan is not None:
                plan.on_segment_write(fh, header_blob)
            fh.write(header_blob)
            fh.flush()
        except OSError as e:
            self._counts["write_errors_total"] += 1
            self._reopen_at = now + self.reopen_backoff_s
            if self._logger:
                self._logger.warning("tsdb: segment open failed (degraded): %s", e)
            try:
                fh.close()  # type: ignore[possibly-undefined]
            except Exception:
                pass
            try:
                # a torn header would stop the next recovery dead at this
                # seq; an empty/absent file never becomes a segment
                os.unlink(path)
            except OSError:
                pass
            return False
        self._fh = fh
        self._active = _Segment(self._seq, path, now)
        self._active.bytes = len(header_blob)
        self._segments.append(self._active)
        return True

    # apm: holds(_lock): rotation happens under the append lock
    def _seal_active_locked(self) -> None:
        if self._fh is not None:
            try:
                self._fh.close()
            except OSError:
                pass
            self._fh = None
        self._active = None

    # apm: holds(_lock): the one writer path, always under the lock
    def _append_locked(self, record: dict, now: float) -> None:
        nrows = len(record.get("rows", ()))
        self._counts["rows_total"] += nrows
        blob = _encode_record(record)
        # rotate on size/age before the write so segments stay bounded
        if self._active is not None and (
            self._active.bytes + len(blob) > self.segment_max_bytes
            or now - self._active.created > self.segment_max_age_s
        ):
            self._seal_active_locked()
        if self._active is None:
            if not self._open_segment_locked(now):
                # disk unavailable: keep the row queryable in memory only
                seg = self._segments[-1] if self._segments and \
                    self._segments[-1].path is None else None
                if seg is None:
                    seg = _Segment(self._seq, None, now)
                    self._segments.append(seg)
                seg.note(record, 0)
                self._counts["dropped_rows_total"] += nrows
                return
        seg = self._active
        assert seg is not None
        if seg.path is None:  # in-memory store
            seg.note(record, len(blob))
            return
        try:
            plan = _faults()
            if plan is not None:
                plan.on_segment_write(self._fh, blob)
            self._fh.write(blob)
            self._fh.flush()
        except OSError as e:
            # drop-and-count: memory keeps serving, disk backs off
            self._counts["write_errors_total"] += 1
            self._counts["dropped_rows_total"] += nrows
            self._reopen_at = now + self.reopen_backoff_s
            if self._logger:
                self._logger.warning("tsdb: append failed (degraded): %s", e)
            # the failed write may have left a torn tail (real ENOSPC tears
            # mid-record): truncate back to the last clean frame so this
            # segment — and everything sealed after it — recovers readable
            try:
                self._fh.truncate(seg.bytes)
            except OSError:
                pass
            self._seal_active_locked()
            seg.note(record, 0)
            return
        seg.note(record, len(blob))

    # -- public append API -------------------------------------------------

    def append_samples(
        self,
        rows: Iterable[Tuple[str, Dict[str, str], float]],
        ts: Optional[float] = None,
        extra_labels: Optional[Dict[str, str]] = None,
    ) -> int:
        """Append (name, labels, value) metric rows stamped at ``ts``."""
        now = time.time()
        t = now if ts is None else float(ts)
        packed = []
        for name, labels, value in rows:
            lbl = dict(labels)
            if extra_labels:
                lbl.update(extra_labels)
            if not (isinstance(value, (int, float)) and math.isfinite(value)):
                continue
            packed.append([name, lbl, value])
        if not packed or self.read_only:
            return 0
        with self._lock:
            if self._closed:
                return 0
            self._append_locked({"k": "s", "t": t, "rows": packed}, now)
        return len(packed)

    def ingest_registry(
        self,
        registry: MetricsRegistry,
        ts: Optional[float] = None,
        extra_labels: Optional[Dict[str, str]] = None,
    ) -> int:
        """Snapshot a live registry (scrape-equivalent) into the store."""
        return self.append_samples(
            parse_prom_text(registry.render()), ts=ts, extra_labels=extra_labels)

    def ingest_prom_text(
        self,
        text: str,
        ts: Optional[float] = None,
        extra_labels: Optional[Dict[str, str]] = None,
    ) -> int:
        return self.append_samples(parse_prom_text(text), ts=ts,
                                   extra_labels=extra_labels)

    def append_spans(self, spans: Iterable[dict],
                     extra: Optional[Dict[str, str]] = None) -> int:
        now = time.time()
        rows = []
        for sp in spans:
            d = dict(sp)
            if extra:
                d.update(extra)
            rows.append(d)
        if not rows or self.read_only:
            return 0
        t = max((float(r.get("start", now)) for r in rows), default=now)
        with self._lock:
            if self._closed:
                return 0
            self._append_locked({"k": "t", "t": t, "rows": rows}, now)
        return len(rows)

    def append_decisions(self, decisions: Iterable[dict],
                         extra: Optional[Dict[str, str]] = None) -> int:
        now = time.time()
        rows = []
        for dec in decisions:
            d = dict(dec)
            if extra:
                d.update(extra)
            rows.append(d)
        if not rows or self.read_only:
            return 0
        t = max((float(r.get("ts", now)) for r in rows), default=now)
        with self._lock:
            if self._closed:
                return 0
            self._append_locked({"k": "d", "t": t, "rows": rows}, now)
        return len(rows)

    # -- compaction / retention --------------------------------------------

    def compact(self, now: Optional[float] = None) -> dict:
        """Time-based retention + downsample-on-compact. Safe on a timer;
        failures degrade (the raw segment stays) rather than raise."""
        now = time.time() if now is None else float(now)
        dropped = rewritten = 0
        with self._lock:
            if self._closed or self.read_only:
                return {"dropped": 0, "downsampled": 0}
            keep: List[_Segment] = []
            for seg in self._segments:
                aged = (seg.max_ts < now - self.retention_s) if seg.records \
                    else (seg.created < now - self.retention_s)
                if seg is not self._active and aged:
                    if seg.path is not None:
                        try:
                            os.unlink(seg.path)
                        except OSError:
                            pass
                    self._counts["retention_drops_total"] += 1
                    dropped += 1
                    continue
                keep.append(seg)
            self._segments = keep
            if self.downsample_after_s is not None:
                for seg in self._segments:
                    if seg is self._active or seg.downsampled or seg.path is None:
                        continue
                    if seg.max_ts >= now - self.downsample_after_s:
                        continue
                    if self._downsample_locked(seg):
                        rewritten += 1
        return {"dropped": dropped, "downsampled": rewritten}

    # apm: holds(_lock): compact() holds the lock across the rewrite
    def _downsample_locked(self, seg: _Segment) -> bool:
        step = self.downsample_step_s
        last: Dict[tuple, Tuple[float, list]] = {}
        others: List[dict] = []
        order: List[tuple] = []
        for rec in seg.records:
            if rec.get("k") != "s":
                others.append(rec)  # spans/decisions are sparse: keep raw
                continue
            t = float(rec.get("t", 0.0))
            bucket = math.floor(t / step) * step
            for row in rec.get("rows", ()):
                key = (bucket, row[0], _labelkey(row[1]))
                if key not in last:
                    order.append(key)
                last[key] = (t, row)  # LAST value per bucket wins
        sample_recs: Dict[float, dict] = {}
        for key in order:
            t, row = last[key]
            bucket = key[0]
            rec = sample_recs.setdefault(
                bucket, {"k": "s", "t": bucket, "rows": []})
            rec["rows"].append(row)
        new_records = sorted(sample_recs.values(), key=lambda r: r["t"]) + others
        header = {"created": seg.created, "ds": step}
        blob = _encode_segment_blob(header, new_records)
        try:
            _write_segment_atomic(seg.path, blob)
        except OSError as e:
            self._counts["write_errors_total"] += 1
            if self._logger:
                self._logger.warning("tsdb: downsample failed (raw kept): %s", e)
            return False
        seg.records = new_records
        seg.bytes = len(blob)
        seg.downsampled = step
        self._counts["compactions_total"] += 1
        return True

    # -- queries -----------------------------------------------------------

    def series_points(
        self,
        name: str,
        start: float,
        end: float,
        labels: Optional[Dict[str, str]] = None,
    ) -> Dict[tuple, List[Tuple[float, float]]]:
        """Raw points per labelset for one series name within [start, end]."""
        out: Dict[tuple, List[Tuple[float, float]]] = {}
        with self._lock:
            segs = list(self._segments)
        for seg in segs:
            if seg.max_ts < start or seg.min_ts > end:
                continue
            for rec in seg.records:
                if rec.get("k") != "s":
                    continue
                t = float(rec.get("t", 0.0))
                if t < start or t > end:
                    continue
                for row in rec.get("rows", ()):
                    if row[0] != name:
                        continue
                    lbl = row[1]
                    if labels and any(str(lbl.get(k)) != str(v)
                                      for k, v in labels.items()):
                        continue
                    out.setdefault(_labelkey(lbl), []).append((t, float(row[2])))
        for pts in out.values():
            pts.sort(key=lambda p: p[0])
        return out

    def series_names(self, prefix: str = "") -> List[str]:
        names = set()
        with self._lock:
            segs = list(self._segments)
        for seg in segs:
            for rec in seg.records:
                if rec.get("k") != "s":
                    continue
                for row in rec.get("rows", ()):
                    if row[0].startswith(prefix):
                        names.add(row[0])
        return sorted(names)

    def _rows_of_kind(self, kind: str, start: float, end: float,
                      match: Optional[Dict[str, str]], tskey: str,
                      limit: int) -> List[dict]:
        out: List[dict] = []
        with self._lock:
            segs = list(self._segments)
        for seg in segs:
            if seg.max_ts < start or seg.min_ts > end:
                continue
            for rec in seg.records:
                if rec.get("k") != kind:
                    continue
                for row in rec.get("rows", ()):
                    t = float(row.get(tskey, rec.get("t", 0.0)) or rec.get("t", 0.0))
                    if t < start or t > end:
                        continue
                    if match and any(str(row.get(k)) != str(v)
                                     for k, v in match.items()):
                        continue
                    out.append(row)
        out.sort(key=lambda r: float(r.get(tskey, 0.0) or 0.0))
        return out[-limit:] if limit else out

    def spans(self, start: float = 0.0, end: float = math.inf,
              match: Optional[Dict[str, str]] = None,
              limit: int = 0) -> List[dict]:
        return self._rows_of_kind("t", start, end, match, "start", limit)

    def decisions(self, start: float = 0.0, end: float = math.inf,
                  match: Optional[Dict[str, str]] = None,
                  limit: int = 0) -> List[dict]:
        return self._rows_of_kind("d", start, end, match, "ts", limit)

    def tail(self, n: int = 64) -> List[dict]:
        """Last ``n`` record batches (newest last) — the flight-bundle
        'trajectory into the crash' source."""
        with self._lock:
            recs: List[dict] = []
            for seg in self._segments:
                recs.extend(seg.records)
            return [dict(r) for r in recs[-n:]]

    def stats(self) -> dict:
        with self._lock:
            st = dict(self._counts)
            st["segments"] = len(self._segments)
            st["bytes"] = sum(s.bytes for s in self._segments)
            st["degraded"] = self._reopen_at > time.time()
            min_ts = min((s.min_ts for s in self._segments if s.records),
                         default=math.inf)
            max_ts = max((s.max_ts for s in self._segments if s.records),
                         default=-math.inf)
        st["oldest_ts"] = None if math.isinf(min_ts) else min_ts
        st["newest_ts"] = None if math.isinf(max_ts) else max_ts
        return st

    def close(self) -> None:
        with self._lock:
            self._closed = True
            self._seal_active_locked()


# ---------------------------------------------------------------------------
# Range-query expression evaluation (the /query endpoint and qstat --range)
# ---------------------------------------------------------------------------

_EXPR_RE = re.compile(
    r"^\s*(?:(?P<fn>rate|increase|histogram_quantile)\s*\(\s*"
    r"(?:(?P<q>[0-9.]+)\s*,\s*)?)?"
    r"(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<sel>[^}]*)\})?"
    r"(?:\[(?P<win>[0-9.]+)s\])?"
    r"\s*\)?\s*$"
)


def parse_selector(sel: Optional[str]) -> Dict[str, str]:
    out: Dict[str, str] = {}
    if not sel:
        return out
    for part in sel.split(","):
        part = part.strip()
        if not part:
            continue
        k, _, v = part.partition("=")
        out[k.strip()] = v.strip().strip('"')
    return out


def _instant(points: List[Tuple[float, float]], t: float,
             lookback: float) -> Optional[float]:
    """Last value at or before ``t`` within ``lookback`` (prometheus
    instant-vector semantics, bounded staleness)."""
    best = None
    for ts, v in points:
        if ts > t:
            break
        if ts >= t - lookback:
            best = v
    return best


def _increase(points: List[Tuple[float, float]], t: float,
              window: float) -> Optional[Tuple[float, float]]:
    """Reset-aware counter increase over (t-window, t] -> (increase,
    observed span); None with fewer than two in-window points."""
    win = [(ts, v) for ts, v in points if t - window < ts <= t]
    if len(win) < 2:
        return None
    inc = 0.0
    for (_, a), (_, b) in zip(win, win[1:]):
        if b >= a:
            inc += b - a
        else:
            inc += b  # counter reset: the new value is the increment
    return max(0.0, inc), win[-1][0] - win[0][0]


def _rate(points: List[Tuple[float, float]], t: float,
          window: float) -> Optional[float]:
    """Counter rate over (t-window, t]: the reset-aware increase divided
    by the observed span."""
    got = _increase(points, t, window)
    if got is None:
        return None
    inc, span = got
    return inc / span if span > 0 else None


_MAX_EVAL_STEPS = 11000  # prometheus caps range resolution the same way


def eval_range(
    store: TimeSeriesStore,
    expr: str,
    start: float,
    end: float,
    step: float,
) -> dict:
    """Evaluate a range query over the store.

    Supported expressions (the qstat subset):

    - ``name`` / ``name{label="v"}`` — instant vector per step
    - ``rate(name[Ns])`` — reset-aware counter rate (window defaults to
      4×step when ``[Ns]`` is omitted)
    - ``increase(name[Ns])`` — the reset-aware counter increase itself
      (undivided). The query plane merges histograms with this: per-shard
      bucket increases are summable, per-shard quantiles are not.
    - ``histogram_quantile(q, name[Ns])`` — prometheus quantile over the
      ``name_bucket`` series, grouped by labels minus ``le``. Buckets are
      WINDOWED first (reset-aware increase over ``[Ns]``, defaulting to
      4×step — the ``histogram_quantile(q, rate(...))`` idiom), so the
      quantile reflects the queried range, not the cumulative
      since-process-start distribution.
    """
    m = _EXPR_RE.match(expr or "")
    if not m:
        raise ValueError(f"unsupported query expression: {expr!r}")
    step = max(0.001, float(step))
    start, end = float(start), float(end)
    if end < start:
        raise ValueError("end < start")
    # a huge range at a tiny step would spin the serving thread for minutes
    # (start=0 over epoch seconds is 10^8 steps); cap like prometheus does
    if (end - start) / step > _MAX_EVAL_STEPS:
        raise ValueError(
            f"range/step yields more than {_MAX_EVAL_STEPS} steps; "
            f"widen the step or narrow the range")
    fn = m.group("fn")
    name = m.group("name")
    sel = parse_selector(m.group("sel"))
    window = float(m.group("win")) if m.group("win") else 4.0 * step
    lookback = max(step, 15.0)
    steps = []
    t = start
    while t <= end + 1e-9:
        steps.append(t)
        t += step
    series_out = []

    if fn == "histogram_quantile":
        if m.group("q") is None:
            raise ValueError("histogram_quantile needs a quantile argument")
        q = float(m.group("q"))
        base = name[:-len("_bucket")] if name.endswith("_bucket") else name
        groups = store.series_points(base + "_bucket", start - window, end, sel)
        merged: Dict[tuple, Dict[float, List[Tuple[float, float]]]] = {}
        for key, pts in groups.items():
            le = None
            rest = []
            for k, v in key:
                if k == "le":
                    le = math.inf if v in ("+Inf", "inf") else float(v)
                else:
                    rest.append((k, v))
            if le is None:
                continue
            # (rest, le) == the full original labelset: each list stays one
            # counter series, already time-sorted by series_points
            merged.setdefault(tuple(rest), {}).setdefault(le, []).extend(pts)
        for key, by_le in sorted(merged.items()):
            pts_out = []
            for t in steps:
                buckets = []
                for le, pts in by_le.items():
                    got = _increase(pts, t, window)
                    if got is not None:
                        buckets.append((le, got[0]))
                val = histogram_quantile(buckets, q) if buckets else None
                pts_out.append([t, None if val is None or not math.isfinite(val)
                                else val])
            series_out.append({"labels": dict(key), "points": pts_out})
        return {"expr": expr, "start": start, "end": end, "step": step,
                "series": series_out}

    lb = window if fn in ("rate", "increase") else lookback
    groups = store.series_points(name, start - lb, end, sel)
    for key, pts in sorted(groups.items()):
        pts_out = []
        for t in steps:
            if fn == "rate":
                v = _rate(pts, t, window)
            elif fn == "increase":
                got = _increase(pts, t, window)
                v = got[0] if got is not None else None
            else:
                v = _instant(pts, t, lookback)
            pts_out.append([t, None if v is None or not math.isfinite(v) else v])
        series_out.append({"labels": dict(key), "points": pts_out})
    return {"expr": expr, "start": start, "end": end, "step": step,
            "series": series_out}


def matrix_doc(doc: dict) -> dict:
    """Convert an :func:`eval_range` result into Prometheus range-matrix
    JSON (``format=matrix``): one ``{metric, values}`` entry per labelset,
    values as ``[unixtime, "string"]`` pairs with null points dropped —
    the shape a Grafana JSON datasource consumes directly. Extra serving
    fields the query plane added (``shards``/``partial``/...) do not
    belong to the Prometheus schema and are not carried over."""
    result = []
    for s in doc.get("series", []):
        values = [[t, repr(float(v))] for t, v in s.get("points", [])
                  if v is not None]
        result.append({"metric": dict(s.get("labels", {})), "values": values})
    return {"status": "success",
            "data": {"resultType": "matrix", "result": result}}


def make_query_route(store_fn: Callable[[], Optional[TimeSeriesStore]]):
    """Build a TelemetryServer ``/query`` route over a store accessor.

    ``GET /query?series=<expr>&start=&end=&step=`` evaluates a range
    expression; ``GET /query?kind=spans|decisions|names|stats`` reads the
    other record kinds (the dead-shard triage path). Label filters ride
    as plain query params (e.g. ``&module=shard0``). ``&format=matrix``
    reshapes series results into Prometheus range-matrix JSON (a Grafana
    JSON datasource consumes it directly); the default shape is unchanged.
    """
    _reserved = {"series", "kind", "start", "end", "step", "limit", "q",
                 "format", "cache"}

    def route(query):
        # the exporter hands parse_qs dicts (list values) and expects a
        # str body; plain-dict queries (unit tests) work too
        q = {k: (v[0] if isinstance(v, list) else v) for k, v in query.items()}
        store = store_fn()
        if store is None:
            return 404, "text/plain; charset=utf-8", "no time-series store configured\n"
        now = time.time()
        try:
            start = float(q.get("start", now - 300.0))
            end = float(q.get("end", now))
            step = float(q.get("step", 10.0))
            limit = int(q.get("limit", 256))
        except ValueError:
            return 400, "text/plain; charset=utf-8", "bad start/end/step/limit\n"
        match = {k: v for k, v in q.items() if k not in _reserved}
        kind = q.get("kind")
        try:
            if kind in ("spans", "decisions"):
                rows = (store.spans if kind == "spans" else store.decisions)(
                    start, end, match or None, limit)
                body = {"kind": kind, "start": start, "end": end, "rows": rows}
            elif kind == "names":
                body = {"kind": "names", "names": store.series_names()}
            elif kind == "stats":
                body = {"kind": "stats", "stats": store.stats()}
            elif q.get("series"):
                body = eval_range(store, q["series"], start, end, step)
                if q.get("format") == "matrix":
                    body = matrix_doc(body)
            else:
                return 400, "text/plain; charset=utf-8", \
                    "need ?series=<expr> or ?kind=spans|decisions|names|stats\n"
        except ValueError as e:
            return 400, "text/plain; charset=utf-8", f"{e}\n"
        return 200, "application/json", json.dumps(body, default=repr)

    return route
