"""Per-module telemetry HTTP server: /metrics, /healthz, /profile.

One daemon thread per module process (stdlib ThreadingHTTPServer — the
same no-new-deps discipline as the rest of the transport), started by
ModuleRuntime when the module's config carries a ``metricsPort``
(0 = ephemeral, for tests and colocated fleets; the bound port is
exposed as :attr:`TelemetryServer.port`). Routes:

- ``GET /metrics`` — the process registry in Prometheus text format
  (content type ``text/plain; version=0.0.4``): Grafana/Prometheus get a
  scrape target exactly like the reference's dashboards had.
- ``GET /healthz`` — JSON from registered health providers (tick
  liveness, emission backlog, device presence, child fleet state...).
  200 when every provider reports ``ok``, 503 otherwise — load-balancer
  and supervisor friendly.
- ``GET /profile?ms=500`` — on-demand capture: a jax.profiler trace of
  ``ms`` milliseconds into a timestamped directory (TensorBoard/perfetto
  readable) plus a heap snapshot via utils.profiling — the live
  "attach the inspector" affordance, now one curl away. Captures are
  serialized process-wide (jax.profiler is a process-global singleton):
  a second concurrent request — even against another TelemetryServer in
  the same process — gets 409 instead of racing two traces.
- ``GET /trace[?trace_id=&n=]`` — recent spans from the process trace
  ring (obs.trace), JSON; the per-module half of distributed traces (the
  manager's own ``/trace`` stitches across children by trace_id).
- ``GET /attrib`` — the wall-clock attribution plane (obs.attrib): the
  per-stage busy/blocked/idle table, time-weighted occupancies, and the
  critical-path bottleneck verdict (the manager's ``/attrib`` merges
  across children).
- ``GET /decisions[?trace_id=&n=]`` — recent alert decision records
  (obs.decisions): why each page fired, resolvable by trace_id.
- ``GET /flight?reason=...`` — on-demand flight-recorder bundle when the
  module runs one (obs.flight); the manager's watchdog requests this
  from a wedged child right before force-restarting it. A degraded
  /healthz also dumps a bundle (rate-limited).
- ``GET /metrics?exemplars=1`` — OpenMetrics-style exposition with
  histogram bucket exemplars (``# {trace_id="..."} value ts``).
- extra routes via :meth:`add_route` (the manager mounts ``/fleet``).
  Registered routes OVERRIDE the built-ins at the same path — the
  manager uses this to replace its per-process ``/query`` ``/trace``
  ``/decisions`` ``/attrib`` with the fleet-wide query plane
  (obs.queryplane), which scatter-gathers across children and falls
  back to the recorder store for dead shards.

Health providers and routes are plain callables so modules register
without this module importing them (no cycle into pipeline/runtime).
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Optional, Tuple
from urllib.parse import parse_qs, urlparse

from .registry import MetricsRegistry, get_registry

PROM_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"
OPENMETRICS_CONTENT_TYPE = "application/openmetrics-text; version=1.0.0; charset=utf-8"

# jax.profiler is a process-global singleton: captures must serialize across
# every TelemetryServer in the process, not per instance (two modules'
# exporters in one standalone process used to race start_trace/stop_trace)
_profile_capture_lock = threading.Lock()

# live exporter count: single-process topologies (standalone) start ONE
# exporter on the lead runtime while satellites share the process registry —
# they gate their collector registration on this instead of owning a server
_active = 0
_active_lock = threading.Lock()


def telemetry_active() -> bool:
    """True while any TelemetryServer in this process is serving."""
    return _active > 0


class TelemetryServer:
    def __init__(
        self,
        registry: Optional[MetricsRegistry] = None,
        *,
        port: int = 0,
        host: str = "127.0.0.1",
        profile_dir: str = "logs",
        module: str = "apm",
        logger=None,
    ):
        self.registry = registry if registry is not None else get_registry()
        self._requested_port = port
        self.host = host
        self.profile_dir = profile_dir
        self.module = module
        self.logger = logger
        self.port: Optional[int] = None
        self._server: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._health: Dict[str, Callable[[], dict]] = {}
        self._routes: Dict[str, Callable[[dict], Tuple[int, str, str]]] = {}
        # the module's FlightRecorder when it runs one (ModuleRuntime wires
        # it); serves /flight and the degraded-healthz auto-dump
        self.flight = None

    # -- registration ---------------------------------------------------------
    def add_health(self, name: str, fn: Callable[[], dict]) -> None:
        """``fn() -> dict``; an ``"ok": False`` key degrades /healthz to 503."""
        self._health[name] = fn

    def add_route(self, path: str, fn: Callable[[dict], Tuple[int, str, str]]) -> None:
        """``fn(query) -> (status, content_type, body)`` for extra GET paths."""
        self._routes[path] = fn

    # -- handlers -------------------------------------------------------------
    def _handle_metrics(self, query) -> Tuple[int, str, str]:
        if query.get("exemplars"):
            # OpenMetrics-style exposition: bucket lines carry trace_id
            # exemplars linking the latency histogram back to /trace
            return (
                200,
                OPENMETRICS_CONTENT_TYPE,
                self.registry.render(exemplars=True) + "# EOF\n",
            )
        return 200, PROM_CONTENT_TYPE, self.registry.render()

    def _handle_healthz(self, _query) -> Tuple[int, str, str]:
        body = {"module": self.module, "ts": time.time()}
        ok = True
        for name, fn in list(self._health.items()):
            try:
                section = fn() or {}
            except Exception as e:  # a broken probe IS a health failure
                section = {"ok": False, "error": repr(e)}
            if section.get("ok") is False:
                ok = False
            body[name] = section
        body["status"] = "ok" if ok else "degraded"
        if not ok and self.flight is not None:
            # degradation is a flight-recorder trigger; rate-limited inside
            # dump() so a flapping probe cannot churn the bundle directory
            try:
                bundle = self.flight.dump("healthz_degraded")
                if bundle:
                    body["flight_bundle"] = bundle
            except Exception:
                pass
        return (200 if ok else 503), "application/json", json.dumps(body, indent=1)

    def _handle_trace(self, query) -> Tuple[int, str, str]:
        from .trace import get_tracer

        trace_id = (query.get("trace_id") or [None])[0]
        try:
            n = max(1, min(int((query.get("n") or ["256"])[0]), 4096))
        except (TypeError, ValueError):
            return 400, "application/json", json.dumps({"error": "bad n parameter"})
        tracer = get_tracer()
        spans = tracer.ring.spans(trace_id=trace_id, n=n)
        body = {
            "module": self.module,
            "sample_rate": tracer.rate,
            "count": len(spans),
            "spans": spans,
        }
        return 200, "application/json", json.dumps(body, indent=1, default=repr)

    def _handle_attrib(self, _query) -> Tuple[int, str, str]:
        from .attrib import get_attrib

        return 200, "application/json", json.dumps(
            get_attrib().snapshot(), indent=1, default=repr
        )

    def _handle_decisions(self, query) -> Tuple[int, str, str]:
        from .decisions import get_decisions

        trace_id = (query.get("trace_id") or [None])[0]
        try:
            n = max(1, min(int((query.get("n") or ["128"])[0]), 4096))
        except (TypeError, ValueError):
            return 400, "application/json", json.dumps({"error": "bad n parameter"})
        ring = get_decisions()
        records = ring.recent(n, trace_id=trace_id)
        body = {
            "module": self.module,
            "total": ring.total,
            "count": len(records),
            "decisions": records,
        }
        return 200, "application/json", json.dumps(body, indent=1, default=repr)

    def _handle_flight(self, query) -> Tuple[int, str, str]:
        if self.flight is None:
            return 404, "application/json", json.dumps(
                {"error": "flight recorder not configured (observability.flightDir)"}
            )
        reason = (query.get("reason") or ["on_demand"])[0][:64]
        try:
            path = self.flight.dump(reason, force=True)
        except Exception as e:
            return 500, "application/json", json.dumps({"error": repr(e)})
        return 200, "application/json", json.dumps({"module": self.module, "bundle": path})

    def _handle_profile(self, query) -> Tuple[int, str, str]:
        """Capture a bounded device trace + heap snapshot; serialized
        process-wide so two concurrent curls (or two exporters in one
        process) cannot interleave jax.profiler start/stop or land two
        captures in the same directory."""
        try:
            ms = max(1, min(int(query.get("ms", ["500"])[0]), 60_000))
        except (TypeError, ValueError):
            return 400, "application/json", json.dumps({"error": "bad ms parameter"})
        if not _profile_capture_lock.acquire(blocking=False):
            return 409, "application/json", json.dumps({"error": "profile capture already running"})
        try:
            import os

            from ..utils.profiling import heap_snapshot

            # pid + uuid: two captures in the same second (or from two
            # processes sharing a log dir) must not collide on one directory
            import uuid

            stamp = f"{time.strftime('%Y%m%d-%H%M%S')}-{os.getpid()}-{uuid.uuid4().hex[:6]}"
            trace_dir = os.path.join(self.profile_dir, f"profile-{self.module}-{stamp}")
            result = {"module": self.module, "ms": ms}
            try:
                import jax

                jax.profiler.start_trace(trace_dir)
                time.sleep(ms / 1000.0)
                jax.profiler.stop_trace()
                result["trace_dir"] = trace_dir
            except Exception as e:  # no device / profiler unavailable: still
                # return the heap side — diagnostics degrade, never 500
                result["trace_error"] = repr(e)
            result["heap_snapshot"] = heap_snapshot(
                self.profile_dir, f"{self.module}-profile", logger=self.logger
            )
            status = 200 if ("trace_dir" in result or result["heap_snapshot"]) else 503
            return status, "application/json", json.dumps(result, indent=1)
        finally:
            _profile_capture_lock.release()

    # -- lifecycle ------------------------------------------------------------
    def start(self) -> int:
        outer = self

        class _Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 - http.server API
                parsed = urlparse(self.path)
                route = {
                    "/metrics": outer._handle_metrics,
                    "/healthz": outer._handle_healthz,
                    "/profile": outer._handle_profile,
                    "/trace": outer._handle_trace,
                    "/attrib": outer._handle_attrib,
                    "/decisions": outer._handle_decisions,
                    "/flight": outer._handle_flight,
                    **outer._routes,
                }.get(parsed.path)
                if route is None:
                    self.send_error(404)
                    return
                try:
                    status, ctype, body = route(parse_qs(parsed.query))
                except Exception as e:
                    status, ctype, body = 500, "text/plain", f"handler error: {e!r}"
                data = body.encode("utf-8")
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def log_message(self, *_args):  # scrapes must not spam the module log
                pass

        self._server = ThreadingHTTPServer((self.host, self._requested_port), _Handler)
        self._server.daemon_threads = True
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            name=f"telemetry-{self.module}",
            daemon=True,
        )
        self._thread.start()
        global _active
        with _active_lock:
            _active += 1
        if self.logger:
            self.logger.info(
                f"Telemetry exporter listening on http://{self.host}:{self.port} "
                f"(/metrics /healthz /profile /trace /attrib /decisions /flight)"
            )
        return self.port

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def stop(self) -> None:
        global _active
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
            with _active_lock:
                _active -= 1
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
