"""Per-module telemetry HTTP server: /metrics, /healthz, /profile.

One daemon thread per module process (stdlib ThreadingHTTPServer — the
same no-new-deps discipline as the rest of the transport), started by
ModuleRuntime when the module's config carries a ``metricsPort``
(0 = ephemeral, for tests and colocated fleets; the bound port is
exposed as :attr:`TelemetryServer.port`). Routes:

- ``GET /metrics`` — the process registry in Prometheus text format
  (content type ``text/plain; version=0.0.4``): Grafana/Prometheus get a
  scrape target exactly like the reference's dashboards had.
- ``GET /healthz`` — JSON from registered health providers (tick
  liveness, emission backlog, device presence, child fleet state...).
  200 when every provider reports ``ok``, 503 otherwise — load-balancer
  and supervisor friendly.
- ``GET /profile?ms=500`` — on-demand capture: a jax.profiler trace of
  ``ms`` milliseconds into a timestamped directory (TensorBoard/perfetto
  readable) plus a heap snapshot via utils.profiling — the live
  "attach the inspector" affordance, now one curl away.
- extra routes via :meth:`add_route` (the manager mounts ``/fleet``).

Health providers and routes are plain callables so modules register
without this module importing them (no cycle into pipeline/runtime).
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Optional, Tuple
from urllib.parse import parse_qs, urlparse

from .registry import MetricsRegistry, get_registry

PROM_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

# live exporter count: single-process topologies (standalone) start ONE
# exporter on the lead runtime while satellites share the process registry —
# they gate their collector registration on this instead of owning a server
_active = 0
_active_lock = threading.Lock()


def telemetry_active() -> bool:
    """True while any TelemetryServer in this process is serving."""
    return _active > 0


class TelemetryServer:
    def __init__(
        self,
        registry: Optional[MetricsRegistry] = None,
        *,
        port: int = 0,
        host: str = "127.0.0.1",
        profile_dir: str = "logs",
        module: str = "apm",
        logger=None,
    ):
        self.registry = registry if registry is not None else get_registry()
        self._requested_port = port
        self.host = host
        self.profile_dir = profile_dir
        self.module = module
        self.logger = logger
        self.port: Optional[int] = None
        self._server: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._health: Dict[str, Callable[[], dict]] = {}
        self._routes: Dict[str, Callable[[dict], Tuple[int, str, str]]] = {}
        self._profile_lock = threading.Lock()

    # -- registration ---------------------------------------------------------
    def add_health(self, name: str, fn: Callable[[], dict]) -> None:
        """``fn() -> dict``; an ``"ok": False`` key degrades /healthz to 503."""
        self._health[name] = fn

    def add_route(self, path: str, fn: Callable[[dict], Tuple[int, str, str]]) -> None:
        """``fn(query) -> (status, content_type, body)`` for extra GET paths."""
        self._routes[path] = fn

    # -- handlers -------------------------------------------------------------
    def _handle_metrics(self, _query) -> Tuple[int, str, str]:
        return 200, PROM_CONTENT_TYPE, self.registry.render()

    def _handle_healthz(self, _query) -> Tuple[int, str, str]:
        body = {"module": self.module, "ts": time.time()}
        ok = True
        for name, fn in list(self._health.items()):
            try:
                section = fn() or {}
            except Exception as e:  # a broken probe IS a health failure
                section = {"ok": False, "error": repr(e)}
            if section.get("ok") is False:
                ok = False
            body[name] = section
        body["status"] = "ok" if ok else "degraded"
        return (200 if ok else 503), "application/json", json.dumps(body, indent=1)

    def _handle_profile(self, query) -> Tuple[int, str, str]:
        """Capture a bounded device trace + heap snapshot; serialized so two
        concurrent curls cannot interleave jax.profiler start/stop."""
        try:
            ms = max(1, min(int(query.get("ms", ["500"])[0]), 60_000))
        except (TypeError, ValueError):
            return 400, "application/json", json.dumps({"error": "bad ms parameter"})
        if not self._profile_lock.acquire(blocking=False):
            return 409, "application/json", json.dumps({"error": "profile capture already running"})
        try:
            import os

            from ..utils.profiling import heap_snapshot

            stamp = time.strftime("%Y%m%d-%H%M%S")
            trace_dir = os.path.join(self.profile_dir, f"profile-{self.module}-{stamp}")
            result = {"module": self.module, "ms": ms}
            try:
                import jax

                jax.profiler.start_trace(trace_dir)
                time.sleep(ms / 1000.0)
                jax.profiler.stop_trace()
                result["trace_dir"] = trace_dir
            except Exception as e:  # no device / profiler unavailable: still
                # return the heap side — diagnostics degrade, never 500
                result["trace_error"] = repr(e)
            result["heap_snapshot"] = heap_snapshot(
                self.profile_dir, f"{self.module}-profile", logger=self.logger
            )
            status = 200 if ("trace_dir" in result or result["heap_snapshot"]) else 503
            return status, "application/json", json.dumps(result, indent=1)
        finally:
            self._profile_lock.release()

    # -- lifecycle ------------------------------------------------------------
    def start(self) -> int:
        outer = self

        class _Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 - http.server API
                parsed = urlparse(self.path)
                route = {
                    "/metrics": outer._handle_metrics,
                    "/healthz": outer._handle_healthz,
                    "/profile": outer._handle_profile,
                    **outer._routes,
                }.get(parsed.path)
                if route is None:
                    self.send_error(404)
                    return
                try:
                    status, ctype, body = route(parse_qs(parsed.query))
                except Exception as e:
                    status, ctype, body = 500, "text/plain", f"handler error: {e!r}"
                data = body.encode("utf-8")
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def log_message(self, *_args):  # scrapes must not spam the module log
                pass

        self._server = ThreadingHTTPServer((self.host, self._requested_port), _Handler)
        self._server.daemon_threads = True
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            name=f"telemetry-{self.module}",
            daemon=True,
        )
        self._thread.start()
        global _active
        with _active_lock:
            _active += 1
        if self.logger:
            self.logger.info(
                f"Telemetry exporter listening on http://{self.host}:{self.port} "
                f"(/metrics /healthz /profile)"
            )
        return self.port

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def stop(self) -> None:
        global _active
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
            with _active_lock:
                _active -= 1
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
