"""Fleet query plane: hash-routed scatter-gather serving with a durable
degraded read path (ISSUE 20).

The fleet is sharded (parallel.fleet), self-rebalancing (manager
rebalancer), and durably telemetered (obs.recorder -> obs.store), but
every read surface is per-process. This module is the single front door:
a :class:`QueryPlane` hosted on the manager (or standalone via
``python -m apmbackend_tpu.obs.queryplane``) serving ``GET /query``,
``/trace``, ``/decisions``, and ``/attrib`` fleet-wide.

Routing
-------
A single-service query (``?service=NAME`` or a ``service="NAME"``
selector label) routes via the pinned ``service_partition`` FNV-1a hash
and the live owner map to exactly the owning shard — the same placement
the write path uses, so the answer comes from the one shard that holds
the service. Everything else scatters to all shards under bounded
fan-out concurrency and merges with correct semantics:

- counters / rates / instants: colliding labelsets SUM per step,
  disjoint labelsets union (prometheus ``sum by`` over shards);
- ``histogram_quantile``: per-shard BUCKET INCREASES are fetched
  (``increase(name_bucket[..])``), summed per labelset per step, and the
  quantile is computed over the merged buckets — never by averaging
  per-shard quantiles, which is wrong for any skewed placement;
- spans and decisions dedup by identity (the recorder's keys), so a row
  that reached both a live ring and the durable store appears once.

Rebalance consistency: the owner feed is read *with a seq* before and
after every fan-out. If ownership changed underneath the query, the
query retries (bounded by ``move_retries``) so a read racing a partition
handoff neither double-counts nor drops the moving partition.

Degraded reads
--------------
A dead shard does not 404 the fleet: its slice is served from the
recorder's durable TimeSeriesStore (filtered by the shard's ``module``
label, which is then stripped so merged output is shape-identical to the
live path) and the response carries ``partial: true``, ``stale: true``,
and per-shard ``{status, freshness_s}`` so the dashboard shows *how old*
the degraded slice is instead of silently mixing epochs.

Serving
-------
A TTL read-through cache with in-flight coalescing absorbs
dashboard-repeated queries (``&cache=0`` bypasses); serving stats are
exported through the registry (``apm_queryplane_*``) and persisted
through the recorder like every other manager metric.

Import-time stdlib-only (the obs-package rule): ``service_partition``
is imported lazily from ``parallel.fleet`` on the routing path.
"""

from __future__ import annotations

import json
import math
import threading
import time
import urllib.parse
import urllib.request
from typing import Callable, Dict, List, Optional, Tuple

from .registry import MetricsRegistry, histogram_quantile
from .store import TimeSeriesStore, _EXPR_RE, eval_range, matrix_doc

# targets feed: () -> [(name, base_url)] — the FleetRecorder contract
Targets = Callable[[], List[Tuple[str, str]]]
# owner feed: () -> (seq, {partition: target name}); seq bumps only on change
Owners = Callable[[], Tuple[int, Dict[int, str]]]

_SPAN_KEY = ("trace_id", "name", "start")
_DECISION_KEY = ("trace_id", "ts", "service", "channel")


class _BadRequest(ValueError):
    """Client error: rendered as 400, never counted as a serving error."""


class _TTLCache:
    """TTL read-through cache with in-flight coalescing.

    One leader computes per key; concurrent followers wait on the
    leader's event and re-read (counted as hits — they were absorbed).
    A leader that raises releases its followers to elect a new leader,
    so one failed compute cannot wedge the key. ``ttl_s <= 0`` disables.
    """

    _MAX_ENTRIES = 512  # dashboards repeat a handful of queries; bound it

    def __init__(self, ttl_s: float):
        self.ttl_s = float(ttl_s)
        self._lock = threading.Lock()
        self._entries: Dict[tuple, Tuple[float, object]] = {}  # guarded-by: _lock
        self._inflight: Dict[tuple, threading.Event] = {}  # guarded-by: _lock

    def get_or_compute(self, key, fn):
        """-> (value, hit)."""
        if self.ttl_s <= 0:
            return fn(), False
        while True:
            with self._lock:
                now = time.monotonic()
                ent = self._entries.get(key)
                if ent is not None and ent[0] > now:
                    return ent[1], True
                ev = self._inflight.get(key)
                if ev is None:
                    ev = threading.Event()
                    self._inflight[key] = ev
                    leader = True
                else:
                    leader = False
            if leader:
                try:
                    value = fn()
                    with self._lock:
                        if len(self._entries) >= self._MAX_ENTRIES:
                            self._entries = {
                                k: v for k, v in self._entries.items()
                                if v[0] > now
                            }
                        self._entries[key] = (time.monotonic() + self.ttl_s,
                                              value)
                    return value, False
                finally:
                    with self._lock:
                        self._inflight.pop(key, None)
                    ev.set()
            else:
                # bounded: a stuck leader must not hang followers forever
                ev.wait(timeout=30.0)

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)


def _pmap(fn, items: list, limit: int) -> List[Tuple[str, object]]:
    """Bounded thread fan-out; ordered ``("ok", result) | ("err", exc)``."""
    items = list(items)
    if not items:
        return []
    results: List[Tuple[str, object]] = [("err", None)] * len(items)
    sem = threading.Semaphore(max(1, int(limit)))

    def run(i, item):
        with sem:
            try:
                results[i] = ("ok", fn(item))
            except Exception as e:  # per-shard failure -> degraded, not 500
                results[i] = ("err", e)

    threads = [threading.Thread(target=run, args=(i, it), daemon=True)
               for i, it in enumerate(items)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return results


def _expr_str(fn: Optional[str], q: Optional[float], name: str,
              sel: Dict[str, str], window: Optional[float]) -> str:
    """Rebuild a canonical expression string for per-shard dispatch."""
    s = name
    if sel:
        s += "{" + ",".join(f'{k}="{v}"' for k, v in sorted(sel.items())) + "}"
    if window is not None:
        s += f"[{window:g}s]"
    if fn == "histogram_quantile":
        return f"histogram_quantile({q:g}, {s})"
    if fn in ("rate", "increase"):
        return f"{fn}({s})"
    return s


def _merge_series(docs: List[dict]) -> List[dict]:
    """Sum colliding labelsets per step across shard results; union the
    disjoint ones. None means absent (identity), not zero — a step where
    every shard is None stays None."""
    merged: Dict[tuple, List[list]] = {}
    for doc in docs:
        for s in doc.get("series", []):
            key = tuple(sorted(s.get("labels", {}).items()))
            pts = s.get("points", [])
            cur = merged.get(key)
            if cur is None:
                merged[key] = [list(p) for p in pts]
                continue
            for i, p in enumerate(pts):
                if i >= len(cur):
                    cur.append(list(p))
                    continue
                v = p[1]
                if v is None:
                    continue
                cur[i][1] = v if cur[i][1] is None else cur[i][1] + v
    return [{"labels": dict(k), "points": pts}
            for k, pts in sorted(merged.items())]


def _merge_histogram(docs: List[dict], q: float) -> List[dict]:
    """Bucket-merge-then-quantile: ``docs`` are per-shard
    ``increase(name_bucket[..])`` results. Bucket increases sum per full
    labelset per step (summable; per-shard quantiles are not), then the
    quantile is computed over the merged buckets per labels-minus-le
    group — identical math to the single-store eval_range path, which is
    what makes the golden bit-equality check possible."""
    summed = _merge_series(docs)
    groups: Dict[tuple, Dict[float, List[list]]] = {}
    for s in summed:
        labels = dict(s["labels"])
        le_s = labels.pop("le", None)
        if le_s is None:
            continue
        le = math.inf if le_s in ("+Inf", "inf") else float(le_s)
        groups.setdefault(tuple(sorted(labels.items())), {})[le] = s["points"]
    series_out = []
    for key, by_le in sorted(groups.items()):
        n = max((len(p) for p in by_le.values()), default=0)
        pts_out = []
        for i in range(n):
            t = None
            buckets = []
            for le, pts in by_le.items():
                if i < len(pts):
                    t = pts[i][0]
                    if pts[i][1] is not None:
                        buckets.append((le, pts[i][1]))
            val = histogram_quantile(buckets, q) if buckets else None
            if val is not None and not math.isfinite(val):
                val = None
            pts_out.append([t, val])
        series_out.append({"labels": dict(key), "points": pts_out})
    return series_out


def _dedup_rows(rows: List[dict], key_fields: Tuple[str, ...]) -> List[dict]:
    seen = set()
    out = []
    for row in rows:
        if not isinstance(row, dict):
            continue
        k = tuple(row.get(f) for f in key_fields)
        if k in seen:
            continue
        seen.add(k)
        out.append(row)
    return out


class QueryPlane:
    """The fleet read front door; see the module docstring for semantics.

    ``targets``/``owners`` follow the recorder / OwnerMap contracts;
    ``store`` is the durable fallback (None -> dead shards stay dead);
    ``freshness`` optionally maps target name -> unixtime of its last
    successful recorder scrape (staleness honesty for degraded serves).
    """

    def __init__(
        self,
        targets: Targets,
        *,
        owners: Optional[Owners] = None,
        store: Optional[TimeSeriesStore] = None,
        partitions: int = 0,
        partition_key: str = "service",
        registry: Optional[MetricsRegistry] = None,
        cache_ttl_s: float = 2.0,
        fanout: int = 8,
        timeout_s: float = 2.0,
        move_retries: int = 2,
        freshness: Optional[Callable[[], Dict[str, float]]] = None,
        logger=None,
    ):
        self.targets = targets
        self.owners = owners
        self.store = store
        self.partitions = int(partitions)
        self.partition_key = partition_key
        self.timeout_s = float(timeout_s)
        self.fanout = max(1, int(fanout))
        self.move_retries = max(0, int(move_retries))
        self.freshness = freshness
        self._logger = logger
        self._cache = _TTLCache(cache_ttl_s)
        self._lock = threading.Lock()
        self._last_shards: Dict[str, dict] = {}  # guarded-by: _lock
        # guarded-by: _lock
        self._counts = {"requests": 0, "errors": 0, "cache_hits": 0}
        reg = registry
        self._m_requests = {
            r: reg.counter("apm_queryplane_requests_total",
                           "Fleet query plane requests served",
                           {"route": r}) if reg else None
            for r in ("query", "trace", "decisions", "attrib")
        }
        if reg is not None:
            self._m_errors = reg.counter(
                "apm_queryplane_errors_total",
                "Fleet query plane requests that failed (5xx)")
            self._m_cache_hits = reg.counter(
                "apm_queryplane_cache_hits_total",
                "Queries absorbed by the TTL cache (incl. coalesced waits)")
            self._m_fanout = reg.counter(
                "apm_queryplane_fanout_shards_total",
                "Shard sub-requests issued by the query plane")
            self._m_stale = reg.counter(
                "apm_queryplane_stale_serves_total",
                "Shard slices served from the durable store fallback")
            self._m_moves = reg.counter(
                "apm_queryplane_move_retries_total",
                "Query retries forced by an owner-map change mid-fanout")
            self._m_latency = reg.histogram(
                "apm_queryplane_latency_seconds",
                "Fleet query plane request latency")
        else:
            self._m_errors = self._m_cache_hits = self._m_fanout = None
            self._m_stale = self._m_moves = self._m_latency = None

    # -- owner feed -----------------------------------------------------------
    def _read_owners(self) -> Tuple[int, Dict[int, str]]:
        if self.owners is None:
            return 0, {}
        try:
            seq, owners = self.owners()
            return int(seq), dict(owners)
        except Exception:
            return 0, {}

    def _route_single(self, service: Optional[str], partition,
                      owners: Dict[int, str],
                      known: set) -> Tuple[Optional[str], Optional[int]]:
        """-> (owner name or None for scatter, partition or None)."""
        if partition is None and service is None:
            return None, None
        if self.partitions <= 0:
            return None, None
        if partition is not None:
            try:
                p = int(partition)
            except (TypeError, ValueError):
                raise _BadRequest("bad partition parameter")
        else:
            from ..parallel.fleet import service_partition

            p = service_partition(str(service), self.partitions)
        owner = owners.get(p)
        if owner in known:
            return owner, p
        return None, p  # owner unknown/dead-named: scatter rather than guess

    # -- shard I/O ------------------------------------------------------------
    def _fetch_json(self, url: str) -> dict:
        with urllib.request.urlopen(url, timeout=self.timeout_s) as resp:
            return json.loads(resp.read().decode("utf-8", "replace"))

    def _note_shard(self, name: str, status: str,
                    freshness_s: Optional[float]) -> None:
        with self._lock:
            self._last_shards[name] = {"status": status,
                                       "freshness_s": freshness_s}

    def _staleness(self, name: str, now: float) -> Optional[float]:
        if self.freshness is not None:
            try:
                last = self.freshness().get(name)
            except Exception:
                last = None
            if last:
                return round(max(0.0, now - float(last)), 3)
        return None

    def _fan(self, targets: List[Tuple[str, str]], live_fn,
             store_fn) -> Tuple[List[Tuple[str, object]], Dict[str, dict]]:
        """Fan ``live_fn(name, url)`` over targets under bounded
        concurrency; a failed shard degrades to ``store_fn(name)`` (the
        durable slice) instead of failing the query. Returns the ordered
        per-shard docs (None for dead) and the shard status map."""
        now = time.time()
        shard_status: Dict[str, dict] = {}
        results = _pmap(lambda t: live_fn(t[0], t[1]), targets, self.fanout)
        if self._m_fanout is not None:
            self._m_fanout.inc(len(targets))
        docs: List[Tuple[str, object]] = []
        for (name, _url), (status, res) in zip(targets, results):
            if status == "ok":
                shard_status[name] = {"status": "live", "freshness_s": 0.0}
                self._note_shard(name, "live", 0.0)
                docs.append((name, res))
                continue
            if self.store is not None:
                try:
                    doc = store_fn(name)
                except Exception:
                    doc = None
            else:
                doc = None
            if doc is not None:
                fresh = self._staleness(name, now)
                shard_status[name] = {"status": "stale", "freshness_s": fresh}
                self._note_shard(name, "stale", fresh)
                if self._m_stale is not None:
                    self._m_stale.inc()
                docs.append((name, doc))
            else:
                shard_status[name] = {"status": "dead", "freshness_s": None}
                self._note_shard(name, "dead", None)
                docs.append((name, None))
        return docs, shard_status

    @staticmethod
    def _strip_module(doc: dict) -> dict:
        """Drop the recorder's ``module`` label from a store-fallback
        eval so the degraded slice merges shape-identically with live
        shard output (bit-equality with the healthy-path answer)."""
        for s in doc.get("series", []):
            s.get("labels", {}).pop("module", None)
        return doc

    # -- /query ---------------------------------------------------------------
    def _serve_series(self, q: dict, now: float) -> dict:
        expr = q.get("series", "")
        m = _EXPR_RE.match(expr or "")
        if not m:
            raise _BadRequest(f"unsupported query expression: {expr!r}")
        try:
            start = float(q["start"]) if "start" in q else now - 300.0
            end = float(q["end"]) if "end" in q else now
            step = max(0.001, float(q.get("step", 10.0)))
        except ValueError:
            raise _BadRequest("bad start/end/step")
        fn = m.group("fn")
        qv = float(m.group("q")) if m.group("q") is not None else None
        if fn == "histogram_quantile" and qv is None:
            raise _BadRequest("histogram_quantile needs a quantile argument")
        name = m.group("name")
        from .store import parse_selector

        sel = parse_selector(m.group("sel"))
        window = float(m.group("win")) if m.group("win") else 4.0 * step
        service = q.get("service") or sel.get(self.partition_key)
        partition = q.get("partition")

        if fn == "histogram_quantile":
            base = name[:-len("_bucket")] if name.endswith("_bucket") else name
            shard_expr = _expr_str("increase", None, base + "_bucket",
                                   sel, window)
        else:
            shard_expr = _expr_str(fn, None, name, sel, window)

        def live(shard_name, url):
            qs = urllib.parse.urlencode({
                "series": shard_expr, "start": f"{start:.6f}",
                "end": f"{end:.6f}", "step": f"{step:g}"})
            return self._fetch_json(f"{url}/query?{qs}")

        def fallback(shard_name):
            sel2 = dict(sel, module=shard_name)
            return self._strip_module(eval_range(
                self.store, _expr_str("increase" if fn == "histogram_quantile"
                                      else fn, None,
                                      base + "_bucket"
                                      if fn == "histogram_quantile" else name,
                                      sel2, window),
                start, end, step))

        retries = 0
        while True:
            seq0, owners = self._read_owners()
            targets = list(self.targets() or [])
            known = {n for n, _ in targets}
            owner, _p = self._route_single(service, partition, owners, known)
            fan_targets = ([(n, u) for n, u in targets if n == owner]
                           if owner is not None else targets)
            docs, shard_status = self._fan(fan_targets, live, fallback)
            seq1, owners2 = self._read_owners()
            if seq1 == seq0 or retries >= self.move_retries:
                break
            # ownership moved mid-fanout: the slice we just merged may
            # double-count or miss the moving partition — requery against
            # the settled map (bounded; seq stability is the exit)
            retries += 1
            if self._m_moves is not None:
                self._m_moves.inc()

        useful = [d for _n, d in docs if d is not None]
        if fn == "histogram_quantile":
            series = _merge_histogram(useful, qv)
        else:
            series = _merge_series(useful)
        doc = {
            "expr": expr, "start": start, "end": end, "step": step,
            "series": series,
            "shards": shard_status,
            "shards_queried": [n for n, _ in fan_targets],
            "partial": any(v["status"] != "live"
                           for v in shard_status.values()),
            "stale": any(v["status"] == "stale"
                         for v in shard_status.values()),
            "owner_seq": seq1,
            "move_retries": retries,
        }
        return doc

    def _serve_kind(self, q: dict, now: float) -> dict:
        kind = q.get("kind")
        try:
            start = float(q["start"]) if "start" in q else now - 300.0
            end = float(q["end"]) if "end" in q else now
            limit = int(q.get("limit", 256))
            n = max(1, min(int(q.get("n", 256)), 4096))
        except ValueError:
            raise _BadRequest("bad start/end/limit/n")
        if kind in ("spans", "decisions"):
            path, field, keys = (
                ("/trace", "spans", _SPAN_KEY) if kind == "spans"
                else ("/decisions", "decisions", _DECISION_KEY))
            trace_id = q.get("trace_id")

            def live(shard_name, url):
                qs = urllib.parse.urlencode(
                    {"n": n, **({"trace_id": trace_id} if trace_id else {})})
                doc = self._fetch_json(f"{url}{path}?{qs}")
                return [r for r in doc.get(field, []) if isinstance(r, dict)]

            def fallback(shard_name):
                match = {"module": shard_name}
                if trace_id:
                    match["trace_id"] = trace_id
                rows = (self.store.spans if kind == "spans"
                        else self.store.decisions)(start, end, match, limit)
                return rows

            docs, shard_status = self._fan(list(self.targets() or []),
                                           live, fallback)
            rows = _dedup_rows(
                [r for _n, doc in docs if doc for r in doc], keys)
            if limit and len(rows) > limit:
                rows = rows[-limit:]
            return {
                "kind": kind, "start": start, "end": end, "rows": rows,
                "shards": shard_status,
                "partial": any(v["status"] != "live"
                               for v in shard_status.values()),
                "stale": any(v["status"] == "stale"
                             for v in shard_status.values()),
            }
        if kind == "names":
            def live(shard_name, url):
                doc = self._fetch_json(f"{url}/query?kind=names")
                return doc.get("names", [])

            docs, shard_status = self._fan(list(self.targets() or []),
                                           live, lambda _n: None)
            names = set()
            for _n, doc in docs:
                names.update(doc or [])
            if self.store is not None:
                names.update(self.store.series_names())
            return {"kind": "names", "names": sorted(names),
                    "shards": shard_status}
        if kind == "stats":
            body = {"kind": "stats", "plane": self.stats()}
            if self.store is not None:
                body["store"] = self.store.stats()
            return body
        raise _BadRequest(
            "need ?series=<expr> or ?kind=spans|decisions|names|stats")

    # -- /trace /decisions ----------------------------------------------------
    def _serve_ring(self, q: dict, kind: str, now: float) -> dict:
        path, field, keys = (
            ("/trace", "spans", _SPAN_KEY) if kind == "spans"
            else ("/decisions", "decisions", _DECISION_KEY))
        trace_id = q.get("trace_id")
        try:
            n = max(1, min(int(q.get("n", 256)), 4096))
        except ValueError:
            raise _BadRequest("bad n parameter")

        def live(shard_name, url):
            qs = urllib.parse.urlencode(
                {"n": n, **({"trace_id": trace_id} if trace_id else {})})
            doc = self._fetch_json(f"{url}{path}?{qs}")
            return [r for r in doc.get(field, []) if isinstance(r, dict)]

        def fallback(shard_name):
            match = {"module": shard_name}
            if trace_id:
                match["trace_id"] = trace_id
            return (self.store.spans if kind == "spans"
                    else self.store.decisions)(0.0, now + 1.0, match, n)

        docs, shard_status = self._fan(list(self.targets() or []),
                                       live, fallback)
        rows = _dedup_rows([r for _n, doc in docs if doc for r in doc], keys)
        return {
            "fleet": True, "count": len(rows), field: rows,
            "shards": shard_status,
            "partial": any(v["status"] != "live"
                           for v in shard_status.values()),
            "stale": any(v["status"] == "stale"
                         for v in shard_status.values()),
        }

    # -- /attrib --------------------------------------------------------------
    def _attrib_from_store(self, shard_name: str, now: float) -> Optional[dict]:
        """Synthesize a mergeable /attrib snapshot for a dead shard from
        its last recorded ``apm_stage_*`` counters — coarse (no
        occupancy, window unknown) but it keeps the dead shard's stage
        seconds in the fleet bottleneck estimate instead of vanishing."""
        stages: Dict[str, dict] = {}
        found = False
        for metric, field in (
            ("apm_stage_busy_seconds_total", "busy_s"),
            ("apm_stage_blocked_seconds_total", "blocked_s"),
            ("apm_stage_idle_seconds_total", "idle_s"),
            ("apm_stage_events_total", "events"),
        ):
            groups = self.store.series_points(
                metric, 0.0, now + 1.0, {"module": shard_name})
            for key, pts in groups.items():
                if not pts:
                    continue
                stage = dict(key).get("stage", "?")
                st = stages.setdefault(
                    stage, {"busy_s": 0.0, "blocked_s": 0.0, "idle_s": 0.0,
                            "events": 0})
                val = pts[-1][1]
                st[field] = int(val) if field == "events" else float(val)
                found = True
        if not found:
            return None
        window = max((st["busy_s"] + st["blocked_s"] + st["idle_s"]
                      for st in stages.values()), default=0.0)
        return {"module": shard_name, "window_s": round(window, 3),
                "stages": stages, "occupancy": {}}

    def _serve_attrib(self, q: dict, now: float) -> dict:
        from .attrib import merge_snapshots

        def live(shard_name, url):
            return self._fetch_json(f"{url}/attrib")

        def fallback(shard_name):
            return self._attrib_from_store(shard_name, now)

        docs, shard_status = self._fan(list(self.targets() or []),
                                       live, fallback)
        body = merge_snapshots([d for _n, d in docs if d])
        body["shards"] = shard_status
        body["partial"] = any(v["status"] != "live"
                              for v in shard_status.values())
        body["stale"] = any(v["status"] == "stale"
                            for v in shard_status.values())
        return body

    # -- route plumbing -------------------------------------------------------
    def _cache_key(self, route: str, q: dict, now: float) -> tuple:
        ttl = self._cache.ttl_s
        items = {k: v for k, v in q.items() if k != "cache"}
        if route == "query" and q.get("series") and ttl > 0:
            # default now-anchored ranges quantize to the TTL grid so the
            # dashboard's repeated "last 5 minutes" shares one entry —
            # exactly the staleness a TTL cache already promises
            if "end" not in items:
                items["end"] = f"{math.floor(now / ttl) * ttl:.3f}"
            if "start" not in items:
                items["start"] = f"{float(items['end']) - 300.0:.3f}"
        return (route,) + tuple(sorted(items.items()))

    def _wrap(self, route: str, serve):
        def handler(query):
            q = {k: (v[0] if isinstance(v, list) else v)
                 for k, v in query.items()}
            t0 = time.monotonic()
            with self._lock:
                self._counts["requests"] += 1
            if self._m_requests.get(route) is not None:
                self._m_requests[route].inc()
            now = time.time()
            try:
                if q.get("cache") == "0" or self._cache.ttl_s <= 0:
                    body, hit = serve(q, now), False
                else:
                    body, hit = self._cache.get_or_compute(
                        self._cache_key(route, q, now), lambda: serve(q, now))
                if hit:
                    with self._lock:
                        self._counts["cache_hits"] += 1
                    if self._m_cache_hits is not None:
                        self._m_cache_hits.inc()
                    body = dict(body)
                body["cached"] = hit
                if route == "query" and q.get("format") == "matrix" \
                        and "series" in body:
                    body = matrix_doc(body)
                return 200, "application/json", json.dumps(body, default=repr)
            except _BadRequest as e:
                return 400, "text/plain; charset=utf-8", f"{e}\n"
            except Exception as e:
                with self._lock:
                    self._counts["errors"] += 1
                if self._m_errors is not None:
                    self._m_errors.inc()
                if self._logger:
                    self._logger.warning("queryplane: /%s failed: %s",
                                         route, e)
                return 500, "text/plain; charset=utf-8", \
                    f"query plane error: {type(e).__name__}\n"
            finally:
                if self._m_latency is not None:
                    self._m_latency.observe(time.monotonic() - t0)

        return handler

    def make_routes(self) -> Dict[str, Callable]:
        """Route table for :meth:`TelemetryServer.add_route` — mounting
        these on the manager OVERRIDES its per-process /query /trace
        /decisions /attrib with the fleet-wide versions."""
        def serve_query(q, now):
            if q.get("kind"):
                return self._serve_kind(q, now)
            if q.get("series"):
                return self._serve_series(q, now)
            raise _BadRequest(
                "need ?series=<expr> or ?kind=spans|decisions|names|stats")

        return {
            "/query": self._wrap("query", serve_query),
            "/trace": self._wrap(
                "trace", lambda q, now: self._serve_ring(q, "spans", now)),
            "/decisions": self._wrap(
                "decisions",
                lambda q, now: self._serve_ring(q, "decisions", now)),
            "/attrib": self._wrap("attrib", self._serve_attrib),
        }

    # -- introspection --------------------------------------------------------
    def stats(self) -> dict:
        seq, owners = self._read_owners()
        with self._lock:
            return {
                "requests": self._counts["requests"],
                "errors": self._counts["errors"],
                "cache_hits": self._counts["cache_hits"],
                "cache_entries": len(self._cache),
                "cache_ttl_s": self._cache.ttl_s,
                "owner_seq": seq,
                "owned_partitions": len(owners),
                "partitions": self.partitions,
                "shards": dict(self._last_shards),
            }

    def health(self) -> dict:
        """Healthz section: degraded shards flag the plane as degraded
        (still ``ok`` — partial serving is the design, not a failure)."""
        st = self.stats()
        st["ok"] = True
        st["degraded"] = any(v.get("status") != "live"
                             for v in st["shards"].values())
        return st


# ---------------------------------------------------------------------------
# Standalone entry point: python -m apmbackend_tpu.obs.queryplane
# ---------------------------------------------------------------------------


def main(argv=None) -> int:
    """Run the plane standalone against explicit targets — the
    off-manager deployment (a dashboard tier that must survive manager
    restarts). Owner feed: poll the manager's ``/fleet`` text for
    ``apm_fleet_partition_owner`` rows; shard ids map to targets by the
    ``shard<k>`` naming convention (unknown names just scatter)."""
    import argparse

    from .exporter import TelemetryServer

    ap = argparse.ArgumentParser(prog="apmbackend_tpu.obs.queryplane")
    ap.add_argument("--target", action="append", default=[],
                    metavar="NAME=URL", help="shard endpoint (repeatable)")
    ap.add_argument("--store", default=None,
                    help="recorder store directory (durable fallback)")
    ap.add_argument("--port", type=int, default=9464)
    ap.add_argument("--partitions", type=int, default=0)
    ap.add_argument("--partition-key", default="service")
    ap.add_argument("--fleet-url", default=None,
                    help="manager /fleet URL for the live owner feed")
    ap.add_argument("--config", default=None,
                    help="config JSON; reads its queryPlane section")
    args = ap.parse_args(argv)

    qp_cfg = {}
    if args.config:
        with open(args.config, "r", encoding="utf-8") as fh:
            qp_cfg = (json.load(fh) or {}).get("queryPlane", {}) or {}

    static = []
    for spec in args.target:
        name, _, url = spec.partition("=")
        if not url:
            ap.error(f"--target needs NAME=URL, got {spec!r}")
        static.append((name, url.rstrip("/")))

    owners_fn = None
    if args.fleet_url:
        from ..parallel.fleet import OwnerMap, owner_map_from_fleet_text

        omap = OwnerMap()
        refresh_s = float(qp_cfg.get("ownerRefreshSeconds", 5.0))
        state = {"ts": 0.0}

        def owners_fn():
            now = time.monotonic()
            if now - state["ts"] >= refresh_s:
                state["ts"] = now
                try:
                    with urllib.request.urlopen(args.fleet_url,
                                                timeout=2.0) as resp:
                        text = resp.read().decode("utf-8", "replace")
                    omap.update({p: f"shard{s}" for p, s in
                                 owner_map_from_fleet_text(text).items()})
                except Exception:
                    pass  # keep serving on the last good map
            return omap.read()

    store = None
    if args.store:
        store = TimeSeriesStore(args.store)

    reg = MetricsRegistry()
    plane = QueryPlane(
        lambda: static,
        owners=owners_fn,
        store=store,
        partitions=args.partitions,
        partition_key=args.partition_key,
        registry=reg,
        cache_ttl_s=float(qp_cfg.get("cacheTtlSeconds", 2.0)),
        fanout=int(qp_cfg.get("fanoutConcurrency", 8)),
        timeout_s=float(qp_cfg.get("timeoutSeconds", 2.0)),
        move_retries=int(qp_cfg.get("moveRetries", 2)),
    )
    server = TelemetryServer(registry=reg, module="queryplane",
                             port=args.port)
    for path, fn in plane.make_routes().items():
        server.add_route(path, fn)
    server.add_health("queryplane", plane.health)
    port = server.start()
    print(f"query plane serving on http://127.0.0.1:{port} "
          f"(/query /trace /decisions /attrib) over {len(static)} targets",
          flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
