"""Incremental delta checkpoints: the crash-consistent chain store.

The at-least-once epoch cycle (runtime/worker.py) used to pay a full-state
npz snapshot per commit — state-size-proportional, which is why the
`bench_rolling details.delivery` gap sat at −35% and why sub-second epochs
at 8192-row shapes were impossible. This module makes the epoch commit
*ingest-rate*-proportional: a chain is

    base snapshot  +  ordered delta segments  (+ MANIFEST pointing at the base)

where each delta carries only what changed since the previous commit (the
driver's dirty-cell / tick capture, pipeline.py `save_resume_delta`), and a
periodic compaction rewrites the base off the hot path. Recovery replays
``base + deltas`` back into the exact full-snapshot ``data`` dict the npz
loader installs, so a chain restore is bit-identical to a full-snapshot
restore of the same state (asserted by tests/test_delta_chain.py and the
kill−9 chaos harness).

Durability model (the journal + alive-sentinel idiom of obs/flight.py,
promoted to state checkpoints):

- a delta segment is written to a ``.tmp`` name, optionally fsynced, then
  ``os.replace``d into ``delta-<epoch>.seg`` — the RENAME is the commit.
  A crash at any byte before the rename leaves only an ignorable tmp file;
  a crash after it leaves a committed epoch (whose messages, not yet acked,
  are redelivered and absorbed by the dedup window inside that segment).
- every segment carries CRC32s over header and payload plus a random
  ``uid`` and its predecessor's ``prev_uid``. Recovery walks the chain from
  the base and stops at the first missing/invalid/foreign segment — a torn
  tail, a bad length, or a *stale duplicate tail* (a leftover same-epoch
  segment from a pre-crash incarnation whose predecessor was itself
  replaced) can never be replayed past a committed boundary.
- compaction writes ``base-<epoch>.npz`` (tmp + fsync + rename), then swaps
  MANIFEST (tmp + rename), then GCs. The PREVIOUS base and its deltas are
  kept until the *next* compaction, so a base write torn by a crash — or a
  base that later turns out unreadable — falls back one compaction
  generation, exactly like the orbax keep=2 retention in
  parallel/checkpoint.py. Appends continue concurrently during compaction:
  segments are standalone files valid under either base.

Hostile-storage fault injection (the chaos tier): ``APM_CHAOS_FS`` installs
a deterministic fault plan into the write path — ENOSPC/EIO after N
segment writes (leaving a torn tmp, like a real full disk), or SIGKILL of
the process at a named compaction point. Production runs never read the
env var beyond one cached check. See :class:`StorageFaultPlan`.
"""

from __future__ import annotations

import io
import json
import os
import re
import struct
import threading
import zlib
from typing import Dict, List, Optional, Tuple

import numpy as np

_MAGIC = b"APMDCSG1"
_FOOTER = b"APMDCEND"
_MANIFEST = "MANIFEST.json"
_SEG_RE = re.compile(r"^delta-(\d{12})\.seg$")
_BASE_RE = re.compile(r"^base-(\d{12})\.npz$")


class CheckpointWriteError(Exception):
    """A checkpoint write failed (ENOSPC/EIO/permission/...). The caller owns
    the retry/backoff/degradation policy (runtime/worker.py); the chain's
    on-disk state is still a committed epoch boundary."""


class InvalidSegment(Exception):
    """Segment failed validation (torn, truncated, CRC, foreign chain)."""


# ---------------------------------------------------------------------------
# Hostile-storage fault injection (testing seam, APM_CHAOS_FS)
# ---------------------------------------------------------------------------


class StorageFaultPlan:
    """Deterministic storage-fault plan parsed from ``APM_CHAOS_FS``.

    Grammar (';'-separated clauses):

    - ``enospc:after=N[,count=M]`` — segment writes N+1..N+M fail with
      ENOSPC *after* writing partial bytes (a torn tmp file, like a real
      full disk). ``eio:`` is the same with EIO.
    - ``kill:compact=pre_base|pre_manifest`` — SIGKILL this process at the
      named compaction point (before the new base is published / base
      published but MANIFEST not yet swapped) — the two nastiest
      crash-during-compaction windows, made deterministic.

    The plan is process-local state seeded once from the env; the chaos
    harness passes the env var to its worker subprocess.
    """

    def __init__(self, spec: str):
        self.seg_writes = 0  # guarded-by: _lock
        self.fail_after: Optional[int] = None
        self.fail_count = 0
        self.fail_errno = 28  # ENOSPC
        self.kill_at: Optional[str] = None
        self._lock = threading.Lock()
        for clause in spec.split(";"):
            clause = clause.strip()
            if not clause:
                continue
            kind, _, rest = clause.partition(":")
            if kind in ("enospc", "eio"):
                opts = dict(p.split("=", 1) for p in rest.split(",") if "=" in p)
                self.fail_after = int(opts.get("after", 0))
                self.fail_count = int(opts.get("count", 1))
                self.fail_errno = 28 if kind == "enospc" else 5
            elif kind == "kill":
                opts = dict(p.split("=", 1) for p in rest.split(",") if "=" in p)
                self.kill_at = opts.get("compact", "pre_manifest")
            else:
                raise ValueError(f"unknown APM_CHAOS_FS clause: {clause!r}")

    def on_segment_write(self, fh, blob: bytes) -> None:
        """Called with the open tmp file BEFORE the real write; may write a
        torn prefix and raise OSError to simulate a full/broken disk."""
        if self.fail_after is None:
            return
        with self._lock:
            self.seg_writes += 1
            n = self.seg_writes
        if self.fail_after < n <= self.fail_after + self.fail_count:
            fh.write(blob[: max(1, len(blob) // 2)])  # torn partial write
            fh.flush()
            raise OSError(self.fail_errno, "injected storage fault (APM_CHAOS_FS)")

    def on_compact_point(self, point: str) -> None:
        if self.kill_at == point:
            import signal

            os.kill(os.getpid(), signal.SIGKILL)


_fault_plan: Optional[StorageFaultPlan] = None
_fault_checked = False


def _faults() -> Optional[StorageFaultPlan]:
    global _fault_plan, _fault_checked
    if not _fault_checked:
        _fault_checked = True
        spec = os.environ.get("APM_CHAOS_FS")
        if spec:
            _fault_plan = StorageFaultPlan(spec)
    return _fault_plan


def install_fault_plan(plan: Optional[StorageFaultPlan]) -> None:
    """Test hook: install (or clear) a fault plan without the env var."""
    global _fault_plan, _fault_checked
    _fault_plan = plan
    _fault_checked = True


# ---------------------------------------------------------------------------
# Segment encode / decode
# ---------------------------------------------------------------------------


def _encode_segment(
    epoch: int, chain_id: str, uid: str, prev_uid: str,
    arrays: Dict[str, np.ndarray], meta: dict,
) -> bytes:
    """One delta segment as bytes: magic | header_len | header_crc | header
    JSON | raw array payload | payload_crc | footer magic. Every array is
    C-contiguous raw bytes located by (offset, nbytes) in the header — no
    pickling, no zip structure whose truncation behavior is zlib's to
    define; torn-read detection is OURS (CRC + bounds + footer)."""
    entries = []
    payload = io.BytesIO()
    for name in sorted(arrays):
        # np.asarray, NOT ascontiguousarray: the latter promotes 0-d scalars
        # (latest_bucket, ring cursors) to shape (1,); tobytes() below copies
        # in C order regardless of contiguity
        arr = np.asarray(arrays[name])
        if arr.dtype == object:
            raise TypeError(f"object arrays not allowed in delta segments: {name}")
        off = payload.tell()
        blob = arr.tobytes()
        payload.write(blob)
        entries.append(
            {"k": name, "dt": arr.dtype.str, "sh": list(arr.shape),
             "off": off, "n": len(blob)}
        )
    payload_b = payload.getvalue()
    header = {
        "epoch": int(epoch),
        "chain": chain_id,
        "uid": uid,
        "prev_uid": prev_uid,
        "arrays": entries,
        "meta": meta,
    }
    header_b = json.dumps(header, separators=(",", ":")).encode("utf-8")
    out = io.BytesIO()
    out.write(_MAGIC)
    out.write(struct.pack("<II", len(header_b), zlib.crc32(header_b) & 0xFFFFFFFF))
    out.write(header_b)
    out.write(payload_b)
    out.write(struct.pack("<I", zlib.crc32(payload_b) & 0xFFFFFFFF))
    out.write(_FOOTER)
    return out.getvalue()


def _decode_segment(blob: bytes) -> Tuple[dict, Dict[str, np.ndarray]]:
    """Parse + validate one segment; raises :class:`InvalidSegment` on any
    torn/truncated/corrupt/foreign shape (the fixture matrix in
    tests/test_delta_chain.py drives each branch)."""
    fixed = len(_MAGIC) + 8
    if len(blob) < fixed + len(_FOOTER) + 4:
        raise InvalidSegment("truncated: shorter than fixed framing")
    if blob[: len(_MAGIC)] != _MAGIC:
        raise InvalidSegment("bad magic")
    header_len, header_crc = struct.unpack_from("<II", blob, len(_MAGIC))
    if header_len <= 0 or fixed + header_len + 4 + len(_FOOTER) > len(blob):
        raise InvalidSegment("bad header length")
    header_b = blob[fixed : fixed + header_len]
    if zlib.crc32(header_b) & 0xFFFFFFFF != header_crc:
        raise InvalidSegment("header CRC mismatch")
    try:
        header = json.loads(header_b.decode("utf-8"))
    except Exception as e:
        raise InvalidSegment(f"header JSON: {e!r}")
    if blob[-len(_FOOTER):] != _FOOTER:
        raise InvalidSegment("missing footer (torn tail)")
    payload = blob[fixed + header_len : -(len(_FOOTER) + 4)]
    (payload_crc,) = struct.unpack_from("<I", blob, len(blob) - len(_FOOTER) - 4)
    if zlib.crc32(payload) & 0xFFFFFFFF != payload_crc:
        raise InvalidSegment("payload CRC mismatch")
    arrays: Dict[str, np.ndarray] = {}
    for ent in header.get("arrays", ()):
        off, n = int(ent["off"]), int(ent["n"])
        if off < 0 or off + n > len(payload):
            raise InvalidSegment(f"array {ent.get('k')!r} out of payload bounds")
        arr = np.frombuffer(payload[off : off + n], dtype=np.dtype(ent["dt"]))
        shape = tuple(int(s) for s in ent["sh"])
        if int(np.prod(shape, dtype=np.int64)) != arr.size:
            raise InvalidSegment(f"array {ent.get('k')!r} shape/size mismatch")
        arrays[ent["k"]] = arr.reshape(shape).copy()  # own the memory
    return header, arrays


# ---------------------------------------------------------------------------
# Replay: apply one delta onto the full-snapshot `data` dict
# ---------------------------------------------------------------------------


def _grow_data(data: dict, new_capacity: int) -> None:
    """Grow every per-row array to ``new_capacity`` rows with the EXACT pad
    semantics of the live engine's growth-by-recompile (dstats/dzscore/dewma
    grow_state): counts/sums/nsamples/fill/counters/var/count/trend pad 0,
    samples/z values/ewma mean pad NaN. Bit-identical to a run that grew."""
    for key, arr in list(data.items()):
        if key in ("latest_bucket", "registry", "pending_tx", "delivery_state"):
            continue
        if arr.ndim == 0 or arr.shape[0] >= new_capacity:
            continue
        pad = new_capacity - arr.shape[0]
        widths = [(0, pad)] + [(0, 0)] * (arr.ndim - 1)
        if key.endswith("_samples") or key == "samples" or key.endswith("_values") or key.endswith("_mean"):
            data[key] = np.pad(arr, widths, constant_values=np.nan)
        else:
            data[key] = np.pad(arr, widths)


def _advance_stats(data: dict, nb: int, tick_labels: List[int]) -> None:
    """Replay the stats-ring advance for each tick label: clear the (at most
    NB) slots the labels (latest, new] claim — the numpy mirror of
    dstats.advance_span/advance_one, including the stale-label clamp."""
    latest = int(np.asarray(data["latest_bucket"]))
    counts, sums = data["counts"], data["sums"]
    samples, nsamples = data["samples"], data["nsamples"]
    for nl in tick_labels:
        nl = max(int(nl), latest)
        k = min(nl - latest, nb)
        for j in range(k):
            slot = (nl - j) % nb
            counts[:, slot] = 0
            sums[:, slot] = 0
            nsamples[:, slot] = 0
            samples[:, slot, :] = np.nan
        latest = nl
    data["latest_bucket"] = np.asarray(np.int32(latest))


def apply_delta(data: dict, header: dict, arrays: Dict[str, np.ndarray]) -> None:
    """Mutate the full-snapshot ``data`` dict (save_resume key schema) to
    the state this delta's commit captured. Replay order matters only up to
    the clear-then-write rule: every stored value is the POST-epoch content
    of its cell/column, so tick clears replay first and captured writes land
    on top — any feed/clear interleave inside the epoch collapses to the
    same final bits (tests/test_delta_chain.py equivalence suite)."""
    meta = header["meta"]
    cap = int(meta["capacity"])
    _grow_data(data, cap)

    ticks = [int(t) for t in meta.get("ticks", ())]
    if ticks:
        _advance_stats(data, int(meta["nb"]), ticks)

    if "cell_rows" in arrays:
        rows = arrays["cell_rows"].astype(np.int64)
        slots = arrays["cell_slots"].astype(np.int64)
        data["counts"][rows, slots] = arrays["cell_counts"]
        data["sums"][rows, slots] = arrays["cell_sums"]
        data["nsamples"][rows, slots] = arrays["cell_nsamples"]
        data["samples"][rows, slots, :] = arrays["cell_samples"]

    for zk in meta.get("zchannels", ()):
        # zk: {"key": "z360", "lag": L, "pos0": p} — arrays hold either the
        # gathered pushed columns (T < L) or the full ring (T >= L rewrote
        # it). The push array may be tier-padded wider than the tick count
        # (bounded-compile capture shapes); only the first len(ticks)
        # columns are real.
        key, L = zk["key"], int(zk["lag"])
        if f"{key}_push" in arrays:
            T = min(len(ticks), arrays[f"{key}_push"].shape[-1])
            positions = [(int(zk["pos0"]) + t) % L for t in range(T)]
            data[f"{key}_values"][:, :, positions] = arrays[f"{key}_push"][:, :, :T]
        elif f"{key}_values" in arrays:
            data[f"{key}_values"] = arrays[f"{key}_values"]
        data[f"{key}_fill"] = arrays[f"{key}_fill"]
        data[f"{key}_pos"] = arrays[f"{key}_pos"]
        data[f"{key}_counters"] = arrays[f"{key}_counters"]

    for ck in meta.get("echannels", ()):
        # ck: {"key": "e-1x24x360", "slots": [...]} — slot columns touched
        # by this epoch's ticks (or full arrays when every slot was)
        key = ck["key"]
        slots = [int(s) for s in ck.get("slots", ())]
        if f"{key}_mean_cols" in arrays:
            m = len(slots)  # column arrays may be tier-padded wider
            data[f"{key}_mean"][:, :, slots] = arrays[f"{key}_mean_cols"][:, :, :m]
            data[f"{key}_var"][:, :, slots] = arrays[f"{key}_var_cols"][:, :, :m]
            data[f"{key}_trend"][:, :, slots] = arrays[f"{key}_trend_cols"][:, :, :m]
            data[f"{key}_count"][:, slots] = arrays[f"{key}_count_cols"][:, :m]
        else:
            for f in ("mean", "var", "trend", "count"):
                if f"{key}_{f}" in arrays:
                    data[f"{key}_{f}"] = arrays[f"{key}_{f}"]
        data[f"{key}_counters"] = arrays[f"{key}_counters"]

    new_keys = meta.get("registry_new", ())
    if new_keys:
        reg = data["registry"].tolist() if "registry" in data else []
        reg.extend(new_keys)
        data["registry"] = np.array(reg, dtype=object)

    if meta.get("pending") is not None:
        data["pending_tx"] = np.array(meta["pending"], dtype=object)

    dd = meta.get("delivery_delta")
    if dd is not None:
        # incremental dedup-window replay: the window is an append-right /
        # evict-left FIFO, so final = (old + added)[evicted:], and epoch /
        # deduped_total replace wholesale — rate-proportional persistence of
        # the same commit unit the full snapshot carries in delivery_state
        try:
            old = (
                json.loads(data["delivery_state"].item())
                if "delivery_state" in data else {}
            )
        except Exception:
            old = {}
        for qname, rec in dd.items():
            prev = old.get(qname, {})
            window = list(prev.get("dedup", []))
            window.extend(rec.get("added", []))
            evicted = int(rec.get("evicted", 0))
            if evicted:
                window = window[evicted:]
            old[qname] = {
                "epoch": rec.get("epoch", prev.get("epoch", 0)),
                "dedup": window,
                "deduped_total": rec.get(
                    "deduped_total", prev.get("deduped_total", 0)
                ),
            }
        data["delivery_state"] = np.array(json.dumps(old), dtype=object)


# ---------------------------------------------------------------------------
# The chain store
# ---------------------------------------------------------------------------


class RecoveredChain:
    """Result of :func:`DeltaChain.load`: the replayed full-snapshot data
    dict plus the chain position the writer continues from."""

    def __init__(self, data: Optional[dict], epoch: int, chain_id: str,
                 tail_uid: str, base_epoch: int, dropped: List[str]):
        self.data = data
        self.epoch = epoch  # last committed epoch the chain recovers to
        self.chain_id = chain_id
        self.tail_uid = tail_uid
        self.base_epoch = base_epoch
        self.dropped = dropped  # invalid/foreign tail files (diagnostics)


class DeltaChain:
    """Writer + reader for one checkpoint chain directory.

    Thread model: ``append``/``compact``/``gc`` share ``_lock``; compaction
    usually runs on the caller's background thread (``compact_async``) while
    the epoch timer keeps appending — the on-disk protocol is safe for that
    (segments are standalone; MANIFEST swap is atomic), the lock only
    serializes the in-process bookkeeping.
    """

    def __init__(self, directory: str, *, fsync: bool = True, logger=None):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.fsync = bool(fsync)
        self.logger = logger
        self._lock = threading.Lock()
        self._chain_id: Optional[str] = None  # guarded-by: _lock
        self._tail_epoch = 0  # guarded-by: _lock
        self._tail_uid = ""  # guarded-by: _lock
        self._base_epoch = 0  # guarded-by: _lock
        self._compact_thread: Optional[threading.Thread] = None  # guarded-by: _lock
        self.last_delta_bytes = 0  # guarded-by: _lock (telemetry)
        self.compactions = 0  # guarded-by: _lock (telemetry)

    # -- paths ---------------------------------------------------------------
    def _seg_path(self, epoch: int) -> str:
        return os.path.join(self.directory, f"delta-{epoch:012d}.seg")

    def _base_path(self, epoch: int) -> str:
        return os.path.join(self.directory, f"base-{epoch:012d}.npz")

    @property
    def manifest_path(self) -> str:
        return os.path.join(self.directory, _MANIFEST)

    @property
    def tail_epoch(self) -> int:
        with self._lock:
            return self._tail_epoch

    @property
    def initialized(self) -> bool:
        with self._lock:
            return self._chain_id is not None

    def manifest_record(self) -> dict:
        """The chain-position facts a foreign checkpoint (e.g. the sharded
        orbax meta, parallel/checkpoint.py) records so a restore can
        continue THIS chain: id, base, tail epoch and the tail uid the next
        delta must link from."""
        with self._lock:
            return {
                "chain": self._chain_id,
                "dir": self.directory,
                "base_epoch": self._base_epoch,
                "tail_epoch": self._tail_epoch,
                "tail_uid": self._tail_uid,
            }

    # -- io helpers ----------------------------------------------------------
    def _fsync_dir(self) -> None:
        if not self.fsync:
            return
        try:
            fd = os.open(self.directory, os.O_RDONLY)
            try:
                os.fsync(fd)
            finally:
                os.close(fd)
        except OSError:
            pass  # platform without dir fsync: rename atomicity still holds

    def _write_atomic(self, path: str, blob: bytes, *, seg_faults: bool = False) -> None:
        tmp = f"{path}.{os.getpid()}.tmp"
        try:
            with open(tmp, "wb") as fh:
                plan = _faults()
                if plan is not None and seg_faults:
                    plan.on_segment_write(fh, blob)
                fh.write(blob)
                fh.flush()
                if self.fsync:
                    os.fsync(fh.fileno())
            os.replace(tmp, path)
        except OSError:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        self._fsync_dir()

    # -- chain lifecycle -----------------------------------------------------
    def initialize(self, base_arrays: Dict[str, np.ndarray], epoch: int = 0) -> None:
        """Create a brand-new chain: base at ``epoch``, fresh chain id, swap
        MANIFEST. Raises CheckpointWriteError on storage failure."""
        chain_id = os.urandom(8).hex()
        uid = os.urandom(8).hex()
        try:
            self._write_base(epoch, chain_id, uid, base_arrays)
            self._write_manifest(chain_id, epoch, uid)
        except OSError as e:
            raise CheckpointWriteError(f"chain initialize failed: {e}") from e
        with self._lock:
            self._chain_id = chain_id
            self._base_epoch = epoch
            self._tail_epoch = epoch
            self._tail_uid = uid

    def _write_base(self, epoch: int, chain_id: str, uid: str,
                    arrays: Dict[str, np.ndarray]) -> None:
        buf = io.BytesIO()
        chain_meta = np.array(
            json.dumps({"chain": chain_id, "epoch": epoch, "uid": uid}),
            dtype=object,
        )
        np.savez_compressed(buf, chain_meta=chain_meta, **arrays)
        self._write_atomic(self._base_path(epoch), buf.getvalue())

    def _write_manifest(self, chain_id: str, base_epoch: int, base_uid: str) -> None:
        blob = json.dumps(
            {"format": 1, "chain": chain_id, "base_epoch": base_epoch,
             "base_uid": base_uid}
        ).encode("utf-8")
        self._write_atomic(self.manifest_path, blob)

    def load(self) -> Optional[RecoveredChain]:
        """Recover the newest committed epoch boundary: MANIFEST's base (or,
        when that base is unreadable/absent, the newest older base on disk —
        the keep-one-generation fallback), then replay the contiguous valid
        delta chain from it. Returns None when no readable chain exists —
        the caller starts fresh, never crashes (load_resume contract). The
        writer continues from the recovered tail.

        Candidate selection (hardened after protocol model checking,
        DESIGN.md §9.4): every readable base is evaluated and the chain
        recovering the HIGHEST epoch wins (ties go to the manifest base).
        A non-manifest fallback base is additionally cross-checked against
        the delta segment at its own epoch — a compaction that crashed
        before the manifest swap leaves an orphan base, and if the chain
        was later rewritten below it the orphan's content matches no
        committed state; a valid same-epoch delta with a different uid
        (or an unreadable one) exposes it. Within the single-fault storage
        contract the model proves all candidates converge, so this changes
        nothing there — it is defense in depth for multi-fault excursions,
        which degrade to the best surviving boundary instead of the first
        readable one."""
        bases = self._scan_bases()
        manifest = self._read_manifest()
        order: List[int] = []
        if manifest is not None and manifest["base_epoch"] in bases:
            order.append(manifest["base_epoch"])
        order.extend(e for e in sorted(bases, reverse=True) if e not in order)
        best: Optional[RecoveredChain] = None
        for base_epoch in order:
            authoritative = manifest is not None and base_epoch == manifest["base_epoch"]
            rec = self._try_chain(base_epoch, authoritative=authoritative)
            if rec is None:
                continue
            if best is None or rec.epoch > best.epoch:
                best = rec
        if best is None:
            return None
        with self._lock:
            self._chain_id = best.chain_id
            self._base_epoch = best.base_epoch
            self._tail_epoch = best.epoch
            self._tail_uid = best.tail_uid
        if best.dropped and self.logger:
            self.logger.warning(
                f"Checkpoint chain recovered to epoch {best.epoch}; dropped "
                f"uncommitted/invalid tail: {', '.join(best.dropped)}"
            )
        return best

    def _scan_bases(self) -> Dict[int, str]:
        out: Dict[int, str] = {}
        try:
            names = os.listdir(self.directory)
        except OSError:
            return out
        for n in names:
            m = _BASE_RE.match(n)
            if m:
                out[int(m.group(1))] = os.path.join(self.directory, n)
        return out

    def _read_manifest(self) -> Optional[dict]:
        try:
            with open(self.manifest_path, "r", encoding="utf-8") as fh:
                m = json.load(fh)
            return {"chain": m["chain"], "base_epoch": int(m["base_epoch"]),
                    "base_uid": m.get("base_uid", "")}
        except Exception:
            return None

    def _try_chain(self, base_epoch: int,
                   authoritative: bool = True) -> Optional[RecoveredChain]:
        path = self._base_path(base_epoch)
        try:
            with np.load(path, allow_pickle=True) as npz:
                data = {name: npz[name] for name in npz.files}
            cm = json.loads(data.pop("chain_meta").item())
            chain_id, uid = cm["chain"], cm.get("uid", "")
        except Exception as e:
            if self.logger:
                self.logger.error(f"Checkpoint base unreadable (falling back): {path}: {e}")
            return None
        if not authoritative and base_epoch > 0:
            # stale-orphan cross-check: a fallback base must agree with the
            # delta segment that committed its epoch. An orphan base from a
            # dead compaction, stranded above a rewritten chain, carries a
            # uid no longer on the chain — reject it rather than recover a
            # state no commit ever produced. (Absent segment = the epoch's
            # delta was GC'd below a completed compaction: a legitimate
            # previous-generation base.)
            own = self._seg_path(base_epoch)
            if os.path.exists(own):
                try:
                    with open(own, "rb") as fh:
                        own_header, _ = _decode_segment(fh.read())
                    own_uid = own_header.get("uid", "")
                except (InvalidSegment, OSError):
                    own_uid = None  # unreadable delta: ambiguous, reject
                if own_uid != uid:
                    if self.logger:
                        self.logger.warning(
                            f"Checkpoint base {os.path.basename(path)} is a "
                            f"stale orphan (delta-{base_epoch:012d}.seg "
                            f"contradicts its uid); skipping"
                        )
                    return None
        epoch = base_epoch
        dropped: List[str] = []
        while True:
            seg = self._seg_path(epoch + 1)
            if not os.path.exists(seg):
                break
            try:
                with open(seg, "rb") as fh:
                    header, arrays = _decode_segment(fh.read())
                if header.get("chain") != chain_id:
                    raise InvalidSegment("foreign chain id (stale tail)")
                if header.get("prev_uid") != uid:
                    raise InvalidSegment("broken predecessor linkage (duplicate tail)")
                if int(header.get("epoch", -1)) != epoch + 1:
                    raise InvalidSegment("epoch mismatch")
                apply_delta(data, header, arrays)
            except (InvalidSegment, OSError) as e:
                dropped.append(f"{os.path.basename(seg)} ({e})")
                break
            epoch += 1
            uid = header["uid"]
        return RecoveredChain(data, epoch, chain_id, uid, base_epoch, dropped)

    # -- the per-epoch hot path ----------------------------------------------
    def append(self, arrays: Dict[str, np.ndarray], meta: dict) -> int:
        """Commit one epoch: encode + atomically publish the next delta
        segment. Returns the committed epoch. Raises CheckpointWriteError on
        any storage failure — the tail is unchanged and the same (or a
        larger) delta can be retried."""
        with self._lock:
            if self._chain_id is None:
                raise CheckpointWriteError("chain not initialized (call initialize/load)")
            epoch = self._tail_epoch + 1
            chain_id, prev_uid = self._chain_id, self._tail_uid
        uid = os.urandom(8).hex()
        blob = _encode_segment(epoch, chain_id, uid, prev_uid, arrays, meta)
        try:
            self._write_atomic(self._seg_path(epoch), blob, seg_faults=True)
        except OSError as e:
            raise CheckpointWriteError(f"delta append failed at epoch {epoch}: {e}") from e
        with self._lock:
            self._tail_epoch = epoch
            self._tail_uid = uid
            self.last_delta_bytes = len(blob)
        return epoch

    # -- compaction (off the hot path) ----------------------------------------
    def compact(self, epoch: int, arrays: Dict[str, np.ndarray]) -> None:
        """Write a new base at ``epoch`` (a full capture of the state the
        epoch-``epoch`` commit described), swap MANIFEST, GC one generation
        back. Appends may run concurrently — segments > ``epoch`` stay valid
        under both bases. Storage failures raise CheckpointWriteError; the
        old chain remains fully intact."""
        with self._lock:
            chain_id = self._chain_id
            old_base = self._base_epoch
        if chain_id is None:
            raise CheckpointWriteError("chain not initialized")
        # the new base's uid is the uid of the delta segment that committed
        # this epoch (or the current base's for epoch == base): linkage from
        # the base to its successor segment must keep matching
        uid = self._uid_of(epoch)
        if uid is None:
            raise CheckpointWriteError(f"compaction epoch {epoch} not on the chain")
        plan = _faults()
        try:
            if plan is not None:
                plan.on_compact_point("pre_base")
            self._write_base(epoch, chain_id, uid, arrays)
            if plan is not None:
                plan.on_compact_point("pre_manifest")
            self._write_manifest(chain_id, epoch, uid)
        except OSError as e:
            raise CheckpointWriteError(f"compaction at epoch {epoch} failed: {e}") from e
        with self._lock:
            self._base_epoch = epoch
            self.compactions += 1
        # retention after the swap: the NEW base, the OLD base (one
        # generation of fallback against a new base that later proves
        # unreadable) and every delta above the old base. Deltas at/below
        # the old base are covered by it; bases older than it are not on
        # any fallback path anymore.
        self._gc(prev_base=old_base)

    def compact_async(self, epoch: int, arrays: Dict[str, np.ndarray],
                      on_error=None) -> bool:
        """Run :meth:`compact` on a background thread (the hot path only
        pays the state capture). At most one compaction in flight — returns
        False when one is already running (the cadence retries next time)."""
        with self._lock:
            if self._compact_thread is not None and self._compact_thread.is_alive():
                return False

            def _run():
                try:
                    self.compact(epoch, arrays)
                except Exception as e:
                    if self.logger:
                        self.logger.error(f"Background compaction failed: {e}")
                    if on_error is not None:
                        on_error(e)

            t = threading.Thread(target=_run, name="ckpt-compact", daemon=True)
            self._compact_thread = t
        t.start()
        return True

    def wait_compaction(self, timeout_s: float = 60.0) -> None:
        with self._lock:
            t = self._compact_thread
        if t is not None:
            t.join(timeout=timeout_s)

    def _uid_of(self, epoch: int) -> Optional[str]:
        with self._lock:
            base_epoch = self._base_epoch
        if epoch == base_epoch:
            m = self._read_manifest()
            return m.get("base_uid", "") if m else None
        seg = self._seg_path(epoch)
        try:
            with open(seg, "rb") as fh:
                header, _ = _decode_segment(fh.read())
            return header["uid"]
        except Exception:
            return None

    def _gc(self, prev_base: int) -> None:
        """Delete deltas at/below the previous base and bases older than it,
        plus orphaned tmp files. Best-effort: GC failures never fail a
        commit (worst case the directory carries extra history)."""
        try:
            names = os.listdir(self.directory)
        except OSError:
            return
        for n in names:
            path = os.path.join(self.directory, n)
            try:
                if n.endswith(".tmp"):
                    os.unlink(path)
                    continue
                m = _SEG_RE.match(n)
                if m and int(m.group(1)) <= prev_base:
                    os.unlink(path)
                    continue
                b = _BASE_RE.match(n)
                if b and int(b.group(1)) < prev_base:
                    os.unlink(path)
            except OSError:
                pass
