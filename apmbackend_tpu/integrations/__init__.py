"""Outward-facing host integrations: Grafana (render/annotations) and email."""

from .email_sender import EmailSender, build_mime  # noqa: F401
from .grafana import GrafanaClient  # noqa: F401
