"""Email dispatch via the local sendmail binary.

Role parity with sendEmail (util_methods.js:359-396): HTML body; when an
image path is given, the HTML gets ``<br><br><img src="cid:..."/>`` appended
and the PNG rides as an inline related attachment. Transport is the
``sendmail`` executable on stdin (the nodemailer sendmail-transport role),
injectable for tests and gated on the binary existing.
"""

from __future__ import annotations

import os
import shutil
import subprocess
import time
from email.message import EmailMessage
from typing import Callable, Optional


def build_mime(
    from_addr: str,
    to_addrs: str,
    subject: str,
    html: str,
    image_path: Optional[str] = None,
    *,
    clock: Callable[[], float] = time.time,
) -> EmailMessage:
    msg = EmailMessage()
    msg["From"] = from_addr
    msg["To"] = to_addrs
    msg["Subject"] = subject
    if image_path:
        # Stable cid naming like `graph_<epoch ms>` (util_methods.js:375)
        # with a short random tail for uniqueness. Deliberately NOT
        # make_msgid: its pid+random+hostname cid exceeds the 78-char header
        # fold point, and a folded Content-ID header (embedded "\n ") breaks
        # cid: references in strict clients.
        import secrets

        cid = f"<graph_{int(clock() * 1000)}.{secrets.token_hex(4)}@apm>"
        html = f'{html}<br><br><img src="cid:{cid[1:-1]}"/>'
        msg.add_alternative(html, subtype="html")
        with open(image_path, "rb") as fh:
            msg.get_payload()[0].add_related(
                fh.read(), maintype="image", subtype="png", cid=cid,
                filename=os.path.basename(image_path),
            )
    else:
        msg.add_alternative(html, subtype="html")
    return msg


class EmailSender:
    """Callable matching the AlertsManager ``email_sender`` seam:
    ``sender(subject, html, image_path)``."""

    def __init__(
        self,
        from_addr: str,
        to_addrs: str,
        *,
        sendmail_path: str = "/usr/sbin/sendmail",
        logger=None,
        transport: Optional[Callable[[EmailMessage], None]] = None,
        clock: Callable[[], float] = time.time,
    ):
        self.from_addr = from_addr
        self.to_addrs = to_addrs
        self.sendmail_path = sendmail_path
        self.logger = logger
        self.transport = transport
        self.clock = clock

    def available(self) -> bool:
        return self.transport is not None or bool(
            shutil.which(self.sendmail_path) or os.path.exists(self.sendmail_path)
        )

    def __call__(self, subject: str, html: str, image_path: Optional[str] = None) -> bool:
        msg = build_mime(self.from_addr, self.to_addrs, subject, html, image_path, clock=self.clock)
        if self.logger:
            self.logger.info(f"Sending email! subject={subject!r} to={self.to_addrs!r} image={image_path!r}")
        try:
            if self.transport is not None:
                self.transport(msg)
            else:
                if not self.available():
                    raise FileNotFoundError(self.sendmail_path)
                # -t reads recipients from the headers; -i guards against
                # lone-dot line termination (classic sendmail pipe flags).
                subprocess.run(
                    [self.sendmail_path, "-t", "-i"],
                    input=msg.as_bytes(),
                    check=True,
                    timeout=30,
                )
            if self.logger:
                self.logger.info("Message sent")
            return True
        except Exception as e:
            if self.logger:
                self.logger.error(f"sendmail error: {e}")
            return False
