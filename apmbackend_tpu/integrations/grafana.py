"""Grafana HTTP client: alert-inspector URLs, PNG renders, annotations.

Role parity:

- :meth:`GrafanaClient.alert_urls` — generateGrafanaURL/Params
  (stream_process_alerts.js:153-206): one dashboard URL + one /render URL
  covering every server/service/lag in the alert batch, with a from/to window
  of [first alert - 5 min, last alert + 5 min], clamped so "to" stays at least
  ``grafanaNowDelayIntervalMs`` in the past (data-ingest delay),
  and a render height sized to the alert combinatorics
  (servers x services x lags z-score panels + one tx panel per service).
- :meth:`GrafanaClient.render` — renderGraph (stream_process_alerts.js:59-85):
  GET the render URL with the bearer token, stream the PNG to
  ``renderDir/alert_<ISO>.png``.
- :meth:`GrafanaClient.post_annotation` — sendAnnotation
  (apm_manager.js:224-244): POST /api/annotations with time=timeEnd=now.

HTTP is injectable (``http_get``/``http_post``) so everything is testable
without a live Grafana; the default transport is urllib.
"""

from __future__ import annotations

import json
import os
import time
from datetime import datetime, timezone
from typing import Callable, List, Optional, Tuple

from ..entries import EntryFactory


def _default_http_get(url: str, headers: dict, timeout_s: float) -> bytes:
    import urllib.request

    req = urllib.request.Request(url, headers=headers)
    with urllib.request.urlopen(req, timeout=timeout_s) as resp:
        return resp.read()


def _default_http_post(url: str, body: dict, headers: dict, timeout_s: float) -> bytes:
    import urllib.request

    data = json.dumps(body).encode("utf-8")
    req = urllib.request.Request(
        url, data=data, headers={**headers, "Content-Type": "application/json"}, method="POST"
    )
    with urllib.request.urlopen(req, timeout=timeout_s) as resp:
        return resp.read()


class GrafanaClient:
    def __init__(
        self,
        grafana_config: dict,
        *,
        logger=None,
        clock: Callable[[], float] = time.time,
        http_get: Callable[[str, dict, float], bytes] = _default_http_get,
        http_post: Callable[[str, dict, dict, float], bytes] = _default_http_post,
    ):
        self.config = grafana_config
        self.logger = logger
        self.clock = clock
        self.http_get = http_get
        self.http_post = http_post
        self._factory = EntryFactory()

    def set_config(self, grafana_config: dict) -> None:
        self.config = grafana_config

    # -- URL generation (stream_process_alerts.js:153-206) -------------------
    def alert_url_params(self, alert_buffer: List[dict]) -> Tuple[str, int]:
        """(query params, height factor) for a batch of buffered alerts."""
        servers: List[str] = []
        services: List[str] = []
        lags: List = []
        for el in alert_buffer:
            entry = self._factory.from_csv(el["entry"], delim="&")
            if entry is None:
                continue
            if entry.server not in servers:
                servers.append(entry.server)
            if entry.service not in services:
                services.append(entry.service)
            if entry.lag not in lags:
                lags.append(entry.lag)

        first = self._factory.from_csv(alert_buffer[0]["entry"], delim="&")
        last = self._factory.from_csv(alert_buffer[-1]["entry"], delim="&")
        now_ms = self.clock() * 1000.0
        from_ts = int(first.timestamp - 300000)
        to_ts = int(last.timestamp + 300000)
        delay = float(self.config.get("grafanaNowDelayIntervalMs", 90000))
        if now_ms - to_ts <= delay:
            to_ts = int(now_ms - delay)

        params = f"from={from_ts}&to={to_ts}"
        for server in servers:
            params += f"&var-server={server}"
        for service in services:
            params += f"&var-service={service}"
        for lag in lags:
            params += f"&var-lag={lag}"
        height_factor = len(servers) * len(services) * len(lags) + len(services)
        return params, height_factor

    def alert_urls(self, alert_buffer: List[dict]) -> Tuple[str, str]:
        """(dashboard URL, render URL) for an alert batch."""
        params, height_factor = self.alert_url_params(alert_buffer)
        base = self.config.get("grafanaURL", "")
        rel = self.config.get("alertInspectorRelativeURL", "/d/alert-inspector")
        url = f"{base}{rel}?{params}"
        render_height = 100 + int(self.config.get("renderHeightMultiple", 750)) * height_factor
        extra = (
            f"&width={self.config.get('renderWidth', 1800)}&height={render_height}"
            f"{self.config.get('renderExtraParams', '')}"
        )
        render_url = f"{base}/render{rel}?{params}{extra}"
        return url, render_url

    # -- render (stream_process_alerts.js:59-85) -----------------------------
    def render(self, render_url: str) -> Optional[str]:
        """Download the rendered PNG; returns the image path or None on error."""
        if self.logger:
            self.logger.info("Rendering graph...")
        try:
            iso = datetime.fromtimestamp(self.clock(), tz=timezone.utc).isoformat()
            render_dir = self.config.get("renderDir", "renders")
            os.makedirs(render_dir, exist_ok=True)
            image_path = os.path.abspath(os.path.join(render_dir, f"alert_{iso}.png"))
            data = self.http_get(
                render_url,
                {"Authorization": self.config.get("bearerToken", "")},
                float(self.config.get("renderTimeout", 90000)) / 1000.0,
            )
            with open(image_path, "wb") as fh:
                fh.write(data)
            return image_path
        except Exception as e:
            if self.logger:
                self.logger.error(f"Error rendering graph! {e}")
            return None

    # -- annotations (apm_manager.js:224-244) --------------------------------
    def post_annotation(self, text: str, tags: List[str]) -> bool:
        now = int(self.clock() * 1000.0)
        body = {"time": now, "timeEnd": now, "text": text, "tags": tags}
        if self.logger:
            self.logger.info("Submitting annotation...")
        try:
            self.http_post(
                f"{self.config.get('grafanaURL', '')}/api/annotations",
                body,
                {"Authorization": self.config.get("bearerToken", "")},
                10.0,
            )
            return True
        except Exception as e:
            if self.logger:
                self.logger.error(f"Annotation submission failure! {e}")
            return False
