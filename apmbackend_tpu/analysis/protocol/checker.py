"""Explicit-state model checker: BFS over canonical hashable states.

The checker is deliberately tiny and dependency-free — a model is any
object exposing

- ``name``: str, and ``scope``: dict of the bound parameters (reported in
  verdicts and counterexamples so "verified" always carries its bounds),
- ``initial()`` -> state (any hashable value; tuples/namedtuples in
  practice),
- ``actions(state)`` -> iterable of ``(label, next_state)`` pairs — every
  transition enabled in ``state``. Labels are short human-readable strings
  ("deliver(m1)", "crash"); they ARE the counterexample vocabulary.
- ``invariant(state)`` -> ``None`` when the state is fine, else a one-line
  violation message,
- ``describe(state)`` -> compact one-line rendering for schedules.

``check()`` explores breadth-first with a visited set keyed on the state
value itself (models canonicalize internally: sorted token tuples, frozen
sets), so the first violation found is a SHORTEST schedule — the most
readable counterexample that exists at the scope. Predecessor links
reconstruct the full schedule: numbered steps of ``label -> state``.

Exhaustiveness contract: with ``max_states=None`` (the default used by the
gates) the BFS terminates only when the reachable state space at the
model's scope is fully enumerated — "verified" means *every* interleaving
of the modeled actions within the scope bounds, not a sample.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Dict, List, Optional, Tuple


class CheckResult:
    """Outcome of one model check (one model at one scope)."""

    def __init__(self, model_name: str, scope: dict, *, ok: bool,
                 states: int, transitions: int, depth: int, elapsed_s: float,
                 violation: Optional[str] = None,
                 schedule: Optional[List[Tuple[str, str]]] = None,
                 truncated: bool = False):
        self.model_name = model_name
        self.scope = dict(scope)
        self.ok = ok
        self.states = states
        self.transitions = transitions
        self.depth = depth
        self.elapsed_s = elapsed_s
        self.violation = violation  # invariant message, None when ok
        # [(action label, state description)], step 0 = initial state
        self.schedule = schedule or []
        self.truncated = truncated  # hit max_states before exhausting

    def to_dict(self) -> dict:
        return {
            "model": self.model_name,
            "scope": self.scope,
            "ok": self.ok,
            "states": self.states,
            "transitions": self.transitions,
            "depth": self.depth,
            "elapsed_s": round(self.elapsed_s, 3),
            "truncated": self.truncated,
            "violation": self.violation,
            "schedule": [list(step) for step in self.schedule],
        }

    def format_schedule(self) -> str:
        """The human-readable counterexample: a numbered schedule from the
        initial state to the violating state. Empty string when ok."""
        if self.ok:
            return ""
        scope = ", ".join(f"{k}={v}" for k, v in sorted(self.scope.items()))
        lines = [
            f"counterexample for {self.model_name} [{scope}] "
            f"({len(self.schedule) - 1} steps):",
            f"  INVARIANT VIOLATED: {self.violation}",
        ]
        for i, (label, desc) in enumerate(self.schedule):
            arrow = "initial" if i == 0 else label
            lines.append(f"  {i:3d}. {arrow:<28} {desc}")
        return "\n".join(lines)


def check(model, *, max_states: Optional[int] = None) -> CheckResult:
    """Breadth-first exhaustive exploration; returns on the FIRST invariant
    violation (shortest schedule) or after the full reachable space."""
    t0 = time.monotonic()
    init = model.initial()
    # state -> (predecessor state, action label); init maps to itself
    parent: Dict[object, Tuple[object, Optional[str]]] = {init: (init, None)}
    frontier = deque([(init, 0)])
    states = 1
    transitions = 0
    depth = 0
    truncated = False

    def _result(ok, violation=None, bad_state=None):
        schedule = None
        if not ok:
            # walk predecessor links back to the initial state
            chain: List[Tuple[str, object]] = []
            s = bad_state
            while True:
                prev, label = parent[s]
                chain.append((label or "", s))
                if label is None:
                    break
                s = prev
            chain.reverse()
            schedule = [(lbl, model.describe(st)) for lbl, st in chain]
        return CheckResult(
            model.name, model.scope, ok=ok, states=states,
            transitions=transitions, depth=depth,
            elapsed_s=time.monotonic() - t0, violation=violation,
            schedule=schedule, truncated=truncated,
        )

    v = model.invariant(init)
    if v is not None:
        return _result(False, v, init)

    while frontier:
        state, d = frontier.popleft()
        depth = max(depth, d)
        for label, nxt in model.actions(state):
            transitions += 1
            if nxt in parent:
                continue
            parent[nxt] = (state, label)
            states += 1
            v = model.invariant(nxt)
            if v is not None:
                return _result(False, v, nxt)
            if max_states is not None and states >= max_states:
                truncated = True
                return _result(True)
            frontier.append((nxt, d + 1))
    return _result(True)
