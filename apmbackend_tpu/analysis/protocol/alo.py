"""Model of the at-least-once epoch cycle (runtime/worker.py + transports).

One model, three broker semantics (``kind``):

- ``memory`` — the MemoryBroker unacked ledger: tokens settle atomically
  under the broker lock; crash/bounce requeues every unacked delivery at
  the FRONT of the queue in original order (transport/memory.py
  ``requeue_unacked``).
- ``amqp`` — same ledger shape, but acks land ONE AT A TIME on the
  consumer thread (``basic_ack`` marshalling), so a crash can interleave
  a half-acked epoch; stale-generation tokens are dropped (the
  ``_conn_gen`` stamp in transport/amqp.py).
- ``spool`` — the durable cursor (transport/spool.py): acks advance a
  contiguous committed cursor; crash rewinds delivery to the cursor (no
  broker ledger object survives, the file is the ledger).

The worker side is the epoch cycle verbatim: accept (dedup against the
in-memory window, msg joins the bounded FIFO window, line joins the
pending feed buffer, token joins the epoch), drain (bulk feed: pending →
volatile engine state), commit (drain, then persist volatile state + the
dedup window atomically, then ack the epoch's tokens — the persist/ack
boundary is exposed so a crash can land between them), crash (volatile
state lost, durable checkpoint restored, broker redelivers), bounce
(broker restart only: worker memory survives, ledger requeues), and
chaos duplicate delivery (same payload+msg_id+token replayed — the
ChaosChannel ``dup_p`` seam), and the broker outage cycle (ISSUE 15):
``broker_down`` refuses every broker-touching action — the producer
buffers upstream, acks park for retry — and ``reconnect`` requeues all
unacked deliveries (the XAUTOCLAIM / AMQP connection-death path) before
traffic resumes.

Invariants (checked at EVERY reachable state):

- **no-double-effect**: no message's effect appears twice in durable
  state.
- **ack-implies-durable** (= no-loss): a message the broker has settled
  (gone from queue+ledger / behind the spool cursor) must have its effect
  in the durable checkpoint.

Scope preconditions the model makes explicit (and DESIGN.md §9.4
documents): the broker prefetch bound must not exceed the dedup window
size — in-flight deliveries are capped at ``prefetch`` (basic_qos / the
spool prefetch), which is what keeps every unacked message's id inside
the bounded window. The FRONT-requeue order is also load-bearing: the
``alo-requeue-at-back`` mutant shows a broker that requeues at the back
can push a redelivered id out of the window before it is re-seen.

Mutations (seeded protocol bugs — see mutations.py for the catalogue):
``ack_before_persist``, ``dup_ack_early`` (the real PR 3 bug),
``evict_on_persist``, ``skip_drain``, ``ack_on_failed_write``,
``window_not_restored``, ``requeue_back``, ``reconnect_drops_unacked``
(a reconnect that forgets the unacked ledger instead of redelivering it).
"""

from __future__ import annotations

from collections import namedtuple
from typing import Iterator, Optional, Tuple

# sent:     messages published so far (ids 0..sent-1, FIFO)
# queue:    broker queue of msg ids (memory/amqp; spool delivers by index)
# ledger:   unacked deliveries, delivery order: tuple of (gen, msg)
# gen:      broker connection generation (stale-token discriminator)
# cursor:   spool committed cursor (settled = idx < cursor)
# ndeliv:   spool next delivery index
# abeyond:  spool acked-but-not-contiguous indices (in-memory, lost on crash)
# window:   in-memory dedup window, FIFO of msg ids, max W
# pwindow:  dedup window persisted in the last durable checkpoint
# pending:  accepted-not-yet-fed msgs (the _alo_pending buffer), sorted
# vol:      per-msg volatile effect counts (engine state incl. restores)
# dur:      per-msg durable effect counts (the checkpoint)
# tokens:   current epoch's unacked tokens, sorted
# to_ack:   tokens persisted-but-not-yet-acked (the commit→ack window)
# crashes/bounces/dups/wfails: remaining fault budgets
# downs:    remaining broker-outage budget (ISSUE 15 chaos tier)
# down:     1 while the broker is dead: publish/deliver/dup/ack are all
#           refused (the producer buffers upstream); reconnect requeues
#           every unacked delivery exactly like a bounce
S = namedtuple(
    "S",
    "sent queue ledger gen cursor ndeliv abeyond window pwindow pending "
    "vol dur tokens to_ack crashes bounces dups wfails downs down",
)

_MUTATIONS = frozenset({
    "ack_before_persist", "dup_ack_early", "evict_on_persist", "skip_drain",
    "ack_on_failed_write", "window_not_restored", "requeue_back",
    "reconnect_drops_unacked",
})


class AloModel:
    def __init__(self, *, kind: str = "memory", n_msgs: int = 3,
                 window: int = 2, prefetch: Optional[int] = None,
                 crashes: int = 1, bounces: int = 1, dups: int = 1,
                 wfails: int = 0, downs: int = 1,
                 mutations: Tuple[str, ...] = ()):
        if kind not in ("memory", "amqp", "spool"):
            raise ValueError(f"unknown broker kind {kind!r}")
        bad = set(mutations) - _MUTATIONS
        if bad:
            raise ValueError(f"unknown mutations: {sorted(bad)}")
        self.kind = kind
        self.n = n_msgs
        self.w = window
        self.prefetch = window if prefetch is None else prefetch
        self.crashes = crashes
        self.bounces = 0 if kind == "spool" else bounces
        self.dups = dups
        self.wfails = wfails if "ack_on_failed_write" in mutations else 0
        # broker outage: the spool has no broker process to kill — the file
        # IS the broker, and killing the consumer is already `crash`
        self.downs = 0 if kind == "spool" else downs
        self.mut = frozenset(mutations)
        self.name = f"alo-{kind}" + (f"[{'+'.join(sorted(self.mut))}]" if self.mut else "")
        self.scope = {
            "broker": kind, "msgs": n_msgs, "window": window,
            "prefetch": self.prefetch, "crashes": crashes,
            "bounces": self.bounces, "dups": dups, "downs": self.downs,
        }

    # -- state helpers -------------------------------------------------------
    def initial(self) -> S:
        z = (0,) * self.n
        return S(0, (), (), 0, 0, 0, frozenset(), (), (), (), z, z, (), (),
                 self.crashes, self.bounces, self.dups, self.wfails,
                 self.downs, 0)

    @staticmethod
    def _bump(vec: tuple, m: int) -> tuple:
        return vec[:m] + (min(2, vec[m] + 1),) + vec[m + 1:]

    def _settle(self, s: S, tokens) -> S:
        """Broker-side ack semantics for a batch of tokens (idempotent for
        stale tokens — exactly the Channel.ack contract)."""
        if self.kind == "spool":
            cursor, abeyond = s.cursor, set(s.abeyond)
            for idx in sorted(tokens):
                if idx >= cursor:
                    abeyond.add(idx)
            while cursor in abeyond:
                abeyond.discard(cursor)
                cursor += 1
            return s._replace(cursor=cursor, abeyond=frozenset(abeyond))
        toks = set(tokens)
        return s._replace(ledger=tuple(e for e in s.ledger if e not in toks))

    def _requeue(self, s: S) -> S:
        """Broker redelivery of everything unacked (crash / bounce)."""
        if self.kind == "spool":
            return s._replace(ndeliv=s.cursor, abeyond=frozenset())
        redelivered = tuple(m for _g, m in s.ledger)
        if "requeue_back" in self.mut:
            queue = s.queue + redelivered
        else:
            queue = redelivered + s.queue  # FIFO-preserving front requeue
        return s._replace(queue=queue, ledger=(), gen=s.gen + 1)

    def _receive(self, s: S, m: int, token) -> S:
        """One delivery (or chaos duplicate) reaching the worker's
        _consume_at_least_once: dedup window check, absorb, token joins
        the epoch."""
        if m in s.window:
            if "dup_ack_early" in self.mut:
                # the PR 3 bug: the deduped copy's token is acked NOW,
                # advancing the broker past an effect that is not durable
                return self._settle(s, (token,))
            if token in s.tokens:
                return s
            return s._replace(tokens=tuple(sorted(s.tokens + (token,))))
        window = s.window + (m,)
        if len(window) > self.w:
            window = window[1:]  # bounded FIFO eviction
        return s._replace(
            window=window,
            pending=tuple(sorted(s.pending + (m,))),
            tokens=tuple(sorted(set(s.tokens) | {token})),
        )

    def _drain(self, s: S) -> S:
        vol = s.vol
        for m in s.pending:
            vol = self._bump(vol, m)
        return s._replace(vol=vol, pending=())

    # -- transition relation -------------------------------------------------
    def actions(self, s: S) -> Iterator[Tuple[str, S]]:
        out = []
        # broker outage (ISSUE 15): while down, every broker-touching action
        # (publish/deliver/dup/ack/bounce) is refused — send returns False
        # and the producer buffers upstream, acks park for retry. The worker
        # side (drain/commit/crash) keeps running.
        if s.downs > 0 and not s.down:
            out.append(("broker_down", s._replace(down=1, downs=s.downs - 1)))
        if s.down:
            # reconnect: the broker is back; everything unacked is
            # redelivered (PEL idle-claim / AMQP requeue-on-connection-death
            # — the same front-requeue a bounce performs). The seeded
            # reconnect_drops_unacked mutant forgets the ledger instead:
            # delivered-but-unacked messages silently settle (loss).
            if "reconnect_drops_unacked" in self.mut:
                ns = s._replace(ledger=(), gen=s.gen + 1, down=0)
            else:
                ns = self._requeue(s)._replace(down=0)
            out.append(("reconnect", ns))
        # publish: producer stamps the next msg_id and sends
        if s.sent < self.n and not s.down:
            m = s.sent
            ns = s._replace(sent=s.sent + 1)
            if self.kind != "spool":
                ns = ns._replace(queue=ns.queue + (m,))
            out.append((f"publish(m{m})", ns))

        # deliver: broker hands the next message + token to the consumer;
        # prefetch bounds in-flight unacked deliveries (basic_qos)
        if self.kind == "spool":
            if s.ndeliv < s.sent and s.ndeliv - s.cursor < self.prefetch:
                m = s.ndeliv
                ns = s._replace(ndeliv=s.ndeliv + 1)
                out.append((f"deliver(m{m})", self._receive(ns, m, m)))
        elif s.queue and len(s.ledger) < self.prefetch and not s.down:
            m, rest = s.queue[0], s.queue[1:]
            token = (s.gen, m)
            ns = s._replace(queue=rest, ledger=s.ledger + (token,))
            out.append((f"deliver(m{m})", self._receive(ns, m, token)))

        # chaos duplicate: replay an in-flight delivery (same msg_id+token)
        if s.dups > 0 and not s.down:
            if self.kind == "spool":
                inflight = [(i, i) for i in range(s.cursor, s.ndeliv)
                            if i not in s.abeyond]
            else:
                inflight = [(m, tok) for tok in s.ledger for m in [tok[1]]]
            for m, tok in inflight:
                ns = self._receive(s._replace(dups=s.dups - 1), m, tok)
                out.append((f"dup(m{m})", ns))

        # drain: the feed timer / batch-full bulk feed (pending → engine)
        if s.pending:
            out.append(("drain", self._drain(s)))

        # commit: the save_state epoch commit. Correct protocol: drain →
        # persist (state + dedup window, atomically) → ack moves to to_ack
        # (the ack itself is a separate transition so a crash can land in
        # the commit→ack window).
        if "ack_before_persist" in self.mut:
            ns = self._drain(s)
            ns = self._settle(ns, ns.tokens)._replace(tokens=())
            out.append(("commit[ack-first]", ns))
            # the (too-late) persist is its own action
            out.append(("persist", s._replace(dur=s.vol, pwindow=s.window)))
        else:
            ns = s if "skip_drain" in self.mut else self._drain(s)
            pwin = ns.window
            if "evict_on_persist" in self.mut and pwin:
                pwin = pwin[1:]  # persists the window minus its oldest id
            ns = ns._replace(
                dur=ns.vol, pwindow=pwin,
                to_ack=tuple(sorted(set(ns.to_ack) | set(ns.tokens))),
                tokens=(),
            )
            out.append(("commit", ns))

        # failed checkpoint write that acks anyway (mutation only): the
        # correct protocol keeps tokens on failure, which is a no-op state
        if s.wfails > 0 and "ack_on_failed_write" in self.mut:
            ns = self._drain(s._replace(wfails=s.wfails - 1))
            ns = self._settle(ns, ns.tokens)._replace(tokens=())
            out.append(("commit[write-failed,ack]", ns))

        # ack: commit the epoch's tokens on the broker (parked while down —
        # the channel's pending-ack retry path)
        if s.to_ack and not s.down:
            if self.kind == "amqp":
                # marshalled basic_ack: one token per step (a crash can
                # interleave a half-acked epoch)
                tok = s.to_ack[0]
                ns = self._settle(s, (tok,))._replace(to_ack=s.to_ack[1:])
                out.append((f"ack({self._tok(tok)})", ns))
            else:
                ns = self._settle(s, s.to_ack)._replace(to_ack=())
                out.append(("ack", ns))

        # crash: kill −9 + restart. Worker volatile state is lost and the
        # durable checkpoint restored; the broker redelivers every unacked
        # message (front-requeue / cursor rewind).
        if s.crashes > 0:
            ns = s._replace(
                crashes=s.crashes - 1,
                vol=s.dur,
                window=() if "window_not_restored" in self.mut else s.pwindow,
                pending=(), tokens=(), to_ack=(),
            )
            # crash during an outage: the broker can't requeue yet — the
            # ledger survives on the (dead) broker and redelivery happens
            # at reconnect instead
            out.append(("crash+recover", ns if s.down else self._requeue(ns)))

        # bounce: broker restart, worker survives (stale tokens appear)
        if s.bounces > 0 and not s.down:
            out.append(("bounce", self._requeue(s._replace(bounces=s.bounces - 1))))
        return out

    @staticmethod
    def _tok(tok) -> str:
        return f"m{tok}" if isinstance(tok, int) else f"g{tok[0]}:m{tok[1]}"

    # -- invariants ----------------------------------------------------------
    def invariant(self, s: S) -> Optional[str]:
        for m in range(self.n):
            if s.dur[m] >= 2:
                return (f"m{m} effected {s.dur[m]}x in durable state "
                        f"(no-double-effect violated)")
        # settled = the broker will never deliver this message again
        if self.kind == "spool":
            settled = range(s.cursor)
        else:
            present = set(s.queue) | {m for _g, m in s.ledger}
            settled = [m for m in range(s.sent) if m not in present]
        for m in settled:
            if s.dur[m] == 0:
                return (f"m{m} is settled on the broker but has NO durable "
                        f"effect (ack-implies-durable violated: the message "
                        f"is lost)")
        return None

    def describe(self, s: S) -> str:
        if self.kind == "spool":
            broker = f"cur={s.cursor} nd={s.ndeliv}"
        else:
            q = ",".join(f"m{m}" for m in s.queue)
            led = ",".join(self._tok(t) for t in s.ledger)
            broker = f"q=[{q}] led=[{led}]"
        win = ",".join(f"m{m}" for m in s.window)
        pwin = ",".join(f"m{m}" for m in s.pwindow)
        pend = ",".join(f"m{m}" for m in s.pending)
        vol = "".join(str(c) for c in s.vol)
        dur = "".join(str(c) for c in s.dur)
        tok = ",".join(self._tok(t) for t in s.tokens)
        ack = ",".join(self._tok(t) for t in s.to_ack)
        down = " DOWN" if s.down else ""
        return (f"sent={s.sent} {broker}{down} win=[{win}] pwin=[{pwin}] "
                f"pend=[{pend}] vol={vol} dur={dur} tok=[{tok}] toack=[{ack}]")
