"""Trace conformance: real protocol event logs replayed against the models.

A model of the wrong protocol verifies the wrong thing. To pin the models
to the implementation, the worker (``tpuEngine.protocolEventLog``) emits
one JSONL event per protocol step — ``recover`` / ``deliver`` / ``feed``
/ ``checkpoint`` / ``ack`` / ``compact`` — and the chaos harness appends
``crash`` / ``corrupt`` markers at its kill−9 / hostile-storage
injection points. :func:`check_protocol_trace` replays such a log as a
path of the ALO + delta-chain models: a deterministic mirror of the
dedup window, the epoch/chain watermarks, and the pending feed buffer
steps through the events and reports every transition the models do not
allow. An empty report means the run WAS a model path; a non-empty one
means either a protocol regression in the implementation or model drift
— both gate failures.

The rules enforced (each cites the model transition it mirrors):

- ``deliver(dedup=True)`` only for a message currently in the window
  mirror; ``deliver(dedup=False)`` never for one that is (alo._receive);
- a message whose effect is already durable is never re-absorbed
  (no-double-effect);
- ``checkpoint(ok)`` epochs are exactly +1 monotonic, and the pending
  feed buffer is EMPTY at every commit (drain-before-commit);
- delta-chain ``chain_epoch`` advances by exactly 1 per commit;
- ``ack`` follows a successful checkpoint of the same epoch, with no
  crash between (ack-after-checkpoint);
- no worker events between a ``crash`` marker and the next ``recover``;
- ``recover`` lands exactly on the last committed epoch — or, with
  hostile-storage ``corrupt`` markers since the last boot, at most that
  many epochs earlier, and never below the last ACKED epoch
  (recovery-stops-at-last-committed-boundary + ack-implies-durable);
- a ``redelivered`` flag only on messages that were delivered before;
- fleet handoffs (PR 9, shardmodel.py): ``handoff_export`` only with an
  EMPTY unacked ledger (quiesce) and an empty pending-feed buffer, its
  window ids leaving the mirror; ``handoff_import`` bringing ids in; a
  handoff ``checkpoint`` (the sync base rewrite) keeps the chain epoch
  instead of advancing it; ``deliver(mismatch=True)`` (partition-header
  defense) absorbs nothing.

:func:`check_fleet_trace` replays the MERGED logs of every shard (plus
the harness's ``rebalance`` markers) against the fleet-level invariants
of the sharded-epoch model: fleet exactly-once (no message's effect
commits durably on two shards), quiesced handoffs (export ids == import
ids, nobody consumes the partition queue between them), and owner-
locality of consumption (a shard only takes deliveries from queues it
currently owns).
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional


def read_event_log(path: str) -> List[dict]:
    """Parse a protocol event log; a torn final line (the crash case the
    log exists to capture) is tolerated and dropped."""
    events: List[dict] = []
    try:
        with open(path, "r", encoding="utf-8") as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    events.append(json.loads(line))
                except ValueError:
                    continue  # torn tail from a SIGKILL mid-write
    except OSError:
        pass
    return events


class _Mirror:
    """Deterministic replay state: the model variables reconstructible
    from the event stream."""

    def __init__(self, window_size: int):
        self.window_size = window_size
        self.window: List[str] = []
        self.committed: set = set()  # msgs with durable effects
        self.absorbed: set = set()  # absorbed since the last commit
        self.pending = 0  # accepted-not-yet-fed tx lines
        self.epoch = 0
        self.chain_epoch: Optional[int] = None
        self.acked_epoch = 0
        self.seen: set = set()  # every msg id ever delivered
        self.dead = False
        self.corrupts_since_boot = 0
        # epoch -> (window snapshot, committed snapshot) at that commit
        self.snapshots: Dict[int, tuple] = {}

    def snapshot(self) -> None:
        self.snapshots[self.epoch] = (tuple(self.window),
                                      frozenset(self.committed))

    def restore(self, epoch: int) -> None:
        win, comm = self.snapshots.get(epoch, ((), frozenset()))
        self.window = list(win)
        self.committed = set(comm)
        self.absorbed = set()
        self.pending = 0
        self.epoch = epoch


def check_protocol_trace(events: List[dict], *,
                         window_size: int = 65536) -> List[str]:
    """Replay ``events``; returns violation strings (empty == conformant)."""
    out: List[str] = []
    m = _Mirror(window_size)
    m.snapshot()  # epoch 0, empty state

    def bad(i: int, ev: dict, msg: str) -> None:
        out.append(f"event {i} {ev.get('ev')}: {msg}")

    booted = False
    for i, ev in enumerate(events):
        kind = ev.get("ev")
        if kind in ("deliver", "feed", "checkpoint", "ack", "compact",
                    "recover") and m.dead and kind != "recover":
            bad(i, ev, "worker event after a crash marker and before recover")
            continue
        if kind == "recover":
            epoch = int(ev.get("epoch", 0))
            if booted:
                floor = m.epoch - m.corrupts_since_boot
                if epoch > m.epoch:
                    bad(i, ev, f"recovered to epoch {epoch} past the last "
                               f"committed epoch {m.epoch}")
                elif epoch < max(floor, m.acked_epoch):
                    bad(i, ev, f"recovered to epoch {epoch}, below the "
                               f"boundary (committed {m.epoch}, acked "
                               f"{m.acked_epoch}, {m.corrupts_since_boot} "
                               f"injected corruptions)")
            ce = ev.get("chain_epoch")
            m.restore(min(epoch, m.epoch) if booted else epoch)
            m.epoch = epoch
            m.chain_epoch = int(ce) if ce is not None else None
            m.dead = False
            m.corrupts_since_boot = 0
            m.snapshot()
            booted = True
        elif kind == "deliver":
            msg = ev.get("msg")
            if ev.get("mismatch"):
                # partition-header defense: rejected, counted, acked at the
                # epoch — but NEVER absorbed, so the mirror state is
                # untouched (an absorb here would be the mutant's bug)
                if msg is not None:
                    m.seen.add(msg)
                continue
            dedup = bool(ev.get("dedup"))
            in_window = msg in m.window
            if dedup and not in_window:
                bad(i, ev, f"deduped {msg!r} which is NOT in the dedup "
                           f"window mirror")
            if not dedup:
                if in_window:
                    bad(i, ev, f"absorbed {msg!r} which IS in the dedup "
                               f"window (should have been deduped)")
                if msg in m.committed:
                    bad(i, ev, f"re-absorbed {msg!r} whose effect is "
                               f"already durable (double effect)")
                if msg is not None:
                    m.window.append(msg)
                    if len(m.window) > m.window_size:
                        m.window.pop(0)
                    m.absorbed.add(msg)
                if ev.get("tx"):
                    m.pending += 1
            if ev.get("redelivered") and msg not in m.seen:
                # spool redelivered flags are a persisted high-water mark,
                # so a missing flag is fine — a flag on a never-delivered
                # message is not
                bad(i, ev, f"{msg!r} flagged redelivered but never "
                           f"delivered before")
            if msg is not None:
                m.seen.add(msg)
        elif kind == "feed":
            n = int(ev.get("n", 0))
            if n > m.pending:
                bad(i, ev, f"fed {n} lines but only {m.pending} pending")
            m.pending = max(0, m.pending - n)
        elif kind == "checkpoint":
            if not ev.get("ok", True):
                continue  # failed write: no state change, tokens kept
            handoff = bool(ev.get("handoff"))
            epoch = ev.get("epoch")
            if epoch is not None:
                epoch = int(epoch)
                if epoch != m.epoch + 1:
                    bad(i, ev, f"epoch jumped {m.epoch} -> {epoch} "
                               f"(must be +1 monotonic)")
                if m.pending:
                    bad(i, ev, f"committed epoch {epoch} with {m.pending} "
                               f"undrained pending-feed lines (tokens "
                               f"would ack effects not in the snapshot)")
                m.epoch = epoch
            ce = ev.get("chain_epoch")
            if ce is not None:
                ce = int(ce)
                if handoff:
                    # a handoff commit rewrites the BASE at the current
                    # tail (sync compaction) — the chain epoch must NOT
                    # advance (rows moved wholesale; a delta cannot carry
                    # that, and an advancing epoch here would mean one did)
                    if m.chain_epoch is not None and ce != m.chain_epoch:
                        bad(i, ev, f"handoff commit moved the chain epoch "
                                   f"{m.chain_epoch} -> {ce} (must rewrite "
                                   f"the base in place)")
                elif m.chain_epoch is not None and ce != m.chain_epoch + 1:
                    bad(i, ev, f"chain epoch jumped {m.chain_epoch} -> {ce}")
                m.chain_epoch = ce
            m.committed |= m.absorbed
            m.absorbed = set()
            m.snapshot()
        elif kind == "handoff_export":
            if int(ev.get("unacked", 0)) != 0:
                bad(i, ev, f"handoff export with {ev.get('unacked')} unacked "
                           f"deliveries (quiesce violated)")
            if m.pending:
                bad(i, ev, f"handoff export with {m.pending} undrained "
                           f"pending-feed lines")
            ids = set(ev.get("ids") or ())
            missing = ids - set(m.window)
            if missing:
                bad(i, ev, f"exported {len(missing)} window ids the mirror "
                           f"never absorbed (first: {sorted(missing)[0]!r})")
            m.window = [x for x in m.window if x not in ids]
            m.committed -= ids
            m.absorbed -= ids
        elif kind in ("handoff_import", "handoff_abort"):
            ids = list(ev.get("ids") or ())
            if kind == "handoff_import":
                for x in ids:
                    if x not in m.window:
                        m.window.append(x)
                        if len(m.window) > m.window_size:
                            m.window.pop(0)
                m.committed |= set(ids)
            else:
                drop = set(ids)
                m.window = [x for x in m.window if x not in drop]
                m.committed -= drop
        elif kind == "ack":
            epoch = int(ev.get("epoch", -1))
            if epoch != m.epoch:
                bad(i, ev, f"acked epoch {epoch} but the last committed "
                           f"checkpoint is epoch {m.epoch} "
                           f"(ack-after-checkpoint violated)")
            m.acked_epoch = max(m.acked_epoch, epoch)
        elif kind == "compact":
            ce = ev.get("chain_epoch")
            if ce is not None and m.chain_epoch is not None \
                    and int(ce) > m.chain_epoch:
                bad(i, ev, f"compaction at chain epoch {ce} beyond the "
                           f"committed tail {m.chain_epoch}")
        elif kind == "crash":
            m.dead = True
        elif kind == "corrupt":
            m.corrupts_since_boot += 1
    return out


def check_fleet_trace(events: List[dict], *, n_shards: Optional[int] = None,
                      base: str = "transactions") -> List[str]:
    """Replay MERGED shard logs (each event carrying ``shard``, plus the
    harness's ``rebalance``/``crash`` markers) against the fleet-level
    invariants of the sharded-epoch model (shardmodel.py):

    - **fleet exactly-once**: no message's effect becomes durable on two
      shards. Per-shard absorbs are provisional until that shard's next
      successful ``checkpoint``; a ``crash`` discards its provisional set
      (the implementation rolls those effects back at recovery, proven
      bit-identical by the chaos tier).
    - **owner-locality of consumption**: a shard only takes deliveries
      from partition queues it currently owns — initially the striped map
      ``p % n_shards`` (identity when ``n_shards`` is not given, the
      legacy P == N call shape), then per completed ``handoff_import``.
    - **quiesced handoff pairing**: every ``handoff_import`` matches the
      latest ``handoff_export`` of that partition (same id set), nothing
      consumes the partition queue between the two, and exports state
      ``unacked == 0``.

    Events are merged by wall clock (one host; the harness phases are
    coarse enough that clock skew cannot reorder a handoff pair).
    """
    out: List[str] = []

    def bad(i: int, ev: dict, msg: str) -> None:
        out.append(f"event {i} {ev.get('ev')} (s{ev.get('shard')}): {msg}")

    owner: Dict[int, int] = {}  # partition -> shard
    in_flight: Dict[int, tuple] = {}  # partition -> (from_shard, ids)
    committed: Dict[str, int] = {}  # msg -> shard whose effect is durable
    provisional: Dict[int, set] = {}  # shard -> absorbed-not-yet-committed

    def boot_owner(p: int) -> int:
        # the worker's fresh-boot striping (worker._initial_partitions)
        return p % n_shards if n_shards else p

    def partition_of(queue: Optional[str]) -> Optional[int]:
        prefix = f"{base}.p"
        if not queue or not queue.startswith(prefix):
            return None
        tail = queue[len(prefix):]
        return int(tail) if tail.isdigit() else None

    for i, ev in enumerate(events):
        kind = ev.get("ev")
        sh = ev.get("shard")
        if kind == "deliver":
            p = partition_of(ev.get("queue"))
            if p is not None:
                cur = owner.get(p, boot_owner(p))  # striped until a handoff lands
                if p in in_flight:
                    bad(i, ev, f"delivery from q.p{p} during its handoff "
                               f"window (released, not yet adopted)")
                elif sh is not None and cur != sh:
                    bad(i, ev, f"delivery from q.p{p} owned by s{cur}")
            if ev.get("mismatch") or ev.get("dedup"):
                continue
            msg = ev.get("msg")
            if msg is None:
                continue
            if msg in committed:
                bad(i, ev, f"absorbed {msg!r} whose effect is already "
                           f"durable on s{committed[msg]} (fleet "
                           f"exactly-once violated)")
            provisional.setdefault(sh, set()).add(msg)
        elif kind == "checkpoint" and ev.get("ok", True):
            for msg in provisional.pop(sh, set()):
                committed[msg] = sh
        elif kind == "crash":
            provisional.pop(sh, None)
        elif kind == "handoff_export":
            p = int(ev.get("partition", -1))
            ids = frozenset(ev.get("ids") or ())
            if int(ev.get("unacked", 0)) != 0:
                bad(i, ev, f"export of p{p} with a non-empty unacked ledger")
            if owner.get(p, boot_owner(p)) != sh:
                bad(i, ev, f"s{sh} exported p{p} owned by "
                           f"s{owner.get(p, boot_owner(p))}")
            in_flight[p] = (sh, ids)
        elif kind == "handoff_import":
            p = int(ev.get("partition", -1))
            ids = frozenset(ev.get("ids") or ())
            flight = in_flight.pop(p, None)
            if flight is None:
                bad(i, ev, f"import of p{p} without a pending export")
            else:
                frm, exported = flight
                if exported != ids:
                    bad(i, ev, f"import of p{p} carries {len(ids)} window "
                               f"ids but the export carried {len(exported)} "
                               f"(window dropped/forged in transit)")
                # the window's committed effects move with the rows
                for msg in exported:
                    if msg in committed:
                        committed[msg] = sh
            owner[p] = sh
        elif kind == "handoff_abort":
            p = int(ev.get("partition", -1))
            # adopter rolled back: ownership stays in flight (controller
            # must retry adopt); re-arm the export record
            ids = frozenset(ev.get("ids") or ())
            in_flight[p] = (owner.get(p, boot_owner(p)), ids)
    return out
