"""Trace conformance: real protocol event logs replayed against the models.

A model of the wrong protocol verifies the wrong thing. To pin the models
to the implementation, the worker (``tpuEngine.protocolEventLog``) emits
one JSONL event per protocol step — ``recover`` / ``deliver`` / ``feed``
/ ``checkpoint`` / ``ack`` / ``compact`` — and the chaos harness appends
``crash`` / ``corrupt`` markers at its kill−9 / hostile-storage
injection points. :func:`check_protocol_trace` replays such a log as a
path of the ALO + delta-chain models: a deterministic mirror of the
dedup window, the epoch/chain watermarks, and the pending feed buffer
steps through the events and reports every transition the models do not
allow. An empty report means the run WAS a model path; a non-empty one
means either a protocol regression in the implementation or model drift
— both gate failures.

The rules enforced (each cites the model transition it mirrors):

- ``deliver(dedup=True)`` only for a message currently in the window
  mirror; ``deliver(dedup=False)`` never for one that is (alo._receive);
- a message whose effect is already durable is never re-absorbed
  (no-double-effect);
- ``checkpoint(ok)`` epochs are exactly +1 monotonic, and the pending
  feed buffer is EMPTY at every commit (drain-before-commit);
- delta-chain ``chain_epoch`` advances by exactly 1 per commit;
- ``ack`` follows a successful checkpoint of the same epoch, with no
  crash between (ack-after-checkpoint);
- no worker events between a ``crash`` marker and the next ``recover``;
- ``recover`` lands exactly on the last committed epoch — or, with
  hostile-storage ``corrupt`` markers since the last boot, at most that
  many epochs earlier, and never below the last ACKED epoch
  (recovery-stops-at-last-committed-boundary + ack-implies-durable);
- a ``redelivered`` flag only on messages that were delivered before.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional


def read_event_log(path: str) -> List[dict]:
    """Parse a protocol event log; a torn final line (the crash case the
    log exists to capture) is tolerated and dropped."""
    events: List[dict] = []
    try:
        with open(path, "r", encoding="utf-8") as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    events.append(json.loads(line))
                except ValueError:
                    continue  # torn tail from a SIGKILL mid-write
    except OSError:
        pass
    return events


class _Mirror:
    """Deterministic replay state: the model variables reconstructible
    from the event stream."""

    def __init__(self, window_size: int):
        self.window_size = window_size
        self.window: List[str] = []
        self.committed: set = set()  # msgs with durable effects
        self.absorbed: set = set()  # absorbed since the last commit
        self.pending = 0  # accepted-not-yet-fed tx lines
        self.epoch = 0
        self.chain_epoch: Optional[int] = None
        self.acked_epoch = 0
        self.seen: set = set()  # every msg id ever delivered
        self.dead = False
        self.corrupts_since_boot = 0
        # epoch -> (window snapshot, committed snapshot) at that commit
        self.snapshots: Dict[int, tuple] = {}

    def snapshot(self) -> None:
        self.snapshots[self.epoch] = (tuple(self.window),
                                      frozenset(self.committed))

    def restore(self, epoch: int) -> None:
        win, comm = self.snapshots.get(epoch, ((), frozenset()))
        self.window = list(win)
        self.committed = set(comm)
        self.absorbed = set()
        self.pending = 0
        self.epoch = epoch


def check_protocol_trace(events: List[dict], *,
                         window_size: int = 65536) -> List[str]:
    """Replay ``events``; returns violation strings (empty == conformant)."""
    out: List[str] = []
    m = _Mirror(window_size)
    m.snapshot()  # epoch 0, empty state

    def bad(i: int, ev: dict, msg: str) -> None:
        out.append(f"event {i} {ev.get('ev')}: {msg}")

    booted = False
    for i, ev in enumerate(events):
        kind = ev.get("ev")
        if kind in ("deliver", "feed", "checkpoint", "ack", "compact",
                    "recover") and m.dead and kind != "recover":
            bad(i, ev, "worker event after a crash marker and before recover")
            continue
        if kind == "recover":
            epoch = int(ev.get("epoch", 0))
            if booted:
                floor = m.epoch - m.corrupts_since_boot
                if epoch > m.epoch:
                    bad(i, ev, f"recovered to epoch {epoch} past the last "
                               f"committed epoch {m.epoch}")
                elif epoch < max(floor, m.acked_epoch):
                    bad(i, ev, f"recovered to epoch {epoch}, below the "
                               f"boundary (committed {m.epoch}, acked "
                               f"{m.acked_epoch}, {m.corrupts_since_boot} "
                               f"injected corruptions)")
            ce = ev.get("chain_epoch")
            m.restore(min(epoch, m.epoch) if booted else epoch)
            m.epoch = epoch
            m.chain_epoch = int(ce) if ce is not None else None
            m.dead = False
            m.corrupts_since_boot = 0
            m.snapshot()
            booted = True
        elif kind == "deliver":
            msg = ev.get("msg")
            dedup = bool(ev.get("dedup"))
            in_window = msg in m.window
            if dedup and not in_window:
                bad(i, ev, f"deduped {msg!r} which is NOT in the dedup "
                           f"window mirror")
            if not dedup:
                if in_window:
                    bad(i, ev, f"absorbed {msg!r} which IS in the dedup "
                               f"window (should have been deduped)")
                if msg in m.committed:
                    bad(i, ev, f"re-absorbed {msg!r} whose effect is "
                               f"already durable (double effect)")
                if msg is not None:
                    m.window.append(msg)
                    if len(m.window) > m.window_size:
                        m.window.pop(0)
                    m.absorbed.add(msg)
                if ev.get("tx"):
                    m.pending += 1
            if ev.get("redelivered") and msg not in m.seen:
                # spool redelivered flags are a persisted high-water mark,
                # so a missing flag is fine — a flag on a never-delivered
                # message is not
                bad(i, ev, f"{msg!r} flagged redelivered but never "
                           f"delivered before")
            if msg is not None:
                m.seen.add(msg)
        elif kind == "feed":
            n = int(ev.get("n", 0))
            if n > m.pending:
                bad(i, ev, f"fed {n} lines but only {m.pending} pending")
            m.pending = max(0, m.pending - n)
        elif kind == "checkpoint":
            if not ev.get("ok", True):
                continue  # failed write: no state change, tokens kept
            epoch = ev.get("epoch")
            if epoch is not None:
                epoch = int(epoch)
                if epoch != m.epoch + 1:
                    bad(i, ev, f"epoch jumped {m.epoch} -> {epoch} "
                               f"(must be +1 monotonic)")
                if m.pending:
                    bad(i, ev, f"committed epoch {epoch} with {m.pending} "
                               f"undrained pending-feed lines (tokens "
                               f"would ack effects not in the snapshot)")
                m.epoch = epoch
            ce = ev.get("chain_epoch")
            if ce is not None:
                ce = int(ce)
                if m.chain_epoch is not None and ce != m.chain_epoch + 1:
                    bad(i, ev, f"chain epoch jumped {m.chain_epoch} -> {ce}")
                m.chain_epoch = ce
            m.committed |= m.absorbed
            m.absorbed = set()
            m.snapshot()
        elif kind == "ack":
            epoch = int(ev.get("epoch", -1))
            if epoch != m.epoch:
                bad(i, ev, f"acked epoch {epoch} but the last committed "
                           f"checkpoint is epoch {m.epoch} "
                           f"(ack-after-checkpoint violated)")
            m.acked_epoch = max(m.acked_epoch, epoch)
        elif kind == "compact":
            ce = ev.get("chain_epoch")
            if ce is not None and m.chain_epoch is not None \
                    and int(ce) > m.chain_epoch:
                bad(i, ev, f"compaction at chain epoch {ce} beyond the "
                           f"committed tail {m.chain_epoch}")
        elif kind == "crash":
            m.dead = True
        elif kind == "corrupt":
            m.corrupts_since_boot += 1
    return out
