"""Model of pod-scale sharded epochs — the ROADMAP spine, pre-verified.

N workers consume service-hash partitions of the ``transactions`` queue
(the producer shards by service key; one transport queue per partition —
P >= N partitions, striped ``p % N`` at boot, so a rebalance moves a fine
grain instead of half a shard's keyspace), each running its OWN
at-least-once epoch cycle with a per-shard dedup window and per-shard
delta chain. The fleet-level invariants certified before the code ships:

- **fleet-exactly-once**: every message's effect lands in durable state
  exactly once across ALL shards (a per-shard dedup window cannot see
  another shard's absorbs — routing discipline is what keeps the windows
  sufficient); a handoff file in flight counts as a durable location.
- **owner-locality** (at quiescence): the effect lives on the shard that
  owns the message's partition under the final map — reads/serving hit
  the owner, so an effect stranded on a previous owner is a lost write.
- **bounded-consecutive-moves** (policy mode): the controller never
  issues two moves off one stale scrape (a rebalance storm), and a moved
  partition never immediately returns to the shard it just left (a
  rebalance oscillation / ping-pong).

The per-shard cycle is deliberately coarser than alo.py (atomic
persist+ack commit, no feed buffer): those interleavings are verified
there; this model isolates what sharding ADDS — routing, redelivery
across ownership changes, and the rebalance protocol. A correct rebalance
of partition p from shard a to b is modeled as the quiesced handoff the
per-shard chain manifests enable (parallel/checkpoint.py orbax meta):
wait until a has NO unacked deliveries, then move p's ownership together
with its dedup-window entries and its rows of durable/volatile state.

**Policy mode** (``policy=True``) replaces the oracle rebalance with the
automatic controller of ``parallel/rebalancer.py`` as a transition
system — moves are CHOSEN by watermark state, not by an adversary:

- ``scrape``: the controller refreshes its view — per-partition loads
  plus the partition→shard attribution AS OF the scrape (metrics are a
  snapshot; the controller's world is always slightly stale).
- ``release(p: a->b)``: fires only when the VIEW says donor load >= the
  high watermark, recipient load <= the low watermark, the gap STRICTLY
  exceeds the moved partition's load (the hysteresis band: the move must
  strictly improve balance), the partition is re-armed (it has not moved
  since its queue was last touched — the per-partition move budget), and
  the cooldown window has passed (at most one move per scrape). The
  release exports p's rows + window into an in-flight handoff record and
  drops them from the donor (release commit) — NOBODY owns p's queue
  until the adopt or abort lands.
- ``adopt``: the recipient imports the in-flight record (import commit).
- ``abort``: the adopter never saw the handoff file — the releaser
  re-adopts its OWN export (the controller's abort path); ownership and
  state return to the donor, the record is garbage.

Mutations: ``rebalance_mid_epoch`` (ownership moves while deliveries are
in flight, no handoff — the original shard absorbs and commits a message
whose redelivery the new owner also absorbs), ``rebalance_drops_window``
(state rows move but the dedup window does not — redelivered messages
look fresh to the new owner), ``partition_header_mismatch`` (the producer
stamps/routes by a wrong partition hash — one drifted partitioner build
in a fleet — so a message lands on a queue whose owner is not the
service's owner; its effect strands off-owner and serving reads miss it),
``rebalance_storm`` (policy mode: the cooldown is gone — the controller
acts twice on ONE stale scrape, moving partitions off a donor that its
own first move already fixed), ``rebalance_oscillation`` (policy mode:
hysteresis is gone — the band admits zero-improvement moves and a
just-moved partition immediately re-qualifies, so it ping-pongs between
two shards forever).

IMPLEMENTED by ``parallel/fleet.py`` + ``runtime/worker.py`` (PR 9) and
``parallel/rebalancer.py`` (ISSUE 18), kept in sync per the README
"verifying a protocol change" workflow: publish =
``FleetPartitioner.write_line`` (stable FNV-1a ``service_partition`` over
``fleet.partitions`` >= ``fleet.shards``, partition id stamped in
headers); the per-shard cycle = the fleet-mode ``WorkerApp`` epoch cycle
with per-queue ``_DedupWindow``s; the quiesced rebalance =
``WorkerApp.release_partition`` (pause → commit+ack until the ledger is
empty → export rows+window → drop → release commit) then
``WorkerApp.adopt_partition`` (import rows+window → import commit →
consume), the two commits being the linearization points; the abort =
the controller re-issuing the adopt TO THE RELEASER with its own export
(``RebalanceController._abort_move``). The policy clauses map 1:1 onto
``rebalancer.decide``: scrape = the controller's metrics read, the
watermarks/band/cooldown/re-arm are ``fleet.rebalance.*`` config. The
header-mismatch defense in ``_consume_at_least_once`` (reject + count,
never absorb) is why the mismatch mutant's violation cannot happen in
the live fleet.
"""

from __future__ import annotations

from collections import namedtuple
from typing import Iterator, Optional, Tuple

# pmap:    partition -> owning shard (-1 while in flight during a handoff)
# queues:  per-partition FIFO of msg ids
# ledgers: per-shard tuple of (gen, msg) unacked deliveries
# gens:    per-shard broker connection generation
# windows/pwindows: per-shard dedup windows (in-memory / persisted)
# vol/dur: per-shard per-msg effect counts
# crashes/bounces/dups/rebalances: remaining budgets
# view:    controller's last-scraped per-partition loads (policy mode)
# vmap:    partition->shard attribution AS OF that scrape (policy mode)
# cool:    cooldown — scrapes required before the next move may fire
# streak:  moves issued since the last scrape (storm detector)
# lastmove: (p, frm, to) of the last policy move, (-1, -1, -1) when the
#          moved partition's queue has been touched since (re-armed)
# pingpong: latched True when a policy move exactly reverses lastmove
# inflight: () or one (p, frm, to, win, pwin, rows) handoff record
S = namedtuple(
    "S",
    "sent pmap queues ledgers gens windows pwindows tokens vol dur "
    "crashes bounces dups rebalances view vmap cool streak lastmove "
    "pingpong inflight",
)

_MUTATIONS = frozenset({"rebalance_mid_epoch", "rebalance_drops_window",
                        "partition_header_mismatch", "rebalance_storm",
                        "rebalance_oscillation"})
_POLICY_MUTATIONS = frozenset({"rebalance_storm", "rebalance_oscillation"})

_NO_MOVE = (-1, -1, -1)


class ShardedEpochModel:
    def __init__(self, *, n_shards: int = 2, n_msgs: int = 3,
                 n_partitions: Optional[int] = None,
                 window: Optional[int] = None, crashes: int = 1,
                 bounces: int = 1, dups: int = 1, rebalances: int = 1,
                 policy: bool = False, high: int = 1, low: int = 0,
                 cooldown: int = 1,
                 mutations: Tuple[str, ...] = ()):
        bad = set(mutations) - _MUTATIONS
        if bad:
            raise ValueError(f"unknown mutations: {sorted(bad)}")
        if set(mutations) & _POLICY_MUTATIONS and not policy:
            raise ValueError(
                "rebalance_storm/rebalance_oscillation are policy-mode "
                "mutations (pass policy=True)")
        self.k = n_shards
        self.n = n_msgs
        self.np = n_shards if n_partitions is None else n_partitions
        if self.np < self.k:
            raise ValueError("n_partitions must be >= n_shards")
        self.w = n_msgs if window is None else window
        self.crashes = crashes
        self.bounces = bounces
        self.dups = dups
        self.rebalances = rebalances
        self.policy = policy
        self.high = high
        self.low = low
        self.cooldown = cooldown
        self.mut = frozenset(mutations)
        self.name = "sharded-epochs" + ("+policy" if policy else "") + (
            f"[{'+'.join(sorted(self.mut))}]" if self.mut else "")
        self.scope = {
            "shards": n_shards, "partitions": self.np, "msgs": n_msgs,
            "window": self.w, "crashes": crashes, "bounces": bounces,
            "dups": dups, "rebalances": rebalances,
        }
        if policy:
            self.scope.update(policy=True, high=high, low=low,
                              cooldown=cooldown)

    def part(self, m: int) -> int:
        """The service-hash partition of message m (P >= N partitions)."""
        return m % self.np

    def initial(self) -> S:
        zrow = (0,) * self.n
        return S(
            sent=0,
            # striped boot ownership: partition p belongs to shard p % N
            # (identity when P == N) — worker._initial_partitions
            pmap=tuple(p % self.k for p in range(self.np)),
            queues=((),) * self.np,
            ledgers=((),) * self.k,
            gens=(0,) * self.k,
            windows=((),) * self.k,
            pwindows=((),) * self.k,
            tokens=((),) * self.k,
            vol=(zrow,) * self.k,
            dur=(zrow,) * self.k,
            crashes=self.crashes, bounces=self.bounces, dups=self.dups,
            rebalances=self.rebalances,
            view=(0,) * self.np if self.policy else (),
            vmap=tuple(p % self.k for p in range(self.np))
            if self.policy else (),
            cool=0, streak=0, lastmove=_NO_MOVE, pingpong=False,
            inflight=(),
        )

    # -- tuple surgery -------------------------------------------------------
    @staticmethod
    def _set(t: tuple, i: int, v) -> tuple:
        return t[:i] + (v,) + t[i + 1:]

    @classmethod
    def _bump(cls, mat: tuple, sh: int, m: int) -> tuple:
        row = mat[sh]
        return cls._set(mat, sh, cls._set(row, m, min(2, row[m] + 1)))

    def _rearm(self, s: S, p: int) -> S:
        """Partition p's queue was touched (publish/deliver/requeue): the
        controller's per-partition move budget re-arms — a later move of p
        is adaptation to new load, not oscillation."""
        if s.lastmove != _NO_MOVE and s.lastmove[0] == p:
            return s._replace(lastmove=_NO_MOVE)
        return s

    def _receive(self, s: S, sh: int, m: int, token) -> S:
        """Delivery (or chaos dup) reaching shard ``sh``'s worker."""
        if m in s.windows[sh]:
            toks = s.tokens[sh]
            if token in toks:
                return s
            return s._replace(
                tokens=self._set(s.tokens, sh, tuple(sorted(toks + (token,)))))
        win = s.windows[sh] + (m,)
        if len(win) > self.w:
            win = win[1:]
        return s._replace(
            windows=self._set(s.windows, sh, win),
            vol=self._bump(s.vol, sh, m),
            tokens=self._set(
                s.tokens, sh, tuple(sorted(set(s.tokens[sh]) | {token}))),
        )

    def _requeue_shard(self, s: S, sh: int) -> S:
        """Shard sh's unacked deliveries go back to their partition queues
        (front, original order) — routing happens again at redelivery, per
        the CURRENT map."""
        queues = list(s.queues)
        for _g, m in reversed(s.ledgers[sh]):
            p = self.part(m)
            queues[p] = (m,) + queues[p]
            s = self._rearm(s, p)
        return s._replace(
            queues=tuple(queues),
            ledgers=self._set(s.ledgers, sh, ()),
            gens=self._set(s.gens, sh, s.gens[sh] + 1),
        )

    def _move_state(self, s: S, p: int, a: int, b: int,
                    drop_window: bool = False) -> S:
        """Atomic quiesced handoff of partition p's window entries + state
        rows from shard a to b (the oracle transition's body; the policy
        path splits it into release/adopt with an in-flight record)."""
        ns = s._replace(pmap=self._set(s.pmap, p, b))
        if not drop_window:
            moved = tuple(m for m in s.windows[a] if self.part(m) == p)
            kept = tuple(m for m in s.windows[a] if self.part(m) != p)
            ns = ns._replace(
                windows=self._set(
                    self._set(ns.windows, a, kept),
                    b, ns.windows[b] + moved))
            pmoved = tuple(m for m in s.pwindows[a] if self.part(m) == p)
            pkept = tuple(m for m in s.pwindows[a] if self.part(m) != p)
            ns = ns._replace(
                pwindows=self._set(
                    self._set(ns.pwindows, a, pkept),
                    b, ns.pwindows[b] + pmoved))
        # state-row handoff (vol == dur for p's msgs after quiesce; move
        # both so restores stay consistent)
        vol, dur = ns.vol, ns.dur
        for m in range(self.n):
            if self.part(m) != p:
                continue
            for mat_name in ("vol", "dur"):
                mat = vol if mat_name == "vol" else dur
                moved_v = min(2, mat[b][m] + mat[a][m])
                mat = self._set(mat, b, self._set(mat[b], m, moved_v))
                mat = self._set(mat, a, self._set(mat[a], m, 0))
                if mat_name == "vol":
                    vol = mat
                else:
                    dur = mat
        return ns._replace(vol=vol, dur=dur)

    # -- policy helpers ------------------------------------------------------
    def _scraped_loads(self, s: S) -> tuple:
        return tuple(len(q) for q in s.queues)

    def _view_load(self, s: S, sh: int) -> int:
        """Shard sh's load AS THE CONTROLLER SEES IT: stale per-partition
        loads attributed by the stale ownership map — exactly what a
        /metrics scrape yields (rebalancer.observe_fleet)."""
        return sum(s.view[p] for p in range(self.np) if s.vmap[p] == sh)

    def _policy_actions(self, s: S, out) -> None:
        # scrape: refresh the view (loads + attribution), tick the
        # cooldown down, reset the per-scrape move streak
        loads = self._scraped_loads(s)
        ns = s._replace(view=loads, vmap=s.pmap, cool=max(0, s.cool - 1),
                        streak=0)
        if ns != s:
            out.append(("scrape", ns))

        storm = "rebalance_storm" in self.mut
        wobble = "rebalance_oscillation" in self.mut

        # adopt / abort of the in-flight handoff record
        if s.inflight:
            p, a, b, win, pwin, rows = s.inflight
            ns = s._replace(pmap=self._set(s.pmap, p, b), inflight=())
            ns = ns._replace(
                windows=self._set(ns.windows, b, ns.windows[b] + win),
                pwindows=self._set(ns.pwindows, b, ns.pwindows[b] + pwin))
            vol, dur = ns.vol, ns.dur
            for m, cnt in rows:
                vol = self._set(
                    vol, b, self._set(vol[b], m, min(2, vol[b][m] + cnt)))
                dur = self._set(
                    dur, b, self._set(dur[b], m, min(2, dur[b][m] + cnt)))
            out.append((f"adopt(q{p}->s{b})", ns._replace(vol=vol, dur=dur)))
            # abort: the adopter never saw the file — the RELEASER
            # re-adopts its own export; ownership returns to the donor
            ns = s._replace(pmap=self._set(s.pmap, p, a), inflight=())
            ns = ns._replace(
                windows=self._set(ns.windows, a, ns.windows[a] + win),
                pwindows=self._set(ns.pwindows, a, ns.pwindows[a] + pwin))
            vol, dur = ns.vol, ns.dur
            for m, cnt in rows:
                vol = self._set(
                    vol, a, self._set(vol[a], m, min(2, vol[a][m] + cnt)))
                dur = self._set(
                    dur, a, self._set(dur[a], m, min(2, dur[a][m] + cnt)))
            out.append((f"abort(q{p}->s{a})",
                        ns._replace(vol=vol, dur=dur)))
            return  # one move at a time: no new release while in flight

        if s.rebalances <= 0:
            return
        if s.cool > 0 and not storm:
            return  # cooldown: at most one move per scrape window
        for p in range(self.np):
            a = s.pmap[p]
            if a < 0 or s.vmap[p] != a:
                continue  # controller's stale owner is wrong: release fails
            if s.ledgers[a]:
                continue  # release quiesces first (worker-side protocol)
            lp = s.view[p]
            if lp < 1:
                continue
            if not wobble and s.lastmove != _NO_MOVE and s.lastmove[0] == p:
                continue  # hysteresis re-arm: p moved and was not touched
            va = self._view_load(s, a)
            if va < self.high:
                continue
            for b in range(self.k):
                if b == a:
                    continue
                vb = self._view_load(s, b)
                if vb > self.low:
                    continue
                gap = va - vb
                # hysteresis band: the move must STRICTLY improve the
                # balance; the oscillation mutant admits the equality
                # case, where the move just relocates the imbalance
                if (gap >= lp) if wobble else (gap > lp):
                    # the releaser QUIESCES first: save_state until
                    # nothing is pending — a commit (dur:=vol, window
                    # persisted, tokens acked) happens INSIDE the
                    # release, so uncommitted volatile effects travel
                    # with the export instead of stranding on the donor
                    sa = s._replace(
                        dur=self._set(s.dur, a, s.vol[a]),
                        pwindows=self._set(s.pwindows, a, s.windows[a]),
                        tokens=self._set(s.tokens, a, ()))
                    win = tuple(m for m in sa.windows[a]
                                if self.part(m) == p)
                    kept = tuple(m for m in sa.windows[a]
                                 if self.part(m) != p)
                    pwin = tuple(m for m in sa.pwindows[a]
                                 if self.part(m) == p)
                    pkept = tuple(m for m in sa.pwindows[a]
                                  if self.part(m) != p)
                    rows = tuple(
                        (m, sa.dur[a][m]) for m in range(self.n)
                        if self.part(m) == p and sa.dur[a][m])
                    vol, dur = sa.vol, sa.dur
                    for m, _c in rows:
                        vol = self._set(
                            vol, a, self._set(vol[a], m, 0))
                        dur = self._set(
                            dur, a, self._set(dur[a], m, 0))
                    ns = sa._replace(
                        rebalances=s.rebalances - 1,
                        pmap=self._set(s.pmap, p, -1),
                        windows=self._set(s.windows, a, kept),
                        pwindows=self._set(s.pwindows, a, pkept),
                        vol=vol, dur=dur,
                        inflight=(p, a, b, win, pwin, rows),
                        cool=0 if storm else self.cooldown,
                        streak=s.streak + 1,
                        pingpong=s.pingpong or s.lastmove == (p, b, a),
                        lastmove=(p, a, b),
                    )
                    out.append((f"release(q{p}:s{a}->s{b})", ns))

    # -- transition relation -------------------------------------------------
    def actions(self, s: S) -> Iterator[Tuple[str, S]]:
        out = []
        if s.sent < self.n:
            m = s.sent
            p = self.part(m)
            if "partition_header_mismatch" in self.mut:
                # a drifted producer stamps (and therefore routes by) the
                # wrong partition: the message reaches a queue whose owner
                # is NOT the owner of the service's real partition
                p = (p + 1) % self.np
            ns = self._rearm(s, p)
            out.append((f"publish(m{m}->q{p})", ns._replace(
                sent=s.sent + 1,
                queues=self._set(s.queues, p, s.queues[p] + (m,)))))

        for sh in range(self.k):
            # deliver: shard sh pops the front of a partition queue it owns
            if len(s.ledgers[sh]) < self.w:
                for p in range(self.np):
                    if s.pmap[p] != sh or not s.queues[p]:
                        continue
                    m, rest = s.queues[p][0], s.queues[p][1:]
                    token = (s.gens[sh], m)
                    ns = self._rearm(s, p)
                    ns = ns._replace(
                        queues=self._set(s.queues, p, rest),
                        ledgers=self._set(s.ledgers, sh, s.ledgers[sh] + (token,)))
                    out.append((f"deliver(m{m}->s{sh})",
                                self._receive(ns, sh, m, token)))
            # chaos duplicate of an in-flight delivery on this shard
            if s.dups > 0:
                for g, m in s.ledgers[sh]:
                    ns = self._receive(s._replace(dups=s.dups - 1), sh, m, (g, m))
                    out.append((f"dup(m{m}->s{sh})", ns))
            # epoch commit: persist state + window, ack the epoch (atomic
            # here — the persist/ack interleavings are alo.py's job)
            if s.tokens[sh] or s.vol[sh] != s.dur[sh] \
                    or s.windows[sh] != s.pwindows[sh]:
                toks = set(s.tokens[sh])
                ns = s._replace(
                    dur=self._set(s.dur, sh, s.vol[sh]),
                    pwindows=self._set(s.pwindows, sh, s.windows[sh]),
                    ledgers=self._set(
                        s.ledgers, sh,
                        tuple(e for e in s.ledgers[sh] if e not in toks)),
                    tokens=self._set(s.tokens, sh, ()),
                )
                out.append((f"commit(s{sh})", ns))
            # kill −9 + restart of one shard worker
            if s.crashes > 0:
                ns = s._replace(
                    crashes=s.crashes - 1,
                    vol=self._set(s.vol, sh, s.dur[sh]),
                    windows=self._set(s.windows, sh, s.pwindows[sh]),
                    tokens=self._set(s.tokens, sh, ()),
                )
                out.append((f"crash(s{sh})", self._requeue_shard(ns, sh)))

        # broker bounce: every shard's unacked deliveries requeue; workers
        # keep their volatile state and stale tokens
        if s.bounces > 0:
            ns = s._replace(bounces=s.bounces - 1)
            for sh in range(self.k):
                ns = self._requeue_shard(ns, sh)
            out.append(("bounce", ns))

        if self.policy:
            # the watermark controller chooses the moves (release/adopt/
            # abort + scrape); the oracle transition below is disabled
            self._policy_actions(s, out)
            return out

        # rebalance: partition p moves a -> b. The CORRECT protocol is a
        # quiesced handoff: a has nothing unacked, and p's dedup-window
        # entries + state rows move with the ownership (per-shard chain
        # manifest handoff). The mutants break exactly those two clauses.
        if s.rebalances > 0:
            for p in range(self.np):
                a = s.pmap[p]
                for b in range(self.k):
                    if b == a:
                        continue
                    mid_epoch = "rebalance_mid_epoch" in self.mut
                    if s.ledgers[a] and not mid_epoch:
                        continue  # not quiesced: handoff must wait
                    ns = s._replace(rebalances=s.rebalances - 1)
                    if mid_epoch:
                        ns = ns._replace(pmap=self._set(ns.pmap, p, b))
                    else:
                        ns = self._move_state(
                            ns, p, a, b,
                            drop_window="rebalance_drops_window" in self.mut)
                    out.append((f"rebalance(q{p}:s{a}->s{b})", ns))
        return out

    # -- invariants ----------------------------------------------------------
    def invariant(self, s: S) -> Optional[str]:
        for m in range(self.n):
            total = sum(s.dur[sh][m] for sh in range(self.k))
            if s.inflight:
                total += sum(c for mm, c in s.inflight[5] if mm == m)
            if total >= 2:
                where = ",".join(
                    f"s{sh}" for sh in range(self.k) if s.dur[sh][m])
                return (f"m{m} effected {total}x across shards [{where}] "
                        f"(fleet exactly-once violated)")
        if self.policy:
            if s.streak > 1:
                return (f"{s.streak} consecutive moves off ONE stale "
                        f"scrape (rebalance storm: bounded-consecutive-"
                        f"moves violated — no cooldown between decisions)")
            if s.pingpong:
                p, a, b = s.lastmove
                return (f"partition q{p} ping-ponged straight back "
                        f"s{a}->s{b} with its queue untouched (rebalance "
                        f"oscillation: hysteresis violated)")
        # owner-locality at quiescence: everything delivered, absorbed,
        # committed and acked — effects must sit on the owning shard
        quiescent = (
            s.sent == self.n
            and not any(s.queues) and not any(s.ledgers)
            and not any(s.tokens)
            and s.vol == s.dur
            and not s.inflight
        )
        if quiescent:
            for m in range(self.n):
                owner = s.pmap[self.part(m)]
                if s.dur[owner][m] != 1 and sum(
                        s.dur[sh][m] for sh in range(self.k)) == 1:
                    holder = next(
                        sh for sh in range(self.k) if s.dur[sh][m])
                    return (f"m{m}'s effect is stranded on s{holder} but "
                            f"partition q{self.part(m)} is owned by "
                            f"s{owner} (owner-locality violated: serving "
                            f"reads miss the write)")
        return None

    def describe(self, s: S) -> str:
        qs = " ".join(
            f"q{p}[{','.join(f'm{m}' for m in q)}]->"
            f"{'~' if s.pmap[p] < 0 else f's{s.pmap[p]}'}"
            for p, q in enumerate(s.queues))
        shards = " ".join(
            f"s{sh}(led={len(s.ledgers[sh])} win=[{','.join(f'm{m}' for m in s.windows[sh])}] "
            f"vol={''.join(str(c) for c in s.vol[sh])} "
            f"dur={''.join(str(c) for c in s.dur[sh])})"
            for sh in range(self.k))
        pol = ""
        if self.policy:
            pol = (f" view={','.join(str(v) for v in s.view)} "
                   f"cool={s.cool} streak={s.streak}")
            if s.inflight:
                p, a, b = s.inflight[:3]
                pol += f" inflight(q{p}:s{a}->s{b})"
        return f"sent={s.sent} {qs} {shards}{pol}"
