"""Model of pod-scale sharded epochs — the ROADMAP spine, pre-verified.

N workers consume service-hash partitions of the ``transactions`` queue
(the producer shards by service key; one transport queue per partition),
each running its OWN at-least-once epoch cycle with a per-shard dedup
window and per-shard delta chain. The fleet-level invariant the pod-scale
item needs certified before it is built:

- **fleet-exactly-once**: every message's effect lands in durable state
  exactly once across ALL shards (a per-shard dedup window cannot see
  another shard's absorbs — routing discipline is what keeps the windows
  sufficient);
- **owner-locality** (at quiescence): the effect lives on the shard that
  owns the message's partition under the final map — reads/serving hit
  the owner, so an effect stranded on a previous owner is a lost write.

The per-shard cycle is deliberately coarser than alo.py (atomic
persist+ack commit, no feed buffer): those interleavings are verified
there; this model isolates what sharding ADDS — routing, redelivery
across ownership changes, and the rebalance protocol. A correct rebalance
of partition p from shard a to b is modeled as the quiesced handoff the
per-shard chain manifests enable (parallel/checkpoint.py orbax meta):
wait until a has NO unacked deliveries, then move p's ownership together
with its dedup-window entries and its rows of durable/volatile state.

Mutations: ``rebalance_mid_epoch`` (ownership moves while deliveries are
in flight, no handoff — the original shard absorbs and commits a message
whose redelivery the new owner also absorbs), ``rebalance_drops_window``
(state rows move but the dedup window does not — redelivered messages
look fresh to the new owner), ``partition_header_mismatch`` (the producer
stamps/routes by a wrong partition hash — one drifted partitioner build
in a fleet — so a message lands on a queue whose owner is not the
service's owner; its effect strands off-owner and serving reads miss it).

IMPLEMENTED by ``parallel/fleet.py`` + ``runtime/worker.py`` (PR 9), kept
in sync per the README "verifying a protocol change" workflow: publish =
``FleetPartitioner.write_line`` (stable FNV-1a ``service_partition``,
partition id stamped in headers); the per-shard cycle = the fleet-mode
``WorkerApp`` epoch cycle with per-queue ``_DedupWindow``s; the quiesced
rebalance = ``WorkerApp.release_partition`` (pause → commit+ack until the
ledger is empty → export rows+window → drop → release commit) then
``WorkerApp.adopt_partition`` (import rows+window → import commit →
consume), the two commits being the linearization points the model's
atomic ``rebalance`` transition abstracts. The header-mismatch defense in
``_consume_at_least_once`` (reject + count, never absorb) is why the
mismatch mutant's violation cannot happen in the live fleet.
"""

from __future__ import annotations

from collections import namedtuple
from typing import Iterator, Optional, Tuple

# pmap:    partition -> owning shard
# queues:  per-partition FIFO of msg ids
# ledgers: per-shard tuple of (gen, msg) unacked deliveries
# gens:    per-shard broker connection generation
# windows/pwindows: per-shard dedup windows (in-memory / persisted)
# vol/dur: per-shard per-msg effect counts
# crashes/bounces/dups/rebalances: remaining budgets
S = namedtuple(
    "S",
    "sent pmap queues ledgers gens windows pwindows tokens vol dur "
    "crashes bounces dups rebalances",
)

_MUTATIONS = frozenset({"rebalance_mid_epoch", "rebalance_drops_window",
                        "partition_header_mismatch"})


class ShardedEpochModel:
    def __init__(self, *, n_shards: int = 2, n_msgs: int = 3,
                 window: Optional[int] = None, crashes: int = 1,
                 bounces: int = 1, dups: int = 1, rebalances: int = 1,
                 mutations: Tuple[str, ...] = ()):
        bad = set(mutations) - _MUTATIONS
        if bad:
            raise ValueError(f"unknown mutations: {sorted(bad)}")
        self.k = n_shards
        self.n = n_msgs
        self.w = n_msgs if window is None else window
        self.crashes = crashes
        self.bounces = bounces
        self.dups = dups
        self.rebalances = rebalances
        self.mut = frozenset(mutations)
        self.name = "sharded-epochs" + (
            f"[{'+'.join(sorted(self.mut))}]" if self.mut else "")
        self.scope = {
            "shards": n_shards, "msgs": n_msgs, "window": self.w,
            "crashes": crashes, "bounces": bounces, "dups": dups,
            "rebalances": rebalances,
        }

    def part(self, m: int) -> int:
        """The service-hash partition of message m."""
        return m % self.k

    def initial(self) -> S:
        zrow = (0,) * self.n
        return S(
            sent=0,
            pmap=tuple(range(self.k)),
            queues=((),) * self.k,
            ledgers=((),) * self.k,
            gens=(0,) * self.k,
            windows=((),) * self.k,
            pwindows=((),) * self.k,
            tokens=((),) * self.k,
            vol=(zrow,) * self.k,
            dur=(zrow,) * self.k,
            crashes=self.crashes, bounces=self.bounces, dups=self.dups,
            rebalances=self.rebalances,
        )

    # -- tuple surgery -------------------------------------------------------
    @staticmethod
    def _set(t: tuple, i: int, v) -> tuple:
        return t[:i] + (v,) + t[i + 1:]

    @classmethod
    def _bump(cls, mat: tuple, sh: int, m: int) -> tuple:
        row = mat[sh]
        return cls._set(mat, sh, cls._set(row, m, min(2, row[m] + 1)))

    def _receive(self, s: S, sh: int, m: int, token) -> S:
        """Delivery (or chaos dup) reaching shard ``sh``'s worker."""
        if m in s.windows[sh]:
            toks = s.tokens[sh]
            if token in toks:
                return s
            return s._replace(
                tokens=self._set(s.tokens, sh, tuple(sorted(toks + (token,)))))
        win = s.windows[sh] + (m,)
        if len(win) > self.w:
            win = win[1:]
        return s._replace(
            windows=self._set(s.windows, sh, win),
            vol=self._bump(s.vol, sh, m),
            tokens=self._set(
                s.tokens, sh, tuple(sorted(set(s.tokens[sh]) | {token}))),
        )

    def _requeue_shard(self, s: S, sh: int) -> S:
        """Shard sh's unacked deliveries go back to their partition queues
        (front, original order) — routing happens again at redelivery, per
        the CURRENT map."""
        queues = list(s.queues)
        for _g, m in reversed(s.ledgers[sh]):
            p = self.part(m)
            queues[p] = (m,) + queues[p]
        return s._replace(
            queues=tuple(queues),
            ledgers=self._set(s.ledgers, sh, ()),
            gens=self._set(s.gens, sh, s.gens[sh] + 1),
        )

    # -- transition relation -------------------------------------------------
    def actions(self, s: S) -> Iterator[Tuple[str, S]]:
        out = []
        if s.sent < self.n:
            m = s.sent
            p = self.part(m)
            if "partition_header_mismatch" in self.mut:
                # a drifted producer stamps (and therefore routes by) the
                # wrong partition: the message reaches a queue whose owner
                # is NOT the owner of the service's real partition
                p = (p + 1) % self.k
            out.append((f"publish(m{m}->q{p})", s._replace(
                sent=s.sent + 1,
                queues=self._set(s.queues, p, s.queues[p] + (m,)))))

        for sh in range(self.k):
            # deliver: shard sh pops the front of a partition queue it owns
            if len(s.ledgers[sh]) < self.w:
                for p in range(self.k):
                    if s.pmap[p] != sh or not s.queues[p]:
                        continue
                    m, rest = s.queues[p][0], s.queues[p][1:]
                    token = (s.gens[sh], m)
                    ns = s._replace(
                        queues=self._set(s.queues, p, rest),
                        ledgers=self._set(s.ledgers, sh, s.ledgers[sh] + (token,)))
                    out.append((f"deliver(m{m}->s{sh})",
                                self._receive(ns, sh, m, token)))
            # chaos duplicate of an in-flight delivery on this shard
            if s.dups > 0:
                for g, m in s.ledgers[sh]:
                    ns = self._receive(s._replace(dups=s.dups - 1), sh, m, (g, m))
                    out.append((f"dup(m{m}->s{sh})", ns))
            # epoch commit: persist state + window, ack the epoch (atomic
            # here — the persist/ack interleavings are alo.py's job)
            if s.tokens[sh] or s.vol[sh] != s.dur[sh] \
                    or s.windows[sh] != s.pwindows[sh]:
                toks = set(s.tokens[sh])
                ns = s._replace(
                    dur=self._set(s.dur, sh, s.vol[sh]),
                    pwindows=self._set(s.pwindows, sh, s.windows[sh]),
                    ledgers=self._set(
                        s.ledgers, sh,
                        tuple(e for e in s.ledgers[sh] if e not in toks)),
                    tokens=self._set(s.tokens, sh, ()),
                )
                out.append((f"commit(s{sh})", ns))
            # kill −9 + restart of one shard worker
            if s.crashes > 0:
                ns = s._replace(
                    crashes=s.crashes - 1,
                    vol=self._set(s.vol, sh, s.dur[sh]),
                    windows=self._set(s.windows, sh, s.pwindows[sh]),
                    tokens=self._set(s.tokens, sh, ()),
                )
                out.append((f"crash(s{sh})", self._requeue_shard(ns, sh)))

        # broker bounce: every shard's unacked deliveries requeue; workers
        # keep their volatile state and stale tokens
        if s.bounces > 0:
            ns = s._replace(bounces=s.bounces - 1)
            for sh in range(self.k):
                ns = self._requeue_shard(ns, sh)
            out.append(("bounce", ns))

        # rebalance: partition p moves a -> b. The CORRECT protocol is a
        # quiesced handoff: a has nothing unacked, and p's dedup-window
        # entries + state rows move with the ownership (per-shard chain
        # manifest handoff). The mutants break exactly those two clauses.
        if s.rebalances > 0:
            for p in range(self.k):
                a = s.pmap[p]
                for b in range(self.k):
                    if b == a:
                        continue
                    mid_epoch = "rebalance_mid_epoch" in self.mut
                    if s.ledgers[a] and not mid_epoch:
                        continue  # not quiesced: handoff must wait
                    ns = s._replace(
                        rebalances=s.rebalances - 1,
                        pmap=self._set(s.pmap, p, b))
                    if not mid_epoch and "rebalance_drops_window" not in self.mut:
                        moved = tuple(m for m in s.windows[a] if self.part(m) == p)
                        kept = tuple(m for m in s.windows[a] if self.part(m) != p)
                        ns = ns._replace(
                            windows=self._set(
                                self._set(ns.windows, a, kept),
                                b, ns.windows[b] + moved))
                        pmoved = tuple(m for m in s.pwindows[a] if self.part(m) == p)
                        pkept = tuple(m for m in s.pwindows[a] if self.part(m) != p)
                        ns = ns._replace(
                            pwindows=self._set(
                                self._set(ns.pwindows, a, pkept),
                                b, ns.pwindows[b] + pmoved))
                    if not mid_epoch:
                        # state-row handoff (vol == dur for p's msgs after
                        # quiesce; move both so restores stay consistent)
                        vol, dur = ns.vol, ns.dur
                        for m in range(self.n):
                            if self.part(m) != p:
                                continue
                            for mat_name in ("vol", "dur"):
                                mat = vol if mat_name == "vol" else dur
                                moved_v = min(2, mat[b][m] + mat[a][m])
                                mat = self._set(
                                    mat, b, self._set(mat[b], m, moved_v))
                                mat = self._set(
                                    mat, a, self._set(mat[a], m, 0))
                                if mat_name == "vol":
                                    vol = mat
                                else:
                                    dur = mat
                        ns = ns._replace(vol=vol, dur=dur)
                    out.append((f"rebalance(q{p}:s{a}->s{b})", ns))
        return out

    # -- invariants ----------------------------------------------------------
    def invariant(self, s: S) -> Optional[str]:
        for m in range(self.n):
            total = sum(s.dur[sh][m] for sh in range(self.k))
            if total >= 2:
                where = ",".join(
                    f"s{sh}" for sh in range(self.k) if s.dur[sh][m])
                return (f"m{m} effected {total}x across shards [{where}] "
                        f"(fleet exactly-once violated)")
        # owner-locality at quiescence: everything delivered, absorbed,
        # committed and acked — effects must sit on the owning shard
        quiescent = (
            s.sent == self.n
            and not any(s.queues) and not any(s.ledgers)
            and not any(s.tokens)
            and s.vol == s.dur
        )
        if quiescent:
            for m in range(self.n):
                owner = s.pmap[self.part(m)]
                if s.dur[owner][m] != 1 and sum(
                        s.dur[sh][m] for sh in range(self.k)) == 1:
                    holder = next(
                        sh for sh in range(self.k) if s.dur[sh][m])
                    return (f"m{m}'s effect is stranded on s{holder} but "
                            f"partition q{self.part(m)} is owned by "
                            f"s{owner} (owner-locality violated: serving "
                            f"reads miss the write)")
        return None

    def describe(self, s: S) -> str:
        qs = " ".join(
            f"q{p}[{','.join(f'm{m}' for m in q)}]->s{s.pmap[p]}"
            for p, q in enumerate(s.queues))
        shards = " ".join(
            f"s{sh}(led={len(s.ledgers[sh])} win=[{','.join(f'm{m}' for m in s.windows[sh])}] "
            f"vol={''.join(str(c) for c in s.vol[sh])} "
            f"dur={''.join(str(c) for c in s.dur[sh])})"
            for sh in range(self.k))
        return f"sent={s.sent} {qs} {shards}"
