"""The mutation tier: seeded protocol bugs the checker must catch.

A model checker that has never failed is indistinguishable from one that
checks nothing. Every entry here is a deliberately broken variant of one
protocol model — each a bug class that either HAS happened in this
codebase (``alo-dup-ack-early`` is the PR 3 one-message-loss bug, found
then by the kill−9 chaos harness by luck, replayed here as a 3-step
certainty) or is one refactor away from happening (ack-before-checkpoint,
a GC that eats the fallback generation, a mid-epoch shard rebalance).
``verify_mutants()`` requires a counterexample for every one; the tier-1
suite asserts it, so the checker's teeth are themselves regression-tested.

Each mutant's counterexample is a shortest schedule (BFS) — typically
3–10 numbered steps — which doubles as documentation of WHY the
corresponding line of production code is shaped the way it is.

``BOUNDARY_MUTANTS`` are the negative result: recovery-order variants of
the delta chain that the checker proves INDISTINGUISHABLE from the
correct protocol within the documented single-fault storage contract
(every candidate base of one linear history converges to the same tail
unless a second fault strikes the same generation). They are pinned as
still-verifying so the boundary stays explicit — the deltachain.py
recovery hardening (best-chain selection + stale-orphan cross-check)
matters only OUTSIDE that contract, and the model says so.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Tuple

from .alo import AloModel
from .checker import CheckResult, check
from .deltamodel import DeltaChainModel
from .shardmodel import ShardedEpochModel

# name -> (description, model factory). Names are stable identifiers used
# in tests, --json output, and the DESIGN.md §9.4 catalogue.
MUTANTS: Dict[str, Tuple[str, Callable[[], object]]] = {
    "alo-ack-before-checkpoint": (
        "save_state acks the epoch's tokens before the checkpoint write — "
        "a crash between them loses every message of the epoch",
        lambda: AloModel(mutations=("ack_before_persist",)),
    ),
    "alo-dup-ack-early": (
        "THE PR 3 BUG, replayed: a deduped in-flight duplicate's token is "
        "acked immediately instead of joining the epoch; the duplicate "
        "shares the original's broker ledger entry, so the ack advances "
        "the broker past an effect that is not yet durable",
        lambda: AloModel(mutations=("dup_ack_early",)),
    ),
    "alo-dedup-evict-before-commit": (
        "the persisted dedup window drops its oldest id before the epoch "
        "that absorbed it commits — a redelivered copy after restart "
        "looks fresh and double-counts",
        lambda: AloModel(mutations=("evict_on_persist",)),
    ),
    "alo-checkpoint-skips-feed-drain": (
        "the epoch commit snapshots state WITHOUT draining the pending "
        "feed buffer but still acks the buffered messages' tokens — "
        "ack-implies-durable broken at the commit itself",
        lambda: AloModel(mutations=("skip_drain",)),
    ),
    "alo-ack-on-failed-write": (
        "a failed checkpoint write (ENOSPC) acks anyway instead of "
        "keeping the tokens for redelivery",
        lambda: AloModel(mutations=("ack_on_failed_write",), wfails=1),
    ),
    "alo-dedup-window-not-restored": (
        "restart ignores the persisted dedup window (_seed_delivery "
        "skipped) — committed messages redelivered after a crash "
        "double-count",
        lambda: AloModel(mutations=("window_not_restored",)),
    ),
    "alo-requeue-at-back": (
        "the broker requeues unacked deliveries at the BACK of the queue "
        "instead of the front — newer absorbs push a committed-but-"
        "unacked id out of the bounded window before its redelivery is "
        "re-seen (why transport/memory.py front-requeues)",
        lambda: AloModel(mutations=("requeue_back",)),
    ),
    "alo-reconnect-drops-unacked": (
        "the broker-outage reconnect forgets the unacked ledger instead of "
        "redelivering it (a Redis group whose PEL is never XAUTOCLAIMed, "
        "or an AMQP reconnect that drops the old connection's deliveries "
        "on the floor) — a delivered-but-unacked message silently settles "
        "with no durable effect: loss (why transport/redis_streams.py "
        "claims idle pending on every pump, and transport/amqp.py requeues "
        "on connection death)",
        lambda: AloModel(mutations=("reconnect_drops_unacked",)),
    ),
    "dc-compaction-gc-live-base": (
        "compaction GC deletes the previous base generation and its "
        "deltas immediately — a new base that later proves unreadable "
        "has no fallback and committed (acked) epochs are gone",
        lambda: DeltaChainModel(mutations=("gc_live_base",)),
    ),
    "dc-skip-prev-uid-check": (
        "recovery accepts a tail segment whose prev_uid does not match "
        "the chain — a forged/zombie duplicate replays past the last "
        "committed boundary",
        lambda: DeltaChainModel(mutations=("skip_prev_uid",)),
    ),
    "dc-skip-crc-validation": (
        "recovery replays a torn/bit-rotted segment instead of stopping "
        "at the boundary — recovered state matches no committed state",
        lambda: DeltaChainModel(mutations=("skip_crc",)),
    ),
    "dc-commit-before-rename": (
        "append reports the epoch committed (and the worker acks) before "
        "the tmp→seg rename lands — a crash mid-write loses an acked "
        "epoch",
        lambda: DeltaChainModel(mutations=("commit_before_rename",)),
    ),
    "dc-capture-reset-on-enospc": (
        "a failed segment write drops its capture window instead of "
        "retrying a superset — the next committed delta silently misses "
        "those changes and recovery diverges",
        lambda: DeltaChainModel(
            mutations=("capture_reset_on_enospc",), enospcs=1),
    ),
    "shard-rebalance-mid-epoch": (
        "partition ownership moves while deliveries are in flight, with "
        "no state/window handoff — the old owner commits its absorb "
        "while the new owner absorbs the redelivery: one message, two "
        "durable effects",
        lambda: ShardedEpochModel(mutations=("rebalance_mid_epoch",)),
    ),
    "shard-rebalance-drops-window": (
        "the rebalance hands off state rows but not the dedup window — "
        "redelivered messages look fresh to the new owner",
        lambda: ShardedEpochModel(mutations=("rebalance_drops_window",)),
    ),
    "shard-partition-header-mismatch": (
        "a drifted producer stamps/routes a message by the wrong partition "
        "hash — at best its effect strands on a non-owner (serving reads "
        "miss the write), and a broker bounce redelivers it onto the "
        "CORRECT queue where the owner's dedup window has never seen it: "
        "one message, two shards' durable effects (why the fleet worker "
        "verifies the partition header against its queue and rejects "
        "mismatches instead of absorbing them)",
        lambda: ShardedEpochModel(mutations=("partition_header_mismatch",)),
    ),
    "shard-rebalance-storm": (
        "the automatic rebalance controller has NO cooldown — it issues "
        "a second move off the SAME stale metrics scrape, moving load "
        "away from a donor that its own first move already fixed: "
        "unbounded consecutive moves, the fleet churns instead of "
        "converging (why rebalancer.decide enforces one move per "
        "cooldown window)",
        lambda: ShardedEpochModel(
            n_partitions=4, crashes=1, bounces=0, dups=0, rebalances=2,
            policy=True, mutations=("rebalance_storm",)),
    ),
    "shard-rebalance-oscillation": (
        "the automatic rebalance controller has NO hysteresis — the "
        "watermark band admits zero-improvement moves and a just-moved "
        "partition immediately re-qualifies, so one hot partition "
        "ping-pongs between two shards forever (why rebalancer.decide "
        "requires the gap to STRICTLY exceed the moved load, and blocks "
        "re-moving a partition until its queue is touched again)",
        lambda: ShardedEpochModel(
            n_partitions=4, crashes=1, bounces=0, dups=0, rebalances=2,
            policy=True, mutations=("rebalance_oscillation",)),
    ),
}

# Proven-indistinguishable variants (see module docstring): these MUST
# still verify clean at the contract scope — a counterexample appearing
# here means the fault model widened and the docs need updating.
BOUNDARY_MUTANTS: Dict[str, Tuple[str, Callable[[], object]]] = {
    "dc-fallback-first-chain": (
        "recovery takes the first readable base's chain instead of the "
        "best — within the single-fault contract all candidate chains "
        "of one linear history converge, so this is unobservable",
        lambda: DeltaChainModel(
            mutations=("fallback_first_chain",),
            corrupts=2, crashes=3, compacts=2, max_epochs=5),
    ),
    "dc-fallback-stale-base": (
        "recovery skips the stale-orphan base cross-check — also "
        "unobservable within the contract (an orphan base can only go "
        "stale through a second same-generation fault)",
        lambda: DeltaChainModel(
            mutations=("fallback_stale_base",),
            corrupts=2, crashes=3, compacts=2, max_epochs=5),
    ),
}


def verify_mutants(names=None) -> List[Tuple[str, str, CheckResult]]:
    """Run every catalogued mutant; returns [(name, description, result)].
    The gate requires ``not result.ok`` (a counterexample) for each."""
    out = []
    for name in (MUTANTS if names is None else names):
        desc, factory = MUTANTS[name]
        out.append((name, desc, check(factory())))
    return out
