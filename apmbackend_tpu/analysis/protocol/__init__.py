"""Protocol model checker: exhaustive small-scope verification of the
delivery, delta-chain, and sharded-epoch protocols.

The chaos harness (PR 3/7) SAMPLES interleavings of the at-least-once
epoch cycle and the delta-chain recovery; the static plane (PR 6) checks
structure. Neither enumerates schedules — and the one real loss bug the
harness caught (the dup-of-uncommitted-message ack) was caught by luck.
This package checks the protocols themselves: stdlib-only explicit-state
models (checker.py BFS, canonical state hashing, shortest-counterexample
schedules) of

- the **ALO epoch cycle** (:mod:`.alo`) across the memory / AMQP / spool
  ledger semantics — producer msg_id stamping, unacked ledger, bounded
  persisted dedup window, crash/bounce/duplicate at every step;
- the **delta-chain commit/recovery protocol** (:mod:`.deltamodel`) —
  tmp+rename commits, uid linkage, background compaction with
  keep-one-generation GC, torn/stale/forged tails, base rot;
- **sharded epochs** (:mod:`.shardmodel`) — the pod-scale spine
  pre-verified: per-shard cycles over service-hash partitions, quiesced
  rebalance handoff, fleet-level exactly-once + owner-locality.

Two tiers of scope: ``small`` runs inside ``run_tests.sh --lint`` and
tier-1 (< 10 s, a hard gate), ``deep`` behind ``run_tests.sh --model``.
The checker proves it can fail via :mod:`.mutations` — every seeded
protocol bug must yield a human-readable counterexample schedule.

Conformance to the real implementation is pinned two ways: the models
mirror named functions (each model docstring cites its code), and
:mod:`.conformance` replays protocol event logs emitted by the REAL
worker (``tpuEngine.protocolEventLog``) — including kill−9 chaos runs —
as paths of the models.
"""

from __future__ import annotations

from typing import List

from .alo import AloModel
from .checker import CheckResult, check
from .conformance import (check_fleet_trace, check_protocol_trace,
                          read_event_log)
from .deltamodel import DeltaChainModel
from .mutations import BOUNDARY_MUTANTS, MUTANTS, verify_mutants
from .shardmodel import ShardedEpochModel

# The verified scopes. Documented in DESIGN.md §9.4 — "verified" always
# means "at these bounds": N messages, window W, fault budgets per run.
SCOPES = {
    "small": [
        # ~58k states total, ~2 s — the --lint gate (hard 15 s budget in
        # tests/test_protocol_models.py, sized for full-suite contention)
        lambda: AloModel(kind="memory"),
        lambda: AloModel(kind="amqp"),
        lambda: AloModel(kind="spool"),
        lambda: DeltaChainModel(),
        lambda: ShardedEpochModel(),
        # the automatic-rebalance policy as a transition system: moves
        # chosen by watermark state over a P > N keyspace, release/adopt/
        # abort handoff in flight — certifies fleet-exactly-once +
        # owner-locality + bounded-consecutive-moves for the controller
        lambda: ShardedEpochModel(n_shards=2, n_partitions=4, n_msgs=3,
                                  crashes=1, bounces=0, dups=1,
                                  rebalances=2, policy=True),
    ],
    "deep": [
        # minutes-scale exhaustive sweep — the --model tier
        lambda: AloModel(kind="memory", n_msgs=4, crashes=2, bounces=2, dups=2),
        lambda: AloModel(kind="amqp", n_msgs=4, crashes=2, bounces=2, dups=2),
        lambda: AloModel(kind="spool", n_msgs=4, crashes=2, dups=2),
        lambda: AloModel(kind="memory", n_msgs=3, window=3, crashes=3,
                         bounces=2, dups=2),
        lambda: DeltaChainModel(max_epochs=6, crashes=3, corrupts=2,
                                compacts=2),
        lambda: ShardedEpochModel(n_msgs=3, crashes=2, bounces=1, dups=2,
                                  rebalances=2),
        lambda: ShardedEpochModel(n_shards=3, n_msgs=3, crashes=1,
                                  bounces=1, dups=1, rebalances=1),
        lambda: ShardedEpochModel(n_shards=2, n_partitions=4, n_msgs=3,
                                  crashes=1, bounces=1, dups=1,
                                  rebalances=2, policy=True),
        lambda: ShardedEpochModel(n_shards=3, n_partitions=6, n_msgs=4,
                                  crashes=1, bounces=0, dups=1,
                                  rebalances=2, policy=True),
    ],
}


def run_model_checks(tier: str = "small") -> List[CheckResult]:
    """Check every protocol model at the named tier's scopes. All results
    must have ``ok`` — a violation is a protocol bug (or a model drift)
    and fails the gate exactly like an analyzer finding."""
    if tier not in SCOPES:
        raise ValueError(f"unknown model-check tier {tier!r} "
                         f"(expected one of {sorted(SCOPES)})")
    return [check(factory()) for factory in SCOPES[tier]]


__all__ = [
    "AloModel", "DeltaChainModel", "ShardedEpochModel", "CheckResult",
    "check", "run_model_checks", "SCOPES", "MUTANTS", "BOUNDARY_MUTANTS",
    "verify_mutants", "check_protocol_trace", "check_fleet_trace",
    "read_event_log",
]
