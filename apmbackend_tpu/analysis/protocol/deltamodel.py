"""Model of the delta-chain commit/recovery protocol (deltachain.py).

The on-disk directory is the state: delta segments (uid / prev_uid
linkage, header epoch, CRC-intactness, content completeness), base
snapshots, and the MANIFEST pointer. The writer appends one segment per
epoch (tmp + rename IS the commit — a crash mid-append leaves nothing at
the committed name), runs background compaction in its two crash windows
(base written / manifest swapped / GC), and recovers with the exact
``DeltaChain.load`` walk: manifest base first, then newer-to-older base
fallback, then the contiguous valid delta chain — stopping at the first
missing, torn, foreign-epoch, or linkage-broken segment.

Hostile storage is modeled as the chaos harness injects it
(``corrupt_chain_tail`` + ``APM_CHAOS_FS``): a torn/bit-rotted tail (the
page-cache loss a SIGKILL cannot produce — it UN-commits that epoch, so
the ghost ``committed`` watermark steps back with it, which is safe
because the ALO ack for that epoch never happened), a stale duplicate
tail (the tail copied one epoch forward, old header), a forged duplicate
(plausible header epoch but stale ``prev_uid`` linkage — only the uid
chain rejects it), and bit rot of the newest base (allowed only when an
older generation exists: the keep-one-generation retention promise).

Ghost variable: ``committed`` = the last epoch whose append durably
returned (what the worker is allowed to ack up to). Invariant, checked at
every recovery:

- **recovery-stops-at-last-committed-boundary**: recovered epoch ==
  committed at recovery time — less is loss of committed (acked!) epochs,
  more means a stale/uncommitted tail was replayed past the boundary;
- **state-intact**: the replayed chain never includes a torn, incomplete,
  or foreign segment (recovered state is bit-identical to the committed
  state).

Mutations: ``gc_live_base`` (compaction GC deletes the fallback
generation), ``skip_prev_uid`` / ``skip_epoch_check`` / ``skip_crc``
(validation gaps), ``commit_before_rename`` (epoch reported committed
before the rename lands), ``capture_reset_on_enospc`` (a failed append's
capture window is dropped instead of retried as a superset).
"""

from __future__ import annotations

from collections import namedtuple
from typing import Iterator, Optional, Tuple

# segs:  tuple of (epoch, uid, prev_uid, hdr_epoch, intact, content_ok,
#        complete), sorted — intact = CRC/footer valid; content_ok = the
#        payload really is this epoch's delta (False for stale/forged
#        duplicates); complete = the capture covered everything since the
#        previous commit (False after the capture_reset mutant's gap)
# bases: tuple of (epoch, uid, intact, clean), sorted
# manifest: base epoch the MANIFEST points at (None = missing)
# alive: writer process up
# tail/tail_uid/wbase: writer memory (chain position + base for GC)
# wclean: writer's live state is uncorrupted
# nuid: fresh-uid counter
# committed: ghost — last epoch durably committed (ackable watermark)
# cuids: ghost — cuids[e] = uid of the write that last LEGITIMATELY
#        committed epoch e (the identity a stale orphan base fails to match)
# gprev: ghost — old_base of the last COMPLETED compaction (-1 before any);
#        the retention contract promises a fallback generation from here
# gap: a failed append's capture was dropped (capture_reset mutant)
# cphase: in-flight compaction (stage, target_epoch, target_uid, old_base)
# last_rec: (recovered_epoch, clean, committed_at_recovery) or None
# crashes/corrupts/brots/compacts/enospcs: remaining budgets
S = namedtuple(
    "S",
    "segs bases manifest alive tail tail_uid wbase wclean nuid committed "
    "cuids gprev gap cphase last_rec crashes corrupts brots compacts enospcs",
)

_MUTATIONS = frozenset({
    "gc_live_base", "skip_prev_uid", "skip_crc", "commit_before_rename",
    "capture_reset_on_enospc", "fallback_first_chain", "fallback_stale_base",
})


class DeltaChainModel:
    def __init__(self, *, max_epochs: int = 4, crashes: int = 2,
                 corrupts: int = 1, base_rots: int = 1, compacts: int = 1,
                 enospcs: int = 0, mutations: Tuple[str, ...] = ()):
        bad = set(mutations) - _MUTATIONS
        if bad:
            raise ValueError(f"unknown mutations: {sorted(bad)}")
        self.e = max_epochs
        self.mut = frozenset(mutations)
        self.crashes = crashes
        self.corrupts = corrupts
        self.base_rots = base_rots
        self.compacts = compacts
        self.enospcs = enospcs if "capture_reset_on_enospc" in self.mut else 0
        self.name = "delta-chain" + (f"[{'+'.join(sorted(self.mut))}]" if self.mut else "")
        self.scope = {
            "epochs": max_epochs, "crashes": crashes, "corrupts": corrupts,
            "base_rots": base_rots, "compactions": compacts,
        }

    def initial(self) -> S:
        # initialize(): base at epoch 0 (uid 0) + MANIFEST — the first
        # committed boundary, laid down before any ack can happen
        return S(
            segs=(), bases=((0, 0, True, True),), manifest=0, alive=True,
            tail=0, tail_uid=0, wbase=0, wclean=True, nuid=1, committed=0,
            cuids=(0,), gprev=-1, gap=False, cphase=None, last_rec=None,
            crashes=self.crashes, corrupts=self.corrupts,
            brots=self.base_rots, compacts=self.compacts,
            enospcs=self.enospcs,
        )

    # -- file helpers --------------------------------------------------------
    @staticmethod
    def _put_seg(segs: tuple, seg: tuple) -> tuple:
        """os.replace semantics: a new segment overwrites the file at the
        same epoch name."""
        return tuple(sorted(s for s in segs if s[0] != seg[0])) + (seg,)

    @staticmethod
    def _seg_at(segs: tuple, epoch: int):
        for s in segs:
            if s[0] == epoch:
                return s
        return None

    # -- the load() walk -----------------------------------------------------
    def _recover(self, s: S):
        """DeltaChain.load(): every readable base is a candidate chain
        start; the chain recovering the HIGHEST epoch wins (manifest-first
        on ties), and a non-manifest fallback base is rejected when the
        delta segment at its own epoch contradicts it (missing-or-matching
        required: a valid delta with a different uid, or an unreadable
        delta, marks the base a stale orphan from a dead compaction).
        Returns (epoch, clean, base_used) or None when no base is
        readable. ``clean`` additionally consults the ghost ``cuids`` so a
        stale base accepted by a mutant is visibly wrong state."""
        order = []
        by_epoch = {b[0]: b for b in s.bases}
        if s.manifest is not None and s.manifest in by_epoch:
            order.append(s.manifest)
        order.extend(e for e in sorted(by_epoch, reverse=True) if e not in order)
        best = None
        for be in order:
            _e, uid, intact, base_clean = by_epoch[be]
            if not intact:
                continue  # unreadable base: fall back one generation
            if be != s.manifest and "fallback_stale_base" not in self.mut:
                own = self._seg_at(s.segs, be)
                if own is not None and (not own[4] or own[1] != uid):
                    continue  # stale orphan base (contradicted by delta)
            # ghost staleness: the base's content is epoch `be` of SOME
            # incarnation; it matches the committed history only when its
            # uid is the one that last committed that epoch
            ghost_ok = be < len(s.cuids) and s.cuids[be] == uid
            epoch, clean = be, base_clean and ghost_ok
            while True:
                seg = self._seg_at(s.segs, epoch + 1)
                if seg is None:
                    break
                _se, suid, sprev, shdr, sintact, scontent, scomplete = seg
                if not sintact and "skip_crc" not in self.mut:
                    break  # torn/rotted tail: stop at the boundary
                if shdr != epoch + 1:
                    break  # header/filename epoch mismatch (stale dup)
                if sprev != uid and "skip_prev_uid" not in self.mut:
                    break  # broken predecessor linkage (foreign tail)
                clean = clean and sintact and scontent and scomplete
                epoch, uid = epoch + 1, suid
            cand = (epoch, clean, be)
            if "fallback_first_chain" in self.mut:
                return cand  # the pre-fix load(): first readable base wins
            if best is None or epoch > best[0]:
                best = cand
        return best

    # -- transition relation -------------------------------------------------
    def actions(self, s: S) -> Iterator[Tuple[str, S]]:
        out = []
        if s.alive:
            # append: commit one epoch (tmp + rename; the rename IS the
            # durability point, so the ghost watermark moves only here)
            if s.tail < self.e:
                epoch, uid = s.tail + 1, s.nuid
                seg = (epoch, uid, s.tail_uid, epoch, True, True, not s.gap)
                out.append((f"append(e{epoch})", s._replace(
                    segs=tuple(sorted(self._put_seg(s.segs, seg))),
                    tail=epoch, tail_uid=uid, nuid=s.nuid + 1,
                    committed=epoch, cuids=s.cuids[:epoch] + (uid,),
                    gap=False,
                )))
                # crash mid-append: the tmp never renamed — no file at the
                # committed name, watermark unchanged (the mutant reports
                # success before the rename: watermark moves, file doesn't)
                if s.crashes > 0:
                    ns = s._replace(alive=False, cphase=None,
                                    crashes=s.crashes - 1)
                    if "commit_before_rename" in self.mut:
                        ns = ns._replace(committed=epoch, nuid=s.nuid + 1)
                    out.append((f"append(e{epoch})+crash-mid-write", ns))
            # a failed append (ENOSPC): the chain tail is unchanged and the
            # correct writer retries a SUPERSET capture — a no-op state.
            # The mutant drops the capture window, so the next committed
            # delta is missing those changes.
            if s.enospcs > 0 and "capture_reset_on_enospc" in self.mut:
                out.append(("append-enospc[capture-reset]", s._replace(
                    enospcs=s.enospcs - 1, gap=True)))
            # compaction (background thread), staged through its two crash
            # windows: base published -> manifest swapped -> GC
            if s.cphase is None and s.compacts > 0 and s.tail > s.wbase:
                base = (s.tail, s.tail_uid, True, s.wclean)
                out.append((f"compact-base(e{s.tail})", s._replace(
                    bases=tuple(sorted(b for b in s.bases if b[0] != s.tail) + [base]),
                    cphase=(1, s.tail, s.tail_uid, s.wbase),
                    compacts=s.compacts - 1,
                )))
            elif s.cphase is not None and s.cphase[0] == 1:
                _st, target, tuid, old_base = s.cphase
                out.append((f"compact-manifest(e{target})", s._replace(
                    manifest=target, cphase=(2, target, tuid, old_base))))
            elif s.cphase is not None and s.cphase[0] == 2:
                _st, target, _tuid, old_base = s.cphase
                if "gc_live_base" in self.mut:
                    # deletes the fallback generation: deltas <= the NEW
                    # base and every older base
                    segs = tuple(x for x in s.segs if x[0] > target)
                    bases = tuple(b for b in s.bases if b[0] >= target)
                else:
                    # keep-one-generation retention: the previous base and
                    # every delta above it survive this compaction
                    segs = tuple(x for x in s.segs if x[0] > old_base)
                    bases = tuple(b for b in s.bases if b[0] >= old_base)
                out.append((f"compact-gc(e{target})", s._replace(
                    segs=segs, bases=bases, wbase=target, cphase=None,
                    gprev=old_base)))
            # crash anywhere (including inside either compaction window —
            # the kill:compact=pre_base/pre_manifest fault points)
            if s.crashes > 0:
                out.append(("crash", s._replace(
                    alive=False, cphase=None, crashes=s.crashes - 1)))
        else:
            # hostile storage strikes while the process is down
            if s.corrupts > 0 and s.segs:
                tail = s.segs[-1]
                te, tuid, tprev, thdr, _ti, tcont, tcomp = tail
                torn = self._put_seg(
                    s.segs, (te, tuid, tprev, thdr, False, tcont, tcomp))
                # a torn tail means the LAST segment write never fully hit
                # the platter: the epoch UN-commits, and its ALO ack never
                # happened either (the coupled contract: fsync=True acks
                # only after a durable rename; fsync=False narrows the
                # fault model to process death, where tails cannot tear).
                # Only physically possible while it IS the last durable
                # write — any base file written after it (compaction
                # fsyncs) proves the delta landed, so such tails are past
                # the fault window.
                if all(te > b[0] for b in s.bases):
                    out.append((f"corrupt-torn-tail(e{te})", s._replace(
                        segs=tuple(sorted(torn)), corrupts=s.corrupts - 1,
                        committed=min(s.committed, te - 1))))
                if te < self.e:
                    dup = (te + 1, tuid, tprev, thdr, True, False, tcomp)
                    out.append((f"corrupt-stale-dup(e{te}->e{te + 1})", s._replace(
                        segs=tuple(sorted(self._put_seg(s.segs, dup))),
                        corrupts=s.corrupts - 1)))
                    forged = (te + 1, s.nuid, tprev, te + 1, True, False, True)
                    out.append((f"corrupt-forged-dup(e{te + 1})", s._replace(
                        segs=tuple(sorted(self._put_seg(s.segs, forged))),
                        nuid=s.nuid + 1, corrupts=s.corrupts - 1)))
            intact_bases = [b for b in s.bases if b[2]]
            if s.brots > 0 and s.gprev >= 0 and intact_bases:
                # newest base rots — survivable ONLY because a completed
                # compaction's retention kept the previous generation (the
                # promise gc_live_base breaks). Fault-model scope: one base
                # rot per run — the keep-one-generation contract covers a
                # single lost generation, not independent losses stacking
                # across every generation (DESIGN.md §9.4).
                be, buid, _bi, bclean = max(intact_bases, key=lambda b: b[0])
                bases = tuple(sorted(
                    tuple(b for b in s.bases if b[0] != be)
                    + ((be, buid, False, bclean),)))
                out.append((f"corrupt-base(e{be})", s._replace(
                    bases=bases, brots=s.brots - 1)))
            # restart + DeltaChain.load()
            rec = self._recover(s)
            if rec is None:
                out.append(("recover[NO CHAIN]", s._replace(
                    alive=True, last_rec=(-1, False, s.committed))))
            else:
                epoch, clean, base_used = rec
                out.append((f"recover(e{epoch})", s._replace(
                    alive=True, tail=epoch,
                    tail_uid=self._uid_at(s, epoch, base_used),
                    wbase=base_used, wclean=clean, gap=False,
                    last_rec=(epoch, clean, s.committed))))
        return out

    def _uid_at(self, s: S, epoch: int, base_epoch: int) -> int:
        if epoch == base_epoch:
            for b in s.bases:
                if b[0] == base_epoch:
                    return b[1]
        seg = self._seg_at(s.segs, epoch)
        return seg[1] if seg is not None else -1

    # -- invariants ----------------------------------------------------------
    def invariant(self, s: S) -> Optional[str]:
        if s.last_rec is None:
            return None
        epoch, clean, committed = s.last_rec
        if epoch < committed:
            what = "no readable base survived" if epoch < 0 else f"stopped at e{epoch}"
            return (f"recovery lost committed epochs: {what} but e{committed} "
                    f"was durably committed (acked effects gone)")
        if not clean:
            # covers both replaying a torn/incomplete segment AND walking
            # past the committed boundary into a stale/forged duplicate —
            # either way the recovered state matches no committed state
            return (f"recovery replayed past the last committed boundary "
                    f"(e{committed}): recovered 'e{epoch}' contains a "
                    f"stale, torn, or incomplete segment — the state "
                    f"matches no committed epoch")
        # epoch > committed with CLEAN content is the benign
        # rename-landed-before-success-observed window: the commit is real,
        # the ack never happened, and the dedup window absorbs redelivery
        return None

    def describe(self, s: S) -> str:
        segs = ",".join(
            f"e{e}(u{u}<-u{p},h{h}{'' if i else ',TORN'}"
            f"{'' if co else ',STALE'}{'' if c else ',GAP'})"
            for e, u, p, h, i, co, c in s.segs)
        bases = ",".join(
            f"e{e}(u{u}{'' if i else ',ROT'})" for e, u, i, _c in s.bases)
        st = "up" if s.alive else "DOWN"
        cp = f" compact@{s.cphase[1]}:{s.cphase[0]}" if s.cphase else ""
        return (f"{st} tail=e{s.tail} committed=e{s.committed} "
                f"manifest=e{s.manifest} bases=[{bases}] segs=[{segs}]{cp}")
