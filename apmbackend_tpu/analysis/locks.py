"""Lock-discipline checker driven by ``# guarded-by: <lock>`` annotations.

The transport ledger, the obs rings, and the worker's delivery buffers are
all mutated from multiple threads; their locking contract used to live in
docstrings ("Caller holds self._lock"). This rule makes it machine-checked:

- Declaring: a trailing ``# guarded-by: _lock`` on a ``self.<attr> = ...``
  line (conventionally in ``__init__``) declares the attribute shared
  state owned by ``self._lock``.
- Checking: every ``self.<attr>`` access anywhere in the class must be
  (a) lexically inside ``with self._lock:``, (b) in a method annotated
  ``# apm: holds(_lock): <reason>`` (the ``*_locked`` helper convention),
  or (c) in ``__init__`` itself (construction happens-before publication).

Nested functions and lambdas defined inside a ``with`` block do NOT
inherit the held lock — they may run later on another thread (collector
closures, timer callbacks), which is exactly the PR-5 profiler-race shape.
Deliberate lock-free reads (GIL-atomic snapshots for scrape endpoints)
carry ``# apm: allow(lock-guard): <reason>`` so every one is auditable.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from .core import Finding, Project, SourceFile, rule


def _self_attr(node: ast.AST) -> Optional[str]:
    if (isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def _guarded_attrs(sf: SourceFile, cls: ast.ClassDef) -> Dict[str, str]:
    """{attr: lock} declared via guarded-by comments on self-assign lines."""
    out: Dict[str, str] = {}
    for node in ast.walk(cls):
        targets: List[ast.AST] = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign):
            targets = [node.target]
        else:
            continue
        lock = sf.guarded.get(node.lineno)
        if lock is None:
            continue
        for tgt in targets:
            attr = _self_attr(tgt)
            if attr is not None:
                out[attr] = lock
    return out


class _MethodVisitor(ast.NodeVisitor):
    """Walk one method tracking which self.<lock> locks are lexically held."""

    def __init__(self, sf: SourceFile, cls_name: str, method: ast.FunctionDef,
                 guarded: Dict[str, str], held0: Set[str]):
        self.sf = sf
        self.cls_name = cls_name
        self.method = method
        self.guarded = guarded
        self.held: Set[str] = set(held0)
        self.findings: List[Finding] = []

    def visit_With(self, node: ast.With) -> None:
        acquired = []
        for item in node.items:
            self.visit(item.context_expr)
            attr = _self_attr(item.context_expr)
            if attr is not None:
                acquired.append(attr)
                self.held.add(attr)
            if item.optional_vars is not None:
                self.visit(item.optional_vars)
        for stmt in node.body:
            self.visit(stmt)
        for attr in acquired:
            self.held.discard(attr)

    visit_AsyncWith = visit_With

    def visit_Attribute(self, node: ast.Attribute) -> None:
        attr = _self_attr(node)
        if attr is not None and attr in self.guarded:
            lock = self.guarded[attr]
            if lock not in self.held:
                self.findings.append(Finding(
                    "lock-guard", self.sf.rel, node.lineno,
                    f"{self.cls_name}.{attr} is guarded-by {lock} but accessed "
                    f"in {self.method.name}() without holding it — wrap in "
                    f"'with self.{lock}:' or annotate the method "
                    f"'# apm: holds({lock}): <reason>'"))
        self.generic_visit(node)

    def _enter_closure(self, node) -> None:
        # a closure/lambda body runs later, possibly without the lock
        inner = _MethodVisitor(self.sf, self.cls_name, self.method,
                               self.guarded, set())
        for child in ast.iter_child_nodes(node):
            inner.visit(child)
        self.findings.extend(inner.findings)

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        held0 = set()
        h = self.sf.holds_for_def(node.lineno)
        if h is not None:
            held0.add(h[0])
        inner = _MethodVisitor(self.sf, self.cls_name, node, self.guarded, held0)
        for child in node.body:
            inner.visit(child)
        self.findings.extend(inner.findings)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node: ast.Lambda) -> None:
        self._enter_closure(node)


def _topmost_closures(fn: ast.FunctionDef) -> List[ast.AST]:
    """First-level nested defs/lambdas of ``fn`` (deeper nesting is reached
    through the visitor's own recursion, never visited twice)."""
    out: List[ast.AST] = []

    def walk(node: ast.AST) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                out.append(child)
            else:
                walk(child)

    walk(fn)
    return out


@rule("lock-guard", "guarded-by annotated attributes accessed without the owning lock")
def check_lock_guard(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    for sf in project.files:
        if not sf.guarded:
            continue
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            guarded = _guarded_attrs(sf, node)
            if not guarded:
                continue
            for stmt in node.body:
                if not isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                if stmt.name == "__init__":
                    # construction happens-before publication, so direct
                    # accesses are fine — but closures defined here (collector
                    # callbacks, timers) run later and are still checked
                    v = _MethodVisitor(sf, node.name, stmt, guarded, set())
                    for closure in _topmost_closures(stmt):
                        if isinstance(closure, ast.Lambda):
                            v.visit_Lambda(closure)
                        else:
                            v.visit_FunctionDef(closure)
                    findings.extend(v.findings)
                    continue
                held0: Set[str] = set()
                h = sf.holds_for_def(stmt.lineno)
                if h is not None:
                    held0.add(h[0])
                visitor = _MethodVisitor(sf, node.name, stmt, guarded, held0)
                for child in stmt.body:
                    visitor.visit(child)
                findings.extend(visitor.findings)
    return findings
