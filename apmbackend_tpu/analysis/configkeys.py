"""Config-key cross-reference lint.

``config.py`` ``_DEFAULT_CONFIG`` is the schema: every key code reads must
exist there (``config-key-unknown`` — a typo'd ``tpuEngine.deliveryBatchSize``
fails the gate instead of silently defaulting through ``.get()``), and
every defined key must be read somewhere in the package or benchmarks
(``config-key-unread`` — dead config is a lie waiting for an operator).

Usage extraction is AST-based and deliberately conservative:

- subscript / ``.get()`` chains rooted at a config-shaped name
  (``config``, ``cfg``, ``self.config``, ...) or at a local alias assigned
  from such a chain (``eng = config["tpuEngine"]``);
- ``resolve_path(obj, "dotted.path")`` string arguments;
- chains whose first segment is a known *section* key are auto-anchored at
  that section, so ``section_cfg.get("deliveryBatchSize")`` resolves
  without knowing which variable held the section.

A chain that descends into a non-dict default (lists like
``defaults[0].LAG``, free-form maps like ``statCmdMap``) stops validating
at that point. The unread check covers depth ≤ 2 (sections and their
direct keys); deeper structures are consumed wholesale by their owners.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .core import Finding, Project, SourceFile, rule

# names that conventionally hold the WHOLE config dict. ``cfg``/``conf``
# often hold a SECTION, so they anchor through _key_like instead — a
# whole-config claim there would misreport every section read.
_ROOT_NAMES = {"config", "new_config", "app_config", "apm_config", "full_config"}
_ROOT_ATTRS = {"config", "_config", "app_config"}


def _schema(project: Project) -> Tuple[dict, Dict[Tuple[str, ...], int]]:
    """(nested default tree, {dotted path tuple: config.py line})."""
    def build():
        sf = project.file(f"{project.package}/config.py")
        tree: dict = {}
        lines: Dict[Tuple[str, ...], int] = {}
        if sf is None:
            return tree, lines

        def walk_dict(node: ast.Dict, prefix: Tuple[str, ...], into: dict) -> None:
            for k, v in zip(node.keys, node.values):
                if not (isinstance(k, ast.Constant) and isinstance(k.value, str)):
                    continue
                path = prefix + (k.value,)
                lines[path] = k.lineno
                if isinstance(v, ast.Dict):
                    sub: dict = {}
                    into[k.value] = sub
                    walk_dict(v, path, sub)
                else:
                    into[k.value] = None

        for node in ast.walk(sf.tree):
            target = None
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                target = node.targets[0]
            elif isinstance(node, ast.AnnAssign):
                target = node.target
            if (target is not None and isinstance(target, ast.Name)
                    and target.id == "_DEFAULT_CONFIG"
                    and isinstance(node.value, ast.Dict)):
                walk_dict(node.value, (), tree)
        # keys config.py itself injects at load time (config["apmConfigFilePath"])
        for node in ast.walk(sf.tree):
            if (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Subscript)):
                sub = node.targets[0]
                if (isinstance(sub.value, ast.Name) and sub.value.id in _ROOT_NAMES
                        and isinstance(sub.slice, ast.Constant)
                        and isinstance(sub.slice.value, str)):
                    tree.setdefault(sub.slice.value, None)
                    lines.setdefault((sub.slice.value,), node.lineno)
        return tree, lines
    return project.cached("config.schema", build)


def _chain_of(node: ast.AST) -> Optional[Tuple[ast.AST, List[Tuple[str, int]]]]:
    """Decompose ``root["a"].get("b")`` into (root node, [(seg, line)...]).
    Returns None when the expression isn't a constant-string key chain."""
    segs: List[Tuple[str, int]] = []
    cur = node
    while True:
        if isinstance(cur, ast.Subscript):
            sl = cur.slice
            if isinstance(sl, ast.Constant) and isinstance(sl.value, str):
                segs.append((sl.value, cur.lineno))
                cur = cur.value
                continue
            return None
        if (isinstance(cur, ast.Call) and isinstance(cur.func, ast.Attribute)
                and cur.func.attr == "get" and cur.args
                and isinstance(cur.args[0], ast.Constant)
                and isinstance(cur.args[0].value, str)):
            segs.append((cur.args[0].value, cur.lineno))
            cur = cur.func.value
            continue
        # `(cfg.get("x") or {}).get(...)` — look through the or-{} guard
        if isinstance(cur, ast.BoolOp) and isinstance(cur.op, ast.Or) and cur.values:
            cur = cur.values[0]
            continue
        break
    if not segs:
        return None
    segs.reverse()
    return cur, segs


def _root_prefix(root: ast.AST, aliases: Dict[str, Tuple[str, ...]]) -> Optional[Tuple[str, ...]]:
    """Config-path prefix the chain root stands for, or None if not config."""
    if isinstance(root, ast.Name):
        if root.id in _ROOT_NAMES:
            return ()
        if root.id in aliases:
            return aliases[root.id]
        return None
    if (isinstance(root, ast.Attribute) and isinstance(root.value, ast.Name)
            and root.value.id == "self" and root.attr in _ROOT_ATTRS):
        return ()
    return None


def _dict_nodes(tree: dict, prefix: Tuple[str, ...] = ()):
    """(path, dict node) for every dict in the schema tree — anchor
    candidates for section/subsection variables (``multivariateDetector``
    blocks travel as their own config objects)."""
    for key, sub in tree.items():
        if isinstance(sub, dict):
            path = prefix + (key,)
            yield path, sub
            yield from _dict_nodes(sub, path)


def _descend(tree: dict, path: Tuple[str, ...]) -> Tuple[bool, int]:
    """(valid, depth_validated): walk the schema; descending into a non-dict
    (list / scalar / free-form map) stops validation successfully."""
    cur: Optional[dict] = tree
    for i, seg in enumerate(path):
        if cur is None:
            return True, i  # inside a non-dict default: can't validate further
        if seg not in cur:
            return False, i
        cur = cur[seg]
    return True, len(path)


def _validate(project: Project, sf: SourceFile, segs: List[Tuple[str, int]],
              prefix: Tuple[str, ...], findings: List[Finding],
              used: Set[Tuple[str, ...]]) -> None:
    """Resolve a chain read off a config-shaped object. Components routinely
    receive their SECTION as ``config``/``self.config``, so a chain is
    accepted when it resolves from the tree root OR auto-anchored at any
    section defining its first segment; only a chain no anchor explains is
    a finding. Every successful anchor marks its keys read (over-marking is
    the price of not knowing which section the variable held)."""
    tree, _ = _schema(project)
    names = tuple(s for s, _ in segs)
    if prefix:
        anchors: List[Tuple[str, ...]] = [prefix]  # alias: exact location known
    else:
        anchors = [()]
        anchors += [p for p, node in _dict_nodes(tree) if names[0] in node]
    best: Tuple[int, Tuple[str, ...]] = (-1, names)
    resolved = False
    for anchor in anchors:
        full = anchor + names
        ok, depth = _descend(tree, full)
        if ok:
            resolved = True
            for i in range(len(full)):
                used.add(full[:i + 1])
        elif depth > best[0]:
            best = (depth, full)
    if resolved:
        return
    depth, full = best
    prefix_len = len(full) - len(names)
    seg_idx = min(max(depth - prefix_len, 0), len(segs) - 1)
    _seg, line = segs[seg_idx]
    findings.append(Finding(
        "config-key-unknown", sf.rel, line,
        f"config key {'.'.join(full)!r} not in config.py defaults "
        f"(unknown segment {full[min(depth, len(full) - 1)]!r}) — typo or "
        "missing schema entry"))


def _collect_usage(project: Project, sf: SourceFile,
                   findings: List[Finding], used: Set[Tuple[str, ...]]) -> None:
    tree, _ = _schema(project)

    class V(ast.NodeVisitor):
        def __init__(self):
            self.aliases: Dict[str, Tuple[str, ...]] = {}

        def visit_Assign(self, node: ast.Assign) -> None:
            ch = _chain_of(node.value)
            if ch is not None:
                prefix = _root_prefix(ch[0], self.aliases)
                if prefix is not None and len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Name):
                    names = tuple(s for s, _ in ch[1])
                    ok, _d = _descend(tree, prefix + names)
                    if ok:
                        self.aliases[node.targets[0].id] = prefix + names
            self.generic_visit(node)

        def visit_Subscript(self, node: ast.Subscript) -> None:
            self._check(node)

        def visit_Call(self, node: ast.Call) -> None:
            # resolve_path(obj, "a.b.c")
            fn = node.func
            name = fn.id if isinstance(fn, ast.Name) else (
                fn.attr if isinstance(fn, ast.Attribute) else None)
            if (name == "resolve_path" and len(node.args) >= 2
                    and isinstance(node.args[1], ast.Constant)
                    and isinstance(node.args[1].value, str)):
                path = tuple(node.args[1].value.split("."))
                ok, _d = _descend(tree, path)
                if ok:
                    for i in range(len(path)):
                        used.add(path[:i + 1])
                else:
                    findings.append(Finding(
                        "config-key-unknown", sf.rel, node.args[1].lineno,
                        f"resolve_path key {'.'.join(path)!r} not in config.py "
                        "defaults — typo or missing schema entry"))
                self.generic_visit(node)
                return
            self._check(node)

        def _check(self, node: ast.AST) -> None:
            ch = _chain_of(node)
            if ch is None:
                for child in ast.iter_child_nodes(node):
                    self.visit(child)
                return
            root, segs = ch
            prefix = _root_prefix(root, self.aliases)
            if prefix is not None:
                _validate(project, sf, segs, prefix, findings, used)
            elif _key_like(root):
                # section dicts passed as parameters resolve by auto-anchor
                _validate(project, sf, segs, (), findings, used)
            self.visit(root)

    V().visit(sf.tree)


def _key_like(root: ast.AST) -> bool:
    """Heuristic: chains rooted at *_cfg / *_config / section-named vars are
    section reads worth anchoring (never reported, only marked used)."""
    # NOT "section"/"conf": too generic — healthz payload dicts and the
    # like travel under those names and are not config
    if isinstance(root, ast.Name):
        n = root.id.lower()
        return n.endswith(("cfg", "config", "settings"))
    if isinstance(root, ast.Attribute):
        n = root.attr.lower()
        return n.endswith(("cfg", "config", "settings"))
    return False


@rule("config-key-unknown", "config keys read in code that don't exist in config.py defaults")
def check_config_unknown(project: Project) -> List[Finding]:
    findings, _used = _usage(project)
    return findings


@rule("config-key-unread", "config.py default keys nothing in the code reads")
def check_config_unread(project: Project) -> List[Finding]:
    _findings, used = _usage(project)
    tree, lines = _schema(project)
    findings: List[Finding] = []
    sf = project.file(f"{project.package}/config.py")
    if sf is None:
        return findings
    # literal-string fallback evidence: any string constant equal to the key
    # name anywhere outside _DEFAULT_CONFIG counts as a read (iteration-style
    # consumers, wire formats)
    literals = project.cached("config.literals", lambda: _string_literals(project))
    for path, line in sorted(lines.items()):
        if len(path) > 2:
            continue  # deeper structures are consumed wholesale
        if path in used or path[-1] in literals:
            continue
        findings.append(Finding(
            "config-key-unread", sf.rel, line,
            f"default config key {'.'.join(path)!r} is never read by "
            f"{project.package}/ or benchmarks/ — dead schema or missing wiring"))
    return findings


def _usage(project: Project):
    def build():
        findings: List[Finding] = []
        used: Set[Tuple[str, ...]] = set()
        for sf in project.files:
            _collect_usage(project, sf, findings, used)
        return findings, used
    return project.cached("config.usage", build)


def _string_literals(project: Project) -> Set[str]:
    out: Set[str] = set()
    schema_sf = project.file(f"{project.package}/config.py")
    schema_span = None
    if schema_sf is not None:
        for node in ast.walk(schema_sf.tree):
            if (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and node.targets[0].id == "_DEFAULT_CONFIG"):
                schema_span = (node.lineno, node.end_lineno or node.lineno)
    for sf in project.files:
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Constant) and isinstance(node.value, str):
                if (sf is schema_sf and schema_span
                        and schema_span[0] <= node.lineno <= schema_span[1]):
                    continue  # the schema's own keys are not evidence
                out.add(node.value)
    return out
