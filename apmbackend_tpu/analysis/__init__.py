"""Static correctness plane: in-repo AST analysis for the invariants the
runtime harnesses only catch after the fact.

Five PRs grew a concurrency-heavy system — a multithreaded transport/ack
ledger, a ctypes C++ ingest engine, donated/fused XLA hot paths — whose
invariants were enforced purely at runtime, and the runtime harnesses have
already caught real bugs of exactly the classes a static pass prevents
(the dup-ack one-message loss, the concurrent-profiler race). This package
machine-checks them on every run, wired as a hard gate in
``run_tests.sh --lint`` and runnable standalone::

    python -m apmbackend_tpu.analysis            # whole repo, exit 0 = clean
    python -m apmbackend_tpu.analysis --list-rules

Rule families (see DESIGN.md §9 for the full contract):

- **JAX hot path** (:mod:`.jaxrules`): implicit device syncs
  (``float()``/``int()``/``bool()``/``.item()``/``np.asarray`` on
  device-tainted values) outside functions annotated as sanctioned sync
  boundaries; donated-buffer reuse after a ``donate_argnums`` call;
  recompile hazards (Python scalar literals into jitted callables without
  ``static_argnums``, ``jax.jit`` inside a loop).
- **Lock discipline** (:mod:`.locks`): ``# guarded-by: <lock>`` annotations
  on shared attributes are verified — every annotated access must occur
  under ``with self.<lock>:`` or in a method annotated
  ``# apm: holds(<lock>)``.
- **Config-key cross-reference** (:mod:`.configkeys`): every config key
  read in code exists in ``config.py`` defaults, and every default is read
  somewhere — a typo'd ``tpuEngine.deliveryBatchSize`` fails the gate
  instead of silently defaulting.
- **Metric-catalogue drift** (:mod:`.metriccat`): every metric registered
  via ``obs`` appears in the DESIGN.md §8 catalogue and vice versa.
- **pyflakes-lite** (:mod:`.pyflakes_lite`): unused imports and
  same-scope redefinitions — the hard-requirement core of the pyflakes
  pass for containers that don't ship pyflakes.

Suppressions are inline, deliberate, and auditable::

    x = float(dev_val)  # apm: allow(jax-sync): readback at the emit boundary

A pragma without a written reason is itself a finding (``pragma-bare``),
and a pragma that no longer suppresses anything is too (``pragma-unused``).
Stdlib only; no third-party linter dependencies.
"""

from .core import (
    Finding,
    Project,
    RULES,
    SourceFile,
    run_analysis,
)

__all__ = ["Finding", "Project", "RULES", "SourceFile", "run_analysis"]
