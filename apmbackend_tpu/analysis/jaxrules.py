"""JAX hot-path rules: implicit syncs, donated-buffer reuse, recompiles.

These are the exact bug classes the r6 perf work root-caused by hand:

- ``jax-sync``: a ``float()``/``int()``/``bool()``/``.item()``/``.tolist()``
  /``np.asarray()`` on a *device-tainted* value forces a blocking device
  sync. Syncs are only legal inside functions annotated
  ``# apm: sync-boundary: <reason>`` — the deliberate readback points
  (emit, checkpoint, healthz snapshots) — or under a per-line
  ``# apm: allow(jax-sync): <reason>``.
- ``jax-donated-reuse``: a buffer passed in a ``donate_argnums`` position
  is dead after the call; reading the same name afterwards (without
  rebinding) is use-after-donate — XLA may have already aliased the
  memory. The ``state = step(state, ...)`` rebind idiom is recognized as
  safe.
- ``jax-recompile``: a Python scalar literal passed to a jitted callable
  in a non-``static_argnums`` position retraces per value, and a
  ``jax.jit(...)`` constructed inside a loop rebuilds its cache entry per
  iteration — both silent throughput cliffs.

Taint model (deliberately local and conservative): a value is
device-tainted when it flows from a ``jnp.``/``jax.``/``lax.`` call, a
call through a known jitted callable (``x = jax.jit(...)``, including
``self._x`` attributes and decorated defs), a parameter annotated with a
device container type (any class in the package with a ``jnp.ndarray``/
``jax.Array`` field), or a ``self.<attr>`` assigned from any of those
anywhere in the class. Attribute/subscript access propagates taint.
Branches merge by union; loop bodies are walked twice for loop-carried
taint. Files that never import jax are skipped entirely.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .core import Finding, Project, SourceFile, rule

_SYNC_BUILTINS = {"float", "int", "bool"}
_SYNC_METHODS = {"item", "tolist"}
_NP_SYNC = {"np.asarray", "np.array", "numpy.asarray", "numpy.array"}
_DEVICE_PREFIXES = ("jnp.", "jax.", "lax.")
# jax.* calls that return host/control objects, not device arrays
_NON_DEVICE_JAX = (
    "jax.jit", "jax.pmap", "jax.vmap", "jax.grad", "jax.devices",
    "jax.device_count", "jax.local_devices", "jax.local_device_count",
    "jax.default_backend", "jax.process_index", "jax.process_count",
    "jax.named_scope", "jax.profiler.", "jax.tree_util.", "jax.config.",
    "jax.distributed.", "jax.sharding.", "jax.eval_shape",
    "jnp.shape", "jnp.dtype", "jnp.issubdtype",
)


def _dotted(node: ast.AST) -> Optional[str]:
    """'jax.jit' for Attribute/Name chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _key(node: ast.AST) -> Optional[str]:
    """Trackable lvalue/rvalue key: a bare name or a self attribute."""
    if isinstance(node, ast.Name):
        return node.id
    if (isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return f"self.{node.attr}"
    return None


def _is_device_call(call: ast.Call) -> bool:
    d = _dotted(call.func)
    if d is None:
        return False
    if any(d == p.rstrip(".") or d.startswith(p) for p in _NON_DEVICE_JAX):
        return False
    return d.startswith(_DEVICE_PREFIXES)


def _int_set(node: Optional[ast.AST]) -> Optional[Set[int]]:
    """Literal int / tuple-of-ints keyword value; None when unparseable."""
    if node is None:
        return None
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return {node.value}
    if isinstance(node, (ast.Tuple, ast.List)):
        out = set()
        for el in node.elts:
            if isinstance(el, ast.Constant) and isinstance(el.value, int):
                out.add(el.value)
            else:
                return None
        return out
    return None


class JitInfo:
    __slots__ = ("donate", "static")

    def __init__(self, donate: Optional[Set[int]], static: Optional[Set[int]]):
        self.donate = donate or set()
        self.static = static


def _jit_info_from_call(call: ast.Call) -> Optional[JitInfo]:
    """JitInfo when ``call`` is jax.jit(...) / functools.partial(jax.jit, ...)."""
    d = _dotted(call.func)
    if d in ("jax.jit", "jit"):
        kw = {k.arg: k.value for k in call.keywords}
        return JitInfo(_int_set(kw.get("donate_argnums")), _int_set(kw.get("static_argnums")))
    if d in ("functools.partial", "partial") and call.args:
        inner = _dotted(call.args[0])
        if inner in ("jax.jit", "jit"):
            kw = {k.arg: k.value for k in call.keywords}
            # static_argnames can't be mapped to positions statically; treat
            # the callable as fully static (never flag literal scalars)
            if "static_argnames" in kw:
                return JitInfo(_int_set(kw.get("donate_argnums")), set(range(64)))
            return JitInfo(_int_set(kw.get("donate_argnums")), _int_set(kw.get("static_argnums")))
    return None


def _device_classes(project: Project) -> Set[str]:
    """Names of classes whose annotated fields hold device arrays — the
    NamedTuple state/emission containers (EngineState, TickEmission, ...)."""
    def build() -> Set[str]:
        out: Set[str] = set()
        for sf in project.files:
            for node in ast.walk(sf.tree):
                if not isinstance(node, ast.ClassDef):
                    continue
                for stmt in node.body:
                    if isinstance(stmt, ast.AnnAssign):
                        try:
                            ann = ast.unparse(stmt.annotation)
                        except Exception:
                            continue
                        if "jnp.ndarray" in ann or "jax.Array" in ann:
                            out.add(node.name)
                            break
        return out
    return project.cached("jax.device_classes", build)


def _imports_jax(sf: SourceFile) -> bool:
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Import):
            if any(a.name == "jax" or a.name.startswith("jax.") for a in node.names):
                return True
        elif isinstance(node, ast.ImportFrom):
            if node.module and (node.module == "jax" or node.module.startswith("jax.")):
                return True
    return False


def _module_jitted(sf: SourceFile) -> Dict[str, JitInfo]:
    """File-wide jitted callables: module/class/self assignments from
    jax.jit(...) and @jax.jit/@functools.partial(jax.jit, ...) defs."""
    out: Dict[str, JitInfo] = {}
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            info = _jit_info_from_call(node.value)
            if info is not None:
                for tgt in node.targets:
                    k = _key(tgt)
                    if k:
                        out[k] = info
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for deco in node.decorator_list:
                info = None
                if isinstance(deco, ast.Call):
                    info = _jit_info_from_call(deco)
                elif _dotted(deco) in ("jax.jit", "jit"):
                    info = JitInfo(None, None)
                if info is not None:
                    out[node.name] = info
    return out


def _class_device_attrs(cls: ast.ClassDef, jitted: Dict[str, JitInfo]) -> Set[str]:
    """self.<attr> keys assigned from device/jitted calls anywhere in the
    class — cross-method taint roots (self.state, self._params, ...)."""
    out: Set[str] = set()
    for node in ast.walk(cls):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            call = node.value
            is_dev = _is_device_call(call)
            if not is_dev:
                d = _key(call.func)
                is_dev = d is not None and d in jitted
            if is_dev:
                for tgt in node.targets:
                    k = _key(tgt)
                    if k and k.startswith("self."):
                        out.add(k)
    return out


class _FnState:
    def __init__(self):
        self.tainted: Set[str] = set()
        self.dead: Dict[str, int] = {}  # key -> donation line
        self.jitted: Dict[str, JitInfo] = {}

    def copy(self) -> "_FnState":
        st = _FnState()
        st.tainted = set(self.tainted)
        st.dead = dict(self.dead)
        st.jitted = dict(self.jitted)
        return st

    def merge(self, other: "_FnState") -> None:
        self.tainted |= other.tainted
        for k, ln in other.dead.items():
            self.dead.setdefault(k, ln)
        self.jitted.update(other.jitted)


class _FnChecker:
    """Walks one function's statements in order, tracking taint, donated
    buffers, and jitted locals; emits findings into ``self.findings``."""

    def __init__(self, sf: SourceFile, fn: ast.FunctionDef,
                 jitted: Dict[str, JitInfo], device_classes: Set[str],
                 device_attrs: Set[str], check_sync: bool):
        self.sf = sf
        self.fn = fn
        self.check_sync = check_sync
        self.findings: List[Finding] = []
        self.state = _FnState()
        self.state.jitted.update(jitted)
        self.state.tainted |= device_attrs
        self.loop_depth = 0
        args = fn.args
        for a in (args.posonlyargs + args.args + args.kwonlyargs
                  + ([args.vararg] if args.vararg else [])
                  + ([args.kwarg] if args.kwarg else [])):
            if a.annotation is None:
                continue
            try:
                ann = ast.unparse(a.annotation)
            except Exception:
                continue
            if isinstance(a.annotation, ast.Constant) and isinstance(a.annotation.value, str):
                ann = a.annotation.value
            if ("jnp.ndarray" in ann or "jax.Array" in ann
                    or any(dc in ann for dc in device_classes)):
                self.state.tainted.add(a.arg)

    # -- expression helpers ---------------------------------------------------
    def expr_tainted(self, node: ast.AST) -> bool:
        for sub in ast.walk(node):
            k = _key(sub)
            if k is not None and k in self.state.tainted:
                # self.<attr> taints only via the self-attribute node, not
                # the bare 'self' name inside it
                if isinstance(sub, ast.Name) and sub.id == "self":
                    continue
                return True
            if isinstance(sub, ast.Call):
                if _is_device_call(sub):
                    return True
                d = _key(sub.func)
                if d is not None and d in self.state.jitted:
                    return True
        return False

    def scan_expr(self, node: Optional[ast.AST]) -> None:
        """Findings inside one expression: syncs, donated reads, jit-in-loop,
        literal-scalar args to jitted callables. Donations apply afterwards
        via ``pending_donations``."""
        if node is None:
            return
        self.pending_donations: List[Tuple[str, int]] = getattr(self, "pending_donations", [])
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                self._scan_call(sub)
            else:
                k = _key(sub)
                if (k is not None and isinstance(getattr(sub, "ctx", None), ast.Load)
                        and k in self.state.dead):
                    self.findings.append(Finding(
                        "jax-donated-reuse", self.sf.rel, sub.lineno,
                        f"'{k}' was donated to a donate_argnums call on line "
                        f"{self.state.dead[k]} and read again here — the buffer "
                        "may already be aliased; rebind the result or copy first"))
                    # one report per donation site keeps burn-down tractable
                    self.state.dead.pop(k, None)

    def _scan_call(self, call: ast.Call) -> None:
        d = _dotted(call.func)
        # jax.jit inside a loop: per-iteration retrace/cache churn
        if d in ("jax.jit", "jit") and self.loop_depth > 0:
            self.findings.append(Finding(
                "jax-recompile", self.sf.rel, call.lineno,
                "jax.jit(...) constructed inside a loop — hoist it; each "
                "iteration rebuilds trace/cache state"))
        # implicit syncs
        if self.check_sync:
            if (isinstance(call.func, ast.Name) and call.func.id in _SYNC_BUILTINS
                    and len(call.args) == 1 and self.expr_tainted(call.args[0])):
                self.findings.append(Finding(
                    "jax-sync", self.sf.rel, call.lineno,
                    f"{call.func.id}() on a device value blocks on the device — "
                    "move into a sync-boundary function or batch the readback"))
            elif (isinstance(call.func, ast.Attribute) and call.func.attr in _SYNC_METHODS
                    and self.expr_tainted(call.func.value)):
                self.findings.append(Finding(
                    "jax-sync", self.sf.rel, call.lineno,
                    f".{call.func.attr}() on a device value blocks on the device — "
                    "move into a sync-boundary function or batch the readback"))
            elif (d in _NP_SYNC and call.args and self.expr_tainted(call.args[0])):
                self.findings.append(Finding(
                    "jax-sync", self.sf.rel, call.lineno,
                    f"{d}() on a device value forces a transfer — move into a "
                    "sync-boundary function or batch the readback"))
        # calls through jitted callables: donation + literal-scalar hazards
        k = _key(call.func)
        info = self.state.jitted.get(k) if k is not None else None
        if info is None:
            return
        for pos, arg in enumerate(call.args):
            if pos in info.donate:
                ak = _key(arg)
                if ak is not None:
                    self.pending_donations.append((ak, call.lineno))
            if (isinstance(arg, ast.Constant)
                    and type(arg.value) in (int, float)
                    and (info.static is None or pos not in info.static)):
                self.findings.append(Finding(
                    "jax-recompile", self.sf.rel, call.lineno,
                    f"Python scalar literal {arg.value!r} passed to jitted "
                    f"'{k}' at position {pos} without static_argnums — "
                    "retraces per value; pass an array or mark it static"))

    # -- statement walk -------------------------------------------------------
    def _apply_donations(self, rebound: Set[str]) -> None:
        for ak, ln in getattr(self, "pending_donations", []):
            if ak not in rebound:
                self.state.dead[ak] = ln
        self.pending_donations = []

    def _assign_taint(self, targets: List[ast.AST], value: ast.AST) -> None:
        tainted = self.expr_tainted(value)
        jit_info = _jit_info_from_call(value) if isinstance(value, ast.Call) else None
        for tgt in targets:
            for el in ast.walk(tgt):
                k = _key(el)
                if k is None or (isinstance(el, ast.Name) and el.id == "self"):
                    continue
                self.state.dead.pop(k, None)  # rebind revives the name
                if jit_info is not None:
                    self.state.jitted[k] = jit_info
                elif tainted:
                    self.state.tainted.add(k)
                else:
                    self.state.tainted.discard(k)

    def _targets_keys(self, targets: List[ast.AST]) -> Set[str]:
        out: Set[str] = set()
        for tgt in targets:
            for el in ast.walk(tgt):
                k = _key(el)
                if k and not (isinstance(el, ast.Name) and el.id == "self"):
                    out.add(k)
        return out

    def exec_stmts(self, stmts: List[ast.stmt]) -> bool:
        """Returns True when the block terminates (return/raise/break/
        continue) — a terminated branch must not merge into fall-through
        state, or an if-return's donation would poison the else path."""
        terminated = False
        for stmt in stmts:
            if isinstance(stmt, ast.Assign):
                self.scan_expr(stmt.value)
                self._apply_donations(self._targets_keys(stmt.targets))
                self._assign_taint(stmt.targets, stmt.value)
            elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
                self.scan_expr(stmt.value)
                self._apply_donations(self._targets_keys([stmt.target]))
                self._assign_taint([stmt.target], stmt.value)
            elif isinstance(stmt, ast.AugAssign):
                self.scan_expr(stmt.value)
                self.scan_expr(stmt.target)
                self._apply_donations(set())
            elif isinstance(stmt, (ast.Expr, ast.Return)):
                self.scan_expr(stmt.value)
                self._apply_donations(set())
                if isinstance(stmt, ast.Return):
                    terminated = True
            elif isinstance(stmt, (ast.Raise, ast.Break, ast.Continue)):
                if isinstance(stmt, ast.Raise):
                    self.scan_expr(stmt.exc)
                    self._apply_donations(set())
                terminated = True
            elif isinstance(stmt, ast.If):
                self.scan_expr(stmt.test)
                self._apply_donations(set())
                branch = self.state.copy()
                body_term = self.exec_stmts(stmt.body)
                taken, self.state = self.state, branch
                else_term = self.exec_stmts(stmt.orelse)
                if body_term and else_term:
                    terminated = True
                elif body_term:
                    pass  # fall-through state is the else branch alone
                elif else_term:
                    self.state = taken  # fall-through is the if branch alone
                else:
                    self.state.merge(taken)
            elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                self.scan_expr(stmt.iter)
                self._apply_donations(set())
                self._assign_taint([stmt.target], stmt.iter)
                self.loop_depth += 1
                for _ in range(2):  # second pass catches loop-carried taint
                    body = self.state.copy()
                    self.exec_stmts(stmt.body)
                    self.state.merge(body)
                self.loop_depth -= 1
                self.exec_stmts(stmt.orelse)
            elif isinstance(stmt, ast.While):
                self.scan_expr(stmt.test)
                self._apply_donations(set())
                self.loop_depth += 1
                for _ in range(2):
                    body = self.state.copy()
                    self.exec_stmts(stmt.body)
                    self.state.merge(body)
                self.loop_depth -= 1
                self.exec_stmts(stmt.orelse)
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                for item in stmt.items:
                    self.scan_expr(item.context_expr)
                self._apply_donations(set())
                self.exec_stmts(stmt.body)
            elif isinstance(stmt, ast.Try):
                pre = self.state.copy()
                self.exec_stmts(stmt.body)
                for handler in stmt.handlers:
                    h = self.state.copy()
                    self.state = pre.copy()
                    self.exec_stmts(handler.body)
                    self.state.merge(h)
                self.exec_stmts(stmt.orelse)
                self.exec_stmts(stmt.finalbody)
            elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                pass  # nested defs are analyzed as their own functions
            else:
                for child in ast.iter_child_nodes(stmt):
                    if isinstance(child, ast.expr):
                        self.scan_expr(child)
                self._apply_donations(set())
        return terminated


def _iter_functions(tree: ast.Module):
    """(fn, enclosing_class|None) for every def, including nested ones."""
    def walk(node, cls):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield child, cls
                yield from walk(child, cls)
            elif isinstance(child, ast.ClassDef):
                yield from walk(child, child)
            else:
                yield from walk(child, cls)
    yield from walk(tree, None)


def _check_file(sf: SourceFile, device_classes: Set[str]) -> List[Finding]:
    findings: List[Finding] = []
    jitted = _module_jitted(sf)
    class_attrs: Dict[ast.ClassDef, Set[str]] = {}
    for fn, cls in _iter_functions(sf.tree):
        if cls is not None and cls not in class_attrs:
            class_attrs[cls] = _class_device_attrs(cls, jitted)
        device_attrs = class_attrs.get(cls, set()) if cls is not None else set()
        check_sync = sf.sync_boundary_for_def(fn.lineno) is None
        checker = _FnChecker(sf, fn, jitted, device_classes, device_attrs, check_sync)
        checker.exec_stmts(fn.body)
        findings.extend(checker.findings)
    # loop bodies are walked twice and expressions can be revisited across
    # branch merges: one report per (rule, line, message) is enough
    seen: Set[Tuple[str, int, str]] = set()
    out: List[Finding] = []
    for f in findings:
        key = (f.rule, f.line, f.message)
        if key not in seen:
            seen.add(key)
            out.append(f)
    return out


def _all_jax_findings(project: Project) -> List[Finding]:
    def build() -> List[Finding]:
        out: List[Finding] = []
        dc = _device_classes(project)
        for sf in project.files:
            if _imports_jax(sf):
                out.extend(_check_file(sf, dc))
        return out
    return project.cached("jax.findings", build)


@rule("jax-sync", "implicit device syncs outside sanctioned sync boundaries")
def check_jax_sync(project: Project) -> List[Finding]:
    return [f for f in _all_jax_findings(project) if f.rule == "jax-sync"]


@rule("jax-donated-reuse", "buffer read after being passed to a donate_argnums call")
def check_donated_reuse(project: Project) -> List[Finding]:
    return [f for f in _all_jax_findings(project) if f.rule == "jax-donated-reuse"]


@rule("jax-recompile", "scalar literals into jitted callables / jit inside loops")
def check_recompile(project: Project) -> List[Finding]:
    return [f for f in _all_jax_findings(project) if f.rule == "jax-recompile"]
