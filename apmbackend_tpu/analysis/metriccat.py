"""Metric-catalogue drift check: obs registrations vs DESIGN.md §8.

Every metric name registered through the telemetry plane — ``.counter()``/
``.gauge()``/``.histogram()`` calls and ``Sample(...)`` collector views —
must appear in the DESIGN.md §8 "Metric catalogue" block, and every
catalogued name must still be registered somewhere. PR 2 promised the
catalogue as the operator's index; without a gate it drifts one PR later.

Catalogue grammar (the block from the line containing "Metric catalogue"
to the next markdown heading): backticked tokens, where a brace group
with commas expands (``apm_engine_{capacity,services}``) and a comma-free
trailing group is a label annotation to strip
(``apm_tick_stage_seconds{stage}``). Registrations with dynamic
(non-literal) names can't be checked and are skipped.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Set, Tuple

from .core import Finding, Project, rule

_REG_METHODS = {"counter", "gauge", "histogram"}
_NAME_RE = re.compile(r"`([a-zA-Z_][\w{},.()|-]*)`")
_METRIC_RE = re.compile(r"^apm_[a-z0-9_]+$")


def _registered(project: Project) -> Dict[str, Tuple[str, int]]:
    """{metric name: (file, line)} for every literal registration site."""
    def build() -> Dict[str, Tuple[str, int]]:
        out: Dict[str, Tuple[str, int]] = {}
        for sf in project.files:
            for node in ast.walk(sf.tree):
                if not isinstance(node, ast.Call):
                    continue
                name = None
                if (isinstance(node.func, ast.Attribute)
                        and node.func.attr in _REG_METHODS
                        and node.args and isinstance(node.args[0], ast.Constant)
                        and isinstance(node.args[0].value, str)):
                    name = node.args[0].value
                elif (((isinstance(node.func, ast.Name) and node.func.id == "Sample")
                       or (isinstance(node.func, ast.Attribute) and node.func.attr == "Sample"))
                      and node.args and isinstance(node.args[0], ast.Constant)
                      and isinstance(node.args[0].value, str)):
                    name = node.args[0].value
                if name is not None and name.startswith("apm_"):
                    out.setdefault(name, (sf.rel, node.lineno))
        return out
    return project.cached("metrics.registered", build)


def _expand(token: str) -> Tuple[Set[str], bool]:
    """(names, is_expansion): interpret one catalogue token. A comma brace
    group expands; a comma-free group is a label annotation and strips."""
    m = re.search(r"\{([^{}]*)\}", token)
    if not m:
        return {token}, False
    pre, group, post = token[: m.start()], m.group(1), token[m.end():]
    if "," in group:
        names: Set[str] = set()
        for alt in group.split(","):
            sub, _ = _expand(pre + alt.strip() + post)
            names |= sub
        return names, True
    return _expand(pre + post)  # label annotation: strip and re-examine


def _catalogue(project: Project) -> List[Tuple[str, int, Set[str], bool]]:
    """[(token, DESIGN.md line, expanded names, is_expansion)] from §8."""
    def build():
        out: List[Tuple[str, int, Set[str], bool]] = []
        path = os.path.join(project.root, "DESIGN.md")
        try:
            with open(path, "r", encoding="utf-8") as fh:
                lines = fh.read().splitlines()
        except OSError:
            return out
        in_block = False
        for i, line in enumerate(lines, 1):
            if not in_block:
                if "Metric catalogue" in line:
                    in_block = True
                else:
                    continue
            elif line.startswith("#"):
                break
            for token in _NAME_RE.findall(line):
                names, is_exp = _expand(token)
                if all(_METRIC_RE.match(n) for n in names) and names:
                    out.append((token, i, names, is_exp))
        return out
    return project.cached("metrics.catalogue", build)


@rule("metric-uncatalogued", "metrics registered in code but missing from DESIGN.md §8")
def check_uncatalogued(project: Project) -> List[Finding]:
    registered = _registered(project)
    catalogued: Set[str] = set()
    for _tok, _ln, names, _exp in _catalogue(project):
        catalogued |= names
    findings: List[Finding] = []
    for name, (rel, line) in sorted(registered.items()):
        if name not in catalogued:
            findings.append(Finding(
                "metric-uncatalogued", rel, line,
                f"metric {name!r} is registered here but missing from the "
                "DESIGN.md §8 catalogue — document it"))
    return findings


def _mentioned(project: Project) -> Set[str]:
    """apm_* tokens inside any string constant — evidence for metrics
    emitted as raw exposition text (the manager's ``apm_fleet_child_up``
    f-string markers) rather than through registry instruments."""
    def build() -> Set[str]:
        out: Set[str] = set()
        pat = re.compile(r"apm_[a-z0-9_]+")
        for sf in project.files:
            for node in ast.walk(sf.tree):
                if isinstance(node, ast.Constant) and isinstance(node.value, str):
                    out.update(pat.findall(node.value))
        return out
    return project.cached("metrics.mentioned", build)


@rule("metric-unregistered", "DESIGN.md §8 catalogue entries no code registers")
def check_unregistered(project: Project) -> List[Finding]:
    registered = set(_registered(project)) | _mentioned(project)
    findings: List[Finding] = []
    for token, line, names, _exp in _catalogue(project):
        missing = sorted(n for n in names if n not in registered)
        if missing:
            findings.append(Finding(
                "metric-unregistered", "DESIGN.md", line,
                f"catalogue entry `{token}` names {', '.join(missing)} but "
                "no code registers it — stale catalogue or lost metric"))
    return findings
