"""Transport-header drift check: stamped vs round-tripped vs read.

Message headers are the side channel everything above the transport
quietly depends on: ``msg_id`` is the at-least-once dedup key,
``ingest_ts`` anchors the e2e latency series, ``trace_id`` carries the
distributed trace, and ``redelivered`` flags the crash-recovery hop. The
producer stamps them once (``ProducerQueue.write_line``), but each
transport serializes them its own way — memory tuples, AMQP
BasicProperties, spool JSON — and a header that rides two of three
transports is exactly how trace_id-over-spool drift would slip in: every
test on the memory broker stays green while the spool deployment
silently loses the field.

Three checks, all from string-literal/AST evidence:

- **carry**: every transport backend's ``send`` must reference its
  ``headers`` parameter (opaque pass-through of the whole dict — the
  contract all three backends implement). A send that ignores headers
  drops every stamped key on that transport.
- **synthesized drift**: a header key a transport backend *adds* on its
  own (``headers["redelivered"] = True`` on redelivery) must be
  synthesized by EVERY transport backend — consumers read one key, not
  one-key-per-backend. This is the check that caught the real
  redelivered-over-spool gap (see transport/spool.py).
- **read-but-never-stamped**: a header key consumers read
  (``headers.get("k")`` / ``h["k"]``) must be stamped by the producer or
  synthesized by the transports — a typo'd key silently reads None
  forever.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Set, Tuple

from .core import Finding, Project, SourceFile, rule

# names treated as header dicts at read sites (worker uses `h` for the
# `headers or {}` rebind); anything else is out of scope to keep the rule
# near-zero false positive
_HEADER_NAMES = {"headers", "h"}


def _names_in(node: ast.AST) -> Set[str]:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def _stamped(project: Project) -> Dict[str, Tuple[str, int]]:
    """Header keys stamped at the transport-entry points — both producer
    send paths: ``ProducerQueue.write_line`` (object wire) and
    ``ProducerQueue.write_frames`` (frameMode wire, ISSUE 16). Harvested
    per function: dict-literal keys of ``headers = {...}`` plus
    ``headers["k"] = ...`` subscript assigns."""
    def build() -> Dict[str, Tuple[str, int]]:
        out: Dict[str, Tuple[str, int]] = {}
        sf = project.file("transport/base.py")
        if sf is None:
            return out
        fns = [node for node in ast.walk(sf.tree)
               if isinstance(node, ast.FunctionDef)
               and node.name in ("write_line", "write_frames")]
        for fn in fns:
            for node in ast.walk(fn):
                if (isinstance(node, ast.Assign) and isinstance(node.value, ast.Dict)
                        and any(isinstance(t, ast.Name) and t.id in _HEADER_NAMES
                                for t in node.targets)):
                    for key in node.value.keys:
                        if isinstance(key, ast.Constant) and isinstance(key.value, str):
                            out.setdefault(key.value, (sf.rel, node.lineno))
                if isinstance(node, ast.Assign):
                    for t in node.targets:
                        if (isinstance(t, ast.Subscript)
                                and isinstance(t.value, ast.Name)
                                and t.value.id in _HEADER_NAMES
                                and isinstance(t.slice, ast.Constant)
                                and isinstance(t.slice.value, str)):
                            out.setdefault(t.slice.value, (sf.rel, node.lineno))
        return out
    return project.cached("headers.stamped", build)


def _transport_backends(project: Project) -> List[SourceFile]:
    sep = "/"
    out = []
    for sf in project.files:
        rel = sf.rel.replace(os.sep, sep)
        parts = rel.split(sep)
        # frames.py is the payload codec, not a backend — no send() ledger
        if "transport" in parts[:-1] and parts[-1] not in (
                "base.py", "__init__.py", "frames.py"):
            out.append(sf)
    return out


def _synthesized(sf: SourceFile) -> Dict[str, int]:
    """{key: line} for ``<headers-ish>["k"] = ...`` assigns in a module."""
    out: Dict[str, int] = {}
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.Assign):
            continue
        for t in node.targets:
            if (isinstance(t, ast.Subscript)
                    and isinstance(t.value, ast.Name)
                    and t.value.id in _HEADER_NAMES
                    and isinstance(t.slice, ast.Constant)
                    and isinstance(t.slice.value, str)):
                out.setdefault(t.slice.value, node.lineno)
    return out


def _reads(project: Project) -> List[Tuple[str, str, int]]:
    """[(key, file, line)] for consumer-side header reads:
    ``headers.get("k")`` / ``h.get("k")`` (incl. the ``(headers or
    {}).get`` shape) and ``headers["k"]`` loads."""
    def build() -> List[Tuple[str, str, int]]:
        out: List[Tuple[str, str, int]] = []
        for sf in project.files:
            for node in ast.walk(sf.tree):
                key = None
                if (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr == "get"
                        and node.args
                        and isinstance(node.args[0], ast.Constant)
                        and isinstance(node.args[0].value, str)
                        and _names_in(node.func.value) & _HEADER_NAMES
                        and not isinstance(node.func.value, ast.Attribute)):
                    key = node.args[0].value
                elif (isinstance(node, ast.Subscript)
                        and isinstance(node.ctx, ast.Load)
                        and isinstance(node.value, ast.Name)
                        and node.value.id in _HEADER_NAMES
                        and isinstance(node.slice, ast.Constant)
                        and isinstance(node.slice.value, str)):
                    key = node.slice.value
                if key is not None:
                    out.append((key, sf.rel, node.lineno))
        return out
    return project.cached("headers.reads", build)


@rule("transport-header-drift",
      "message headers must ride every transport and resolve to a stamp")
def check_transport_headers(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    stamped = _stamped(project)
    backends = _transport_backends(project)
    if not backends:
        return findings

    # carry: every backend's send() must pass the headers dict through
    for sf in backends:
        for node in ast.walk(sf.tree):
            if not (isinstance(node, ast.FunctionDef) and node.name == "send"):
                continue
            params = {a.arg for a in node.args.args}
            if "headers" not in params:
                continue
            used = any(
                isinstance(n, ast.Name) and n.id == "headers"
                and isinstance(n.ctx, ast.Load)
                for stmt in node.body for n in ast.walk(stmt))
            if not used:
                findings.append(Finding(
                    "transport-header-drift", sf.rel, node.lineno,
                    "send() ignores its headers parameter — every stamped "
                    "header (msg_id, ingest_ts, trace_id) is dropped on "
                    "this transport"))

    # synthesized drift: transport-added keys must exist on ALL backends
    per_backend = {sf.rel: _synthesized(sf) for sf in backends}
    all_synth: Set[str] = set()
    for keys in per_backend.values():
        all_synth |= set(keys)
    for key in sorted(all_synth):
        have = [rel for rel, keys in per_backend.items() if key in keys]
        for sf in backends:
            if key in per_backend[sf.rel]:
                continue
            findings.append(Finding(
                "transport-header-drift", sf.rel, 1,
                f"header {key!r} is synthesized by {', '.join(sorted(have))} "
                f"but not by this transport — consumers reading it get "
                f"transport-dependent behavior"))

    # read-but-never-stamped
    known = set(stamped) | all_synth
    if known:  # no stamp site found at all: skip (fixture projects)
        for key, rel, line in _reads(project):
            if key not in known:
                findings.append(Finding(
                    "transport-header-drift", rel, line,
                    f"header {key!r} is read here but no producer stamps "
                    f"it and no transport synthesizes it — this read is "
                    f"always None"))
    return findings
