"""pyflakes-lite: the hard-requirement core of the lint gate.

The container image does not bake pyflakes in, and a lint gate that
soft-skips is not a gate. This module implements the two pyflakes checks
with near-zero false-positive rates as in-repo rules, so
``run_tests.sh --lint`` can hard-fail everywhere; when real pyflakes IS
available the script additionally runs it (also hard).

- ``unused-import``: a module-level or function-level import binding never
  referenced in the file. ``__init__.py`` files are exempt (the re-export
  idiom), as are ``__future__`` imports, ``import x as x`` explicit
  re-exports, and names listed in ``__all__``.
- ``redefinition``: a def/class name bound twice in the same scope body
  where the earlier binding is a def/class — shadowed dead code.
  ``@property``/``.setter``/``.deleter``/``@overload``/
  ``@singledispatch .register`` stacks are recognized as intentional.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set, Tuple

from .core import Finding, Project, rule


def _import_bindings(tree: ast.Module) -> List[Tuple[str, int, str]]:
    """(bound name, line, display) for every import in the file."""
    out: List[Tuple[str, int, str]] = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.asname:
                    if alias.asname == alias.name:
                        continue  # explicit re-export idiom
                    out.append((alias.asname, node.lineno, alias.name))
                else:
                    out.append((alias.name.split(".")[0], node.lineno, alias.name))
        elif isinstance(node, ast.ImportFrom):
            if node.module == "__future__":
                continue
            for alias in node.names:
                if alias.name == "*":
                    continue
                if alias.asname:
                    if alias.asname == alias.name:
                        continue
                    out.append((alias.asname, node.lineno, alias.name))
                else:
                    out.append((alias.name, node.lineno, alias.name))
    return out


def _used_names(tree: ast.Module) -> Set[str]:
    used: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Name):
            used.add(node.id)
        elif isinstance(node, ast.Attribute):
            pass  # roots arrive as the inner Name node
        elif isinstance(node, ast.Constant) and isinstance(node.value, str):
            # __all__ strings and string annotations reference names textually
            v = node.value
            if v.isidentifier():
                used.add(v)
            else:
                # 'Optional[EngineState]'-style string annotations
                for part in _ident_parts(v):
                    used.add(part)
    return used


def _ident_parts(s: str) -> List[str]:
    out, cur = [], []
    for ch in s:
        if ch.isalnum() or ch == "_":
            cur.append(ch)
        else:
            if cur:
                out.append("".join(cur))
            cur = []
    if cur:
        out.append("".join(cur))
    return out if len(out) <= 32 else []  # long prose strings aren't annotations


@rule("unused-import", "import bindings never referenced in the file")
def check_unused_imports(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    for sf in project.files:
        if sf.rel.endswith("__init__.py"):
            continue  # re-export surface
        used = _used_names(sf.tree)
        for name, line, display in _import_bindings(sf.tree):
            if name not in used:
                findings.append(Finding(
                    "unused-import", sf.rel, line,
                    f"'{display}' imported but unused"))
    return findings


_SETTER_DECOS = {"setter", "deleter", "getter", "register"}


def _is_intentional_redef(node: ast.AST) -> bool:
    for deco in getattr(node, "decorator_list", []):
        if isinstance(deco, ast.Attribute) and deco.attr in _SETTER_DECOS:
            return True
        if isinstance(deco, ast.Call) and isinstance(deco.func, ast.Attribute) \
                and deco.func.attr in _SETTER_DECOS:
            return True
        if isinstance(deco, ast.Name) and deco.id == "overload":
            return True
        if isinstance(deco, ast.Attribute) and deco.attr == "overload":
            return True
    return False


def _scope_bodies(tree: ast.Module):
    yield tree.body
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            yield node.body


@rule("redefinition", "def/class names rebound in the same scope (shadowed dead code)")
def check_redefinition(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    for sf in project.files:
        for body in _scope_bodies(sf.tree):
            seen: Dict[str, Tuple[int, bool]] = {}  # name -> (line, intentional)
            for stmt in body:
                if not isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                         ast.ClassDef)):
                    continue
                name = stmt.name
                intentional = _is_intentional_redef(stmt)
                if name in seen and not intentional and not seen[name][1]:
                    findings.append(Finding(
                        "redefinition", sf.rel, stmt.lineno,
                        f"'{name}' redefined; earlier definition on line "
                        f"{seen[name][0]} is dead"))
                seen[name] = (stmt.lineno, intentional)
    return findings
