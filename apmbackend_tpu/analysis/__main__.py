"""CLI: ``python -m apmbackend_tpu.analysis`` — the static-correctness gate.

Exit codes: 0 clean, 1 findings, 2 usage/internal error. ``run_tests.sh
--lint`` runs this over the repo as a hard requirement; the tier-1 suite
additionally asserts a clean run (tests/test_analysis.py).
"""

from __future__ import annotations

import argparse
import json
import sys

from .core import Project, RULES, run_analysis
from . import core as _core


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m apmbackend_tpu.analysis",
        description="AST static analysis: JAX hot-path, lock discipline, "
                    "config keys, metric catalogue, pyflakes-lite.",
    )
    ap.add_argument("--root", default=None,
                    help="repo root (default: auto-detected from the package)")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule subset (default: all)")
    ap.add_argument("--list-rules", action="store_true",
                    help="print rule names + descriptions and exit")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable findings")
    ap.add_argument("-q", "--quiet", action="store_true",
                    help="summary line only")
    args = ap.parse_args(argv)

    _core._register_builtin_rules()
    if args.list_rules:
        width = max(len(n) for n in RULES)
        for name in sorted(RULES):
            print(f"{name:<{width}}  {RULES[name][1]}")
        return 0

    rules = None
    if args.rules:
        rules = [r.strip() for r in args.rules.split(",") if r.strip()]
    try:
        project = Project(root=args.root)
        findings = run_analysis(project, rules=rules)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2

    if args.as_json:
        print(json.dumps([f.__dict__ for f in findings], indent=1))
    elif not args.quiet:
        for f in findings:
            print(f.format())
    n_files = len(project.files)
    n_rules = len(rules) if rules is not None else len(RULES)
    status = "clean" if not findings else f"{len(findings)} finding(s)"
    print(f"analysis: {n_files} files, {n_rules} rules — {status}",
          file=sys.stderr)
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
