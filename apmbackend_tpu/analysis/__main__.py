"""CLI: ``python -m apmbackend_tpu.analysis`` — the static-correctness gate.

Exit codes: 0 clean, 1 findings or a protocol model violation, 2 usage/
internal error. ``run_tests.sh --lint`` runs this over the repo as a hard
requirement; the tier-1 suite additionally asserts a clean run
(tests/test_analysis.py).

Beyond the AST rules, the gate runs the protocol model checker
(``analysis/protocol/``): ``--models small`` (the default when analyzing
the whole repo) exhaustively verifies the delivery, delta-chain, and
sharded-epoch protocols at the documented small scopes in well under the
10 s budget; ``--models deep`` is the ``run_tests.sh --model`` tier;
``--models mutants`` additionally requires a counterexample from every
seeded protocol bug. A violated model prints its counterexample schedule
and fails the gate exactly like a finding. ``--json`` emits a single
object ``{"findings": [...], "model_checks": [...], "mutants": [...]}``
for CI annotation.
"""

from __future__ import annotations

import argparse
import json
import sys

from .core import Project, RULES, run_analysis
from . import core as _core


def _run_models(tier: str):
    from .protocol import run_model_checks, verify_mutants

    results = run_model_checks("deep" if tier == "deep" else "small")
    mutants = verify_mutants() if tier in ("mutants", "deep") else []
    return results, mutants


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m apmbackend_tpu.analysis",
        description="AST static analysis + protocol model checking: JAX "
                    "hot-path, lock discipline, config keys, metric "
                    "catalogue, transport headers, durability discipline, "
                    "pyflakes-lite, and exhaustive small-scope verification "
                    "of the delivery/delta-chain/sharded-epoch protocols.",
    )
    ap.add_argument("--root", default=None,
                    help="repo root (default: auto-detected from the package)")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule subset (default: all)")
    ap.add_argument("--list-rules", action="store_true",
                    help="print rule names + descriptions and exit")
    ap.add_argument("--models", default=None,
                    choices=("off", "small", "deep", "mutants"),
                    help="protocol model-check tier (default: small for a "
                         "full-rule run, off when --rules selects a subset)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable findings + model verdicts")
    ap.add_argument("-q", "--quiet", action="store_true",
                    help="summary line only")
    args = ap.parse_args(argv)

    _core._register_builtin_rules()
    if args.list_rules:
        width = max(len(n) for n in RULES)
        for name in sorted(RULES):
            print(f"{name:<{width}}  {RULES[name][1]}")
        return 0

    rules = None
    if args.rules:
        rules = [r.strip() for r in args.rules.split(",") if r.strip()]
    models = args.models
    if models is None:
        models = "off" if rules is not None else "small"
    try:
        project = Project(root=args.root)
        findings = run_analysis(project, rules=rules)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2

    model_results, mutant_results = [], []
    if models != "off":
        model_results, mutant_results = _run_models(models)
    bad_models = [r for r in model_results if not r.ok]
    bad_mutants = [(n, d, r) for n, d, r in mutant_results if r.ok]

    if args.as_json:
        print(json.dumps({
            "findings": [f.__dict__ for f in findings],
            "model_checks": [r.to_dict() for r in model_results],
            "mutants": [
                {"name": n, "description": d, "counterexample_found": not r.ok,
                 "schedule_steps": max(0, len(r.schedule) - 1),
                 "states": r.states}
                for n, d, r in mutant_results
            ],
        }, indent=1))
    elif not args.quiet:
        for f in findings:
            print(f.format())
        for r in bad_models:
            print(r.format_schedule())
        for n, _d, r in bad_mutants:
            print(f"mutant {n}: NO counterexample found ({r.states} states) "
                  f"— the checker lost its teeth for this bug class")

    n_files = len(project.files)
    n_rules = len(rules) if rules is not None else len(RULES)
    parts = [f"{n_files} files", f"{n_rules} rules"]
    if models != "off":
        total_states = sum(r.states for r in model_results)
        parts.append(f"{len(model_results)} protocol models "
                     f"({models}, {total_states} states)")
        if mutant_results:
            parts.append(f"{len(mutant_results)} mutants")
    bad = len(findings) + len(bad_models) + len(bad_mutants)
    status = "clean" if not bad else f"{bad} finding(s)"
    print(f"analysis: {', '.join(parts)} — {status}", file=sys.stderr)
    return 1 if bad else 0


if __name__ == "__main__":
    sys.exit(main())
