"""Analyzer infrastructure: parsed sources, pragma grammar, rule registry.

The pragma grammar (DESIGN.md §9):

- ``# apm: allow(rule[, rule2]): <reason>`` — suppress the named rule(s)
  on this line (trailing comment) or on the next line (comment-only line).
  The reason is mandatory: a bare ``allow`` is reported as ``pragma-bare``,
  and an ``allow`` that suppressed nothing this run as ``pragma-unused`` —
  every exemption stays deliberate and auditable.
- ``# apm: holds(<lock>): <reason>`` — on (or directly above) a ``def``:
  the method is documented as called with ``self.<lock>`` already held;
  the lock-discipline checker treats guarded accesses inside it as covered.
- ``# apm: sync-boundary: <reason>`` — on (or directly above) a ``def``:
  the function IS a sanctioned host/device sync boundary (the emit
  readback, checkpoint save); the JAX sync rule skips its body.
- ``# guarded-by: <lock>`` — trailing on a ``self.<attr> = ...`` line in
  ``__init__``: declares the attribute shared state owned by that lock.

Rules are callables ``rule(project) -> [Finding]`` registered in
:data:`RULES`; per-file work iterates ``project.files``. The runner
applies suppression centrally so every rule gets pragma handling for free.
"""

from __future__ import annotations

import ast
import io
import os
import re
import tokenize
from dataclasses import dataclass, field
from typing import Callable, Dict, Iterable, List, Optional, Tuple

_ALLOW_RE = re.compile(r"#\s*apm:\s*allow\(\s*([\w\-, ]+?)\s*\)\s*(?::\s*(.*\S))?\s*$")
_HOLDS_RE = re.compile(r"#\s*apm:\s*holds\(\s*(?:self\.)?([\w]+)\s*\)\s*(?::\s*(.*\S))?\s*$")
_SYNC_RE = re.compile(r"#\s*apm:\s*sync-boundary\s*(?::\s*(.*\S))?\s*$")
_GUARDED_RE = re.compile(r"#\s*guarded-by:\s*(?:self\.)?([\w]+)")
# anything that claims to be an apm pragma must parse as one of the above
_PRAGMA_ANY_RE = re.compile(r"#\s*apm:")


@dataclass
class Finding:
    rule: str
    path: str  # repo-relative
    line: int
    message: str

    def format(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


@dataclass
class Allow:
    rules: Tuple[str, ...]
    reason: str
    line: int  # line the pragma applies to
    comment_line: int  # line the comment physically sits on
    used: bool = False


@dataclass
class SourceFile:
    path: str  # absolute
    rel: str  # repo-relative
    text: str
    tree: ast.Module
    comments: Dict[int, str] = field(default_factory=dict)  # line -> comment text
    code_lines: set = field(default_factory=set)  # lines bearing non-comment tokens
    allows: List[Allow] = field(default_factory=list)
    holds: Dict[int, Tuple[str, str]] = field(default_factory=dict)  # line -> (lock, reason)
    sync_boundaries: Dict[int, str] = field(default_factory=dict)  # line -> reason
    guarded: Dict[int, str] = field(default_factory=dict)  # line -> lock name

    def allow_for(self, rule: str, line: int) -> Optional[Allow]:
        for al in self.allows:
            if al.line == line and rule in al.rules:
                return al
        return None

    def annotation_lines(self, def_line: int) -> Tuple[int, int]:
        """Lines a function-level pragma may sit on: the ``def`` line itself
        or the comment-only line directly above it (skipping decorators is
        deliberate — the pragma belongs next to the def)."""
        return (def_line - 1, def_line)

    def holds_for_def(self, def_line: int) -> Optional[Tuple[str, str]]:
        for ln in self.annotation_lines(def_line):
            if ln in self.holds:
                return self.holds[ln]
        return None

    def sync_boundary_for_def(self, def_line: int) -> Optional[str]:
        for ln in self.annotation_lines(def_line):
            if ln in self.sync_boundaries:
                return self.sync_boundaries[ln]
        return None


def _collect_comments(text: str) -> Tuple[Dict[int, str], set]:
    comments: Dict[int, str] = {}
    code_lines: set = set()
    try:
        for tok in tokenize.generate_tokens(io.StringIO(text).readline):
            if tok.type == tokenize.COMMENT:
                comments[tok.start[0]] = tok.string
            elif tok.type not in (
                tokenize.NL,
                tokenize.NEWLINE,
                tokenize.INDENT,
                tokenize.DEDENT,
                tokenize.ENDMARKER,
            ):
                code_lines.add(tok.start[0])
    except tokenize.TokenError:
        pass  # compileall is the syntax gate; salvage what tokenized
    return comments, code_lines


def parse_source(path: str, rel: str, text: str) -> SourceFile:
    tree = ast.parse(text, filename=rel)
    comments, code_lines = _collect_comments(text)
    sf = SourceFile(path=path, rel=rel, text=text, tree=tree,
                    comments=comments, code_lines=code_lines)
    for line, comment in comments.items():
        target = line if line in code_lines else line + 1
        m = _ALLOW_RE.search(comment)
        if m:
            rules = tuple(r.strip() for r in m.group(1).split(",") if r.strip())
            sf.allows.append(Allow(rules, (m.group(2) or "").strip(), target, line))
            continue
        m = _HOLDS_RE.search(comment)
        if m:
            sf.holds[line] = (m.group(1), (m.group(2) or "").strip())
            continue
        m = _SYNC_RE.search(comment)
        if m:
            sf.sync_boundaries[line] = (m.group(1) or "").strip()
            continue
        m = _GUARDED_RE.search(comment)
        if m:
            sf.guarded[line] = m.group(1)
            continue
        if _PRAGMA_ANY_RE.search(comment):
            # a malformed apm pragma silently suppressing nothing is worse
            # than no pragma; surfaced through a dedicated pseudo-rule below
            sf.allows.append(Allow(("pragma-malformed",), comment, target, line))
    return sf


class Project:
    """The analyzed tree: parsed package sources + repo-level artifacts
    (config schema, DESIGN.md) shared by rules via cached properties."""

    def __init__(self, root: Optional[str] = None,
                 package: str = "apmbackend_tpu",
                 extra_dirs: Tuple[str, ...] = ()):
        if root is None:
            root = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
        self.root = os.path.abspath(root)
        self.package = package
        self.extra_dirs = extra_dirs
        self.files: List[SourceFile] = []
        self.parse_errors: List[Finding] = []
        self._scan()
        self._cache: dict = {}

    def _scan(self) -> None:
        dirs = [os.path.join(self.root, self.package)]
        dirs += [os.path.join(self.root, d) for d in self.extra_dirs]
        for base in dirs:
            for dirpath, dirnames, filenames in os.walk(base):
                dirnames[:] = sorted(d for d in dirnames if d != "__pycache__")
                for name in sorted(filenames):
                    if not name.endswith(".py"):
                        continue
                    path = os.path.join(dirpath, name)
                    rel = os.path.relpath(path, self.root)
                    try:
                        with open(path, "r", encoding="utf-8") as fh:
                            text = fh.read()
                        self.files.append(parse_source(path, rel, text))
                    except (OSError, SyntaxError, ValueError) as e:
                        self.parse_errors.append(
                            Finding("parse-error", rel, getattr(e, "lineno", 0) or 0, str(e))
                        )

    def file(self, rel_suffix: str) -> Optional[SourceFile]:
        for sf in self.files:
            if sf.rel.endswith(rel_suffix):
                return sf
        return None

    def cached(self, key: str, fn: Callable[[], object]):
        if key not in self._cache:
            self._cache[key] = fn()
        return self._cache[key]


RuleFn = Callable[[Project], List[Finding]]
RULES: Dict[str, Tuple[RuleFn, str]] = {}


def rule(name: str, description: str) -> Callable[[RuleFn], RuleFn]:
    def deco(fn: RuleFn) -> RuleFn:
        RULES[name] = (fn, description)
        return fn
    return deco


def _register_builtin_rules() -> None:
    # imported for their @rule side effects; late import breaks the cycle
    from . import (configkeys, durability, jaxrules, locks, metriccat,
                   pyflakes_lite, transport_headers)
    _ = (configkeys, durability, jaxrules, locks, metriccat,
         pyflakes_lite, transport_headers)


def run_analysis(
    project: Optional[Project] = None,
    rules: Optional[Iterable[str]] = None,
) -> List[Finding]:
    """Run the selected rules (default: all) and the pragma audit; returns
    findings with suppressed ones already removed. Clean repo == []."""
    _register_builtin_rules()
    if project is None:
        project = Project()
    enabled = list(RULES) if rules is None else list(rules)
    unknown = [r for r in enabled if r not in RULES]
    if unknown:
        raise ValueError(f"unknown rule(s): {', '.join(unknown)}")

    findings: List[Finding] = list(project.parse_errors)
    for name in enabled:
        fn, _ = RULES[name]
        for f in fn(project):
            sf = project.file(f.path)
            al = sf.allow_for(f.rule, f.line) if sf else None
            if al is not None:
                al.used = True
            else:
                findings.append(f)

    # pragma audit: bare, malformed, and unused allows. Only audit pragmas
    # naming an enabled rule — a subset run must not flag the others' pragmas.
    for sf in project.files:
        for al in sf.allows:
            if al.rules == ("pragma-malformed",):
                findings.append(Finding(
                    "pragma-malformed", sf.rel, al.comment_line,
                    f"unrecognized apm pragma: {al.reason.strip()!r}"))
                continue
            if not any(r in enabled for r in al.rules):
                continue
            if not al.reason:
                findings.append(Finding(
                    "pragma-bare", sf.rel, al.comment_line,
                    f"allow({', '.join(al.rules)}) without a written reason — "
                    "every suppression must say why"))
            if not al.used:
                findings.append(Finding(
                    "pragma-unused", sf.rel, al.comment_line,
                    f"allow({', '.join(al.rules)}) suppresses nothing — "
                    "remove it or fix the rule name"))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings
