"""Durability discipline: no raw writes under crash-consistent directories.

Every crash-consistency proof in this repo (delta-chain recovery, spool
cursor atomicity, flight-journal promotion) rests on ONE idiom: write a
tmp file, optionally fsync, then ``os.replace`` onto the final name — the
rename is the commit. A raw ``open(path, "w")`` or a bare rename on a
path under a checkpoint/spool/flight directory bypasses that idiom, and
the failure it introduces (a torn file AT the committed name) is exactly
the one the recovery walks cannot always detect. PR 7's durability audit
found the spool cursor's shared-tmp bug by hand; this rule makes the
discipline machine-checked.

Mechanics:

- a *durable write* is ``open(..., "w"/"wb"/...)`` (or ``os.fdopen`` with
  a write mode), ``os.rename`` or ``os.replace`` whose path expression
  mentions a durability-flavored token (spool/cursor/chain/manifest/
  checkpoint/resume/flight/journal/sentinel/.seg/.npz) — or ANY such call
  inside the modules that own durable state (deltachain, transport/spool,
  obs/flight, utils/resume);
- the *sanctioned atomic-writer* exemption: a function whose body
  renames/replaces FROM a tmp name (``os.replace(tmp, path)``) is an
  atomic commit helper — its open-the-tmp and rename calls are the idiom
  itself. Everything else is a finding: fix it, or carry an explicit
  ``# apm: allow(durability-discipline): <reason>`` (the chaos harness's
  deliberate corruption injectors do).

Append-mode opens are NOT flagged: append-only journals with record
framing (the spool, the protocol event log) are a legitimate second
discipline — torn tails there are detected by the reader, not prevented
by rename.
"""

from __future__ import annotations

import ast
import re
from typing import List, Optional

from .core import Finding, Project, rule

_PATH_TOKEN_RE = re.compile(
    r"(spool|cursor|chain|manifest|checkpoint|resume|flight|journal|"
    r"sentinel|seg|\.npz)", re.IGNORECASE)

# modules whose whole job is durable state: every write-ish call in them
# is in scope regardless of what the path expression looks like
_DURABILITY_MODULES = (
    "deltachain.py", "transport/spool.py", "obs/flight.py",
    "utils/resume.py",
)


def _is_os_call(node: ast.Call, name: str) -> bool:
    f = node.func
    return (isinstance(f, ast.Attribute) and f.attr == name
            and isinstance(f.value, ast.Name) and f.value.id == "os")


def _write_call(node: ast.Call) -> Optional[ast.AST]:
    """The path expression of a durable-write call, or None."""
    f = node.func
    if (isinstance(f, ast.Name) and f.id == "open") or \
            (isinstance(f, ast.Attribute) and f.attr == "fdopen"
             and isinstance(f.value, ast.Name) and f.value.id == "os"):
        if (len(node.args) >= 2 and isinstance(node.args[1], ast.Constant)
                and isinstance(node.args[1].value, str)
                and node.args[1].value.startswith(("w", "x"))):
            return node.args[0]
        return None
    if _is_os_call(node, "rename") or _is_os_call(node, "replace"):
        # the destination is the committed name; the source tells us
        # whether this is the sanctioned tmp->final commit
        return node.args[1] if len(node.args) >= 2 else None
    return None


def _expr_text(node: ast.AST) -> str:
    try:
        return ast.unparse(node)
    except Exception:
        return ""


def _string_payload(node: ast.AST) -> str:
    """All string constants inside an expression (f-string parts, concat
    pieces) — the path evidence the relevance regex runs over."""
    parts = []
    for n in ast.walk(node):
        if isinstance(n, ast.Constant) and isinstance(n.value, str):
            parts.append(n.value)
    return " ".join(parts)


def _atomic_writer_functions(tree: ast.Module) -> List[ast.AST]:
    """Functions containing an ``os.replace/rename`` whose SOURCE operand
    mentions tmp — the sanctioned atomic-commit helpers."""
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call) and (
                    _is_os_call(sub, "replace") or _is_os_call(sub, "rename")):
                if sub.args and "tmp" in _expr_text(sub.args[0]).lower():
                    out.append(node)
                    break
    return out


@rule("durability-discipline",
      "raw writes/renames on durable paths outside atomic tmp+rename helpers")
def check_durability(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    for sf in project.files:
        rel_posix = sf.rel.replace("\\", "/")
        owner_module = any(rel_posix.endswith(m) for m in _DURABILITY_MODULES)
        sanctioned_spans = [
            (fn.lineno, max(getattr(fn, "end_lineno", fn.lineno), fn.lineno))
            for fn in _atomic_writer_functions(sf.tree)
        ]

        def inside_sanctioned(line: int) -> bool:
            return any(lo <= line <= hi for lo, hi in sanctioned_spans)

        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            path = _write_call(node)
            if path is None:
                continue
            text = _expr_text(path) + " " + _string_payload(node)
            if not (owner_module or _PATH_TOKEN_RE.search(text)):
                continue
            if inside_sanctioned(node.lineno):
                continue
            kind = ("rename" if isinstance(node.func, ast.Attribute)
                    and node.func.attr in ("rename", "replace") else "open-for-write")
            findings.append(Finding(
                "durability-discipline", sf.rel, node.lineno,
                f"raw {kind} on a durable path ({_expr_text(path)[:60]}) "
                f"outside a sanctioned atomic tmp+rename helper — a crash "
                f"here leaves a torn file at a committed name; use the "
                f"tmp+fsync+os.replace idiom or pragma with a reason"))
    return findings
