"""Native (C++) runtime components + ctypes bindings.

Sources live in ``native/`` at the repo root; this package builds them on
demand with ``make`` (g++, no external deps) and exposes:

- :func:`tail_binary_path` — the ``apm_tail`` per-file tailer binary
  (perl_tail.pl role), consumed by ingest.tailer.NativeTailer/TailManager.
- :class:`LineRing` — lock-free SPSC byte ring (native/ring.cpp): the
  bounded host buffer between producers and the device step loop, with
  full-ring push failure as the backpressure signal (queue.js:250-256 role).
- :class:`TxDecoder` — batch tx pipe-CSV decoder (native/decoder.cpp): one
  C++ pass over a newline-joined blob -> dense (end_ts, elapsed, key id,
  line span) arrays with first-appearance key interning; the host intake
  hot path behind pipeline.feed_csv_batch.
- :class:`ParserEngineNative` — the log-correlation parser's ingest fast
  path (native/parser.cpp): chunked marker pre-filter + field extraction +
  the (logId, service) TTL correlation join, plus the per-file SOAP/audit
  state machines; consumed by ingest.parser.TransactionParser.read_lines
  (APM_PARSE_NO_NATIVE=1 kills it).
- :func:`frames_pack_native` — the APF1 frame-batch packer (apmfrm_pack in
  native/parser.cpp): newline-joined tx lines -> one packed frame batch
  for transport/frames.py (APM_FRAMES_NO_NATIVE=1 kills it).

Everything degrades gracefully: with no compiler available the build
functions return None and callers fall back to the pure-Python paths.
"""

from __future__ import annotations

import ctypes
import os
import struct
import subprocess
import threading
from typing import Optional

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", "..", "native")
_BUILD_LOCK = threading.Lock()
_BUILD_RESULT: dict = {}


def native_source_dir() -> str:
    return os.path.abspath(_NATIVE_DIR)


def ensure_built(*, quiet: bool = True, timeout_s: float = 45.0) -> Optional[str]:
    """Run ``make`` in native/ once per process; returns the build dir or
    None when the toolchain/sources are unavailable.

    ``APM_NATIVE_SANITIZE=1`` switches to the ASan+UBSan instrumented
    artifacts (``make sanitize`` -> build-sanitize/) — the hardened mode
    ``run_tests.sh --sanitize`` drives the differential fuzz suite under,
    with libasan LD_PRELOADed so the instrumented .so files resolve their
    runtime inside the stock Python process."""
    sanitize = os.environ.get("APM_NATIVE_SANITIZE", "") not in ("", "0")
    key = "sanitize-dir" if sanitize else "dir"
    with _BUILD_LOCK:
        if key in _BUILD_RESULT:
            return _BUILD_RESULT[key]
        src = native_source_dir()
        result: Optional[str] = None
        if os.path.isfile(os.path.join(src, "Makefile")):
            cmd = ["make", "-C", src] + (["sanitize"] if sanitize else [])
            try:
                proc = subprocess.run(
                    cmd,
                    stdout=subprocess.PIPE,
                    stderr=subprocess.STDOUT,
                    timeout=timeout_s,
                )
                if proc.returncode == 0:
                    result = os.path.join(
                        src, "build-sanitize" if sanitize else "build")
                elif not quiet:
                    raise RuntimeError(f"native build failed:\n{proc.stdout.decode()}")
            except (OSError, subprocess.TimeoutExpired):
                if not quiet:
                    raise
        _BUILD_RESULT[key] = result
        return result


def tail_binary_path() -> Optional[str]:
    """Path to the apm_tail binary, building if needed; None if unavailable."""
    build = ensure_built()
    if build is None:
        return None
    path = os.path.join(build, "apm_tail")
    return path if os.access(path, os.X_OK) else None


_ring_lib = None


def _load_ring_lib():
    global _ring_lib
    if _ring_lib is not None:
        return _ring_lib
    build = ensure_built()
    if build is None:
        return None
    so = os.path.join(build, "libapmring.so")
    if not os.path.isfile(so):
        return None
    lib = ctypes.CDLL(so)
    lib.apmring_create.restype = ctypes.c_void_p
    lib.apmring_create.argtypes = [ctypes.c_uint64]
    lib.apmring_destroy.argtypes = [ctypes.c_void_p]
    lib.apmring_capacity.restype = ctypes.c_uint64
    lib.apmring_capacity.argtypes = [ctypes.c_void_p]
    lib.apmring_used.restype = ctypes.c_uint64
    lib.apmring_used.argtypes = [ctypes.c_void_p]
    lib.apmring_dropped.restype = ctypes.c_uint64
    lib.apmring_dropped.argtypes = [ctypes.c_void_p]
    lib.apmring_push.restype = ctypes.c_int
    lib.apmring_push.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint32]
    lib.apmring_pop.restype = ctypes.c_int64
    lib.apmring_pop.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint32]
    _ring_lib = lib
    return lib


_decode_lib = None


def _load_decode_lib():
    global _decode_lib
    if _decode_lib is not None:
        return _decode_lib
    build = ensure_built()
    if build is None:
        return None
    so = os.path.join(build, "libapmdecode.so")
    if not os.path.isfile(so):
        return None
    lib = ctypes.CDLL(so)
    lib.apmdec_create.restype = ctypes.c_void_p
    lib.apmdec_create.argtypes = []
    lib.apmdec_destroy.argtypes = [ctypes.c_void_p]
    lib.apmdec_key_count.restype = ctypes.c_int32
    lib.apmdec_key_count.argtypes = [ctypes.c_void_p]
    lib.apmdec_batch.restype = ctypes.c_int64
    lib.apmdec_batch.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64,
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
        ctypes.c_uint64, ctypes.POINTER(ctypes.c_uint64),
    ]
    lib.apmdec_keys.restype = ctypes.c_int64
    lib.apmdec_keys.argtypes = [
        ctypes.c_void_p, ctypes.c_int32, ctypes.c_char_p, ctypes.c_uint64,
    ]
    _decode_lib = lib
    return lib


class TxDecoder:
    """Batch decoder for ``tx|...`` wire lines over libapmdecode.

    ``decode(blob)`` parses a newline-separated byte blob in one native pass
    and returns numpy arrays; (server, service) keys are interned to dense
    int32 ids in first-appearance order, monotonic for the decoder's
    lifetime (``key_count``/``keys_from`` expose the id -> key mapping).
    Numeric fields follow entries.js_parse_int semantics; records whose
    numeric fields contain non-ASCII bytes come back flagged so the caller
    re-parses them with the Python reference implementation.
    """

    def __init__(self):
        lib = _load_decode_lib()
        if lib is None:
            raise RuntimeError("native decoder unavailable (no toolchain?)")
        self._lib = lib
        self._h = lib.apmdec_create()
        if not self._h:
            raise MemoryError("apmdec_create failed")

    def decode(self, blob: bytes):
        """-> (end_ts[f8], elapsed[f8], keyid[i4], line_off[i8], line_len[i4],
        flags[u1], n_bad). Arrays are trimmed to the parsed record count."""
        import numpy as np

        if not self._h:
            raise RuntimeError("decoder closed")
        # upper bound on records: one per newline + the unterminated tail
        cap = blob.count(b"\n") + 1
        end_ts = np.empty(cap, np.float64)
        elapsed = np.empty(cap, np.float64)
        keyid = np.empty(cap, np.int32)
        line_off = np.empty(cap, np.int64)
        line_len = np.empty(cap, np.int32)
        flags = np.empty(cap, np.uint8)
        n_bad = ctypes.c_uint64(0)
        n = self._lib.apmdec_batch(
            self._h, blob, len(blob),
            end_ts.ctypes.data_as(ctypes.c_void_p),
            elapsed.ctypes.data_as(ctypes.c_void_p),
            keyid.ctypes.data_as(ctypes.c_void_p),
            line_off.ctypes.data_as(ctypes.c_void_p),
            line_len.ctypes.data_as(ctypes.c_void_p),
            flags.ctypes.data_as(ctypes.c_void_p),
            cap, ctypes.byref(n_bad),
        )
        n = int(n)
        return (end_ts[:n], elapsed[:n], keyid[:n], line_off[:n], line_len[:n],
                flags[:n], int(n_bad.value))

    @property
    def key_count(self) -> int:
        return int(self._lib.apmdec_key_count(self._h)) if self._h else 0

    def keys_from(self, start: int):
        """[(server, service), ...] for interned ids >= start, in id order."""
        if not self._h:
            return []
        cap = 1 << 16
        while True:
            buf = ctypes.create_string_buffer(cap)
            n = int(self._lib.apmdec_keys(self._h, start, buf, cap))
            if n >= 0:
                raw = buf.raw[:n]
                break
            cap = -n
        out = []
        for rec in raw.split(b"\n"):
            if rec:
                srv, _, svc = rec.partition(b"\x00")
                out.append((srv.decode("utf-8", "replace"), svc.decode("utf-8", "replace")))
        return out

    def close(self) -> None:
        if self._h:
            self._lib.apmdec_destroy(self._h)
            self._h = None

    def __del__(self):  # pragma: no cover - GC timing
        try:
            self.close()
        except Exception:
            pass


class LineRing:
    """SPSC byte-record ring over libapmring. One pushing thread, one popping
    thread; ``push`` returning False = ring full = raise backpressure."""

    def __init__(self, capacity_bytes: int = 1 << 22, *, max_record: int = 1 << 16):
        lib = _load_ring_lib()
        if lib is None:
            raise RuntimeError("native ring unavailable (no toolchain?)")
        self._lib = lib
        self._ring = lib.apmring_create(ctypes.c_uint64(capacity_bytes))
        if not self._ring:
            raise MemoryError("apmring_create failed")
        self._buf = ctypes.create_string_buffer(max_record)
        # guards every native call against close(): an interval-stats timer
        # or an in-flight broker delivery can overlap shutdown, and apmring_*
        # dereference the handle blindly. The producer and consumer do
        # contend on this mutex per record, but an uncontended/lightly
        # contended futex (~tens of ns) is noise next to the ctypes call
        # itself (~1 us) — measured intake with the locked hot path is
        # ~236k lines/s, unchanged from the lock-free version.
        self._close_lock = threading.Lock()

    def push(self, data: bytes) -> bool:
        with self._close_lock:
            if not self._ring:
                return False
            return bool(self._lib.apmring_push(self._ring, data, len(data)))

    def pop(self) -> Optional[bytes]:
        """One record, or None when empty. The pop-side buffer grows to fit
        oversized records (SPSC: only the popping thread touches it)."""
        with self._close_lock:
            if not self._ring:
                return None
            n = self._lib.apmring_pop(self._ring, self._buf, len(self._buf))
            if n == 0:
                return None
            if n < 0:  # record larger than our buffer: grow and retry
                self._buf = ctypes.create_string_buffer(int(-n))
                n = self._lib.apmring_pop(self._ring, self._buf, len(self._buf))
                if n <= 0:
                    return None
            return self._buf.raw[:n]

    def _stat(self, fn) -> int:
        with self._close_lock:
            if not self._ring:
                return 0
            return int(fn(self._ring))

    @property
    def used_bytes(self) -> int:
        return self._stat(self._lib.apmring_used)

    @property
    def dropped(self) -> int:
        return self._stat(self._lib.apmring_dropped)

    @property
    def capacity(self) -> int:
        return self._stat(self._lib.apmring_capacity)

    def close(self) -> None:
        with self._close_lock:
            if self._ring:
                self._lib.apmring_destroy(self._ring)
                self._ring = None

    def __del__(self):  # pragma: no cover - GC timing
        try:
            self.close()
        except Exception:
            pass


# ------------------------------------------------------------------- parser

_parser_lib = None


def _load_parser_lib():
    global _parser_lib
    if _parser_lib is not None:
        return _parser_lib
    build = ensure_built()
    if build is None:
        return None
    so = os.path.join(build, "libapmparser.so")
    if not os.path.isfile(so):
        return None
    lib = ctypes.CDLL(so)
    lib.apmpar_create.restype = ctypes.c_void_p
    lib.apmpar_create.argtypes = [ctypes.c_double, ctypes.c_double, ctypes.c_double]
    lib.apmpar_destroy.argtypes = [ctypes.c_void_p]
    lib.apmpar_stats.argtypes = [ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint64)]
    lib.apmpar_sweep.argtypes = [ctypes.c_void_p, ctypes.c_double]
    lib.apmpar_clear.argtypes = [ctypes.c_void_p]
    lib.apmpar_park.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int32, ctypes.c_char_p,
        ctypes.c_int32, ctypes.c_int32, ctypes.c_char_p, ctypes.c_int32,
        ctypes.c_double,
    ]
    lib.apmpar_take.restype = ctypes.c_int32
    lib.apmpar_take.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int32, ctypes.c_char_p,
        ctypes.c_int32, ctypes.c_double, ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
    ]
    lib.apmpar_pool.restype = ctypes.c_void_p
    lib.apmpar_pool.argtypes = [ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint64)]
    lib.apmpar_peek.restype = ctypes.c_int64
    lib.apmpar_peek.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int32, ctypes.c_double,
    ]
    lib.apmpar_drain_expired.restype = ctypes.c_int64
    lib.apmpar_drain_expired.argtypes = [ctypes.c_void_p]
    lib.apmpar_expired_pending.restype = ctypes.c_uint64
    lib.apmpar_expired_pending.argtypes = [ctypes.c_void_p]
    lib.apmpar_chunk.restype = ctypes.c_int64
    lib.apmpar_chunk.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64, ctypes.c_int32,
        ctypes.c_int32, ctypes.c_int32, ctypes.c_double, ctypes.c_void_p,
        ctypes.c_uint64, ctypes.POINTER(ctypes.c_uint64),
    ]
    lib.apmpar_soap_get.restype = ctypes.c_int32
    lib.apmpar_soap_get.argtypes = [ctypes.c_void_p, ctypes.c_int32]
    lib.apmpar_soap_set.argtypes = [
        ctypes.c_void_p, ctypes.c_int32, ctypes.c_char_p, ctypes.c_int32,
    ]
    lib.apmpar_soap_arm.argtypes = [ctypes.c_void_p, ctypes.c_int32]
    lib.apmpar_soap_close.argtypes = [ctypes.c_void_p, ctypes.c_int32]
    try:
        lib.apmfrm_pack.restype = ctypes.c_int64
        lib.apmfrm_pack.argtypes = [
            ctypes.c_char_p, ctypes.c_int64, ctypes.c_char_p, ctypes.c_int64,
        ]
    except AttributeError:
        # stale pre-frame .so: the codec's Python encoder takes over
        pass
    _parser_lib = lib
    return lib


def have_native_parser() -> bool:
    """True when libapmparser built/loaded (toolchain present)."""
    return _load_parser_lib() is not None


def frames_pack_native(lines_b):
    """Pack line bytes into one APF1 frame batch via the native scanner
    (apmfrm_pack). Returns a bytearray whose exotic records still carry
    NaN numerics — transport/frames.py patches those with the full
    js_parse_int semantics — or None when the library is unavailable, the
    symbol is stale, or the native record count disagrees with the input
    (embedded newline) and the Python encoder must take over."""
    lib = _load_parser_lib()
    if lib is None or not hasattr(lib, "apmfrm_pack"):
        return None
    blob = b"\n".join(lines_b)
    cap = 16 + 32 * len(lines_b) + len(blob) + 1
    out = ctypes.create_string_buffer(cap)
    ret = lib.apmfrm_pack(blob, len(blob), out, cap)
    if ret <= 0 or ret > cap:
        return None
    raw = bytearray(out.raw[:ret])
    (nrec,) = struct.unpack_from("<I", raw, 4)
    if nrec != len(lines_b):
        return None
    return raw


def _parser_event_dtype():
    """numpy mirror of ApmEvent (native/parser.cpp). Spans with off >= 0
    index the chunk buffer; off < 0 index the returned pool at (-off - 1);
    len < 0 means the field is absent."""
    import numpy as np

    return np.dtype([
        ("line_off", np.int64), ("line_len", np.int32),
        ("cls", np.int32), ("flags", np.int32),
        ("logid_off", np.int32), ("logid_len", np.int32),
        ("ts_off", np.int32), ("ts_len", np.int32),
        ("svc_off", np.int32), ("svc_len", np.int32),
        ("ela_off", np.int32), ("ela_len", np.int32),
        ("jts_off", np.int32), ("jts_len", np.int32),
        ("jserver", np.int32),
        ("baf_off", np.int32), ("baf_len", np.int32),
        ("bits", np.int32),
        ("_pad", np.int32),  # C tail padding made explicit (sizeof == 80)
    ], align=False)


class ParserEngineNative:
    """Ingest fast path over libapmparser: batched marker pre-filter +
    field extraction + the (logId, service) TTL correlation map.

    One instance backs one TransactionParser. ``chunk()`` processes a
    newline-separated byte blob for one file and returns the event array;
    ``park``/``take``/``peek`` are the per-line shims that let the Python
    reference handler (RAW-line fallback, read_line API, tests) operate on
    the SAME correlation map. All entry points take ``now`` from the
    parser's injectable clock — TTL semantics replicate ingest/ttlcache.py.
    """

    # class constants mirrored from parser.cpp
    CLS_RAW = 0
    CLS_EJB_ENTRY = 1
    CLS_EJB_EXIT = 2
    CLS_CT_ENTRY = 3
    CLS_CT_EXIT = 4
    CLS_SOAP_ACCT = 12
    CLS_SOAP_ALT_VALUE = 14
    CLS_ACCT_SAVE_BAF = 21
    CLS_AUDIT_STOP = 22
    CLS_AUDIT_LOG = 23
    FL_JOIN_FOUND = 1
    FL_BAF = 2
    FL_LOGID_EMPTY = 4
    FL_JOIN_NOKEY = 8
    FL_INSERT_DB = 16
    LOG_MISSING_CTX = 1
    LOG_UNRESOLVED = 2
    LOG_NO_START = 3
    LOG_NO_STOP = 4
    LOG_DATA_INDEX = 5

    def __init__(self, ttl_s: float, sweep_interval_s: float, now: float):
        lib = _load_parser_lib()
        if lib is None:
            raise RuntimeError("native parser unavailable (no toolchain?)")
        self._lib = lib
        self._h = lib.apmpar_create(
            ctypes.c_double(ttl_s), ctypes.c_double(sweep_interval_s),
            ctypes.c_double(now),
        )
        if not self._h:
            raise MemoryError("apmpar_create failed")
        self.dtype = _parser_event_dtype()

    def _pool_bytes(self) -> bytes:
        n = ctypes.c_uint64(0)
        ptr = self._lib.apmpar_pool(self._h, ctypes.byref(n))
        if not n.value:
            return b""
        return ctypes.string_at(ptr, n.value)

    def chunk(self, data: bytes, kind: int, server_id: int, file_id: int,
              now: float):
        """-> (events structured array, pool bytes, counts tuple). counts =
        (lines, prefilter_rejected, parked, events, pool_bytes, consumed).
        ``consumed < len(data)`` means the scan stopped at a RAW barrier:
        process the events, then call again on ``data[consumed:]``."""
        import numpy as np

        cap = data.count(b"\n") + 1
        ev = np.zeros(cap, self.dtype)
        counts = (ctypes.c_uint64 * 6)()
        n = self._lib.apmpar_chunk(
            self._h, data, len(data), kind, server_id, file_id,
            ctypes.c_double(now),
            ev.ctypes.data_as(ctypes.c_void_p), cap, counts,
        )
        if n < 0:  # structurally impossible (cap >= line count); never retry
            raise RuntimeError("apmpar_chunk event overflow")
        # snapshot the pool NOW: the next native call on this handle
        # invalidates it
        return ev[: int(n)], self._pool_bytes(), tuple(int(c) for c in counts)

    # -- soap context shims (shared state for the per-line reference path) --
    def soap_get(self, file_id: int):
        """(log_id bytes, pull flag) of the open context, or None."""
        rc = self._lib.apmpar_soap_get(self._h, file_id)
        if rc < 0:
            return None
        return self._pool_bytes(), rc == 1

    def soap_set(self, file_id: int, log_id: bytes) -> None:
        self._lib.apmpar_soap_set(self._h, file_id, log_id, len(log_id))

    def soap_arm(self, file_id: int) -> None:
        self._lib.apmpar_soap_arm(self._h, file_id)

    def soap_close(self, file_id: int) -> None:
        self._lib.apmpar_soap_close(self._h, file_id)

    def park(self, log_id: bytes, service: bytes, server_id: int,
             start_ts: bytes, now: float) -> None:
        self._lib.apmpar_park(
            self._h, log_id, len(log_id), service, len(service), server_id,
            start_ts, len(start_ts), ctypes.c_double(now),
        )

    def take(self, log_id: bytes, service: bytes, now: float):
        """-> None (no key), () (key but no service), or (server_id,
        start_ts bytes) when found+popped — mirroring _join_exit's three
        cases."""
        srv = ctypes.c_int32(-1)
        ts_off = ctypes.c_int32(0)
        ts_len = ctypes.c_int32(0)
        rc = self._lib.apmpar_take(
            self._h, log_id, len(log_id), service, len(service),
            ctypes.c_double(now), ctypes.byref(srv), ctypes.byref(ts_off),
            ctypes.byref(ts_len),
        )
        if rc == 0:
            return None
        if rc == 1:
            return ()
        pool = self._pool_bytes()
        off = -int(ts_off.value) - 1
        return int(srv.value), pool[off: off + int(ts_len.value)]

    def peek(self, log_id: bytes, now: float):
        """TTLCache.get parity view: None on miss (counted), else the
        live {service: (server_id, start_ts)} map (hit counted)."""
        n = self._lib.apmpar_peek(self._h, log_id, len(log_id),
                                  ctypes.c_double(now))
        if n < 0:
            return None
        out = {}
        for rec in self._pool_bytes().split(b"\x1e"):
            if rec:
                svc, srv, ts = rec.split(b"\x1f")
                out[svc] = (int(srv), ts)
        return out

    def sweep(self, now: float) -> None:
        self._lib.apmpar_sweep(self._h, ctypes.c_double(now))

    def clear(self) -> None:
        self._lib.apmpar_clear(self._h)

    def stats(self):
        out = (ctypes.c_uint64 * 3)()
        self._lib.apmpar_stats(self._h, out)
        return int(out[0]), int(out[1]), int(out[2])  # keys, hits, misses

    def expired_pending(self) -> int:
        return int(self._lib.apmpar_expired_pending(self._h))

    def drain_expired(self):
        """[(log_id bytes, service bytes), ...] expired since last drain."""
        self._lib.apmpar_drain_expired(self._h)
        out = []
        for rec in self._pool_bytes().split(b"\x1e"):
            if rec:
                log_id, _, svc = rec.partition(b"\x1f")
                out.append((log_id, svc))
        return out

    def close(self) -> None:
        if self._h:
            self._lib.apmpar_destroy(self._h)
            self._h = None

    def __del__(self):  # pragma: no cover - GC timing
        try:
            self.close()
        except Exception:
            pass


# ---------------------------------------------------------------- percentile

_pct_lib = None


def _load_percentile_lib():
    global _pct_lib
    if _pct_lib is not None:
        return _pct_lib
    build = ensure_built()
    if build is None:
        return None
    so = os.path.join(build, "libapmpercentile.so")
    if not os.path.isfile(so):
        return None
    lib = ctypes.CDLL(so)
    lib.apm_window_percentiles.restype = ctypes.c_int
    lib.apm_window_percentiles.argtypes = [
        ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int, ctypes.c_void_p,
    ]
    lib.apm_window_percentiles_counts.restype = ctypes.c_int
    lib.apm_window_percentiles_counts.argtypes = [
        ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int,
        ctypes.c_void_p,
    ]
    _pct_lib = lib
    return lib


def have_native_percentiles() -> bool:
    """True when libapmpercentile built/loaded (toolchain present)."""
    return _load_percentile_lib() is not None


def window_percentiles_native(samples, mask, ps, counts=None):
    """Exact reference percentiles over the window reservoir, selected with
    std::nth_element per row — the CPU-fallback fast path for the staged
    executor's percentile stage (native/percentile.cpp; exact-parity with
    ops/stats.py topk/sort in the no-overflow regime, fuzz-tested).

    samples: [S, NB, CAP] float32 C-contiguous numpy (NaN = empty slot);
    mask: [NB] bool window-slot selector; ps: iterable of int percentiles;
    counts (optional): [S, NB] int32 filled-prefix lengths (the engine's
    nsamples panel) — lets the kernel gather only each bucket's live
    prefix instead of NaN-scanning all CAP slots (the dominant tick cost
    at sparse occupancy; results identical, fuzz-tested).
    Returns [S, len(ps)] float32 (NaN where a row's window is empty).
    Raises RuntimeError when the library is unavailable or rejects the call.
    """
    import numpy as np

    lib = _load_percentile_lib()
    if lib is None:
        raise RuntimeError("libapmpercentile unavailable (no native toolchain?)")
    samples = np.ascontiguousarray(samples, dtype=np.float32)
    S, NB, CAP = samples.shape
    mask_u8 = np.ascontiguousarray(np.asarray(mask, bool), dtype=np.uint8)
    if mask_u8.shape != (NB,):
        raise ValueError(f"mask shape {mask_u8.shape} != ({NB},)")
    ps_arr = np.ascontiguousarray(list(ps), dtype=np.int32)
    out = np.empty((S, len(ps_arr)), np.float32)
    if counts is None:
        rc = lib.apm_window_percentiles(
            samples.ctypes.data, S, NB, CAP,
            mask_u8.ctypes.data, ps_arr.ctypes.data, len(ps_arr), out.ctypes.data,
        )
    else:
        counts = np.ascontiguousarray(counts, dtype=np.int32)
        if counts.shape != (S, NB):
            raise ValueError(f"counts shape {counts.shape} != ({S}, {NB})")
        rc = lib.apm_window_percentiles_counts(
            samples.ctypes.data, S, NB, CAP,
            mask_u8.ctypes.data, counts.ctypes.data,
            ps_arr.ctypes.data, len(ps_arr), out.ctypes.data,
        )
    if rc != 0:
        raise RuntimeError(f"apm_window_percentiles rc={rc}")
    return out


# ------------------------------------------------------------------ rebuild

_rebuild_lib = None


def _load_rebuild_lib():
    global _rebuild_lib
    if _rebuild_lib is not None:
        return _rebuild_lib
    build = ensure_built()
    if build is None:
        return None
    so = os.path.join(build, "libapmrebuild.so")
    if not os.path.isfile(so):
        return None
    lib = ctypes.CDLL(so)
    lib.apm_rebuild_window_aggs.restype = ctypes.c_int
    lib.apm_rebuild_window_aggs.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.c_int64, ctypes.c_int64,
        ctypes.c_int64, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
    ]
    _rebuild_lib = lib
    return lib


def have_native_rebuild() -> bool:
    """True when libapmrebuild built/loaded (toolchain present)."""
    return _load_rebuild_lib() is not None


def window_aggs_native(ring_chunk, anchor, last_slot: int):
    """Streaming anchored window moments over a [R, 3, L] ring chunk — the
    native partial producer of the staggered sliding-aggregate rebuild
    (native/rebuild.cpp; double accumulators, so strictly tighter than the
    f32 XLA reduce it substitutes on the CPU path). Merge-back happens in
    ops/zscore.py merge_agg_slice, shared with the XLA producer.

    ring_chunk: [R, 3, L] C-contiguous numpy, float32 or bfloat16 exposed as
    uint16 (ml_dtypes bfloat16 views also accepted); anchor: [R, 3] float32;
    last_slot: the (pos - 1) mod L ring slot of the most recent push.
    Returns (cnt i32, vsum f32, vsumsq f32, vmin f32, vmax f32, last_push
    f32), each [R, 3]. Raises RuntimeError when the library is unavailable.
    """
    import numpy as np

    lib = _load_rebuild_lib()
    if lib is None:
        raise RuntimeError("libapmrebuild unavailable (no native toolchain?)")
    ring_chunk = np.ascontiguousarray(ring_chunk)
    if ring_chunk.dtype == np.float32:
        is_bf16 = 0
    elif ring_chunk.dtype.itemsize == 2:  # bfloat16 (ml_dtypes) or uint16 bits
        is_bf16 = 1
    else:
        raise ValueError(f"unsupported ring dtype {ring_chunk.dtype}")
    R, M, L = ring_chunk.shape
    if M != 3:
        raise ValueError(f"expected metric axis 3, got {M}")
    anchor = np.ascontiguousarray(anchor, np.float32)
    if anchor.shape != (R, 3):
        raise ValueError(f"anchor shape {anchor.shape} != ({R}, 3)")
    cnt = np.empty((R, 3), np.int32)
    vsum = np.empty((R, 3), np.float32)
    vsumsq = np.empty((R, 3), np.float32)
    vmin = np.empty((R, 3), np.float32)
    vmax = np.empty((R, 3), np.float32)
    last_push = np.empty((R, 3), np.float32)
    rc = lib.apm_rebuild_window_aggs(
        ring_chunk.ctypes.data, is_bf16, R, L, int(last_slot),
        anchor.ctypes.data, cnt.ctypes.data, vsum.ctypes.data,
        vsumsq.ctypes.data, vmin.ctypes.data, vmax.ctypes.data,
        last_push.ctypes.data,
    )
    if rc != 0:
        raise RuntimeError(f"apm_rebuild_window_aggs rc={rc}")
    return cnt, vsum, vsumsq, vmin, vmax, last_push
