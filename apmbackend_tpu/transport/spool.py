"""Durable file-backed spool transport — the kill−9 fabric.

Promoted out of ``testing/chaos.py`` (which re-exports it for
compatibility): the spool is a real transport backend, not a test double —
it shares the manual-ack Channel contract with the memory broker and AMQP
(DESIGN.md §7.1), and both the chaos harness and the delta-checkpoint
hostile-storage tier run the production worker over it.

Durability audit (ISSUE 7 satellite):

- the consumer's committed cursor advances ONLY on ``ack()`` and is
  persisted tmp → ``os.replace`` — atomic against SIGKILL at any byte: a
  reader either sees the previous cursor or the new one, never a torn file
  (regression-tested in tests/test_spool_durability.py, including a torn
  leftover ``.tmp`` from a crash mid-write).
- the tmp name is **pid-suffixed**: a not-quite-dead predecessor process
  racing a restarted consumer must not interleave writes into one shared
  tmp file (the old constant ``<cursor>.tmp`` name allowed exactly that).
- ``fsync=True`` upgrades atomicity to power-loss durability: cursor and
  spool appends fsync before the rename / after the write, plus a directory
  fsync so the rename itself is journaled. Default off — the chaos model is
  process death (SIGKILL), where the page cache survives; flip it on when
  the spool must survive kernel panics or power loss.
"""

from __future__ import annotations

import base64
import json
import os
import threading
from typing import Callable, Dict, List, Optional, Tuple

from .base import Channel


class _SpoolQueue:
    """Consumer-side view of one spool file: incremental record parsing plus
    the acked-cursor bookkeeping."""

    def __init__(self, directory: str, name: str, *, fsync: bool = False):
        self.path = os.path.join(directory, f"{name}.spool")
        self.cursor_path = os.path.join(directory, f"{name}.cursor")
        self.fsync = fsync
        self.records: List[Tuple[bytes, Optional[dict]]] = []
        self._buf = b""
        self._read_off = 0
        self.acked_upto = 0  # records [0, acked_upto) are committed
        self._acked_set: set = set()
        self.next_deliver = 0
        # delivered high-water mark, persisted beside the cursor: records
        # below it were handed to a consumer by SOME incarnation, so a
        # post-restart re-delivery must carry headers["redelivered"] like
        # the memory broker and AMQP do (the transport-header-drift rule
        # caught this field riding only two of three transports). Best-
        # effort by design: the hwm is persisted only on ack-driven cursor
        # writes, so deliveries after the last persist lose the flag — the
        # dedup window never depends on it, only trace annotation does.
        self.delivered_hwm = 0
        self.boot_redeliver = 0  # indexes below this flag redelivered
        if os.path.exists(self.cursor_path):
            try:
                with open(self.cursor_path, "r", encoding="utf-8") as fh:
                    cur = json.load(fh)
                self.acked_upto = int(cur["acked"])
                self.delivered_hwm = int(cur.get("delivered", cur["acked"]))
            except Exception:
                self.acked_upto = 0  # torn cursor: redeliver from zero (safe)
                self.delivered_hwm = 0
        self.next_deliver = self.acked_upto
        self.boot_redeliver = self.delivered_hwm

    def poll(self) -> None:
        """Parse any newly appended COMPLETE records (a concurrently writing
        producer may leave a partial trailing line — it stays buffered)."""
        if not os.path.exists(self.path):
            return
        with open(self.path, "rb") as fh:
            fh.seek(self._read_off)
            chunk = fh.read()
        if not chunk:
            return
        self._read_off += len(chunk)
        self._buf += chunk
        *lines, self._buf = self._buf.split(b"\n")
        for line in lines:
            if not line:
                continue
            try:
                rec = json.loads(line)
                if "f" in rec:
                    # binary record (frame batch): base64 round trip
                    self.records.append((base64.b64decode(rec["f"]), rec.get("h")))
                else:
                    self.records.append((rec["p"].encode("utf-8"), rec.get("h")))
            except Exception:
                # a mangled record is a poison message: skip it rather than
                # wedging the queue forever
                self.records.append((b"", None))

    def ack(self, index: int) -> bool:
        """Mark one record committed; returns True when the contiguous
        cursor advanced (caller persists it)."""
        if index < self.acked_upto:
            return False  # idempotent re-ack
        self._acked_set.add(index)
        advanced = False
        while self.acked_upto in self._acked_set:
            self._acked_set.discard(self.acked_upto)
            self.acked_upto += 1
            advanced = True
        return advanced

    def persist_cursor(self) -> None:
        # pid-suffixed tmp + atomic rename: SIGKILL at any byte leaves the
        # previous cursor intact, and a zombie predecessor cannot share (and
        # corrupt) the tmp a restarted consumer is writing
        tmp = f"{self.cursor_path}.{os.getpid()}.tmp"
        self.delivered_hwm = max(self.delivered_hwm, self.next_deliver)
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump({"acked": self.acked_upto,
                       "delivered": self.delivered_hwm}, fh)
            if self.fsync:
                fh.flush()
                os.fsync(fh.fileno())
        os.replace(tmp, self.cursor_path)
        if self.fsync:
            try:
                fd = os.open(os.path.dirname(self.cursor_path), os.O_RDONLY)
                try:
                    os.fsync(fd)
                finally:
                    os.close(fd)
            except OSError:
                pass  # platform without dir fsync


class SpoolChannel(Channel):
    """Durable file-backed broker channel — the kill−9 fabric.

    One append-only JSON-lines spool per queue under ``directory``; the
    consumer's committed cursor lives in ``<queue>.cursor`` and is advanced
    ONLY by ``ack()`` (atomic tmp+rename, optional fsync). SIGKILL the
    consumer process at any instant and a fresh SpoolChannel resumes
    delivery from the last committed cursor — everything
    delivered-but-unacked is redelivered, the exact contract a durable AMQP
    queue with manual acks provides, minus the network. ``send`` appends
    with flush (the producer/harness process survives the chaos, so
    line-buffered append is durable enough; ``fsync=True`` hardens it).

    Delivery is pumped (``deliver()`` / ``start_pump_thread``) like the
    memory broker. Ack-on-receipt consumers advance the cursor at delivery;
    manual-ack consumers receive ``(queue, index)`` tokens.
    """

    def __init__(self, directory: str, *, prefetch: int = 100000, fsync: bool = False):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.prefetch = prefetch
        self.fsync = fsync
        self._queues: Dict[str, _SpoolQueue] = {}  # guarded-by: _lock
        # (tag, callback, manual) per queue
        self._consumers: Dict[str, Tuple[str, Callable, bool]] = {}  # guarded-by: _lock
        self._send_fhs: Dict[str, object] = {}  # guarded-by: _lock
        self._lock = threading.RLock()
        self._drain_cbs: List[Callable[[], None]] = []
        self._pump_thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # -- Channel contract ----------------------------------------------------
    def assert_queue(self, name: str) -> None:
        with self._lock:
            if name not in self._queues:
                self._queues[name] = _SpoolQueue(self.directory, name, fsync=self.fsync)

    def send(self, name: str, payload: bytes, headers: Optional[dict] = None) -> bool:
        with self._lock:
            self.assert_queue(name)
            fh = self._send_fhs.get(name)
            if fh is None:
                fh = open(os.path.join(self.directory, f"{name}.spool"), "ab")
                self._send_fhs[name] = fh
            try:
                # text record: the pre-frame wire format, byte for byte
                rec = json.dumps({"p": payload.decode("utf-8"), "h": headers})
            except UnicodeDecodeError:
                # binary record (APF1 frame batch): base64 into "f". One
                # append+flush(+fsync) per BATCH — the whole frame batch is
                # one spool record, one delivery, one ack/cursor advance:
                # the amortized group-commit slice for the frame path.
                rec = json.dumps({
                    "f": base64.b64encode(payload).decode("ascii"),
                    "h": headers,
                })
            fh.write(rec.encode("utf-8") + b"\n")
            fh.flush()
            if self.fsync:
                os.fsync(fh.fileno())
        return True

    def consume(self, name: str, callback: Callable[[bytes], None], consumer_tag: str,
                manual_ack: bool = False) -> None:
        from .base import accepts_headers

        if not manual_ack and not accepts_headers(callback):
            inner = callback
            callback = lambda payload, _h=None, _cb=inner: _cb(payload)  # noqa: E731
        with self._lock:
            self.assert_queue(name)
            self._consumers[name] = (consumer_tag, callback, manual_ack)

    def cancel(self, consumer_tag: str) -> None:
        with self._lock:
            self._consumers = {
                q: c for q, c in self._consumers.items() if c[0] != consumer_tag
            }

    def ack(self, tokens) -> None:
        with self._lock:
            advanced: set = set()
            for name, index in tokens:
                q = self._queues.get(name)
                if q is not None and q.ack(index):
                    advanced.add(name)
            for name in advanced:
                self._queues[name].persist_cursor()

    def on_drain(self, callback: Callable[[], None]) -> None:
        self._drain_cbs.append(callback)

    def close(self) -> None:
        self.stop()
        with self._lock:
            for fh in self._send_fhs.values():
                try:
                    fh.close()
                except Exception:
                    pass
            self._send_fhs.clear()

    # -- delivery ------------------------------------------------------------
    def deliver(self, max_messages: Optional[int] = None) -> int:
        delivered = 0
        while max_messages is None or delivered < max_messages:
            batch = []
            with self._lock:
                for name, (tag, cb, manual) in self._consumers.items():
                    q = self._queues[name]
                    q.poll()
                    if q.next_deliver >= len(q.records):
                        continue
                    if manual and q.next_deliver - q.acked_upto >= self.prefetch:
                        continue  # unacked ledger at the prefetch bound
                    payload, headers = q.records[q.next_deliver]
                    index = q.next_deliver
                    q.next_deliver += 1
                    if index < q.boot_redeliver:
                        # delivered by a previous incarnation and never
                        # acked: the same crash-redelivery hop the memory
                        # broker and AMQP flag
                        headers = dict(headers or {})
                        headers["redelivered"] = True
                    if not manual and q.ack(index):
                        q.persist_cursor()
                    batch.append((cb, payload, headers, manual, (name, index)))
            if not batch:
                break
            for cb, payload, headers, manual, token in batch:
                if manual:
                    cb(payload, headers, token)
                else:
                    cb(payload, headers)
                delivered += 1
        return delivered

    def acked_count(self, name: str) -> int:
        with self._lock:
            q = self._queues.get(name)
            return q.acked_upto if q else 0

    def delivered_count(self, name: str) -> int:
        with self._lock:
            q = self._queues.get(name)
            return q.next_deliver if q else 0

    def queue_lag(self, name: str) -> int:
        """Records persisted to the spool but not yet acked by this consumer
        — the backlog it still owes. Scrape-time view for the
        ``apm_queue_lag`` gauge (the per-queue lag SLO input); polls so a
        producer-only burst shows up without waiting for a delivery."""
        with self._lock:
            q = self._queues.get(name)
            if q is None:
                # observer path: a fresh channel over an existing spool dir
                # (the manager probing a dead consumer's backlog) gets the
                # same disk-backed view — cursor and records read from disk
                q = self._queues[name] = _SpoolQueue(
                    self.directory, name, fsync=self.fsync)
            q.poll()
            return max(0, len(q.records) - q.acked_upto)

    def start_pump_thread(self, poll_s: float = 0.005) -> None:
        if self._pump_thread is not None:
            return

        def _loop():
            while not self._stop.is_set():
                if self.deliver() == 0:
                    self._stop.wait(poll_s)

        self._pump_thread = threading.Thread(target=_loop, name="spool-pump", daemon=True)
        self._pump_thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._pump_thread is not None:
            self._pump_thread.join(timeout=2.0)
            self._pump_thread = None


def read_spool_cursor(directory: str, queue: str) -> int:
    """Committed (acked) record count for ``queue`` — an external observer's
    view of a (possibly dead) consumer's progress, read straight off disk."""
    path = os.path.join(os.path.abspath(directory), f"{queue}.cursor")
    if not os.path.exists(path):
        return 0
    try:
        with open(path, "r", encoding="utf-8") as fh:
            return int(json.load(fh)["acked"])
    except Exception:
        return 0
