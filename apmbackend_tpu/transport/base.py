"""Broker abstraction with the reference's backpressure contract.

Contract parity with queue.js:

- ``QueueManager.get_queue(name, 'p'|'c', consume_cb)`` returns a producer or
  consumer handle for a named durable queue (queue.js:108-154).
- ``ProducerQueue.write_line(line)``: when the underlying channel refuses the
  message (full), the line is buffered locally and a global ``pause`` event is
  emitted (queue.js:245-263). Stream modules react by cancelling consumption;
  the parser additionally creates the tail pause file.
- On drain, the manager retries every producer buffer; once ALL buffers are
  empty a global ``resume`` event fires (queue.js:88-106).
- ``ConsumerQueue``: by default messages are acked on receipt, before
  processing (at-most-once past the ack, queue.js:277-283). ``start_consume``
  / ``stop_consume`` toggle delivery.

**At-least-once mode** (``manual_ack=True``, no reference equivalent): the
backend defers the ack until the consumer explicitly commits it. The callback
receives ``cb(payload, headers, token)`` and the consumer calls
``ConsumerQueue.ack(tokens)`` once the processing layer has made the
messages' effects durable (the worker's ack-after-checkpoint epoch cycle,
runtime/worker.py). Unacked messages are redelivered — on channel close /
broker bounce for the memory backend, by the broker itself for AMQP — with
``headers["redelivered"]`` set when the backend knows; consumers dedup
redeliveries by the producer-stamped ``msg_id`` header.

Backends: :mod:`.memory` (bounded in-process queues — the fake broker the
reference never had, SURVEY.md §4) and :mod:`.amqp` (RabbitMQ via an AMQP
client when available).
"""

from __future__ import annotations

import inspect
import os
import threading
import time
from collections import defaultdict
from typing import Callable, Dict, List, Optional, Tuple

from ..utils.counters import QueueStats


def accepts_headers(cb: Callable) -> bool:
    """True when ``cb`` takes a second positional arg — the transport then
    delivers ``cb(payload, headers)``; legacy one-arg consumers keep their
    ``cb(payload)`` shape. Headers are the end-to-end latency channel: the
    producer stamps ``ingest_ts`` at transport entry and the pipeline
    measures ingest→emit / ingest→alert from it (obs plane)."""
    try:
        params = list(inspect.signature(cb).parameters.values())
    except (TypeError, ValueError):  # builtins/C callables: stay conservative
        return False
    positional = [
        p for p in params
        if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD)
    ]
    return len(positional) >= 2 or any(p.kind == p.VAR_POSITIONAL for p in params)


class EventEmitter:
    """Minimal synchronous event emitter (Node EventEmitter analog)."""

    def __init__(self):
        self._handlers: Dict[str, List[Callable]] = defaultdict(list)

    def on(self, event: str, handler: Callable) -> None:
        self._handlers[event].append(handler)

    def emit(self, event: str, *args) -> None:
        for handler in list(self._handlers[event]):
            handler(*args)


class Channel:
    """Transport-level channel a backend must provide."""

    def assert_queue(self, name: str) -> None:
        raise NotImplementedError

    def send(self, name: str, payload: bytes, headers: Optional[dict] = None) -> bool:
        """Returns False when the channel/queue is full (backpressure).
        ``headers`` is best-effort message metadata (``ingest_ts`` wall
        stamp); a backend that cannot carry it may drop it."""
        raise NotImplementedError

    def consume(
        self,
        name: str,
        callback: Callable[[bytes], None],
        consumer_tag: str,
        manual_ack: bool = False,
    ) -> None:
        """``manual_ack=True`` switches the queue to at-least-once delivery:
        the callback is invoked ``cb(payload, headers, token)`` and the
        message stays on the broker's unacked ledger until ``ack([token])``.
        Backends that cannot defer acks raise."""
        raise NotImplementedError

    def ack(self, tokens) -> None:
        """Commit manual-ack deliveries (idempotent; unknown/stale tokens are
        ignored — the broker will redeliver whatever was never acked)."""
        raise NotImplementedError

    def cancel(self, consumer_tag: str) -> None:
        raise NotImplementedError

    def on_drain(self, callback: Callable[[], None]) -> None:
        raise NotImplementedError

    def close(self) -> None:
        pass


class ProducerQueue(EventEmitter):
    def __init__(self, queue_name: str, channel: Channel, queue_stats: QueueStats, logger=None,
                 transport_cfg: Optional[dict] = None):
        super().__init__()
        self.queue_name = queue_name
        self.channel = channel
        self.queue_stats = queue_stats
        self.logger = logger
        # buffered entries keep their original ingest stamp: a pause episode
        # must show up as queue-wait latency downstream, not vanish from it.
        # Entries are str lines or bytes frame batches — both ride the same
        # FIFO so a pause episode cannot reorder frames against lines.
        self.buffer: List[Tuple[object, Optional[dict]]] = []  # guarded-by: _lock
        self.paused = False  # guarded-by: _lock
        self.type = "p"
        self._lock = threading.Lock()
        # flow-control cap on the pause buffer: an unbounded buffer turns a
        # stalled consumer into a producer OOM. 0 keeps the legacy unbounded
        # behavior; past the cap the OLDEST buffered lines are evicted under
        # the configured policy — counted drop (the stream self-heals via
        # redelivery/dedup upstream) or spill to a durable spool — and the
        # episode degrades loudly (error log + decision record + "overflow"
        # event the runtime turns into a flight bundle), never silently.
        transport_cfg = transport_cfg or {}
        self.buffer_max_lines = int(transport_cfg.get("producerBufferMaxLines", 0) or 0)
        self.overflow_policy = str(transport_cfg.get("producerOverflowPolicy", "drop-oldest"))
        self._spill_dir = transport_cfg.get("spillDirectory") or "spool/overflow"
        self._spill: Optional[Channel] = None  # guarded-by: _lock
        self._overflow_note = 0  # guarded-by: _lock (evictions not yet reported)
        # message-id stamp for at-least-once consumers: unique across
        # producers and producer restarts (redelivered messages carry the
        # ORIGINAL id — the broker retains headers — so consumers dedup on
        # it). One string concat per line; at-most-once consumers ignore it.
        self._msg_prefix = f"{os.getpid():x}-{os.urandom(4).hex()}-"
        self._msg_seq = 0  # guarded-by: _lock
        # fleet partitioning (parallel/fleet.py): when this producer queue is
        # one service-hash partition channel of a sharded `transactions`
        # fabric, the partition id is stamped into every message's headers so
        # the consuming shard can verify routing discipline (the shardmodel
        # `partition_header_mismatch` mutant shows what an unstamped or
        # wrongly-routed message costs: owner-locality breaks silently).
        # Set once by FleetPartitioner before the first write_line.
        self.partition: Optional[int] = None
        # the trace plane (obs/trace): this producer IS the transport-entry
        # ingest boundary; every sample_rate-th message gets a trace_id
        # header + an ingest span. The singleton is configured in place by
        # ModuleRuntime, so caching the reference here is order-independent;
        # rate 0 (tracing off) costs one integer compare per message.
        from ..obs.trace import get_tracer

        self._tracer = get_tracer()
        # attribution plane (obs/attrib): transport-entry send busy,
        # blocked-while-paused (pause entry -> drain retry), and the pause
        # buffer's time-weighted occupancy. Cached references; no-ops off.
        from ..obs.attrib import STAGE_TRANSPORT_SEND, get_attrib

        _att = get_attrib()
        self._att_send = _att.clock(STAGE_TRANSPORT_SEND)
        self._att_buf_occ = _att.occupancy(f"producer_buffer:{queue_name}")
        self._pause_t0: Optional[float] = None  # guarded-by: _lock
        from ..obs import get_registry

        # buffer depth is the flow-control health signal: the runtime's
        # /healthz degrades when it nears the cap, and the SLO engine can
        # burn against it like any other gauge series
        get_registry().gauge(
            "apm_producer_buffer_lines",
            "Lines held in the producer pause buffer (waiting for drain)",
            labels={"queue": queue_name},
        ).set_fn(lambda: float(self.buffer_count()))
        self._overflow_counter = get_registry().counter(
            "apm_producer_buffer_overflow_total",
            "Buffered lines evicted past producerBufferMaxLines "
            "(dropped or spilled per producerOverflowPolicy)",
            labels={"queue": queue_name},
        )
        self.queue_stats.add_counter(queue_name, "p")
        channel.assert_queue(queue_name)

    def buffer_count(self) -> int:
        with self._lock:
            return len(self.buffer)

    # apm: holds(_lock): every caller acquires it (write_line, write_frames, retry_buffer)
    def _send_locked(
        self, line, headers: Optional[dict], verbose: bool, requeue_front: bool = False
    ) -> bool:
        """Caller holds self._lock. Returns True when a pause was entered.

        ``line`` is a str line or a bytes frame batch (write_frames); both
        take the same buffer/pause path so pressure episodes preserve FIFO
        order across the two shapes.

        ``requeue_front`` is set by retry_buffer: a line popped from the front
        of the buffer that the channel refuses must go BACK to the front
        (queue.js:230-243 unshift), not the back — appending would rotate one
        line to the end of the stream on every pressure episode.
        """
        if self.paused:
            if requeue_front:
                self.buffer.insert(0, (line, headers))
            else:
                self.buffer.append((line, headers))
            self._enforce_cap_locked()
            self._att_buf_occ.sample(len(self.buffer))
            return False
        payload = line.encode("utf-8") if isinstance(line, str) else line
        if self._att_send.enabled:
            t0 = time.perf_counter()
            ok = self.channel.send(self.queue_name, payload, headers)
            self._att_send.add_busy(time.perf_counter() - t0)
        else:
            ok = self.channel.send(self.queue_name, payload, headers)
        if not ok:
            if requeue_front:
                self.buffer.insert(0, (line, headers))
            else:
                self.buffer.append((line, headers))
            self._enforce_cap_locked()
            self._att_buf_occ.sample(len(self.buffer))
            self.paused = True
            self._pause_t0 = time.perf_counter()
            return True
        if verbose and self.logger:
            self.logger.info(f"QUEUE: {self.queue_name} ::: {line!r}"
                             if isinstance(line, bytes) else
                             f"QUEUE: {self.queue_name} ::: {line}")
        self.queue_stats.incr(self.queue_name)
        return False

    # apm: holds(_lock): called from _send_locked right after a buffer append
    def _enforce_cap_locked(self) -> None:
        """Evict past ``producerBufferMaxLines`` — oldest first, so the
        buffer keeps the most recent window of the stream (the same choice
        every bounded telemetry ring in the repo makes). Reporting is
        deferred to ``_note_overflow`` outside the lock."""
        if self.buffer_max_lines <= 0:
            return
        while len(self.buffer) > self.buffer_max_lines:
            old_line, old_headers = self.buffer.pop(0)
            self._overflow_counter.inc()
            if self.overflow_policy == "spill-spool":
                if self._spill is None:
                    from .spool import SpoolChannel

                    self._spill = SpoolChannel(self._spill_dir)
                    self._spill.assert_queue(self.queue_name)
                self._spill.send(
                    self.queue_name,
                    old_line.encode("utf-8") if isinstance(old_line, str) else old_line,
                    old_headers,
                )
            self._overflow_note += 1

    def _note_overflow(self, evicted: int) -> None:
        """Loud degradation, outside the lock: error log + decision record
        (replayable provenance for the page) + an ``overflow`` event the
        QueueManager forwards so the runtime can dump a flight bundle."""
        action = "spilled" if self.overflow_policy == "spill-spool" else "dropped"
        if self.logger:
            self.logger.error(
                f"--- PRODUCER BUFFER OVERFLOW (Q={self.queue_name}) --- "
                f"{action} {evicted} oldest buffered lines (cap={self.buffer_max_lines})"
            )
        from ..obs.decisions import get_decisions

        get_decisions().record({
            "kind": "producer_buffer_overflow",
            "queue": self.queue_name,
            "policy": self.overflow_policy,
            "evicted": evicted,
            "cap": self.buffer_max_lines,
            "ts": time.time(),
        })
        self.emit("overflow", self.queue_name, evicted)

    def write_line(self, line: str, verbose: bool = False) -> None:
        # the transport-entry stamp: every message carries when it entered
        # the fabric, the anchor of the ingest->emit/alert latency series —
        # plus the unique msg_id at-least-once consumers dedup redeliveries
        # by. The seq increment lives under the lock: two threads writing
        # the same producer queue must not mint duplicate msg_ids (the
        # at-least-once dedup window would silently drop a real message).
        with self._lock:
            self._msg_seq += 1
            seq = self._msg_seq
            now = time.time()
            headers = {"ingest_ts": now, "msg_id": self._msg_prefix + str(seq)}
            if self.partition is not None:
                headers["partition"] = self.partition
            tr = self._tracer
            if tr.rate > 0 and seq % tr.rate == 0:
                # head-sampled trace context: deterministic in the message
                # sequence, carried end to end in headers (redelivery keeps
                # it, like msg_id). The ingest span runs from the last noted
                # raw-read boundary (tailer/replay chunk) to transport entry.
                trace_id = "t-" + headers["msg_id"]
                headers["trace_id"] = trace_id
                start = tr.ingest_start
                tr.span(
                    trace_id, "ingest",
                    now if start is None or start > now else start, now,
                    queue=self.queue_name,
                )
            entered_pause = self._send_locked(line, headers, verbose)
            overflowed, self._overflow_note = self._overflow_note, 0
        if overflowed:
            self._note_overflow(overflowed)
        if entered_pause:
            if self.logger:
                self.logger.info(
                    f"--- PRODUCER CHANNEL BUFFER FULL (Q={self.queue_name}) --- Pausing until drain event"
                )
            self.emit("pause")

    def write_frames(self, blob: bytes, n_records: int, verbose: bool = False) -> None:
        """write_line's frame sibling: send one packed APF1 frame batch
        (transport/frames.py) as ONE message. The transport-entry headers —
        ``ingest_ts``, ``msg_id``, ``partition``, sampled ``trace_id`` — are
        stamped once per BATCH, not per record: at-least-once dedup and the
        fleet partition-routing check operate at batch granularity (one
        deliver event, one pending entry, one ack token downstream), which
        is what keeps the protocol-conformance mirror's accounting exact.
        ``frames`` carries the record count so consumers and lag accounting
        can weigh the batch without parsing it."""
        with self._lock:
            self._msg_seq += 1
            seq = self._msg_seq
            now = time.time()
            headers = {
                "ingest_ts": now,
                "msg_id": self._msg_prefix + str(seq),
                "frames": int(n_records),
            }
            if self.partition is not None:
                headers["partition"] = self.partition
            tr = self._tracer
            if tr.rate > 0:
                # a carriage-traced batch keeps the parser's trace_id (the
                # ingest span is already recorded at flush); only an
                # untraced batch gets the producer's own head sample
                from . import frames as _frames

                car_tid = _frames.carriage_trace_id(blob)
                if car_tid:
                    headers["trace_id"] = car_tid
                elif seq % tr.rate == 0:
                    trace_id = "t-" + headers["msg_id"]
                    headers["trace_id"] = trace_id
                    start = tr.ingest_start
                    tr.span(
                        trace_id, "ingest",
                        now if start is None or start > now else start, now,
                        queue=self.queue_name,
                    )
            entered_pause = self._send_locked(blob, headers, verbose)
            overflowed, self._overflow_note = self._overflow_note, 0
        if overflowed:
            self._note_overflow(overflowed)
        if entered_pause:
            if self.logger:
                self.logger.info(
                    f"--- PRODUCER CHANNEL BUFFER FULL (Q={self.queue_name}) --- Pausing until drain event"
                )
            self.emit("pause")

    def retry_buffer(self) -> None:
        """Re-send buffered lines until empty or the channel refuses again

        (queue.js:230-243). Runs under the lock so a concurrent write_line
        cannot jump the FIFO order while the buffer drains."""
        with self._lock:
            if self._pause_t0 is not None:
                # the pause episode up to this drain was time this producer
                # spent blocked on its downstream fabric
                self._att_send.add_blocked(time.perf_counter() - self._pause_t0)
                self._pause_t0 = None
            self.paused = False
            while self.buffer and not self.paused:
                line, headers = self.buffer.pop(0)
                self._send_locked(line, headers, False, requeue_front=True)
            remaining = len(self.buffer)
            self._att_buf_occ.sample(remaining)
        if remaining and self.logger:
            self.logger.info(
                f"Records still remaining in {self.queue_name} buffer, waiting for next drain: "
                f"{remaining} records"
            )


class ConsumerQueue(EventEmitter):
    def __init__(
        self,
        queue_name: str,
        channel: Channel,
        queue_stats: QueueStats,
        consume_cb: Callable[[str], None],
        logger=None,
        manual_ack: bool = False,
    ):
        super().__init__()
        self.queue_name = queue_name
        self.channel = channel
        self.queue_stats = queue_stats
        self.consume_cb = consume_cb
        self.logger = logger
        self.consumer_tag = f"xConsumerTagx-{queue_name}"
        self.is_consuming = False
        self.type = "c"
        # at-least-once mode: deliveries stay unacked on the broker until the
        # consumer commits them via ack(tokens); consume_cb must then take
        # (line, headers, token)
        self.manual_ack = manual_ack
        # frame dispatch (transport/frames.py): a payload carrying the APF1
        # magic is a packed frame batch. A frames-aware consumer (the worker
        # sets this, like FleetPartitioner sets producer.partition) receives
        # the raw bytes blob as ONE delivery; an unaware auto-ack consumer
        # gets the batch unfolded into per-line callbacks (same records,
        # shared headers); an unaware manual-ack consumer also gets the raw
        # blob — the ack token is batch-granular and unfolding would orphan
        # it. Undecodable batches are dropped loudly (counter + log), never
        # fed downstream as garbage text.
        self.frames_aware = False
        self.queue_stats.add_counter(queue_name, "c")
        # resolved ONCE (this runs per message): does the consumer want the
        # transport headers, the queue-wait histogram instrument, and the
        # process tracer (queue spans + bucket exemplars for sampled messages)
        self._cb_headers = accepts_headers(consume_cb)
        from ..obs import get_registry
        from ..obs.trace import get_tracer

        self._trace = get_tracer()
        self._wait_hist = get_registry().histogram(
            "apm_queue_wait_seconds",
            "Transport latency: producer ingest stamp -> consumer delivery",
            labels={"queue": queue_name},
        )
        self._frame_decode_errors = get_registry().counter(
            "apm_frame_decode_errors_total",
            "APF1 frame batches that failed envelope validation/decode "
            "(batch dropped loudly, never fed downstream as text)",
            labels={"queue": queue_name},
        )
        # per-queue lag accounting (the SLO engine's queue_lag objective):
        # backends that can count undelivered+unacked work expose queue_lag()
        # and the gauge samples it at scrape time — uniform across the memory
        # broker and the durable spool (which has no depth gauge otherwise)
        ch_lag = getattr(channel, "queue_lag", None)
        if ch_lag is not None:
            get_registry().gauge(
                "apm_queue_lag",
                "Messages accepted but not yet acked for this queue "
                "(backlog the consumer still owes)",
                labels={"queue": queue_name},
            ).set_fn(lambda: float(ch_lag(queue_name)))
        channel.assert_queue(queue_name)

    def _observe_delivery(self, headers: dict) -> None:
        """Queue-wait histogram + (for sampled messages) the queue span and
        the histogram's trace exemplar. One dict.get per message beyond the
        pre-trace cost; only sampled messages (1/rate) do more."""
        ts = headers.get("ingest_ts")
        trace_id = headers.get("trace_id")
        now = time.time()
        if ts is not None:
            if trace_id is not None:
                self._wait_hist.observe_exemplar(now - ts, trace_id)
            else:
                self._wait_hist.observe(now - ts)
        if trace_id is not None:
            self._trace.span(
                trace_id, "queue", ts if ts is not None else now, now,
                queue=self.queue_name,
                redelivered=bool(headers.get("redelivered")),
            )

    def _wrapped(self, payload: bytes, headers: Optional[dict] = None) -> None:
        # Ack-on-receipt semantics: the backend has already removed the message
        # by the time we see it (queue.js:277-283).
        self.queue_stats.incr(self.queue_name)
        if headers:
            self._observe_delivery(headers)
        from . import frames as _frames

        if _frames.is_frames(payload):
            if self.frames_aware:
                if self._cb_headers:
                    self.consume_cb(bytes(payload), headers)
                else:
                    self.consume_cb(bytes(payload))
                return
            # unaware consumer: unfold the batch into per-line deliveries
            # (shared headers — same ingest stamp, one msg_id for the batch)
            try:
                lines = _frames.decode_lines(payload)
            except Exception as e:
                self._frame_decode_errors.inc()
                if self.logger:
                    self.logger.error(
                        f"Frame batch decode failed on {self.queue_name} "
                        f"(batch dropped): {e}"
                    )
                return
            for line in lines:
                if self._cb_headers:
                    self.consume_cb(line, headers)
                else:
                    self.consume_cb(line)
            return
        if self._cb_headers:
            self.consume_cb(payload.decode("utf-8"), headers)
        else:
            self.consume_cb(payload.decode("utf-8"))

    def _wrapped_manual(self, payload: bytes, headers: Optional[dict], token) -> None:
        # At-least-once: the broker still holds this message on its unacked
        # ledger; the consumer owes ack([token]) after its effect is durable.
        self.queue_stats.incr(self.queue_name)
        if headers:
            self._observe_delivery(headers)
        from . import frames as _frames

        if _frames.is_frames(payload):
            # batch-granular token: the blob is ONE delivery whether or not
            # the consumer is frames-aware (unfolding would orphan the ack)
            self.consume_cb(bytes(payload), headers, token)
            return
        self.consume_cb(payload.decode("utf-8"), headers, token)

    def ack(self, tokens) -> None:
        """Commit manual-ack deliveries (the epoch-commit hook)."""
        self.channel.ack(tokens)

    def start_consume(self) -> None:
        if not self.is_consuming:
            self.is_consuming = True
            if self.manual_ack:
                self.channel.consume(
                    self.queue_name, self._wrapped_manual, self.consumer_tag,
                    manual_ack=True,
                )
            else:
                self.channel.consume(self.queue_name, self._wrapped, self.consumer_tag)

    def stop_consume(self) -> None:
        self.is_consuming = False
        try:
            self.channel.cancel(self.consumer_tag)
        except Exception as e:  # reference swallows cancel errors (queue.js:297-304)
            if self.logger:
                self.logger.error(f"channel.cancel() threw an error: {e}")


class QueueManager(EventEmitter):
    """One producer channel + one consumer channel per process, named queues,

    pause/resume propagation (queue.js:67-189)."""

    def __init__(self, backend_factory: Callable[[str], Channel], stat_log_interval_s: int = 60, logger=None,
                 transport_config: Optional[dict] = None):
        super().__init__()
        self._backend_factory = backend_factory
        self.queue_stats = QueueStats(stat_log_interval_s, logger=logger)
        self.logger = logger
        # the `transport` config section (producer buffer cap + overflow
        # policy), handed to every ProducerQueue this manager creates
        self.transport_cfg = transport_config or {}
        self.producer_channel: Optional[Channel] = None
        self.consumer_channel: Optional[Channel] = None
        self.queue_map: Dict[str, object] = {}

    def set_interval(self, interval_s: int) -> None:
        self.queue_stats.set_interval(interval_s)

    def producer_buffer_counts(self) -> Dict[str, int]:
        """{queue: buffered line count} across producers — the /healthz
        flow-control provider's input."""
        return {
            name: q.buffer_count()
            for name, q in self.queue_map.items() if q.type == "p"
        }

    def retry_all_queue_buffers(self) -> None:
        for queue in self.queue_map.values():
            if queue.type == "p":
                queue.retry_buffer()
        total = sum(q.buffer_count() for q in self.queue_map.values() if q.type == "p")
        if total == 0:
            self.emit("resume")

    def get_queue(self, queue_name: str, qtype: str, consume_cb=None, *, manual_ack: bool = False):
        if queue_name in self.queue_map:
            return self.queue_map[queue_name]
        if qtype not in ("p", "c"):
            raise ValueError("Type must be either 'p' or 'c'.")
        if qtype == "c" and consume_cb is None:
            raise ValueError("A callback must be provided when consuming a queue.")

        if qtype == "p":
            if self.producer_channel is None:
                self.producer_channel = self._backend_factory("p")
                self.producer_channel.on_drain(self._on_drain)
            queue = ProducerQueue(queue_name, self.producer_channel, self.queue_stats, self.logger,
                                  transport_cfg=self.transport_cfg)
            queue.on("pause", lambda: self.emit("pause"))
            queue.on("overflow", lambda *a: self.emit("overflow", *a))
        else:
            if self.consumer_channel is None:
                self.consumer_channel = self._backend_factory("c")
            queue = ConsumerQueue(
                queue_name, self.consumer_channel, self.queue_stats, consume_cb,
                self.logger, manual_ack=manual_ack,
            )
        self.queue_map[queue_name] = queue
        return queue

    def _on_drain(self) -> None:
        if self.logger:
            self.logger.info("+++ DRAIN EVENT +++ on producer channel")
        self.retry_all_queue_buffers()

    def shutdown(self) -> None:
        self.queue_stats.stop()
        for ch in (self.producer_channel, self.consumer_channel):
            if ch is not None:
                try:
                    ch.close()
                except Exception as e:
                    if self.logger:
                        self.logger.error(f"channel.close() error: {e}")
        self.producer_channel = None
        self.consumer_channel = None
