"""Packed binary transaction frames — the zero-object byte spine's wire unit.

A *frame batch* carries N transaction lines plus a fixed-layout per-record
header, so every hop between the parser and the engine can route, count,
dedup, and partition WITHOUT materializing a Python object per record:

- the producer stamps ``msg_id``/``ingest_ts``/``partition``/``trace_id``
  once per batch (``ProducerQueue.write_frames``),
- the fleet partitioner reads each record's service field straight out of
  the frame (FNV-1a over the span — no ``split('|')``, no TxEntry),
- the worker feeds the lines region into the native bulk CSV decoder in
  one call (``PipelineDriver.feed_frames``),
- every transport fabric carries the batch as one opaque payload — one
  send / one spool append / one XADD / one publish per batch.

Layout (little-endian; DESIGN.md §4.1)::

    +0   b"APF1"                      magic
    +4   u32  nrec
    +8   u64  lines_off               == 16 + 32*nrec
    +16  nrec x 32-byte record structs
    +lines_off                        line bytes, each line + b"\\n"

Record struct (32 bytes, all fields naturally aligned)::

    +0   f8   end_ts      js_parse_int(field 6) — NaN when absent/NaN
    +8   f8   elapsed     js_parse_int(field 7)
    +16  u32  line_len    line bytes, excluding the separator "\\n"
    +20  u16  srv_off     field-1 span, relative to the line start
    +22  u16  srv_len
    +24  u16  svc_off     field-2 span (the fleet partition key)
    +26  u16  svc_len
    +28  u8   flags
    +29  u8   pad
    +30  u16  reserved

``line_off`` is not stored: records are packed in order, so offsets are the
running sum of ``line_len + 1`` (:func:`line_offsets`).

Flags:

- ``FL_EXOTIC`` — a numeric field was not a plain ASCII digit run (or was
  absent): the header's ``end_ts``/``elapsed`` were derived via the full
  ``js_parse_int`` semantics and downstream decoders should treat the line
  text as authoritative (the TxDecoder exotic contract).
- ``FL_NONTX`` — not a ``tx|…`` line (or too short to carry a server
  field): never counted as a transaction, partition 0 under either key.
- ``FL_NOSVC`` — no routing key (fewer than 4 ``|``-fields, the
  ``tx_partition_key`` None rule): partition 0 for either key kind
  under the service key, mirroring ``tx_partition_key`` returning None.

Field semantics (split on ``|``, no maxsplit) are EXACTLY the reference
``EntryFactory.from_csv`` / ``tx_partition_key`` view of a line, so frame
routing and line routing can never disagree on the same bytes. Oversized
lines (> 0xFFFF bytes — spans would not fit u16) are carried verbatim but
flagged ``FL_EXOTIC|FL_NONTX|FL_NOSVC``.

The encoder has a native fast path (``apmfrm_pack`` in native/parser.cpp —
plain numerics parsed in C++, exotic records flagged and patched here via
``js_parse_int``) and a pure-Python fallback; ``APM_FRAMES_NO_NATIVE=1``
forces the fallback, and tests pin the two bit-identical.

Carriage trailer (the frame-native observability plane)
-------------------------------------------------------

Batch-granular header stamping went dark on per-record latency: one
``ingest_ts`` per batch collapses 512 records onto a single stamp, and the
pipelined shm-ring hop (``channel.send`` straight from the parser) carries
no headers at all. The OPTIONAL carriage trailer rides after the lines
region and restores both axes in-band::

    +0   b"APC1"                      carriage magic
    +4   u32  nrec                    echo of the batch header's nrec
    +8   f8   ingest_base             unix seconds, min ingest stamp
    +16  u16  trace_len               sampled trace_id byte length (0 = none)
    +18  u16[nrec] delta_ms           per-record (ingest_ts - base) millis,
                                      clamped to [0, 65535]
    +18+2*nrec  trace_id utf-8 bytes

A blob WITHOUT the trailer is byte-identical to the pre-carriage wire and
every reader still accepts it (``read_carriage`` → None); the writer-side
kill switch is ``APM_NO_FRAME_CARRIAGE=1`` (parser flush — mirroring
``APM_NO_FRAMES``). Because the trailer is payload, not headers, it
survives every fabric — spool replay, redis/AMQP redelivery (the original
trace_id rides the redelivered payload, matching per-line header
retention), and the header-less shm ring.
"""

from __future__ import annotations

import os
import struct
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from ..entries import js_parse_int

FRAME_MAGIC = b"APF1"
HEADER = struct.Struct("<4sIQ")  # magic, nrec, lines_off
HEADER_SIZE = HEADER.size  # 16
RECORD_SIZE = 32

CARRIAGE_MAGIC = b"APC1"
_CARRIAGE_HDR = struct.Struct("<4sIdH")  # magic, nrec echo, ingest_base, trace_len
_CARRIAGE_HDR_SIZE = _CARRIAGE_HDR.size  # 18
_DELTA_MAX = 0xFFFF

FL_EXOTIC = 0x01
FL_NONTX = 0x02
FL_NOSVC = 0x04

_SPAN_MAX = 0xFFFF
_PLAIN_MAX_DIGITS = 18  # fits u64 exactly; longer runs take the exotic path

RECORD_DTYPE = np.dtype(
    [
        ("end_ts", "<f8"),
        ("elapsed", "<f8"),
        ("line_len", "<u4"),
        ("srv_off", "<u2"),
        ("srv_len", "<u2"),
        ("svc_off", "<u2"),
        ("svc_len", "<u2"),
        ("flags", "u1"),
        ("pad", "u1"),
        ("reserved", "<u2"),
    ]
)
assert RECORD_DTYPE.itemsize == RECORD_SIZE


def is_frames(payload) -> bool:
    """True when ``payload`` is (the start of) a frame batch. str payloads
    can never be frames — the magic is checked on raw bytes only."""
    return (
        isinstance(payload, (bytes, bytearray, memoryview))
        and bytes(payload[:4]) == FRAME_MAGIC
    )


def frame_count(blob) -> int:
    """Record count from the batch header (0 for a torn/short blob)."""
    if len(blob) < HEADER_SIZE:
        return 0
    _magic, nrec, _off = HEADER.unpack_from(bytes(blob[:HEADER_SIZE]), 0)
    return int(nrec)


class FrameError(ValueError):
    pass


def _check(blob) -> Tuple[int, int, int]:
    """Validate the batch envelope; returns (nrec, lines_off, lines_end).

    ``lines_end`` is the byte offset one past the lines region (including
    the final separator): ``len(blob)`` for a bare batch, the carriage
    trailer's start otherwise. Any other surplus is a torn blob."""
    if len(blob) < HEADER_SIZE:
        raise FrameError(f"frame batch shorter than its header ({len(blob)}B)")
    magic, nrec, lines_off = HEADER.unpack_from(bytes(blob[:HEADER_SIZE]), 0)
    if magic != FRAME_MAGIC:
        raise FrameError(f"bad frame magic {magic!r}")
    if lines_off != HEADER_SIZE + RECORD_SIZE * nrec or lines_off > len(blob):
        raise FrameError(
            f"frame batch header inconsistent: nrec={nrec} "
            f"lines_off={lines_off} size={len(blob)}"
        )
    rec = np.frombuffer(blob, RECORD_DTYPE, count=nrec, offset=HEADER_SIZE)
    want = int(lines_off) + int(rec["line_len"].sum()) + int(nrec)
    if want == len(blob):
        return int(nrec), int(lines_off), want
    if want < len(blob) and bytes(blob[want : want + 4]) == CARRIAGE_MAGIC:
        # surplus bytes are acceptable ONLY as a valid carriage trailer
        # that consumes the blob exactly to its end
        if len(blob) >= want + _CARRIAGE_HDR_SIZE:
            _magic, cn, _base, tlen = _CARRIAGE_HDR.unpack_from(
                bytes(blob[want : want + _CARRIAGE_HDR_SIZE]), 0
            )
            if cn == nrec and want + _CARRIAGE_HDR_SIZE + 2 * cn + tlen == len(blob):
                return int(nrec), int(lines_off), want
        raise FrameError(
            f"frame carriage trailer torn: lines end {want}B, got {len(blob)}B"
        )
    # a torn lines region must fail loudly, not feed a truncated line
    raise FrameError(
        f"frame batch size mismatch: header wants {want}B, got {len(blob)}B"
    )


def records(blob) -> np.ndarray:
    """Zero-copy structured view of the per-record headers."""
    nrec, _lines_off, _end = _check(blob)
    return np.frombuffer(blob, RECORD_DTYPE, count=nrec, offset=HEADER_SIZE)


def lines_region(blob) -> memoryview:
    """The newline-joined lines region WITHOUT the trailing separator —
    directly feedable to the bulk CSV decoder (feed_csv_bytes)."""
    nrec, lines_off, lines_end = _check(blob)
    mv = memoryview(blob)[lines_off:lines_end]
    if nrec and len(mv) and mv[-1] == 0x0A:
        mv = mv[:-1]
    return mv


def line_offsets(rec: np.ndarray) -> np.ndarray:
    """Per-record byte offsets into the lines region (running sum of
    ``line_len + 1``), length nrec+1 (the last entry is the region size)."""
    offs = np.zeros(len(rec) + 1, dtype=np.int64)
    np.cumsum(rec["line_len"].astype(np.int64) + 1, out=offs[1:])
    return offs


def iter_lines(blob) -> List[bytes]:
    """Every line as bytes, verbatim (no trailing separator)."""
    nrec, lines_off, _end = _check(blob)
    rec = np.frombuffer(blob, RECORD_DTYPE, count=nrec, offset=HEADER_SIZE)
    offs = line_offsets(rec)
    mv = memoryview(blob)
    out = []
    for i in range(nrec):
        base = lines_off + int(offs[i])
        out.append(bytes(mv[base : base + int(rec["line_len"][i])]))
    return out


def decode_lines(blob) -> List[str]:
    """Frames → text lines (the compat/unfold path for frame-unaware
    consumers; ``errors='replace'`` mirrors the tailer's decode posture)."""
    return [b.decode("utf-8", "replace") for b in iter_lines(blob)]


def tx_count(blob) -> int:
    """Transactions in the batch (records without FL_NONTX)."""
    rec = records(blob)
    if not len(rec):
        return 0
    return int(np.count_nonzero((rec["flags"] & FL_NONTX) == 0))


# ---------------------------------------------------------------- encoding


def _as_bytes_lines(lines: Iterable) -> List[bytes]:
    out = []
    for line in lines:
        b = line.encode("utf-8") if isinstance(line, str) else bytes(line)
        if b"\n" in b:
            raise FrameError("frame lines must not contain newlines")
        out.append(b)
    return out


def _exotic_num(fields: Sequence[bytes], idx: int) -> float:
    if len(fields) <= idx:
        return float("nan")
    return js_parse_int(fields[idx].decode("utf-8", "replace"))


def _is_plain(field: bytes) -> bool:
    return 0 < len(field) <= _PLAIN_MAX_DIGITS and field.isdigit()


def _classify(lb: bytes, rec_row) -> None:
    """Fill one record row from one line — the single source of truth the
    native packer (apmfrm_pack) mirrors byte for byte."""
    rec_row["line_len"] = len(lb)
    if len(lb) > _SPAN_MAX:
        rec_row["flags"] = FL_EXOTIC | FL_NONTX | FL_NOSVC
        rec_row["end_ts"] = rec_row["elapsed"] = float("nan")
        return
    f = lb.split(b"|")
    if len(f) < 2 or f[0] != b"tx":
        rec_row["flags"] = FL_NONTX | FL_NOSVC
        rec_row["end_ts"] = rec_row["elapsed"] = float("nan")
        return
    flags = 0
    srv_off = len(f[0]) + 1
    rec_row["srv_off"] = srv_off
    rec_row["srv_len"] = len(f[1])
    if len(f) >= 3:
        rec_row["svc_off"] = srv_off + len(f[1]) + 1
        rec_row["svc_len"] = len(f[2])
    if len(f) < 4:
        # tx_partition_key wants 4+ fields before it yields a key: such
        # degenerate lines route to partition 0 under EITHER key kind
        flags |= FL_NOSVC
    if len(f) > 6 and _is_plain(f[6]):
        rec_row["end_ts"] = float(int(f[6]))
    else:
        flags |= FL_EXOTIC
        rec_row["end_ts"] = _exotic_num(f, 6)
    if len(f) > 7 and _is_plain(f[7]):
        rec_row["elapsed"] = float(int(f[7]))
    else:
        flags |= FL_EXOTIC
        rec_row["elapsed"] = _exotic_num(f, 7)
    rec_row["flags"] = flags


def _encode_python(lines_b: List[bytes]) -> bytes:
    n = len(lines_b)
    rec = np.zeros(n, dtype=RECORD_DTYPE)
    for i, lb in enumerate(lines_b):
        _classify(lb, rec[i])
    head = HEADER.pack(FRAME_MAGIC, n, HEADER_SIZE + RECORD_SIZE * n)
    return head + rec.tobytes() + b"".join(lb + b"\n" for lb in lines_b)


def _patch_exotics(raw: bytearray, lines_b: List[bytes]) -> bytes:
    """Native pack leaves exotic records' numerics NaN; re-derive them with
    the full js_parse_int semantics here (the decoder.cpp exotic contract)."""
    rec = np.frombuffer(raw, RECORD_DTYPE, count=len(lines_b), offset=HEADER_SIZE)
    exotic = np.nonzero(rec["flags"] & FL_EXOTIC)[0]
    for i in exotic:
        if rec["flags"][i] & FL_NONTX:
            continue
        f = lines_b[i].split(b"|")
        rec["end_ts"][i] = _exotic_num(f, 6)
        rec["elapsed"][i] = _exotic_num(f, 7)
    return bytes(raw)


def _native_disabled() -> bool:
    return os.environ.get("APM_FRAMES_NO_NATIVE", "") not in ("", "0")


def encode_lines(lines: Iterable) -> bytes:
    """Pack transaction lines (str or bytes, no embedded newlines) into one
    frame batch. Native scan when the toolchain built it; pure-Python
    fallback otherwise (bit-identical, pinned by tests/test_frames.py)."""
    lines_b = _as_bytes_lines(lines)
    if not lines_b:
        return HEADER.pack(FRAME_MAGIC, 0, HEADER_SIZE)
    if not _native_disabled():
        try:
            from ..native import frames_pack_native
        except Exception:
            frames_pack_native = None
        if frames_pack_native is not None:
            raw = frames_pack_native(lines_b)
            if raw is not None:
                return _patch_exotics(raw, lines_b)
    return _encode_python(lines_b)


# ------------------------------------------------------------- carriage plane


def has_carriage(blob) -> bool:
    """True when the batch carries an APC1 trailer (validated envelope)."""
    _nrec, _off, lines_end = _check(blob)
    return lines_end < len(blob)


def append_carriage(blob, ingest_base: float, delta_ms, trace_id: str = "") -> bytes:
    """Append the carriage trailer to a bare batch: per-record ingest
    stamps as ``base + u16 delta-millis`` (clamped to 65.535 s — a record
    older than that saturates rather than wraps) plus an optional sampled
    ``trace_id``. Returns a NEW blob; the input is never mutated."""
    nrec, _off, lines_end = _check(blob)
    if lines_end != len(blob):
        raise FrameError("frame batch already carries a trailer")
    deltas = np.asarray(delta_ms, dtype=np.int64)
    if len(deltas) != nrec:
        raise FrameError(
            f"carriage wants {nrec} per-record deltas, got {len(deltas)}"
        )
    tid = trace_id.encode("utf-8") if trace_id else b""
    if len(tid) > _DELTA_MAX:
        tid = tid[:_DELTA_MAX]
    packed = np.clip(deltas, 0, _DELTA_MAX).astype("<u2").tobytes()
    return (
        bytes(blob)
        + _CARRIAGE_HDR.pack(CARRIAGE_MAGIC, nrec, float(ingest_base), len(tid))
        + packed
        + tid
    )


def read_carriage(blob) -> Optional[Tuple[float, np.ndarray, str]]:
    """``(ingest_base, u16 delta-millis array, trace_id)`` from the trailer,
    or None for a bare (pre-carriage / kill-switched) batch. The deltas
    array is a zero-copy view into the blob."""
    nrec, _off, lines_end = _check(blob)
    if lines_end == len(blob):
        return None
    _magic, _cn, base, tlen = _CARRIAGE_HDR.unpack_from(
        bytes(blob[lines_end : lines_end + _CARRIAGE_HDR_SIZE]), 0
    )
    deltas = np.frombuffer(
        blob, "<u2", count=nrec, offset=lines_end + _CARRIAGE_HDR_SIZE
    )
    tid_off = lines_end + _CARRIAGE_HDR_SIZE + 2 * nrec
    trace_id = bytes(blob[tid_off : tid_off + tlen]).decode("utf-8", "replace")
    return float(base), deltas, trace_id


def strip_carriage(blob) -> bytes:
    """The bare batch without its trailer — byte-identical to the
    pre-carriage wire (compat escape hatch; tests pin this)."""
    _nrec, _off, lines_end = _check(blob)
    return bytes(blob[:lines_end])


def carriage_trace_id(blob) -> str:
    """The trailer's sampled trace_id, or "" (no carriage / unsampled /
    torn blob) — the producer's is-this-batch-already-traced probe; never
    raises."""
    try:
        car = read_carriage(blob)
    except Exception:
        return ""
    return car[2] if car is not None else ""


def record_ingest_ts(blob) -> Optional[np.ndarray]:
    """Per-record ingest stamps (unix seconds, f8, length nrec) recovered
    from the carriage, or None for a bare batch."""
    car = read_carriage(blob)
    if car is None:
        return None
    base, deltas, _tid = car
    return base + deltas.astype(np.float64) / 1000.0


# ---------------------------------------------------------- partition plane

_FNV_OFFSET = 0x811C9DC5
_FNV_PRIME = 0x01000193


def _fnv1a32(data: bytes) -> int:
    h = _FNV_OFFSET
    for b in data:
        h = ((h ^ b) * _FNV_PRIME) & 0xFFFFFFFF
    return h


def partition_ids(blob, n_partitions: int, key: str = "service") -> List[int]:
    """Per-record partition ids, FNV-1a over the routing-key span read
    straight from the frame — the same stable hash ``service_partition``
    computes from a parsed line, without parsing one. Records without a
    routing key land on partition 0 (the ``tx_partition_key`` None rule —
    FL_NOSVC marks those for either key kind)."""
    nrec, lines_off, _end = _check(blob)
    rec = np.frombuffer(blob, RECORD_DTYPE, count=nrec, offset=HEADER_SIZE)
    offs = line_offsets(rec)
    mv = memoryview(blob)
    use_service = key != "server"
    out = []
    for i in range(nrec):
        flags = int(rec["flags"][i])
        if flags & (FL_NONTX | FL_NOSVC):
            out.append(0)
            continue
        base = lines_off + int(offs[i])
        if use_service:
            o, ln = int(rec["svc_off"][i]), int(rec["svc_len"][i])
        else:
            o, ln = int(rec["srv_off"][i]), int(rec["srv_len"][i])
        out.append(_fnv1a32(bytes(mv[base + o : base + o + ln])) % n_partitions)
    return out


def split_by_partition(blob, n_partitions: int,
                       key: str = "service") -> Dict[int, bytes]:
    """Split one mixed batch into per-partition sub-batches (record order
    preserved within each partition) — the fleet producer's frame router.
    A carriage trailer is split along with its records: every sub-batch
    keeps its own delta slice (same base, same sampled trace_id), so fleet
    routing never collapses per-record ingest stamps back to batch
    granularity."""
    parts = partition_ids(blob, n_partitions, key)
    if not parts:
        return {}
    car = read_carriage(blob)
    lines = iter_lines(blob)
    grouped: Dict[int, List[bytes]] = {}
    grouped_deltas: Dict[int, List[int]] = {}
    for i, (p, lb) in enumerate(zip(parts, lines)):
        grouped.setdefault(p, []).append(lb)
        if car is not None:
            grouped_deltas.setdefault(p, []).append(int(car[1][i]))
    out = {}
    for p, g in grouped.items():
        sub = encode_lines(g)
        if car is not None:
            sub = append_carriage(sub, car[0], grouped_deltas[p], car[2])
        out[p] = sub
    return out


def count_partition_mismatches(blob, n_partitions: int, expected: int,
                               key: str = "service") -> int:
    """Transactions in the batch whose routing key does NOT hash to
    ``expected`` — the worker's frame-path partition-header defense."""
    rec = records(blob)
    if not len(rec):
        return 0
    parts = partition_ids(blob, n_partitions, key)
    bad = 0
    for p, flags in zip(parts, rec["flags"]):
        if int(flags) & FL_NONTX:
            continue
        if p != expected:
            bad += 1
    return bad


def summarize(blob) -> dict:
    """Cheap batch stats for logs/benches: record counts + byte split."""
    nrec, lines_off, lines_end = _check(blob)
    rec = np.frombuffer(blob, RECORD_DTYPE, count=nrec, offset=HEADER_SIZE)
    n_tx = int(np.count_nonzero((rec["flags"] & FL_NONTX) == 0)) if nrec else 0
    n_exotic = int(np.count_nonzero(rec["flags"] & FL_EXOTIC)) if nrec else 0
    return {
        "records": nrec,
        "tx": n_tx,
        "exotic": n_exotic,
        "header_bytes": lines_off,
        "line_bytes": lines_end - lines_off,
        "carriage_bytes": len(blob) - lines_end,
    }


def batch_end_ts_max(blob) -> Optional[float]:
    """Max end_ts across tx records (NaN-safe); None when the batch carries
    no finite stamp — a one-pass header read benches/latency probes use."""
    rec = records(blob)
    if not len(rec):
        return None
    ts = rec["end_ts"][(rec["flags"] & FL_NONTX) == 0]
    ts = ts[~np.isnan(ts)]
    if not len(ts):
        return None
    return float(ts.max())
