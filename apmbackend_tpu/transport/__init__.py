from .base import Channel, ConsumerQueue, EventEmitter, ProducerQueue, QueueManager  # noqa: F401
from .memory import MemoryBroker, MemoryChannel  # noqa: F401
from .amqp import AmqpChannel, HAVE_PIKA  # noqa: F401
from .spool import SpoolChannel, read_spool_cursor  # noqa: F401
from .redis_streams import HAVE_REDIS, RedisStreamsChannel  # noqa: F401


def effective_broker_backend(config: dict) -> str:
    """Broker selection: ``transport.broker`` wins when set, else the
    top-level ``brokerBackend`` (kept for pre-ISSUE-15 configs)."""
    transport_cfg = config.get("transport", {}) or {}
    return transport_cfg.get("broker") or config.get("brokerBackend", "memory")


def make_queue_manager(config: dict, *, broker=None, logger=None,
                       redis_module=None, start_pumps: bool = True) -> QueueManager:
    """Build a QueueManager for the configured backend.

    ``brokerBackend: "memory"`` shares the passed (or a fresh) MemoryBroker
    between the producer and consumer channels; ``"amqp"`` connects to
    ``amqpConnectionString`` per channel like the reference
    (queue.js:120-137); ``"redis"`` builds one RedisStreamsChannel per
    direction from the ``redis`` section (``redis_module`` injects the
    in-process fake); ``"spool"`` shares one durable SpoolChannel fabric
    under ``transport.spoolDirectory``.

    Pumped backends (a memory broker created here, redis, spool) get their
    pump thread started: the pump owns delivery, reconnect, ack retry, and
    — on redis, where drain is polled rather than pushed — the drain
    detection that resumes a paused producer. ``start_pumps=False`` leaves
    pumping to the caller (tests that drive ``pump_once()`` themselves);
    a broker passed in is assumed already pumped by its owner.
    """
    backend = effective_broker_backend(config)
    interval = config.get("statLogIntervalInSeconds", 60)
    transport_cfg = config.get("transport", {}) or {}
    if backend == "memory":
        shared = broker if broker is not None else MemoryBroker()
        if broker is None and start_pumps:
            shared.start_pump_thread()

        def factory(_kind: str):
            return MemoryChannel(shared)

        qm = QueueManager(factory, interval, logger=logger, transport_config=transport_cfg)
        qm.broker = shared
        return qm
    if backend == "amqp":
        conn = config["amqpConnectionString"]

        def factory(kind: str):
            return AmqpChannel(conn, direction=kind, logger=logger)

        return QueueManager(factory, interval, logger=logger, transport_config=transport_cfg)
    if backend == "redis":
        redis_cfg = config.get("redis", {}) or {}

        def factory(_kind: str):
            ch = RedisStreamsChannel(
                redis_cfg.get("connectionString", "redis://localhost:6379/0"),
                redis_module=redis_module, logger=logger,
                group=redis_cfg.get("group", "apm"),
                stream_maxlen=redis_cfg.get("streamMaxlen", 100000),
                claim_idle_ms=redis_cfg.get("claimIdleMs", 5000),
                prefetch=redis_cfg.get("prefetchCount", 1000),
            )
            if start_pumps:
                # producer-side channels need the pump too: drain is
                # polled, not pushed, so a paused producer only resumes
                # when something re-checks the backlog
                ch.start_pump_thread()
            return ch

        return QueueManager(factory, interval, logger=logger, transport_config=transport_cfg)
    if backend == "spool":
        shared_spool = SpoolChannel(transport_cfg.get("spoolDirectory", "spool/broker"))
        if start_pumps:
            shared_spool.start_pump_thread()

        def factory(_kind: str):
            return shared_spool

        qm = QueueManager(factory, interval, logger=logger, transport_config=transport_cfg)
        qm.spool = shared_spool
        return qm
    if backend == "shmring":
        from .shmring import DEFAULT_RING_BYTES, ShmRingChannel

        def factory(_kind: str):
            ch = ShmRingChannel(
                transport_cfg.get("shmRingDirectory", "spool/shmring"),
                ring_bytes=int(transport_cfg.get("shmRingBytes", DEFAULT_RING_BYTES)),
                logger=logger,
            )
            if start_pumps:
                # producer-side channels need the pump too: drain (free
                # space after a refusal) is polled off the mmap, not pushed
                ch.start_pump_thread()
            return ch

        return QueueManager(factory, interval, logger=logger, transport_config=transport_cfg)
    raise ValueError(f"Unknown brokerBackend: {backend}")
