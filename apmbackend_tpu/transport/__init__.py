from .base import Channel, ConsumerQueue, EventEmitter, ProducerQueue, QueueManager  # noqa: F401
from .memory import MemoryBroker, MemoryChannel  # noqa: F401
from .amqp import AmqpChannel, HAVE_PIKA  # noqa: F401
from .spool import SpoolChannel, read_spool_cursor  # noqa: F401


def make_queue_manager(config: dict, *, broker=None, logger=None) -> QueueManager:
    """Build a QueueManager for the configured backend.

    ``brokerBackend: "memory"`` shares the passed (or a fresh) MemoryBroker
    between the producer and consumer channels; ``"amqp"`` connects to
    ``amqpConnectionString`` per channel like the reference (queue.js:120-137).
    """
    backend = config.get("brokerBackend", "memory")
    interval = config.get("statLogIntervalInSeconds", 60)
    if backend == "memory":
        shared = broker if broker is not None else MemoryBroker()

        def factory(_kind: str):
            return MemoryChannel(shared)

        qm = QueueManager(factory, interval, logger=logger)
        qm.broker = shared
        return qm
    if backend == "amqp":
        conn = config["amqpConnectionString"]

        def factory(kind: str):
            return AmqpChannel(conn, direction=kind, logger=logger)

        return QueueManager(factory, interval, logger=logger)
    raise ValueError(f"Unknown brokerBackend: {backend}")
