"""Redis Streams transport backend — consumer-group at-least-once fabric.

The DAQ swap the roadmap names (PAPERS.md, arxiv 2511.14894): Redis
Streams' consumer groups map 1:1 onto the manual-ack Channel contract —
XREADGROUP ``">"`` delivers and records each entry in the group's pending
entries list (PEL), XACK commits, and XAUTOCLAIM is the redelivery path:
entries a dead or stalled consumer left pending are re-claimed once idle
longer than ``claim_idle_ms`` and re-delivered with
``headers["redelivered"]`` set, exactly like a broker bounce on the
memory backend or AMQP connection death.

Flow control is send-side and explicit: Redis itself never refuses an
XADD — ``MAXLEN`` trimming silently deletes the OLDEST entries instead,
which under a stalled consumer is data loss, not backpressure. So
``send`` refuses (returns False → ProducerQueue buffers + pause) while
the group backlog (PEL pending + undelivered lag) is at
``stream_maxlen``, and the retention trim rides far behind at
``2 * stream_maxlen`` (approximate) so it only ever eats the acked
prefix. Drain fires when the backlog falls to half the cap. The backlog
probe (XINFO GROUPS) is amortized: far below the cap it runs once per
``backlog_check_every`` sends against a locally-advanced estimate, and
only near the cap does every send pay the round trip.

Durability class: bounded-loss durable — entries survive broker restart
(RDB/AOF) and consumer crashes (PEL + XAUTOCLAIM), but retention trimming
caps history at ``2 * stream_maxlen`` per stream; XAUTOCLAIM surfaces any
entry trimmed out from under the PEL in its *deleted* list and the
channel counts those loudly rather than hiding them.

Connection loss is absorbed the same way fullness is: ``send`` returns
False (the producer buffers upstream under its own cap), acks park in a
retry list (XACK is idempotent, so retrying after reconnect is safe), and
the pump thread reconnects with decorrelated-jitter backoff.

The redis-py client is optional exactly like pika: ``redis_module``
injects an in-process fake (tests/fake_redis.py) so tier-1 never needs a
server; real-server tests auto-skip when nothing listens.
"""

from __future__ import annotations

import json
import threading
import time
from collections import defaultdict
from typing import Callable, Dict, List, Optional, Set, Tuple

from .base import Channel, accepts_headers

try:  # pragma: no cover - exercised only where redis-py is installed
    import redis as _redis  # type: ignore

    HAVE_REDIS = True
except Exception:  # pragma: no cover
    _redis = None
    HAVE_REDIS = False


def _s(x) -> str:
    """redis-py (decode_responses=False) hands back bytes; fakes hand str."""
    return x.decode("utf-8") if isinstance(x, (bytes, bytearray)) else str(x)


def _field(fields: dict, key: str):
    """Field lookup tolerant of bytes keys (real client) and str (fake)."""
    if key in fields:
        return fields[key]
    return fields.get(key.encode("utf-8"))


class RedisStreamsChannel(Channel):
    """Channel over Redis Streams consumer groups (DESIGN.md §7.1).

    One channel serves either direction: producers only ``send``, consumers
    only ``consume``/``deliver``. Delivery is pumped (``deliver()`` /
    ``start_pump_thread``) like the memory broker and the spool; the pump
    thread also owns reconnect, ack retry, and drain detection, so a
    producer-side channel needs it too (drain is observed by polling the
    group backlog, not pushed by the broker).
    """

    def __init__(
        self,
        connection_string: str = "redis://localhost:6379/0",
        *,
        redis_module=None,
        logger=None,
        group: str = "apm",
        consumer_name: Optional[str] = None,
        stream_maxlen: int = 100000,
        claim_idle_ms: int = 5000,
        prefetch: int = 1000,
        reconnect_base_backoff_s: float = 0.05,
        reconnect_max_backoff_s: float = 2.0,
        jitter_rng=None,
    ):
        mod = redis_module if redis_module is not None else _redis
        if mod is None:
            raise RuntimeError(
                "redis-py is not installed and no redis_module fake was "
                "injected — RedisStreamsChannel needs one or the other")
        self._mod = mod
        self._conn_errors = (mod.exceptions.ConnectionError, OSError)
        self._resp_error = mod.exceptions.ResponseError
        self.connection_string = connection_string
        self.logger = logger
        self.group = group
        self.consumer_name = consumer_name or f"c-{id(self):x}"
        self.stream_maxlen = int(stream_maxlen)
        self.claim_idle_ms = int(claim_idle_ms)
        self.prefetch = int(prefetch)
        self._lock = threading.RLock()
        self._cli = None  # guarded-by: _lock
        self._queues: Set[str] = set()  # guarded-by: _lock
        # queue -> (tag, callback, manual) — one consumer per queue, like spool
        self._consumers: Dict[str, Tuple[str, Callable, bool]] = {}  # guarded-by: _lock
        self._groups_ready: Set[str] = set()  # guarded-by: _lock
        self._unacked: Set[Tuple[str, str]] = set()  # guarded-by: _lock
        self._pending_acks: List[Tuple[str, str]] = []  # guarded-by: _lock
        self._pressure = False  # guarded-by: _lock
        self._pressured: Set[str] = set()  # guarded-by: _lock
        self._backlog_est: Dict[str, int] = {}  # guarded-by: _lock
        self._sends_since_check: Dict[str, int] = {}  # guarded-by: _lock
        self.backlog_check_every = 64  # sends between XINFO checks while well below cap
        self._next_connect_at = 0.0  # guarded-by: _lock
        self._backoff_s = reconnect_base_backoff_s  # guarded-by: _lock
        self._base_backoff_s = reconnect_base_backoff_s
        self._max_backoff_s = reconnect_max_backoff_s
        if jitter_rng is None:
            import random

            jitter_rng = random.Random()
        self._rng = jitter_rng
        self.deleted_count = 0  # guarded-by: _lock (PEL entries lost to trim)
        self._drain_cbs: List[Callable[[], None]] = []
        self._pump_thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # -- connection ----------------------------------------------------------
    # apm: holds(_lock): every caller acquires it (send, deliver, ack, pump)
    def _ensure_client_locked(self):
        """Caller holds self._lock. Returns a live client or raises one of
        ``self._conn_errors`` (respecting the reconnect backoff window)."""
        if self._cli is not None:
            return self._cli
        now = time.monotonic()
        if now < self._next_connect_at:
            raise self._conn_errors[0]("reconnect backoff in effect")
        try:
            cli = self._mod.Redis.from_url(self.connection_string)
            cli.ping()
        except self._conn_errors:
            # decorrelated jitter (same policy as AmqpChannel._next_backoff):
            # spreads a fleet's reconnect herd after a broker restart
            self._backoff_s = min(
                self._max_backoff_s,
                self._rng.uniform(self._base_backoff_s,
                                  max(self._backoff_s * 3, self._base_backoff_s)))
            self._next_connect_at = now + self._backoff_s
            raise
        self._cli = cli
        self._backoff_s = self._base_backoff_s
        self._next_connect_at = 0.0
        return cli

    # apm: holds(_lock): callers are the op paths that just caught a conn error
    def _drop_client_locked(self, err: Exception) -> None:
        if self._cli is not None and self.logger:
            self.logger.error(f"redis connection lost: {err}")
        self._cli = None
        # a restarted server without persistence may have lost the groups;
        # re-creating is one idempotent XGROUP CREATE per queue (BUSYGROUP
        # swallowed), so re-learn them after every reconnect
        self._groups_ready.clear()
        # backlog estimates are per-server state: re-measure after reconnect
        self._backlog_est.clear()
        self._sends_since_check.clear()

    # apm: holds(_lock): group bookkeeping is shared consumer state
    def _ensure_group_locked(self, cli, name: str) -> None:
        if name in self._groups_ready:
            return
        try:
            # id="0": a group created after the producer already streamed
            # entries must still see them ("$" would skip the backlog)
            cli.xgroup_create(name, self.group, id="0", mkstream=True)
        except self._resp_error as e:
            if "BUSYGROUP" not in str(e):
                raise
        self._groups_ready.add(name)

    # apm: holds(_lock): reads shared group bookkeeping
    def _backlog_locked(self, cli, name: str) -> int:
        """Messages this channel's group still owes: PEL pending + entries
        never delivered (lag). Before any group exists (no consumer started
        anywhere yet) the whole stream is backlog."""
        try:
            infos = cli.xinfo_groups(name)
        except self._resp_error as e:
            # XINFO GROUPS on a stream no XADD has created yet (first send,
            # or a non-persistent broker restart wiped it) raises
            # "ERR no such key" — an empty stream owes nothing
            if "no such key" in str(e).lower():
                return 0
            raise
        for info in infos:
            if _s(info.get("name")) == self.group:
                return int(info.get("pending", 0)) + int(info.get("lag", 0) or 0)
        return int(cli.xlen(name))

    # apm: holds(_lock): reads/updates the shared backlog estimate
    def _admit_send_locked(self, cli, name: str) -> bool:
        """Backlog gate for one XADD, without an XINFO round trip per send.

        Between measurements the backlog can only have grown by this
        channel's own sends (acks shrink it, other producers can add — the
        estimate is exact for a single producer, conservative-late by at
        most ``backlog_check_every`` entries with several). So the broker
        round trip is paid only every ``backlog_check_every`` sends while
        the estimate plus that slack stays below ``stream_maxlen``; within
        one interval of the cap every send re-measures, keeping refusal
        exact exactly where it matters."""
        est = self._backlog_est.get(name)
        since = self._sends_since_check.get(name, 0)
        if (est is not None
                and since < self.backlog_check_every
                and est + since + self.backlog_check_every < self.stream_maxlen):
            return True
        backlog = self._backlog_locked(cli, name)
        self._backlog_est[name] = backlog
        self._sends_since_check[name] = 0
        return backlog < self.stream_maxlen

    # -- Channel contract ----------------------------------------------------
    def assert_queue(self, name: str) -> None:
        with self._lock:
            self._queues.add(name)

    def send(self, name: str, payload: bytes, headers: Optional[dict] = None) -> bool:
        fields = {"p": payload, "h": json.dumps(headers or {})}
        with self._lock:
            try:
                cli = self._ensure_client_locked()
                if not self._admit_send_locked(cli, name):
                    # Redis never refuses an XADD — MAXLEN trim would eat the
                    # oldest entries instead. Refuse HERE so the overload
                    # surfaces as producer pause, not silent loss.
                    self._pressure = True
                    self._pressured.add(name)
                    return False
                # retention trim rides at 2x the refusal cap: with sends
                # refused at stream_maxlen backlog, trimming only ever
                # removes the acked prefix
                cli.xadd(name, fields, maxlen=self.stream_maxlen * 2,
                         approximate=True)
                self._sends_since_check[name] = \
                    self._sends_since_check.get(name, 0) + 1
                return True
            except self._conn_errors as e:
                # connection loss looks like fullness to the producer: it
                # buffers under its own cap and waits for the drain event
                self._drop_client_locked(e)
                self._pressure = True
                self._pressured.add(name)
                return False

    def consume(self, name: str, callback: Callable, consumer_tag: str,
                manual_ack: bool = False) -> None:
        if not manual_ack and not accepts_headers(callback):
            inner = callback
            callback = lambda payload, _h=None, _cb=inner: _cb(payload)  # noqa: E731
        with self._lock:
            self._queues.add(name)
            self._consumers[name] = (consumer_tag, callback, manual_ack)

    def cancel(self, consumer_tag: str) -> None:
        with self._lock:
            self._consumers = {
                q: c for q, c in self._consumers.items() if c[0] != consumer_tag
            }

    def ack(self, tokens) -> None:
        per_queue: Dict[str, List[str]] = defaultdict(list)
        for name, entry_id in tokens:
            per_queue[name].append(entry_id)
        with self._lock:
            for name, ids in per_queue.items():
                self._ack_ids_locked(name, ids)
            fire = self._drain_ready_locked()
        if fire:
            self._fire_drain()

    # apm: holds(_lock): mutates the unacked ledger and the ack-retry list
    def _ack_ids_locked(self, name: str, ids: List[str]) -> None:
        try:
            cli = self._ensure_client_locked()
            cli.xack(name, self.group, *ids)
            for entry_id in ids:
                self._unacked.discard((name, entry_id))
        except self._conn_errors as e:
            # XACK is idempotent: park the tokens and retry after reconnect.
            # They stay on _unacked too, so prefetch keeps gating deliveries
            # until the broker really confirmed the commit.
            self._drop_client_locked(e)
            self._pending_acks.extend((name, entry_id) for entry_id in ids)

    def on_drain(self, callback: Callable[[], None]) -> None:
        self._drain_cbs.append(callback)

    def close(self) -> None:
        self.stop()
        with self._lock:
            self._retry_pending_acks_locked()
            if self._cli is not None:
                try:
                    self._cli.close()
                except Exception:
                    pass
                self._cli = None

    # -- delivery ------------------------------------------------------------
    def deliver(self, max_messages: Optional[int] = None) -> int:
        """One delivery pass: XAUTOCLAIM idle pending (redelivery), then
        XREADGROUP new entries; invokes callbacks outside the lock."""
        delivered = 0
        while max_messages is None or delivered < max_messages:
            batch = self._collect_batch(
                None if max_messages is None else max_messages - delivered)
            if not batch:
                break
            to_ack: Dict[str, List[str]] = defaultdict(list)
            for cb, payload, headers, manual, token in batch:
                if manual:
                    cb(payload, headers, token)
                else:
                    cb(payload, headers)
                    to_ack[token[0]].append(token[1])
                delivered += 1
            if to_ack:
                with self._lock:
                    for name, ids in to_ack.items():
                        self._ack_ids_locked(name, ids)
        with self._lock:
            fire = self._drain_ready_locked()
        if fire:
            self._fire_drain()
        return delivered

    def _collect_batch(self, limit: Optional[int]):
        out = []
        with self._lock:
            try:
                cli = self._ensure_client_locked()
            except self._conn_errors:
                return out
            for name, (tag, cb, manual) in list(self._consumers.items()):
                budget = self.prefetch - len(self._unacked) if manual else 256
                if limit is not None:
                    budget = min(budget, limit - len(out))
                if budget <= 0:
                    continue
                try:
                    self._ensure_group_locked(cli, name)
                    entries = self._claim_locked(cli, name, budget)
                    got = len(entries)
                    if got < budget:
                        resp = cli.xreadgroup(
                            self.group, self.consumer_name, {name: ">"},
                            count=budget - got)
                        for _stream, fresh in resp or []:
                            entries.extend((eid, fields, False)
                                           for eid, fields in fresh)
                except self._conn_errors as e:
                    self._drop_client_locked(e)
                    return out
                for entry_id, fields, reclaimed in entries:
                    payload = _field(fields, "p") or b""
                    if not isinstance(payload, (bytes, bytearray)):
                        payload = str(payload).encode("utf-8")
                    try:
                        headers = json.loads(_s(_field(fields, "h") or "{}"))
                    except Exception:
                        headers = {}
                    if reclaimed:
                        # the crash-redelivery hop, same flag as a memory
                        # bounce, an AMQP connection death, or a spool boot
                        headers["redelivered"] = True
                    token = (name, _s(entry_id))
                    if manual:
                        self._unacked.add(token)
                    out.append((cb, bytes(payload), headers, manual, token))
        return out

    # apm: holds(_lock): walks the shared unacked ledger
    def _claim_locked(self, cli, name: str, budget: int):
        """Idle-PEL redelivery. Entries trimmed out from under the PEL come
        back in XAUTOCLAIM's deleted list — count them loudly (the loss a
        too-small stream_maxlen buys) instead of silently shrinking."""
        resp = cli.xautoclaim(
            name, self.group, self.consumer_name, self.claim_idle_ms,
            start_id="0-0", count=budget)
        # Redis < 7.0 replies (next, claimed); 7.0+ appends the deleted list
        claimed = resp[1]
        deleted = resp[2] if len(resp) > 2 else []
        if deleted:
            self.deleted_count += len(deleted)
            for entry_id in deleted:
                self._unacked.discard((name, _s(entry_id)))
            if self.logger:
                self.logger.error(
                    f"redis trimmed {len(deleted)} unacked entries on "
                    f"'{name}' — stream_maxlen is too small for this backlog")
        return [(eid, fields, True) for eid, fields in claimed]

    # apm: holds(_lock): drains the shared ack-retry list
    def _retry_pending_acks_locked(self) -> None:
        if not self._pending_acks:
            return
        pending, self._pending_acks = self._pending_acks, []
        per_queue: Dict[str, List[str]] = defaultdict(list)
        for name, entry_id in pending:
            per_queue[name].append(entry_id)
        for name, ids in per_queue.items():
            self._ack_ids_locked(name, ids)

    # apm: holds(_lock): reads/clears the shared pressure flags
    def _drain_ready_locked(self) -> bool:
        """True when pressure just cleared. The caller fires the drain
        callbacks AFTER releasing ``_lock`` — a drain callback re-enters
        ``ProducerQueue._lock``, and write_line takes those two locks in the
        opposite order, so firing under ``_lock`` would deadlock (the memory
        broker's ``_maybe_drain`` makes the same split)."""
        if not self._pressure or self._cli is None:
            return False
        low_water = max(1, self.stream_maxlen // 2)
        try:
            for name in self._pressured:
                backlog = self._backlog_locked(self._cli, name)
                self._backlog_est[name] = backlog
                self._sends_since_check[name] = 0
                if backlog > low_water:
                    return False
        except self._conn_errors as e:
            self._drop_client_locked(e)
            return False
        self._pressure = False
        self._pressured.clear()
        return True

    def _fire_drain(self) -> None:
        for cb in list(self._drain_cbs):
            cb()

    def queue_lag(self, name: str) -> int:
        """Group backlog (pending + undelivered) for the scrape-time
        ``apm_queue_lag`` gauge. Never raises: while disconnected the lag is
        unknowable and reads 0 — the SLO that matters then is availability."""
        with self._lock:
            try:
                cli = self._ensure_client_locked()
                backlog = self._backlog_locked(cli, name)
                self._backlog_est[name] = backlog
                self._sends_since_check[name] = 0
                return backlog
            except Exception:
                return 0

    def pump_once(self) -> int:
        """One maintenance cycle: reconnect (backoff permitting), retry
        parked acks, deliver, re-check drain. Producer-side channels need
        this too — drain is polled, not pushed."""
        with self._lock:
            try:
                self._ensure_client_locked()
            except self._conn_errors:
                return 0
            self._retry_pending_acks_locked()
        n = self.deliver()
        return n

    def start_pump_thread(self, poll_s: float = 0.01) -> None:
        if self._pump_thread is not None:
            return

        def _loop():
            while not self._stop.is_set():
                try:
                    if self.pump_once() == 0:
                        self._stop.wait(poll_s)
                except Exception as e:  # keep the pump alive across surprises
                    if self.logger:
                        self.logger.error(f"redis pump error: {e}")
                    self._stop.wait(poll_s)

        self._pump_thread = threading.Thread(
            target=_loop, name="redis-pump", daemon=True)
        self._pump_thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._pump_thread is not None:
            self._pump_thread.join(timeout=2.0)
            self._pump_thread = None
