"""Shared-memory mmap ring transport — the zero-copy parser→worker hop.

The memory broker moves every message through Python queues in ONE
process; the spool pays an fsync'd file append per batch. This backend is
the third point in that space: one mmap'd SPSC ring file per queue
(``<shmRingDirectory>/<queue>.ring``, ``transport.shmRingBytes`` data
bytes) shared between exactly one producer process and one consumer
process. A send is two bounded memcpys into the ring plus a tail bump; a
delivery is the mirror image. No broker process, no serialization beyond
the frame/line payload itself — built for ``transport.frameMode``, where
a record is a packed APF1 batch the worker feeds straight down the
columnar path.

Layout (little-endian, offsets in bytes)::

    0   8s  magic     b"APMSHM1\\0"
    8   Q   capacity  data-region size (fixed at file creation; the FILE
                      is authoritative — a config change needs a new file)
    16  Q   tail      bytes produced, monotonic   # guarded-by: SPSC — written only by the single producer process
    24  Q   head      bytes consumed, monotonic   # guarded-by: SPSC — written only by the single consumer process
    32  Q   msgs_in   records produced, monotonic # guarded-by: SPSC — producer-only
    40  Q   msgs_out  records consumed, monotonic # guarded-by: SPSC — consumer-only
    64      data region (records wrap byte-wise across the end)

    record := u32 rec_len | u32 hdr_len | hdr json | payload
              (rec_len = 8 + hdr_len + len(payload))

The SPSC discipline IS the synchronization: the producer reads ``head``
and writes data-then-``tail``; the consumer reads ``tail`` and writes
``head`` after copying out. Each 8-byte counter has a single writer, so
torn reads cannot happen on any platform this repo targets; within a
process a lock still serializes the multiple threads a QueueManager may
point at one channel.

Contract mapping:

- ``send`` returns False when the record does not fit the free span
  (capacity − (tail − head)) — the ProducerQueue buffers + pauses, and
  the producer-side pump polls the ring until free space crosses the
  half-capacity low-water mark, then fires ``drain`` (the Redis backend's
  polled-drain shape: nothing pushes events across the mmap).
- Delivery is at-most-once only: a record is consumed by advancing
  ``head`` — there is no unacked ledger to redeliver from, so
  ``consume(manual_ack=True)`` raises instead of silently weakening the
  at-least-once contract. Use the spool/redis/AMQP fabrics for epoch-ack
  workers.
- Durability: none across producer+consumer loss (the file persists but
  a crashed consumer's in-flight record is gone with its process) —
  same class as the memory broker, minus the single-process constraint.
"""

from __future__ import annotations

import json
import mmap
import os
import struct
import threading
import time
from typing import Callable, Dict, List, Optional

from .base import Channel

MAGIC = b"APMSHM1\0"
_HDR = struct.Struct("<8sQQQQQ")  # magic, capacity, tail, head, msgs_in, msgs_out
_OFF_TAIL = 16
_OFF_HEAD = 24
_OFF_MSGS_IN = 32
_OFF_MSGS_OUT = 40
DATA_OFF = 64
_REC = struct.Struct("<II")  # rec_len, hdr_len

DEFAULT_RING_BYTES = 8 * 1024 * 1024


class _Ring:
    """One queue's mmap'd ring. All offsets into ``mm`` are absolute;
    head/tail are monotonic byte counters (position = counter % capacity)."""

    def __init__(self, path: str, ring_bytes: int):
        self.path = path
        self._fd, created = self._open_or_create(path, ring_bytes)
        self.mm = mmap.mmap(self._fd, 0)
        if created:
            # data region first, magic LAST: a peer that maps the file mid-
            # init sees no magic and keeps waiting instead of reading junk
            self.mm[8:DATA_OFF] = struct.pack("<QQQQQ", ring_bytes, 0, 0, 0, 0) \
                + b"\0" * (DATA_OFF - 8 - 40)
            self.mm[0:8] = MAGIC
            self.mm.flush(0, DATA_OFF)
        else:
            deadline = time.monotonic() + 5.0
            while self.mm[0:8] != MAGIC:  # peer still initializing
                if time.monotonic() > deadline:
                    raise RuntimeError(f"shm ring never initialized: {path}")
                time.sleep(0.005)
        (self.capacity,) = struct.unpack_from("<Q", self.mm, 8)
        if self.capacity <= 0 or DATA_OFF + self.capacity > len(self.mm):
            raise RuntimeError(f"shm ring header corrupt: {path}")

    @staticmethod
    def _open_or_create(path: str, ring_bytes: int):
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        try:
            fd = os.open(path, os.O_RDWR | os.O_CREAT | os.O_EXCL, 0o644)
        except FileExistsError:
            return os.open(path, os.O_RDWR), False
        os.ftruncate(fd, DATA_OFF + ring_bytes)
        return fd, True

    # -- the six header words (each has exactly one writer: SPSC) ----------
    def tail(self) -> int:
        return struct.unpack_from("<Q", self.mm, _OFF_TAIL)[0]

    def head(self) -> int:
        return struct.unpack_from("<Q", self.mm, _OFF_HEAD)[0]

    def msgs_in(self) -> int:
        return struct.unpack_from("<Q", self.mm, _OFF_MSGS_IN)[0]

    def msgs_out(self) -> int:
        return struct.unpack_from("<Q", self.mm, _OFF_MSGS_OUT)[0]

    def used(self) -> int:
        return self.tail() - self.head()

    def lag(self) -> int:
        return self.msgs_in() - self.msgs_out()

    def _write_span(self, counter: int, data: bytes) -> None:
        pos = counter % self.capacity
        first = min(len(data), self.capacity - pos)
        self.mm[DATA_OFF + pos: DATA_OFF + pos + first] = data[:first]
        if first < len(data):  # wrap
            self.mm[DATA_OFF: DATA_OFF + len(data) - first] = data[first:]

    def _read_span(self, counter: int, n: int) -> bytes:
        pos = counter % self.capacity
        first = min(n, self.capacity - pos)
        out = self.mm[DATA_OFF + pos: DATA_OFF + pos + first]
        if first < n:  # wrap
            out += self.mm[DATA_OFF: DATA_OFF + n - first]
        return out

    def push(self, payload: bytes, headers: Optional[dict]) -> bool:
        """Producer side: False = full (backpressure, not an error)."""
        hdr = json.dumps(headers or {}, separators=(",", ":")).encode("utf-8")
        rec_len = _REC.size + len(hdr) + len(payload)
        if rec_len > self.capacity:
            raise ValueError(
                f"record of {rec_len} bytes can never fit a "
                f"{self.capacity}-byte shm ring ({self.path}); raise "
                f"transport.shmRingBytes or lower transport.frameMaxRecords"
            )
        tail = self.tail()
        if rec_len > self.capacity - (tail - self.head()):
            return False
        self._write_span(tail, _REC.pack(rec_len, len(hdr)) + hdr + payload)
        # record bytes land before the tail bump publishes them (the
        # consumer only ever reads below tail)
        struct.pack_into("<Q", self.mm, _OFF_TAIL, tail + rec_len)
        struct.pack_into("<Q", self.mm, _OFF_MSGS_IN, self.msgs_in() + 1)
        return True

    def pop(self):
        """Consumer side: (payload, headers) or None when empty."""
        head = self.head()
        if self.tail() - head < _REC.size:
            return None
        rec_len, hdr_len = _REC.unpack(self._read_span(head, _REC.size))
        body = self._read_span(head + _REC.size, rec_len - _REC.size)
        hdr_b, payload = body[:hdr_len], body[hdr_len:]
        try:
            headers = json.loads(hdr_b) if hdr_b else {}
        except ValueError:
            headers = {}
        # copy-out complete; the head bump frees the span for the producer
        struct.pack_into("<Q", self.mm, _OFF_HEAD, head + rec_len)
        struct.pack_into("<Q", self.mm, _OFF_MSGS_OUT, self.msgs_out() + 1)
        return payload, headers

    def close(self) -> None:
        try:
            self.mm.close()
        finally:
            os.close(self._fd)


def ring_stats(path: str) -> Optional[dict]:
    """Read-only header peek at an EXISTING ring file — the observer path
    (``qstat --lag``, flight-recorder sources). Never creates or maps the
    file: a CLI probe must not materialize empty rings in the fabric
    directory or race a peer's init. ``None`` when the file is absent,
    short, or not yet initialized (no magic)."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return None
    try:
        raw = os.pread(fd, _HDR.size, 0)
    except OSError:
        return None
    finally:
        os.close(fd)
    if len(raw) < _HDR.size:
        return None
    magic, capacity, tail, head, msgs_in, msgs_out = _HDR.unpack(raw)
    if magic != MAGIC or capacity <= 0:
        return None
    return {
        "capacity": int(capacity),
        "used_bytes": int(tail - head),
        "lag": int(msgs_in - msgs_out),
        "msgs_in": int(msgs_in),
        "msgs_out": int(msgs_out),
    }


class ShmRingLagObserver:
    """The ``Channel.queue_lag`` contract over ring FILES instead of open
    channel state: ``ShmRingChannel.queue_lag`` answers 0 for rings the
    process never opened, which is correct for a worker but useless for an
    out-of-process observer. This reads the mmap header counters of
    whatever ring files exist — disconnected (absent) rings read 0 by the
    lag-row contract, never raise."""

    def __init__(self, directory: str):
        self.directory = directory

    def _path(self, name: str) -> str:
        return os.path.join(self.directory, f"{name}.ring")

    def queue_lag(self, name: str) -> int:
        st = ring_stats(self._path(name))
        return st["lag"] if st is not None else 0

    def queue_stats(self, name: str) -> Optional[dict]:
        return ring_stats(self._path(name))

    def close(self) -> None:  # observer holds no fds between reads
        pass


class ShmRingChannel(Channel):
    """Channel over per-queue mmap SPSC rings (DESIGN.md §7.1 "shmring").

    One channel object serves either direction of one process: producers
    only ``send``; consumers register callbacks and the pump thread
    delivers. The producer-side pump exists purely for drain detection —
    free space is polled, never pushed (the Redis backend's shape)."""

    def __init__(self, directory: str, ring_bytes: int = DEFAULT_RING_BYTES,
                 logger=None):
        self.directory = directory
        self.ring_bytes = int(ring_bytes)
        self.logger = logger
        self._lock = threading.Lock()
        # wall-clock attribution (obs.attrib): push/pop busy at the memcpy
        # boundaries we already pay, pump idle on empty polls, and a
        # time-weighted occupancy per ring (the integral the instantaneous
        # apm_shmring_occupancy_bytes gauge cannot give the estimator)
        from ..obs.attrib import (
            STAGE_SHMRING_POP,
            STAGE_SHMRING_PUSH,
            STAGE_TRANSPORT_PUMP,
            get_attrib,
        )

        self._att = get_attrib()
        self._att_push = self._att.clock(STAGE_SHMRING_PUSH)
        self._att_pop = self._att.clock(STAGE_SHMRING_POP)
        self._att_pump = self._att.clock(STAGE_TRANSPORT_PUMP)
        self._att_occ: Dict[str, object] = {}  # guarded-by: _lock (queue -> Occupancy)
        self._rings: Dict[str, _Ring] = {}  # guarded-by: _lock
        self._consumers: Dict[str, Callable] = {}  # guarded-by: _lock (queue -> wrapped cb)
        self._tags: Dict[str, str] = {}  # guarded-by: _lock (consumer_tag -> queue)
        self._pressured: set = set()  # guarded-by: _lock (queues that refused a send)
        self._drain_cbs: List[Callable[[], None]] = []
        self._pump_thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # apm: holds(_lock): every caller acquires it (assert_queue, send, deliver, queue_lag)
    def _ring_locked(self, name: str) -> _Ring:
        ring = self._rings.get(name)
        if ring is None:
            ring = self._rings[name] = _Ring(
                os.path.join(self.directory, f"{name}.ring"), self.ring_bytes
            )
            from ..obs import get_registry

            get_registry().gauge(
                "apm_shmring_occupancy_bytes",
                "Bytes in flight in the shared-memory ring "
                "(produced, not yet consumed)",
                labels={"queue": name},
            ).set_fn(lambda r=ring: float(r.used()))
            self._att_occ[name] = self._att.occupancy(
                f"shmring:{name}", capacity=ring.capacity
            )
        return ring

    def assert_queue(self, name: str) -> None:
        with self._lock:
            self._ring_locked(name)

    def send(self, name: str, payload: bytes, headers: Optional[dict] = None) -> bool:
        with self._lock:
            ring = self._ring_locked(name)
            if self._att_push.enabled:
                t0 = time.perf_counter()
                ok = ring.push(payload, headers)
                self._att_push.add_busy(time.perf_counter() - t0)
            else:
                ok = ring.push(payload, headers)
            self._att_occ[name].sample(ring.used())
            if not ok:
                self._pressured.add(name)
        return ok

    def consume(self, name: str, callback: Callable, consumer_tag: str,
                manual_ack: bool = False) -> None:
        if manual_ack:
            raise NotImplementedError(
                "shmring delivery is at-most-once (head advance = consume; "
                "no unacked ledger to redeliver from) — use the spool, "
                "redis, or amqp backend for atLeastOnce workers"
            )
        with self._lock:
            self._ring_locked(name)
            self._consumers[name] = callback
            self._tags[consumer_tag] = name

    def cancel(self, consumer_tag: str) -> None:
        with self._lock:
            name = self._tags.pop(consumer_tag, None)
            if name is not None:
                self._consumers.pop(name, None)

    def ack(self, tokens) -> None:
        raise NotImplementedError("shmring has no manual-ack ledger")

    def on_drain(self, callback: Callable[[], None]) -> None:
        self._drain_cbs.append(callback)

    def queue_lag(self, name: str) -> int:
        with self._lock:
            ring = self._rings.get(name)
            return ring.lag() if ring is not None else 0

    def deliver(self, max_records: int = 1024) -> int:
        """Pop up to ``max_records`` across the registered consumers and
        invoke their callbacks outside the lock (a callback that writes a
        downstream queue on this same channel must not deadlock)."""
        batch = []
        t0 = time.perf_counter() if self._att_pop.enabled else 0.0
        with self._lock:
            for name, cb in list(self._consumers.items()):
                ring = self._rings.get(name)
                if ring is None:
                    continue
                while len(batch) < max_records:
                    rec = ring.pop()
                    if rec is None:
                        break
                    headers = rec[1]
                    # every backend synthesizes the redelivery flag; here it
                    # is constant — consuming IS the head advance, so a shm
                    # ring delivery can only ever be the first one
                    headers["redelivered"] = False
                    batch.append((cb, rec[0], headers))
                self._att_occ[name].sample(ring.used())
        if batch and self._att_pop.enabled:
            self._att_pop.add_busy(time.perf_counter() - t0)
        for cb, payload, headers in batch:
            try:
                cb(payload, headers)
            except Exception as e:  # a bad message must not kill the pump
                if self.logger:
                    self.logger.error(f"shmring consumer callback error: {e}")
        return len(batch)

    # apm: holds(_lock): pump_once acquires it around the pressure probe
    def _drain_ready_locked(self) -> bool:
        """True when every pressured ring has fallen below the half-capacity
        low-water mark. The caller fires the drain callbacks AFTER releasing
        the lock — a drain callback re-enters send() via retry_buffer."""
        if not self._pressured:
            return False
        for name in list(self._pressured):
            ring = self._rings.get(name)
            if ring is not None and ring.used() > ring.capacity // 2:
                return False
        self._pressured.clear()
        return True

    def pump_once(self) -> int:
        n = self.deliver()
        with self._lock:
            fire = self._drain_ready_locked()
        if fire:
            for cb in list(self._drain_cbs):
                cb()
        return n

    def start_pump_thread(self, poll_s: float = 0.002) -> None:
        if self._pump_thread is not None:
            return

        def _loop():
            while not self._stop.is_set():
                try:
                    if self.pump_once() == 0:
                        self._stop.wait(poll_s)
                        self._att_pump.add_idle(poll_s)
                except Exception as e:  # keep the pump alive across surprises
                    if self.logger:
                        self.logger.error(f"shmring pump error: {e}")
                    self._stop.wait(poll_s)

        self._pump_thread = threading.Thread(
            target=_loop, name="shmring-pump", daemon=True)
        self._pump_thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._pump_thread is not None:
            self._pump_thread.join(timeout=2.0)
            self._pump_thread = None

    def close(self) -> None:
        self.stop()
        with self._lock:
            for ring in self._rings.values():
                try:
                    ring.close()
                except Exception:
                    pass
            self._rings.clear()
            self._consumers.clear()
            self._tags.clear()
