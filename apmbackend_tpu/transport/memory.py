"""In-memory broker backend: the fake broker the reference never had.

Bounded per-queue deques with the pause/drain contract: ``send`` returns False
when a queue crosses its high-water mark; once the depth falls to the
low-water mark a drain callback fires on the producer channel. Delivery is
either *pumped* deterministically (tests, single-process pipelines) or driven
by a background thread (live mode).

The broker object is shareable between modules in one process, standing in for
the external RabbitMQ server; queue depth/memory introspection mirrors what
``rabbitmqctl list_queues`` provided the manager (apm_manager.js:429-453).

At-least-once (``manual_ack``) consumers get RabbitMQ's unacked-ledger
semantics: a delivered message moves to the broker's unacked map instead of
vanishing, ``ack(tokens)`` discards it, and anything still unacked when the
consumer channel closes — or when :meth:`MemoryBroker.bounce` simulates a
broker restart — is requeued at the FRONT of its queue with
``headers["redelivered"]`` set, exactly what a real broker does after a
consumer dies mid-flight.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

from .base import Channel, accepts_headers


class _NamedQueue:
    def __init__(self, capacity: int):
        self.capacity = capacity
        # (payload, headers) pairs — headers carry the transport-entry
        # ingest_ts stamp through the fake broker like AMQP properties would
        self.items: deque = deque()
        # (tag, callback, wants_headers, manual_ack)
        self.consumers: List[Tuple[str, Callable, bool, bool]] = []


class MemoryBroker:
    """Process-local named-queue store shared by producer/consumer channels."""

    def __init__(self, capacity: int = 10000, low_water_ratio: float = 0.5):
        self.capacity = capacity
        self.low_water_ratio = low_water_ratio
        self._queues: Dict[str, _NamedQueue] = {}  # guarded-by: _lock
        self._lock = threading.RLock()
        self._drain_callbacks: List[Callable[[], None]] = []  # guarded-by: _lock
        self._was_full = False  # guarded-by: _lock
        self._pump_thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._work = threading.Event()
        # manual-ack ledger: token -> (queue_name, payload, headers), in
        # delivery order (dict preserves insertion order — requeue walks it
        # newest-last so redelivery keeps the original FIFO)
        self._unacked: Dict[int, Tuple[str, bytes, Optional[dict]]] = {}  # guarded-by: _lock
        self._next_token = 0  # guarded-by: _lock

    # -- queue admin ---------------------------------------------------------
    def assert_queue(self, name: str) -> None:
        with self._lock:
            if name not in self._queues:
                self._queues[name] = _NamedQueue(self.capacity)

    def queue_depth(self, name: str) -> int:
        with self._lock:
            q = self._queues.get(name)
            return len(q.items) if q else 0

    def queue_names(self) -> List[str]:
        with self._lock:
            return list(self._queues)

    def queue_memory_bytes(self, name: str) -> int:
        with self._lock:
            q = self._queues.get(name)
            return sum(len(p) for p, _h in q.items) if q else 0

    # -- producer side -------------------------------------------------------
    def send(self, name: str, payload: bytes, headers: Optional[dict] = None) -> bool:
        with self._lock:
            q = self._queues[name]
            if len(q.items) >= q.capacity:
                self._was_full = True
                return False
            q.items.append((payload, headers))
        self._work.set()
        return True

    def on_drain(self, callback: Callable[[], None]) -> None:
        with self._lock:
            self._drain_callbacks.append(callback)

    # -- consumer side -------------------------------------------------------
    def consume(self, name: str, callback: Callable[[bytes], None], tag: str,
                manual_ack: bool = False) -> None:
        with self._lock:
            q = self._queues[name]
            if not any(t == tag for t, _cb, _h, _m in q.consumers):
                q.consumers.append((tag, callback, accepts_headers(callback), manual_ack))
        self._work.set()

    def cancel(self, tag: str) -> None:
        # cancel does NOT requeue unacked deliveries: pause/resume cycles
        # cancel and the in-flight epoch must keep its tokens ackable
        with self._lock:
            for q in self._queues.values():
                q.consumers = [c for c in q.consumers if c[0] != tag]

    def ack(self, tokens) -> None:
        """Discard manual-ack deliveries (idempotent; stale tokens ignored)."""
        with self._lock:
            for t in tokens:
                self._unacked.pop(t, None)

    def unacked_count(self, name: Optional[str] = None) -> int:
        with self._lock:
            if name is None:
                return len(self._unacked)
            return sum(1 for q, _p, _h in self._unacked.values() if q == name)

    def requeue_unacked(self) -> int:
        """Requeue every unacked delivery at the FRONT of its queue (original
        order preserved), marking ``headers["redelivered"]`` — what RabbitMQ
        does when a consumer connection dies. Returns the requeue count."""
        with self._lock:
            pending = list(self._unacked.items())
            self._unacked.clear()
            for _tok, (name, payload, headers) in reversed(pending):
                headers = dict(headers or {})
                headers["redelivered"] = True
                self._queues[name].items.appendleft((payload, headers))
        if pending:
            self._work.set()
        return len(pending)

    def bounce(self) -> int:
        """Simulate a broker restart for chaos tests: redeliver everything
        unacked. (Acked messages were already removed — durability holds.)"""
        return self.requeue_unacked()

    # -- delivery ------------------------------------------------------------
    def pump(self, max_messages: Optional[int] = None) -> int:
        """Deliver pending messages to registered consumers; returns count.

        Ack-on-receipt consumers get the message removed before the callback
        runs; manual-ack consumers get it moved to the unacked ledger and a
        token as third callback arg.
        """
        delivered = 0
        while max_messages is None or delivered < max_messages:
            with self._lock:
                batch = []
                budget = None if max_messages is None else max_messages - delivered
                for qname, q in self._queues.items():
                    if budget is not None and len(batch) >= budget:
                        break
                    if q.consumers and q.items:
                        payload, headers = q.items.popleft()
                        _tag, cb, wants_headers, manual = q.consumers[0]
                        token = None
                        if manual:
                            self._next_token += 1
                            token = self._next_token
                            self._unacked[token] = (qname, payload, headers)
                        batch.append((cb, payload, headers, wants_headers, manual, token))
                if not batch:
                    break
            for cb, payload, headers, wants_headers, manual, token in batch:
                if manual:
                    cb(payload, headers, token)
                elif wants_headers:
                    cb(payload, headers)
                else:
                    cb(payload)
                delivered += 1
            self._maybe_drain()
        self._maybe_drain()
        return delivered

    def _maybe_drain(self) -> None:
        with self._lock:
            if not self._was_full:
                return
            if any(len(q.items) > q.capacity * self.low_water_ratio for q in self._queues.values()):
                return
            self._was_full = False
            callbacks = list(self._drain_callbacks)
        for cb in callbacks:
            cb()

    def start_pump_thread(self) -> None:
        if self._pump_thread is not None:
            return

        def _loop():
            while not self._stop.is_set():
                if self.pump() == 0:
                    self._work.clear()
                    self._work.wait(timeout=0.05)

        self._pump_thread = threading.Thread(target=_loop, name="memory-broker-pump", daemon=True)
        self._pump_thread.start()

    def stop(self) -> None:
        self._stop.set()
        self._work.set()
        if self._pump_thread is not None:
            self._pump_thread.join(timeout=2.0)
            self._pump_thread = None


class MemoryChannel(Channel):
    """Channel view over a shared MemoryBroker."""

    def __init__(self, broker: MemoryBroker):
        self.broker = broker

    def assert_queue(self, name: str) -> None:
        self.broker.assert_queue(name)

    def send(self, name: str, payload: bytes, headers: Optional[dict] = None) -> bool:
        return self.broker.send(name, payload, headers)

    def consume(self, name: str, callback, consumer_tag: str, manual_ack: bool = False) -> None:
        self.broker.consume(name, callback, consumer_tag, manual_ack=manual_ack)

    def ack(self, tokens) -> None:
        self.broker.ack(tokens)

    def cancel(self, consumer_tag: str) -> None:
        self.broker.cancel(consumer_tag)

    def on_drain(self, callback) -> None:
        self.broker.on_drain(callback)

    def queue_lag(self, name: str) -> int:
        """Waiting depth plus unacked in-flight deliveries — the backlog the
        consumer still owes. Scrape-time view for the ``apm_queue_lag``
        gauge (the per-queue lag SLO input), uniform with the spool's."""
        return self.broker.queue_depth(name) + self.broker.unacked_count(name)

    def close(self) -> None:
        # redelivery-on-close: a closing consumer channel abandons its
        # unacked deliveries back to the queues (RabbitMQ connection-death
        # semantics) so the next consumer — or the restarted process on a
        # shared broker — sees them again
        self.broker.requeue_unacked()
