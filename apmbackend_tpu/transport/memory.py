"""In-memory broker backend: the fake broker the reference never had.

Bounded per-queue deques with the pause/drain contract: ``send`` returns False
when a queue crosses its high-water mark; once the depth falls to the
low-water mark a drain callback fires on the producer channel. Delivery is
either *pumped* deterministically (tests, single-process pipelines) or driven
by a background thread (live mode).

The broker object is shareable between modules in one process, standing in for
the external RabbitMQ server; queue depth/memory introspection mirrors what
``rabbitmqctl list_queues`` provided the manager (apm_manager.js:429-453).
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

from .base import Channel, accepts_headers


class _NamedQueue:
    def __init__(self, capacity: int):
        self.capacity = capacity
        # (payload, headers) pairs — headers carry the transport-entry
        # ingest_ts stamp through the fake broker like AMQP properties would
        self.items: deque = deque()
        # (tag, callback, wants_headers)
        self.consumers: List[Tuple[str, Callable, bool]] = []


class MemoryBroker:
    """Process-local named-queue store shared by producer/consumer channels."""

    def __init__(self, capacity: int = 10000, low_water_ratio: float = 0.5):
        self.capacity = capacity
        self.low_water_ratio = low_water_ratio
        self._queues: Dict[str, _NamedQueue] = {}
        self._lock = threading.RLock()
        self._drain_callbacks: List[Callable[[], None]] = []
        self._was_full = False
        self._pump_thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._work = threading.Event()

    # -- queue admin ---------------------------------------------------------
    def assert_queue(self, name: str) -> None:
        with self._lock:
            if name not in self._queues:
                self._queues[name] = _NamedQueue(self.capacity)

    def queue_depth(self, name: str) -> int:
        with self._lock:
            q = self._queues.get(name)
            return len(q.items) if q else 0

    def queue_names(self) -> List[str]:
        with self._lock:
            return list(self._queues)

    def queue_memory_bytes(self, name: str) -> int:
        with self._lock:
            q = self._queues.get(name)
            return sum(len(p) for p, _h in q.items) if q else 0

    # -- producer side -------------------------------------------------------
    def send(self, name: str, payload: bytes, headers: Optional[dict] = None) -> bool:
        with self._lock:
            q = self._queues[name]
            if len(q.items) >= q.capacity:
                self._was_full = True
                return False
            q.items.append((payload, headers))
        self._work.set()
        return True

    def on_drain(self, callback: Callable[[], None]) -> None:
        with self._lock:
            self._drain_callbacks.append(callback)

    # -- consumer side -------------------------------------------------------
    def consume(self, name: str, callback: Callable[[bytes], None], tag: str) -> None:
        with self._lock:
            q = self._queues[name]
            if not any(t == tag for t, _cb, _h in q.consumers):
                q.consumers.append((tag, callback, accepts_headers(callback)))
        self._work.set()

    def cancel(self, tag: str) -> None:
        with self._lock:
            for q in self._queues.values():
                q.consumers = [c for c in q.consumers if c[0] != tag]

    # -- delivery ------------------------------------------------------------
    def pump(self, max_messages: Optional[int] = None) -> int:
        """Deliver pending messages to registered consumers; returns count.

        Messages are removed before the callback runs (ack-on-receipt).
        """
        delivered = 0
        while max_messages is None or delivered < max_messages:
            with self._lock:
                batch = []
                budget = None if max_messages is None else max_messages - delivered
                for q in self._queues.values():
                    if budget is not None and len(batch) >= budget:
                        break
                    if q.consumers and q.items:
                        payload, headers = q.items.popleft()
                        _tag, cb, wants_headers = q.consumers[0]
                        batch.append((cb, payload, headers, wants_headers))
                if not batch:
                    break
            for cb, payload, headers, wants_headers in batch:
                if wants_headers:
                    cb(payload, headers)
                else:
                    cb(payload)
                delivered += 1
            self._maybe_drain()
        self._maybe_drain()
        return delivered

    def _maybe_drain(self) -> None:
        with self._lock:
            if not self._was_full:
                return
            if any(len(q.items) > q.capacity * self.low_water_ratio for q in self._queues.values()):
                return
            self._was_full = False
            callbacks = list(self._drain_callbacks)
        for cb in callbacks:
            cb()

    def start_pump_thread(self) -> None:
        if self._pump_thread is not None:
            return

        def _loop():
            while not self._stop.is_set():
                if self.pump() == 0:
                    self._work.clear()
                    self._work.wait(timeout=0.05)

        self._pump_thread = threading.Thread(target=_loop, name="memory-broker-pump", daemon=True)
        self._pump_thread.start()

    def stop(self) -> None:
        self._stop.set()
        self._work.set()
        if self._pump_thread is not None:
            self._pump_thread.join(timeout=2.0)
            self._pump_thread = None


class MemoryChannel(Channel):
    """Channel view over a shared MemoryBroker."""

    def __init__(self, broker: MemoryBroker):
        self.broker = broker

    def assert_queue(self, name: str) -> None:
        self.broker.assert_queue(name)

    def send(self, name: str, payload: bytes, headers: Optional[dict] = None) -> bool:
        return self.broker.send(name, payload, headers)

    def consume(self, name: str, callback, consumer_tag: str) -> None:
        self.broker.consume(name, callback, consumer_tag)

    def cancel(self, consumer_tag: str) -> None:
        self.broker.cancel(consumer_tag)

    def on_drain(self, callback) -> None:
        self.broker.on_drain(callback)
