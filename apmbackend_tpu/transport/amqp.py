"""AMQP/RabbitMQ backend.

Role parity with queue.js: named durable queues on a RabbitMQ broker,
ack-on-receipt consumption, publish backpressure. Uses ``pika`` when present;
this environment ships without an AMQP client, so construction raises a clear
error and the rest of the framework (which only depends on the Channel
interface) runs on the memory backend. Wire format on the queues is identical
(UTF-8 pipe-CSV), so a deployment with RabbitMQ interoperates with reference
modules consuming the same queues.
"""

from __future__ import annotations

from typing import Callable

from .base import Channel

try:  # pragma: no cover - optional dependency
    import pika  # type: ignore

    HAVE_PIKA = True
except ImportError:  # pragma: no cover
    pika = None
    HAVE_PIKA = False


class AmqpChannel(Channel):  # pragma: no cover - requires live broker
    def __init__(self, connection_string: str):
        if not HAVE_PIKA:
            raise RuntimeError(
                "AMQP backend requires the 'pika' package, which is not installed. "
                "Use brokerBackend='memory' or install pika."
            )
        params = pika.URLParameters(connection_string)
        self._connection = pika.BlockingConnection(params)
        self._channel = self._connection.channel()
        self._drain_callbacks = []
        self._consumer_tags = {}

    def assert_queue(self, name: str) -> None:
        self._channel.queue_declare(queue=name, durable=True)

    def send(self, name: str, payload: bytes) -> bool:
        try:
            self._channel.basic_publish(
                exchange="",
                routing_key=name,
                body=payload,
                properties=pika.BasicProperties(delivery_mode=2),
            )
            return True
        except pika.exceptions.AMQPError:
            return False

    def consume(self, name: str, callback: Callable[[bytes], None], consumer_tag: str) -> None:
        def _on_message(ch, method, properties, body):
            ch.basic_ack(delivery_tag=method.delivery_tag)  # ack-on-receipt
            callback(body)

        tag = self._channel.basic_consume(queue=name, on_message_callback=_on_message, consumer_tag=consumer_tag)
        self._consumer_tags[consumer_tag] = tag

    def cancel(self, consumer_tag: str) -> None:
        self._channel.basic_cancel(consumer_tag)

    def on_drain(self, callback) -> None:
        self._drain_callbacks.append(callback)

    def close(self) -> None:
        try:
            self._channel.close()
        finally:
            self._connection.close()

    def start_io(self) -> None:
        """Blocking consume loop (call from a dedicated thread)."""
        self._channel.start_consuming()
